"""MultiTenantScheduler: cross-tenant batching over the one solve seam.

The coalescing queue (solver/service.py) already proved the core move
for one cluster: concurrent same-shape requests concatenate into ONE
device program and the per-request cost of a decision collapses. This
module adds the FLEET axis (docs/multitenancy.md): N tenant clusters'
per-tick matrices — decide, cost, forecast — are concatenated along
their row/series axis into single device programs, dispatched once, and
scattered back per tenant. Every kernel involved is row-independent
(ops/decision.py, ops/cost.py, forecast/models.py compute each row from
that row's operands only), so a tenant's slice of the concatenated
output is BIT-IDENTICAL to what its own independent dispatch would have
produced — the parity contract tests/test_tenancy.py pins on both the
device and numpy paths. Cross-tenant bin-packs need no new machinery at
all: `solve_all` submits every tenant's problem through the existing
coalescing queue, where same-bucket requests already ride one `lax.map`
dispatch.

Around the concatenation sit the two multi-tenant serving policies:

  * FAIRNESS (tenancy/fairness.py) — each concatenated dispatch admits
    tenants under a deficit-weighted round-robin row budget, so a noisy
    tenant's giant matrix becomes its own round instead of starving the
    queue; deferred tenants carry credit and converge to their weight
    share.
  * ISOLATION (tenancy/isolation.py) — per-tenant breakers: a tenant
    whose gather/dispatch keeps failing is tripped OUT of the shared
    batch and served from the family's bit-identical numpy mirror
    (cost_numpy / forecast_numpy / binpack_numpy) while healthy tenants
    stay on device; the decide family — the never-block kernel with no
    host mirror — degrades to an ISOLATED per-tenant dispatch instead.
    `tenancy.gather.<tenant id>` is the per-tenant fault-injection
    point (faults/registry.py; glob `tenancy.gather.*` hits them all).

Decide batches group by their `now` scalar: lockstep callers (the
simulator, the bench, a tick-driven runtime) share one epoch and ride
one program; callers at different epochs form separate groups rather
than perturbing each other's stabilization-window math.

Metrics ride the TenantMetrics face (tenancy/registry.py):
karpenter_tenant_* series per tenant, retired with the tenant.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_tpu.faults import inject
from karpenter_tpu.ops import decision as D
from karpenter_tpu.tenancy.fairness import WeightedAdmission
from karpenter_tpu.tenancy.isolation import TenantBreakerBoard
from karpenter_tpu.tenancy.registry import TenantRegistry
from karpenter_tpu.utils.log import logger

# per-tenant fault-injection point prefix (module docstring)
GATHER_POINT = "tenancy.gather."

# row bucket for concatenated dispatches: tenant-count jitter moves
# along this ladder instead of recompiling per added tenant (the same
# reason the decide pass buckets its fleet — ops/decision.pad_to)
ROW_BUCKET = 64

# interned "row<i>" labels for ledger records (the tenant simulator's
# rows are synthetic autoscalers): strings are minted once per process,
# so a per-tick ledger batch allocates no new name objects
_ROW_NAMES: list = []


def _row_names(n: int) -> list:
    while len(_ROW_NAMES) < n:
        _ROW_NAMES.append(f"row{len(_ROW_NAMES)}")
    return _ROW_NAMES[:n]


@dataclass
class TenancyStatistics:
    """Plain-int mirror of the scheduler counters (tests and the bench
    read these; the registry carries the per-tenant series)."""

    deadline_escapes: int = 0  # deferred tenants served early (budget out)
    decide_calls: int = 0  # decide_all entries
    decide_rows: int = 0  # tenant rows decided (across all tenants)
    decide_dispatches: int = 0  # shared concatenated decide dispatches
    cost_calls: int = 0
    cost_rows: int = 0
    cost_dispatches: int = 0
    forecast_calls: int = 0
    forecast_series: int = 0
    forecast_dispatches: int = 0
    fused_calls: int = 0  # fused_tick_all entries (--fused-tick)
    fused_rows: int = 0  # tenant rows through the fused megakernel
    fused_dispatches: int = 0  # shared concatenated fused dispatches
    solve_calls: int = 0
    solve_requests: int = 0  # per-tenant bin-packs through the queue
    admission_rounds: int = 0  # rounds across all shared dispatches
    deferrals: int = 0  # tenant admissions pushed past round 1
    isolated_dispatches: int = 0  # per-tenant dispatches outside a batch
    mirror_served: int = 0  # tenant results served from a numpy mirror
    fallback_served: int = 0  # results synthesized by the never-block floor
    probes: int = 0  # isolated recovery attempts for open breakers
    tenant_failures: int = 0  # per-tenant gather/dispatch failures
    breaker_trips: int = 0
    breaker_recoveries: int = 0


class MultiTenantScheduler:
    """One per process (module docstring). `registry` owns tenant
    membership and the per-tenant stacks; `service` (defaulting to the
    registry's) is the shared SolverService every dispatch rides."""

    def __init__(
        self,
        registry: TenantRegistry,
        service=None,
        *,
        max_rows_per_round: int = 4096,
        breaker_threshold: int = 3,
        breaker_reset_s: float = 30.0,
        deadline_s: Optional[float] = None,
        clock=None,
    ):
        import time as _time

        self.registry = registry
        self.service = service if service is not None else registry.service
        if self.service is None:
            raise ValueError(
                "MultiTenantScheduler needs a SolverService (directly or "
                "via the tenant registry)"
            )
        clock = clock or _time.monotonic
        self._clock = clock
        # tenant-weighted solve deadlines (docs/multitenancy.md):
        # fairness bounds ROWS per round, not how long a deferred
        # tenant waits behind earlier rounds — deadline_s bounds that
        # latency. Each tenant's budget scales with its configured
        # weight (budget = deadline_s x weight / mean weight): a
        # heavyweight tenant is entitled to keep its device slot
        # through a long backlog, a lightweight one whose budget runs
        # out mid-schedule stops waiting and serves IMMEDIATELY from
        # the family's bit-identical mirror (or an isolated dispatch
        # for mirror-less families) — the answer is the same answer,
        # only the wait is bounded. None disables the bound (the
        # pre-deadline posture).
        self.deadline_s = deadline_s
        self.admission = WeightedAdmission(budget_rows=max_rows_per_round)
        self.breakers = TenantBreakerBoard(
            threshold=breaker_threshold, reset_s=breaker_reset_s,
            clock=clock,
        )
        self.stats = TenancyStatistics()
        self.metrics = registry.metrics
        registry.on_removed(self._forget)
        # per-family serve log: tenant -> {"rung", "round", "deferred"},
        # reset by decide_all/cost_all — feeds the provenance ledger's
        # tenancy slice (observability/provenance.py)
        self._serving: Dict[str, dict] = {}
        # the in-flight ledger batch spanning decide_all -> cost_all
        # (one tick's records commit once the cost pass has annotated
        # its slice; a decide-only tick commits on the next decide or
        # via flush_provenance). _ledger_owner pins the ledger that
        # STAGED the batch, so a default-ledger swap between ticks
        # (the bench/simulate save-restore pattern) cannot commit a
        # batch into a ledger that never staged it.
        self._ledger_batch = None
        self._ledger_owner = None
        self._ledger_slices: Dict[str, Tuple[int, int]] = {}

    def _forget(self, tenant: str) -> None:
        self.breakers.forget(tenant)
        self.admission.forget(tenant)

    # -- decide ------------------------------------------------------------

    def decide_all(self, batch: Dict[str, D.DecisionInputs]):
        """Concatenate every tenant's fleet DecisionInputs into shared
        dispatches (grouped by `now`, admitted fairly, isolated per
        tenant) and scatter DecisionOutputs back per tenant."""
        self.stats.decide_calls += 1
        self._serving = {}
        self._ledger_begin(batch)
        results: Dict[str, D.DecisionOutputs] = {}
        by_now: Dict[float, Dict[str, D.DecisionInputs]] = {}
        for tenant, inputs in batch.items():
            by_now.setdefault(
                float(np.asarray(inputs.now)), {}
            )[tenant] = inputs
        for group in by_now.values():
            results.update(
                self._run_family(
                    group,
                    family="decide",
                    rows_of=lambda i: int(
                        np.asarray(i.spec_replicas).shape[0]
                    ),
                    concat=concat_decision_inputs,
                    dispatch=self.service.decide,
                    scatter=slice_decision_outputs,
                    isolated=self.service.decide,
                    mirror=None,  # no host mirror: isolate instead
                    fallback=decide_hold,
                )
            )
        self._ledger_after_decide(results)
        return results

    # -- cost --------------------------------------------------------------

    def cost_all(self, batch, backend: Optional[str] = None):
        """Concatenate every tenant's CostInputs into shared
        SolverService.cost dispatches; a degraded tenant serves from the
        bit-identical cost_numpy mirror alone."""
        from karpenter_tpu.ops import cost as CK

        self.stats.cost_calls += 1
        self._serving = {}

        def dispatch(inputs):
            return self.service.cost(inputs, backend=backend)

        results = self._run_family(
            batch,
            family="cost",
            rows_of=lambda i: int(np.asarray(i.base_desired).shape[0]),
            concat=concat_cost_inputs,
            dispatch=dispatch,
            scatter=slice_cost_outputs,
            isolated=dispatch,
            mirror=CK.cost_numpy,
            fallback=cost_blind,
        )
        self._ledger_after_cost(batch, results)
        return results

    # -- forecast ----------------------------------------------------------

    def forecast_all(self, batch, backend: Optional[str] = None):
        """Concatenate every tenant's ForecastInputs along the series
        axis (grouped by history-length bucket) into shared
        SolverService.forecast dispatches; a degraded tenant serves
        from the bit-identical forecast_numpy mirror alone."""
        from karpenter_tpu.forecast import models as FM
        from karpenter_tpu.solver.service import FORECAST_T_FLOOR
        from karpenter_tpu.solver.bucketing import bucket_up

        self.stats.forecast_calls += 1
        results = {}
        by_t: Dict[int, Dict[str, object]] = {}
        for tenant, inputs in batch.items():
            t_bucket = bucket_up(
                int(np.asarray(inputs.values).shape[1]), FORECAST_T_FLOOR
            )
            by_t.setdefault(t_bucket, {})[tenant] = inputs

        def dispatch(inputs):
            return self.service.forecast(inputs, backend=backend)

        for t_bucket, group in by_t.items():
            padded = {
                tenant: FM.pad_forecast_inputs(inputs, t_bucket)
                for tenant, inputs in group.items()
            }
            results.update(
                self._run_family(
                    padded,
                    family="forecast",
                    rows_of=lambda i: int(np.asarray(i.values).shape[0]),
                    concat=concat_forecast_inputs,
                    dispatch=dispatch,
                    scatter=FM.slice_forecast_outputs,
                    isolated=dispatch,
                    mirror=FM.forecast_numpy,
                    fallback=forecast_invalid,
                )
            )
        return results

    # -- fused tick --------------------------------------------------------

    def fused_tick_all(self, batch, backend: Optional[str] = None):
        """Concatenate every tenant's FusedTickInputs into shared
        fused-megakernel dispatches (docs/solver-service.md "Fused
        tick"): one compiled forecast -> decide -> cost program covers
        the whole tenant group, and each tenant's slice of the scattered
        outputs is bit-identical to its own independent fused dispatch
        (the same row/series-independence argument as decide_all /
        forecast_all). Groups by (now epoch, forecast time bucket) so
        concatenation never perturbs stabilization-window math or the
        forecast compile rung; a degraded tenant serves from the
        bit-identical fused_tick_numpy mirror alone."""
        from karpenter_tpu.ops import fusedtick as FT
        from karpenter_tpu.solver.bucketing import bucket_up
        from karpenter_tpu.solver.service import FORECAST_T_FLOOR

        self.stats.fused_calls += 1
        self._serving = {}
        results: Dict[str, object] = {}
        groups: Dict[tuple, Dict[str, object]] = {}
        for tenant, inputs in batch.items():
            t_bucket = 0
            if inputs.forecast is not None:
                t_bucket = bucket_up(
                    int(np.asarray(inputs.forecast.values).shape[1]),
                    FORECAST_T_FLOOR,
                )
            key = (float(np.asarray(inputs.decision.now)), t_bucket)
            groups.setdefault(key, {})[tenant] = inputs

        def dispatch(inputs):
            return self.service.fused_tick(inputs, backend=backend)

        for group in groups.values():
            # per-round tenant spans (series ranges + stage presence),
            # written by concat and read back by scatter — the generic
            # _run_family machinery only threads row offsets, and the
            # fused outputs carry a second (series) axis
            spans: Dict[int, dict] = {}

            def concat(inputs_list, _spans=spans):
                stacked, tenant_spans = concat_fused_inputs(inputs_list)
                _spans.clear()
                _spans.update(tenant_spans)
                return stacked

            def scatter(out, start, stop, _spans=spans):
                return slice_fused_outputs(
                    out, start, stop, _spans.get(start)
                )

            results.update(
                self._run_family(
                    group,
                    family="fused",
                    rows_of=lambda i: int(
                        np.asarray(i.decision.spec_replicas).shape[0]
                    ),
                    concat=concat,
                    dispatch=dispatch,
                    scatter=scatter,
                    isolated=dispatch,
                    mirror=FT.fused_tick_numpy,
                    fallback=fused_hold,
                )
            )
        return results

    # -- solve (bin-pack) --------------------------------------------------

    def solve_all(  # lint: allow-complexity — per-tenant isolation ladder + weighted-deadline classification, one guard each
        self,
        batch,
        buckets: int = 32,
        backend: Optional[str] = None,
        timeout: Optional[float] = None,
    ):
        """Cross-tenant bin-packs through the EXISTING coalescing queue:
        every healthy tenant's problem is submitted before any result is
        awaited, so same-bucket problems concatenate into one `lax.map`
        dispatch exactly like concurrent same-cluster callers do. A
        degraded tenant's solve never enters the queue — it serves from
        the numpy mirror inline (the same binpack_numpy every ladder
        rung ends at)."""
        from karpenter_tpu.ops.numpy_binpack import binpack_numpy
        from karpenter_tpu.solver.service import SolverTimeout

        self.stats.solve_calls += 1
        results: Dict[str, object] = {}
        # (tenant, future, effective timeout, bounded-by-budget?)
        futures: List[Tuple[str, object, Optional[float], bool]] = []
        # tenant-weighted solve deadlines: each tenant's queue wait is
        # bounded by its weighted budget (never loosened past the
        # caller's own timeout) — an expiry serves the bit-identical
        # numpy mirror and counts a deferral, not a breaker failure
        budgets = self._deadline_budgets(
            sorted(batch), self.registry.weights()
        )
        for tenant, inputs in sorted(batch.items()):
            # "probe" needs no special-casing here: the solver
            # service's own ladder answers each queued request from
            # numpy on a device failure (per-request fallback), so a
            # probing tenant cannot fail other riders' results
            if self._admit_tenant(tenant) == "degraded":
                results[tenant] = binpack_numpy(inputs, buckets=buckets)
                self._served_mirror(tenant)
                continue
            budget = budgets.get(tenant)
            t_eff = (
                timeout
                if budget is None
                else (min(timeout, budget) if timeout else budget)
            )
            # a later expiry is only a DEADLINE escape when the
            # weighted budget was the binding bound — an expiry at the
            # caller's own (smaller) timeout is a device-path problem
            # and must keep charging the breaker
            bounded = budget is not None and (
                not timeout or budget < timeout
            )
            try:
                futures.append((tenant, self.service.submit(
                    inputs, buckets=buckets, backend=backend,
                    timeout=t_eff, tenant=tenant,
                ), t_eff, bounded))
                self.stats.solve_requests += 1
            except Exception as error:  # noqa: BLE001 — per-tenant isolation
                self._tenant_failed(tenant, error)
                results[tenant] = binpack_numpy(inputs, buckets=buckets)
                self._served_mirror(tenant)
        for tenant, future, t_eff, bounded in futures:
            try:
                results[tenant] = future.result(t_eff)
                self._tenant_ok(tenant)
            except Exception as error:  # noqa: BLE001 — per-tenant isolation
                if isinstance(error, SolverTimeout) and bounded:
                    # weighted-deadline expiry: bounded-wait serve, no
                    # breaker charge (backlog, not tenant fault)
                    self.stats.deadline_escapes += 1
                    self.stats.deferrals += 1
                    if self.metrics.enabled:
                        self.metrics.deferrals.inc(tenant, "-")
                else:
                    self._tenant_failed(tenant, error)
                results[tenant] = binpack_numpy(
                    batch[tenant], buckets=buckets
                )
                self._served_mirror(tenant)
        return results

    # -- decision provenance (observability/provenance.py) -----------------

    def flush_provenance(self) -> None:
        """Commit a pending decide-only batch (a caller that never runs
        a cost pass flushes before reading /debug/decisions or
        exporting; the next decide_all flushes automatically)."""
        if self._ledger_batch is not None:
            self._ledger_owner.commit(self._ledger_batch)
            self._ledger_batch = None
            self._ledger_owner = None
            self._ledger_slices = {}

    def _ledger_begin(self, batch: Dict[str, object]) -> None:
        """Open the tick's ledger batch: one record per tenant row,
        labeled tenant/group=<tenant id> and name=row<i>. Spans
        decide_all -> cost_all (the cost pass annotates its slice and
        commits); a decide-only caller's batch commits on the next tick
        (or flush_provenance) instead of leaking. No-op (one attribute
        read) when the ledger is disabled."""
        from karpenter_tpu.observability import default_ledger
        from karpenter_tpu.observability.provenance import OBSERVED_WIDTH

        # the previous tick never ran a cost pass: its records are
        # complete as decided — commit (into the ledger that staged
        # them) rather than drop
        self.flush_provenance()
        ledger = default_ledger()
        if not ledger.enabled:
            return
        tenants = sorted(batch)
        sizes = [
            int(np.asarray(batch[t].spec_replicas).shape[0])
            for t in tenants
        ]
        total = sum(sizes)
        if not total:
            return
        tenant_col = np.empty(total, object)
        name_col = np.empty(total, object)
        observed = np.zeros((total, OBSERVED_WIDTH), np.float32)
        observed_n = np.zeros(total, np.int16)
        prev = np.zeros(total, np.int32)
        slices: Dict[str, Tuple[int, int]] = {}
        offset = 0
        for tenant, size in zip(tenants, sizes):
            stop = offset + size
            slices[tenant] = (offset, stop)
            tenant_col[offset:stop] = tenant
            name_col[offset:stop] = _row_names(size)
            values = np.asarray(batch[tenant].metric_value, np.float32)
            m = min(values.shape[1], OBSERVED_WIDTH)
            observed[offset:stop, :m] = values[:, :m]
            observed_n[offset:stop] = values.shape[1]
            prev[offset:stop] = np.asarray(
                batch[tenant].spec_replicas, np.int32
            )
            offset = stop
        self._ledger_batch = ledger.begin(
            "tenant",
            total,
            tenant=tenant_col,
            namespace="-",
            name=name_col,
            group=tenant_col.copy(),
            observed=observed,
            observed_n=observed_n,
            prev_replicas=prev,
        )
        self._ledger_owner = ledger if self._ledger_batch else None
        self._ledger_slices = slices
        # the batch outlives this call (cost_all annotates later): own
        # it on the scheduler, not the begin() thread's TLS slot
        ledger.abort(self._ledger_batch)

    def _ledger_after_decide(self, results: Dict[str, object]) -> None:
        batch = self._ledger_batch
        if batch is None:
            return
        for tenant, (start, stop) in self._ledger_slices.items():
            out = results.get(tenant)
            if out is None:
                continue
            desired = np.asarray(out.desired, np.int32)[: stop - start]
            serve = self._serving.get(tenant, {})
            batch.annotate_slice(
                start, stop,
                base_desired=desired,
                final_desired=desired,
                solver_rung=serve.get("rung", "device"),
                solver_backend=serve.get("backend", ""),
                admission_round=np.int16(serve.get("round", 0)),
                deferred=bool(serve.get("deferred", False)),
            )

    def _ledger_after_cost(
        self, inputs: Dict[str, object], results: Dict[str, object]
    ) -> None:
        """The cost pass annotates its slice and COMMITS the tick's
        records. Tenants absent from the decide batch (cost-only
        callers) are skipped; a cost serve from the mirror/floor
        updates the rung — the refine stage is the one that computed
        the final number."""
        batch = self._ledger_batch
        if batch is None:
            return
        for tenant, (start, stop) in self._ledger_slices.items():
            out = results.get(tenant)
            if out is None or tenant not in inputs:
                continue  # decide-only tenant: its record stands as decided
            size = stop - start
            desired = np.asarray(out.desired, np.int32)[:size]
            serve = self._serving.get(tenant, {})
            rung = serve.get("rung")
            columns = dict(
                final_desired=desired,
                slo_opted=np.asarray(
                    inputs[tenant].slo_valid, bool
                )[:size],
                cost_candidate=desired,
                cost_risk=np.asarray(
                    out.violation_risk, np.float32
                )[:size],
                cost_hourly=np.asarray(
                    out.expected_hourly, np.float32
                )[:size],
                budget_clamped=np.asarray(
                    out.cost_limited, bool
                )[:size],
                cost_blind=bool(rung == "floor"),
            )
            if rung:
                columns["solver_rung"] = rung
            batch.annotate_slice(start, stop, **columns)
        # commit into the ledger that STAGED the batch — the process
        # default may have been swapped since decide_all
        owner = self._ledger_owner
        self._ledger_batch = None
        self._ledger_owner = None
        self._ledger_slices = {}
        owner.commit(batch)

    def _record_serve(
        self, tenant: str, rung: str, round_index: int = 0,
        deferred: bool = False, backend: str = "",
    ) -> None:
        self._serving[tenant] = {
            "rung": rung,
            "round": round_index,
            "deferred": deferred,
            "backend": backend,
        }
        if rung != "device":
            # tenant-stamped marker span for every off-the-shared-batch
            # serve (isolated / mirror / floor): /debug/traces?tenant=
            # surfaces exactly which ticks degraded this tenant and how
            # — bounded by degraded tenants, so the healthy 1k-tenant
            # shared round stays span-free
            from karpenter_tpu.observability import default_tracer

            span = default_tracer().begin(
                "tenancy.serve", tenant=tenant, rung=rung,
            )
            if span is not None:
                span.close()

    # -- the shared fan-in/fan-out machinery -------------------------------

    def _admit_tenant(self, tenant: str) -> str:
        """Breaker gate + per-tenant fault point. Verdicts: "shared"
        (ride the concatenated batch), "probe" (breaker open, probe due
        — ONE isolated recovery dispatch, never the shared batch: the
        failure that opened the breaker must not re-break healthy
        tenants' rounds), or "degraded" (mirror/fallback only)."""
        from karpenter_tpu.tenancy import isolation as I

        state = self.breakers.gate(tenant)
        if state == I.OPEN:
            return "degraded"
        try:
            inject(GATHER_POINT + tenant)
        except Exception as error:  # noqa: BLE001 — injected per-tenant fault
            self._tenant_failed(tenant, error)
            return "degraded"
        return "probe" if state == I.PROBE else "shared"

    def _tenant_failed(self, tenant: str, error: BaseException) -> None:
        self.stats.tenant_failures += 1
        tripped = self.breakers.record_failure(tenant)
        if tripped:
            self.stats.breaker_trips += 1
            logger().warning(
                "tenant %s breaker OPEN after repeated failures (%s: %s); "
                "serving its rows from the mirror while others stay on "
                "device",
                tenant, type(error).__name__, error,
            )
            # flight-recorder event with the tenant FIELD, so
            # /debug/flightrecorder?tenant=<id> surfaces exactly this
            # tenant's degradations (docs/observability.md); NOT a
            # dump-class kind — one sick tenant in a 1k-tenant fleet is
            # supervised degradation, not a control-plane incident
            from karpenter_tpu.observability import (
                default_flight_recorder,
            )

            default_flight_recorder().record(
                "tenant_breaker_trip",
                tenant=tenant,
                error=f"{type(error).__name__}: {error}"[:200],
            )
        if self.metrics.enabled:
            if tripped:
                self.metrics.trips.inc(tenant, "-")
            self.metrics.degraded.set(
                tenant, "-", 1.0 if self.breakers.is_open(tenant) else 0.0
            )

    def _tenant_ok(self, tenant: str) -> None:
        if self.breakers.record_success(tenant):
            self.stats.breaker_recoveries += 1
            logger().info(
                "tenant %s breaker closed; rejoining the shared batch",
                tenant,
            )
        if self.metrics.enabled:
            self.metrics.degraded.set(tenant, "-", 0.0)

    def _served_mirror(self, tenant: str) -> None:
        self.stats.mirror_served += 1
        if self.metrics.enabled:
            self.metrics.mirror.inc(tenant, "-")

    def _run_family(  # lint: allow-complexity — one family pass: gate + admit + rounds, one guard per policy
        self, batch, *, family, rows_of, concat, dispatch, scatter,
        isolated, mirror, fallback,
    ) -> Dict[str, object]:
        """One family pass: breaker-gate, fair-admit, concatenate,
        dispatch shared rounds, scatter per tenant; degraded tenants
        serve from `mirror` (or `isolated` when the family has no host
        mirror, with `fallback` synthesizing the never-block answer if
        even that fails). A shared-round failure falls back to
        per-tenant isolated dispatches so one poisoned tenant cannot
        take the round's healthy tenants down with it. Every tenant in
        `batch` gets a real outputs object back — never an exception."""
        results: Dict[str, object] = {}
        healthy: Dict[str, object] = {}
        for tenant, inputs in sorted(batch.items()):
            n = rows_of(inputs)
            self._count_rows(family, n)
            if self.metrics.enabled:
                self.metrics.backlog.set(tenant, "-", float(n))
            verdict = self._admit_tenant(tenant)
            if verdict == "shared":
                healthy[tenant] = inputs
            elif verdict == "probe":
                results[tenant] = self._probe_tenant(
                    tenant, inputs, isolated, mirror, fallback
                )
            else:
                results[tenant] = self._serve_degraded(
                    tenant, inputs, mirror, isolated, fallback
                )
        if healthy:
            weights = self.registry.weights()
            demand = {t: rows_of(i) for t, i in healthy.items()}
            # group-aware: tenants hosting one PoolGroup's member pools
            # ride the same round, so the joint allocator never scores
            # a partial group (fairness.py module docstring)
            schedule = self.admission.rounds(
                demand, weights, self.registry.pool_groups()
            )
            self.stats.admission_rounds += len(schedule)
            if self.metrics.enabled:
                self.metrics.rounds.set("-", "-", float(len(schedule)))
            budgets = self._deadline_budgets(list(healthy), weights)
            t0 = self._clock()
            for round_index, admitted in enumerate(schedule):
                if budgets and round_index > 0:
                    # tenant-weighted solve deadlines: a deferred
                    # tenant whose weighted budget the earlier rounds
                    # already consumed stops waiting and serves NOW
                    # from the bit-identical mirror (or an isolated
                    # dispatch) — same answer, bounded wait
                    elapsed = self._clock() - t0
                    expired = {
                        t for t in admitted if elapsed > budgets[t]
                    }
                    for tenant in sorted(expired):
                        results[tenant] = self._serve_deadline_escape(
                            tenant, healthy[tenant], mirror, isolated,
                            fallback,
                        )
                    admitted = [t for t in admitted if t not in expired]
                    if not admitted:
                        continue
                if round_index > 0:
                    self.stats.deferrals += len(admitted)
                    if self.metrics.enabled:
                        for tenant in admitted:
                            self.metrics.deferrals.inc(tenant, "-")
                self._dispatch_round(
                    {t: healthy[t] for t in admitted},
                    results, family=family, concat=concat,
                    dispatch=dispatch, scatter=scatter,
                    isolated=isolated, mirror=mirror, fallback=fallback,
                    rows_of=rows_of, round_index=round_index,
                )
        if family in ("decide", "fused") and self.metrics.enabled:
            # karpenter_tenant_decisions_total counts DECIDE rows only
            # (one per autoscaler per tick — the fused megakernel's
            # rows are decisions too), on every serve path — shared
            # scatter, lone round, mirror, and fallback alike
            for tenant in results:
                self.metrics.decisions.inc(
                    tenant, "-", float(rows_of(batch[tenant]))
                )
        return results

    def _count_rows(self, family: str, n: int) -> None:
        if family == "decide":
            self.stats.decide_rows += n
        elif family == "cost":
            self.stats.cost_rows += n
        elif family == "fused":
            self.stats.fused_rows += n
        else:
            self.stats.forecast_series += n

    def _count_family_dispatch(self, family: str) -> None:
        if family == "decide":
            self.stats.decide_dispatches += 1
        elif family == "cost":
            self.stats.cost_dispatches += 1
        elif family == "fused":
            self.stats.fused_dispatches += 1
        else:
            self.stats.forecast_dispatches += 1

    def _deadline_budgets(
        self, tenants: List[str], weights: Dict[str, float]
    ) -> Dict[str, float]:
        """Per-tenant wall-time budgets under --tenant-deadline:
        deadline_s scaled by weight / mean weight, so the fleet's mean
        tenant gets exactly deadline_s and weights shift budget toward
        the tenants an operator declared heavier. Empty when the bound
        is disabled."""
        from karpenter_tpu.tenancy.fairness import effective_weight

        if self.deadline_s is None or not tenants:
            return {}
        w = {t: effective_weight(weights, t) for t in tenants}
        mean = sum(w.values()) / len(w)
        return {t: self.deadline_s * w[t] / mean for t in tenants}

    def _serve_deadline_escape(
        self, tenant, inputs, mirror, isolated, fallback
    ):
        """A deferred tenant whose weighted deadline budget ran out:
        serve immediately from the family's mirror/isolated rung
        instead of waiting out the remaining rounds. Counted as a
        deferral (karpenter_tenant_deferrals_total — the fairness
        ledger the operator already watches) plus deadline_escapes; the
        breaker is NOT charged — backlog is the plane's condition, not
        the tenant's fault."""
        self.stats.deadline_escapes += 1
        self.stats.deferrals += 1
        if self.metrics.enabled:
            self.metrics.deferrals.inc(tenant, "-")
        out = self._serve_degraded(
            tenant, inputs, mirror, isolated, fallback
        )
        serve = self._serving.get(tenant)
        if serve is not None:
            serve["deferred"] = True
        return out

    def _probe_tenant(self, tenant, inputs, isolated, mirror, fallback):
        """An open breaker's recovery probe: ONE isolated dispatch —
        success closes the breaker (the tenant rejoins the shared batch
        next round), failure keeps it open and this round serves from
        the mirror/fallback like any other degraded round."""
        self.stats.probes += 1
        try:
            self.stats.isolated_dispatches += 1
            out = isolated(inputs)
            self._tenant_ok(tenant)
            self._record_serve(tenant, "isolated")
            return out
        except Exception as error:  # noqa: BLE001 — tenant isolation
            self._tenant_failed(tenant, error)
            return self._mirror_or_fallback(
                tenant, inputs, mirror, fallback
            )

    def _serve_degraded(self, tenant, inputs, mirror, isolated, fallback):
        """A tenant outside the shared batch still gets a REAL answer:
        the family's numpy mirror, or an isolated dispatch for
        mirror-less families — and if even that rung fails, the
        family's `fallback` synthesizes the never-block result (hold
        current replicas / pass through cost-blind / invalid forecast)
        so one sick tenant can never hand its caller an exception."""
        if mirror is None:
            try:
                self.stats.isolated_dispatches += 1
                out = isolated(inputs)
                self._record_serve(tenant, "isolated")
                return out
            except Exception as error:  # noqa: BLE001 — tenant isolation
                self._tenant_failed(tenant, error)
            return self._served_fallback(tenant, fallback, inputs)
        return self._mirror_or_fallback(tenant, inputs, mirror, fallback)

    def _mirror_or_fallback(self, tenant, inputs, mirror, fallback):
        if mirror is not None:
            try:
                out = mirror(inputs)
                self._served_mirror(tenant)
                self._record_serve(tenant, "mirror", backend="numpy")
                return out
            except Exception as error:  # noqa: BLE001 — tenant isolation
                self._tenant_failed(tenant, error)
        return self._served_fallback(tenant, fallback, inputs)

    def _served_fallback(self, tenant, fallback, inputs):
        """Count a synthesized never-block result SEPARATELY from
        mirror serves — a fallback answer is a do-nothing floor, not a
        bit-identical mirror, and conflating them on /metrics would
        mask how degraded a tenant really is."""
        self.stats.fallback_served += 1
        if self.metrics.enabled:
            self.metrics.fallback.inc(tenant, "-")
        self._record_serve(tenant, "floor")
        return fallback(inputs)

    def _dispatch_round(  # lint: allow-complexity — shared dispatch + per-tenant fallback ladder, one arm per rung
        self, admitted, results, *, family, concat, dispatch, scatter,
        isolated, mirror, fallback, rows_of, round_index: int = 0,
    ) -> None:
        tenants = sorted(admitted)
        if len(tenants) == 1:
            # a lone tenant (oversized, or just a one-tenant fleet)
            # needs no concatenation — its own matrix IS the program
            tenant = tenants[0]
            try:
                self.stats.isolated_dispatches += 1
                results[tenant] = isolated(admitted[tenant])
                self._tenant_ok(tenant)
                self._record_serve(
                    tenant, "isolated", round_index,
                    deferred=round_index > 0,
                )
            except Exception as error:  # noqa: BLE001 — tenant isolation
                self._tenant_failed(tenant, error)
                results[tenant] = self._serve_degraded(
                    tenant, admitted[tenant], mirror, isolated, fallback
                )
            return
        inputs_list = [admitted[t] for t in tenants]
        sizes = [rows_of(i) for i in inputs_list]
        stacked = concat(inputs_list)
        try:
            out = dispatch(stacked)
        except Exception as error:  # noqa: BLE001 — shared-round failure
            logger().warning(
                "shared %d-tenant dispatch failed (%s: %s); retrying "
                "each tenant in isolation",
                len(tenants), type(error).__name__, error,
            )
            for tenant in tenants:
                try:
                    self.stats.isolated_dispatches += 1
                    results[tenant] = isolated(admitted[tenant])
                    self._tenant_ok(tenant)
                except Exception as tenant_error:  # noqa: BLE001
                    self._tenant_failed(tenant, tenant_error)
                    results[tenant] = self._serve_degraded(
                        tenant, admitted[tenant], mirror, isolated,
                        fallback,
                    )
            return
        self._count_family_dispatch(family)
        offset = 0
        for tenant, size in zip(tenants, sizes):
            results[tenant] = scatter(out, offset, offset + size)
            offset += size
            self._tenant_ok(tenant)
            self._record_serve(
                tenant, "device", round_index,
                deferred=round_index > 0,
            )
        if self.metrics.enabled:
            self.metrics.dispatches.inc("-", "-")


# -- last-resort fallbacks (the never-block floor of the tenant ladder) ------
# Synthesized when a tenant's mirror/isolated rung ALSO fails: each
# family's domain-safe "do nothing" answer, so a sick tenant's result is
# always a real outputs object — never an exception for the caller to
# trip over mid-batch.


def decide_hold(inputs: D.DecisionInputs) -> D.DecisionOutputs:
    """Hold current replicas: the decide family's never-block floor
    (the same posture a failed metric query takes — no movement without
    a trustworthy signal)."""
    spec = np.asarray(inputs.spec_replicas, np.int32)
    n = spec.shape[0]
    return D.DecisionOutputs(
        desired=spec.copy(),
        recommendation=spec.copy(),
        limited=spec.copy(),
        able_to_scale=np.zeros(n, bool),
        scaling_unbounded=np.ones(n, bool),
        able_at=np.zeros(n, np.float32),
        rate_limited=np.zeros(n, bool),
        up_ceiling=spec.copy(),
        down_floor=spec.copy(),
    )


def cost_blind(inputs) -> "object":
    """Pass the base decision through unrefined: the cost family's
    documented degradation (docs/cost.md — cost-blind, never moved)."""
    from karpenter_tpu.ops import cost as CK

    base = np.asarray(inputs.base_desired, np.int32)
    n = base.shape[0]
    return CK.CostOutputs(
        desired=base.copy(),
        expected_hourly=(
            base.astype(np.float32)
            * np.asarray(inputs.unit_cost, np.float32)
        ),
        violation_risk=np.zeros(n, np.float32),
        headroom=np.zeros(n, np.int32),
        cost_limited=np.zeros(n, bool),
        slo_raised=np.zeros(n, bool),
    )


def forecast_invalid(inputs) -> "object":
    """All-invalid forecasts (n_valid = 0): consumers gate on
    n_valid >= min_samples, so the tick proceeds purely reactive —
    the forecast subsystem's own never-block contract."""
    from karpenter_tpu.forecast.models import ForecastOutputs

    s = int(np.asarray(inputs.values).shape[0])
    return ForecastOutputs(
        point=np.zeros(s, np.float32),
        sigma2=np.zeros(s, np.float32),
        n_valid=np.zeros(s, np.int32),
    )


def fused_hold(inputs) -> "object":
    """The fused family's never-block floor: hold replicas (decide
    floor), all-invalid forecasts, and a cost-blind pass-through of the
    held number — each stage's own documented degradation, composed."""
    from karpenter_tpu.ops import cost as CK
    from karpenter_tpu.ops import fusedtick as FT

    decision = decide_hold(inputs.decision)
    forecast = None
    if inputs.forecast is not None:
        forecast = forecast_invalid(inputs.forecast)
    cost = None
    if inputs.slo_valid is not None:
        held = decision.desired
        n = held.shape[0]
        cost = CK.CostOutputs(
            desired=held.copy(),
            expected_hourly=(
                held.astype(np.float32)
                * np.asarray(inputs.unit_cost, np.float32)
            ),
            violation_risk=np.zeros(n, np.float32),
            headroom=np.zeros(n, np.int32),
            cost_limited=np.zeros(n, bool),
            slo_raised=np.zeros(n, bool),
        )
    return FT.FusedTickOutputs(
        decision=decision, forecast=forecast, cost=cost
    )


# -- concatenation / scatter helpers (module docstring parity contract) ------


def _pad_cols(arr: np.ndarray, width: int, fill) -> np.ndarray:
    """Pad a [N, M] operand's column axis to `width` with `fill` —
    semantics-preserving because every kernel masks these columns by
    their own *_valid operand."""
    arr = np.asarray(arr)
    if arr.shape[1] == width:
        return arr
    pad = np.full((arr.shape[0], width - arr.shape[1]), fill, arr.dtype)
    return np.concatenate([arr, pad], axis=1)


def concat_decision_inputs(
    inputs_list: List[D.DecisionInputs], row_bucket: int = ROW_BUCKET,
) -> D.DecisionInputs:
    """Stack per-tenant fleet matrices along the row axis, padding the
    metric (M) and policy-slot (K) axes to the group maximum with
    masked-invalid columns and the row axis up the compile bucket with
    inert rows. Every tenant must share the `now` epoch (decide math is
    relative to it); decide_all groups by `now` before calling."""
    nows = {float(np.asarray(i.now)) for i in inputs_list}
    if len(nows) != 1:
        raise ValueError(
            f"cannot concatenate decide batches across differing now "
            f"epochs: {sorted(nows)}"
        )
    m = max(int(np.asarray(i.metric_value).shape[1]) for i in inputs_list)
    m = max(m, 1)
    k = max(int(np.asarray(i.up_ptype).shape[1]) for i in inputs_list)
    k = max(k, 1)
    has_forecast = any(i.forecast_value is not None for i in inputs_list)
    total = sum(
        int(np.asarray(i.spec_replicas).shape[0]) for i in inputs_list
    )
    n_pad = D.pad_to(total, row_bucket) - total

    def rows(name: str, width: Optional[int], fill):
        parts = []
        for i in inputs_list:
            arr = getattr(i, name)
            if arr is None:  # optional forecast operand, absent here
                n = int(np.asarray(i.metric_value).shape[0])
                arr = np.full((n, width), fill)
            arr = np.asarray(arr)
            parts.append(
                _pad_cols(arr, width, fill) if width is not None else arr
            )
        out = np.concatenate(parts, axis=0)
        if n_pad:
            pad_shape = (n_pad,) + out.shape[1:]
            out = np.concatenate(
                [out, np.full(pad_shape, fill, out.dtype)], axis=0
            )
        return out

    return D.DecisionInputs(
        metric_value=rows("metric_value", m, np.float32(0)),
        target_value=rows("target_value", m, np.float32(0)),
        target_type=rows("target_type", m, np.int32(D.TYPE_UNKNOWN)),
        metric_valid=rows("metric_valid", m, False),
        spec_replicas=rows("spec_replicas", None, np.int32(0)),
        status_replicas=rows("status_replicas", None, np.int32(0)),
        min_replicas=rows("min_replicas", None, np.int32(0)),
        max_replicas=rows("max_replicas", None, np.int32(0)),
        up_window=rows("up_window", None, np.int32(0)),
        down_window=rows("down_window", None, np.int32(0)),
        up_policy=rows("up_policy", None, np.int32(D.POLICY_MAX)),
        down_policy=rows("down_policy", None, np.int32(D.POLICY_MAX)),
        last_scale_time=rows("last_scale_time", None, np.float32(0)),
        has_last_scale=rows("has_last_scale", None, False),
        now=inputs_list[0].now,
        up_ptype=rows("up_ptype", k, np.int32(D.POLICY_TYPE_COUNT)),
        up_pvalue=rows("up_pvalue", k, np.int32(0)),
        up_pperiod=rows("up_pperiod", k, np.int32(0)),
        up_pvalid=rows("up_pvalid", k, False),
        down_ptype=rows("down_ptype", k, np.int32(D.POLICY_TYPE_COUNT)),
        down_pvalue=rows("down_pvalue", k, np.int32(0)),
        down_pperiod=rows("down_pperiod", k, np.int32(0)),
        down_pvalid=rows("down_pvalid", k, False),
        forecast_value=(
            rows("forecast_value", m, np.float32(0))
            if has_forecast else None
        ),
        forecast_valid=(
            rows("forecast_valid", m, False) if has_forecast else None
        ),
    )


def slice_decision_outputs(
    out: D.DecisionOutputs, start: int, stop: int
) -> D.DecisionOutputs:
    return D.DecisionOutputs(
        **{
            f.name: np.asarray(getattr(out, f.name))[start:stop]
            for f in dataclasses.fields(D.DecisionOutputs)
        }
    )


def concat_cost_inputs(inputs_list, row_bucket: int = ROW_BUCKET):
    """Stack per-tenant CostInputs along the row axis (metric axis
    padded to the group maximum with demand_valid=False columns, rows
    padded up the bucket with slo_valid=False pass-through rows)."""
    from karpenter_tpu.ops import cost as CK

    m = max(int(np.asarray(i.slo_target).shape[1]) for i in inputs_list)
    m = max(m, 1)
    total = sum(
        int(np.asarray(i.base_desired).shape[0]) for i in inputs_list
    )
    n_pad = D.pad_to(total, row_bucket) - total

    def rows(name: str, width: Optional[int], fill):
        parts = [
            _pad_cols(np.asarray(getattr(i, name)), width, fill)
            if width is not None
            else np.asarray(getattr(i, name))
            for i in inputs_list
        ]
        out = np.concatenate(parts, axis=0)
        if n_pad:
            pad_shape = (n_pad,) + out.shape[1:]
            out = np.concatenate(
                [out, np.full(pad_shape, fill, out.dtype)], axis=0
            )
        return out

    return CK.CostInputs(
        base_desired=rows("base_desired", None, np.int32(0)),
        min_replicas=rows("min_replicas", None, np.int32(0)),
        max_replicas=rows("max_replicas", None, np.int32(0)),
        unit_cost=rows("unit_cost", None, np.float32(0)),
        slo_weight=rows("slo_weight", None, np.float32(0)),
        max_hourly_cost=rows("max_hourly_cost", None, np.float32(0)),
        slo_valid=rows("slo_valid", None, False),
        slo_target=rows("slo_target", m, np.float32(1)),
        demand_mu=rows("demand_mu", m, np.float32(0)),
        demand_sigma=rows("demand_sigma", m, np.float32(0)),
        demand_valid=rows("demand_valid", m, False),
    )


def slice_cost_outputs(out, start: int, stop: int):
    from karpenter_tpu.ops import cost as CK

    return CK.CostOutputs(
        **{
            f.name: np.asarray(getattr(out, f.name))[start:stop]
            for f in dataclasses.fields(CK.CostOutputs)
        }
    )


def concat_forecast_inputs(inputs_list):
    """Stack per-tenant ForecastInputs along the series axis (the time
    axis was already padded to a shared bucket by forecast_all). Reuses
    the forecast model's own concat — the same code path the coalescing
    queue runs for same-cluster concurrent forecasts."""
    from karpenter_tpu.forecast import models as FM
    from karpenter_tpu.solver.service import FORECAST_S_FLOOR
    from karpenter_tpu.solver.bucketing import bucket_up

    total = sum(int(np.asarray(i.values).shape[0]) for i in inputs_list)
    return FM.concat_forecast_inputs(
        inputs_list, bucket_up(total, FORECAST_S_FLOOR)
    )


def concat_fused_inputs(
    inputs_list, row_bucket: int = ROW_BUCKET
) -> Tuple[object, Dict[int, dict]]:
    """Stack per-tenant FusedTickInputs: decision matrices along the
    row axis (concat_decision_inputs), forecast series along the series
    axis with per-tenant ROW-OFFSET fixups on the scatter maps, and the
    masked cost operands along the row axis. Returns (stacked, spans):
    spans[row_offset] = {"series": (s0, s1) | None, "cost": bool} — the
    aux geometry slice_fused_outputs needs to scatter the second
    (series) axis back per tenant.

    Trash-row fixup: each tenant's pad series point at its OWN grid's
    trash row (row >= its N); after concatenation that index is a REAL
    row of the next tenant, so those references are remapped to the
    concatenated grid's trash row (the padded row count)."""
    from karpenter_tpu.forecast import models as FM
    from karpenter_tpu.ops import fusedtick as FT
    from karpenter_tpu.solver.bucketing import bucket_up
    from karpenter_tpu.solver.service import (
        FORECAST_S_FLOOR,
        FORECAST_T_FLOOR,
    )

    sizes = [
        int(np.asarray(i.decision.spec_replicas).shape[0])
        for i in inputs_list
    ]
    total = sum(sizes)
    n_total = D.pad_to(total, row_bucket)
    decision = concat_decision_inputs(
        [i.decision for i in inputs_list], row_bucket
    )
    m = int(np.asarray(decision.metric_value).shape[1])

    spans: Dict[int, dict] = {}
    f_parts: List[object] = []
    row_parts, col_parts, need_parts, blend_parts = [], [], [], []
    t_bucket = max(
        [
            bucket_up(
                int(np.asarray(i.forecast.values).shape[1]),
                FORECAST_T_FLOOR,
            )
            for i in inputs_list
            if i.forecast is not None
        ],
        default=0,
    )
    offset = 0
    s_offset = 0
    for inputs, size in zip(inputs_list, sizes):
        span = {"series": None, "cost": inputs.slo_valid is not None}
        if inputs.forecast is not None:
            s = int(np.asarray(inputs.forecast.values).shape[0])
            span["series"] = (s_offset, s_offset + s)
            s_offset += s
            # same left-aligned T padding the tenant's own isolated
            # dispatch would get at the service door (fused_tick_all
            # groups by t_bucket, so this is bit-preserving)
            f_parts.append(
                FM.pad_forecast_inputs(inputs.forecast, t_bucket)
            )
            rows = np.asarray(inputs.series_row, np.int64)
            row_parts.append(
                np.where(rows >= size, n_total, rows + offset).astype(
                    np.int32
                )
            )
            col_parts.append(np.asarray(inputs.series_col, np.int32))
            need_parts.append(np.asarray(inputs.series_need, np.int32))
            blend_parts.append(np.asarray(inputs.series_blend, bool))
        spans[offset] = span
        offset += size

    kwargs: dict = {}
    if f_parts:
        s_pad = bucket_up(s_offset, FORECAST_S_FLOOR)
        extra = s_pad - s_offset
        kwargs["forecast"] = FM.concat_forecast_inputs(f_parts, s_pad)
        # the concat's own pad series route to the shared trash row
        # with an unreachable sample threshold — inert in every stage
        kwargs["series_row"] = np.concatenate(
            row_parts + [np.full(extra, n_total, np.int32)]
        )
        kwargs["series_col"] = np.concatenate(
            col_parts + [np.zeros(extra, np.int32)]
        )
        kwargs["series_need"] = np.concatenate(
            need_parts
            + [np.full(extra, np.iinfo(np.int32).max, np.int32)]
        )
        kwargs["series_blend"] = np.concatenate(
            blend_parts + [np.zeros(extra, bool)]
        )
    if any(i.slo_valid is not None for i in inputs_list):
        kwargs.update(
            _concat_fused_cost(inputs_list, sizes, n_total, m)
        )
    return FT.FusedTickInputs(decision=decision, **kwargs), spans


def _concat_fused_cost(
    inputs_list, sizes: List[int], n_total: int, m: int
) -> dict:
    """Row-axis concat of the fused cost operand group. Tenants without
    an SLO opt-in contribute all-masked rows (slo_valid=False is the
    kernel's pass-through), identical to the absent-group wire; the
    metric axis pads to the decision grid's width with demand-invalid
    columns and the row axis up the bucket with masked rows."""

    def rows(name: str, width, fill, dtype):
        parts = []
        for inputs, size in zip(inputs_list, sizes):
            arr = getattr(inputs, name)
            if arr is None:
                shape = (size,) if width is None else (size, width)
                arr = np.full(shape, fill, dtype)
            else:
                arr = np.asarray(arr, dtype)
                if width is not None:
                    arr = _pad_cols(arr, width, fill)
            parts.append(arr)
        out = np.concatenate(parts, axis=0)
        n_pad = n_total - out.shape[0]
        if n_pad:
            pad_shape = (n_pad,) + out.shape[1:]
            out = np.concatenate(
                [out, np.full(pad_shape, fill, out.dtype)], axis=0
            )
        return out

    return dict(
        ha_min=rows("ha_min", None, np.int32(0), np.int32),
        ha_max=rows("ha_max", None, np.int32(0), np.int32),
        unit_cost=rows("unit_cost", None, np.float32(0), np.float32),
        slo_weight=rows("slo_weight", None, np.float32(0), np.float32),
        max_hourly_cost=rows(
            "max_hourly_cost", None, np.float32(0), np.float32
        ),
        slo_valid=rows("slo_valid", None, False, bool),
        slo_target=rows("slo_target", m, np.float32(1), np.float32),
        observed=rows("observed", m, np.float32(0), np.float32),
        demand_base_valid=rows(
            "demand_base_valid", m, False, bool
        ),
        prior_point=rows("prior_point", m, np.float32(0), np.float32),
        prior_sigma2=rows(
            "prior_sigma2", m, np.float32(0), np.float32
        ),
        prior_valid=rows("prior_valid", m, False, bool),
    )


def slice_fused_outputs(out, start: int, stop: int, span):
    """One tenant's slice of a shared fused dispatch: decision/cost by
    row range, forecast by the tenant's series range (from the concat's
    span record). Stages the tenant never carried come back None —
    byte-identical to its own independent dispatch."""
    from karpenter_tpu.forecast import models as FM
    from karpenter_tpu.ops import fusedtick as FT

    forecast = None
    cost = None
    if span is not None and out.forecast is not None:
        series = span.get("series")
        if series is not None:
            forecast = FM.slice_forecast_outputs(
                out.forecast, series[0], series[1]
            )
    if span is not None and span.get("cost") and out.cost is not None:
        cost = slice_cost_outputs(out.cost, start, stop)
    return FT.FusedTickOutputs(
        decision=slice_decision_outputs(out.decision, start, stop),
        forecast=forecast,
        cost=cost,
    )
