"""TenantRegistry: one control plane, thousands of namespaced clusters.

Every subsystem in this repo was built for ONE cluster per process: one
store, one forecaster history, one cost model, one journal dir, one
gauge label set. The registry is the multiplexing layer that namespaces
that full stack under a tenant id (docs/multitenancy.md):

  * STACK — each tenant gets its own Store, FleetForecaster (history +
    skill EWMAs), CostModel (optionally fed by a per-tenant pricing
    file — cost/pricing.py), CostEngine, and WarmPoolEngine. All of
    them ride the ONE shared SolverService, which is the whole point:
    the expensive resource (device dispatch) is shared, the state is
    not.
  * FENCING — with a journal dir configured, each tenant's crash-safe
    state lives in its own `tenants/<id>/` subdirectory: fence
    generations, journals, and checkpoints are claimed and replayed
    PER TENANT, so one tenant's restart storm (or a stale incarnation
    of it) cannot fence or corrupt another's actuation
    (recovery/fence.py generalized along the tenant axis).
  * METRICS — per-tenant `karpenter_tenant_*` series labeled
    {name=<tenant id>} in the shared registry, RETIRED when the tenant
    is removed (the frozen-series discipline every per-object gauge
    family in this repo follows). The scheduler (tenancy/scheduler.py)
    publishes through the same TenantMetrics face.

Tenant ids are flat strings (cluster names); weights feed the
scheduler's fair-admission policy (tenancy/fairness.py).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

SUBSYSTEM = "tenant"


@dataclass(slots=True)
class TenantSpec:
    """Declarative tenant config (one entry of --tenant-config)."""

    id: str
    # fair-admission weight (tenancy/fairness.py): a tenant's long-run
    # share of the shared dispatch budget is weight / sum(weights)
    weight: float = 1.0
    # PoolGroup coalition id (tenancy/fairness.py): tenants declaring
    # the same id host member pools of one PoolGroup and are admitted
    # into the same batch round, so the joint allocator
    # (ops/poolgroup.py) never sees a partial group; None = ungrouped
    pool_group: Optional[str] = None
    # per-tenant pricing feed (cost/pricing.py): a JSON/YAML catalog
    # file reloaded on mtime change; None = the built-in catalog
    pricing_file: Optional[str] = None
    # per-tenant cost-model knobs (runtime Options analogs)
    cost_default_hourly: float = 1.0
    cost_spot_multiplier: float = 0.35
    # metric-history ring capacity for this tenant's forecaster
    forecast_history: int = 64

    def validate(self) -> None:
        if not self.id or "/" in self.id or self.id in (".", ".."):
            raise ValueError(
                f"tenant id must be a non-empty path-safe string, "
                f"got {self.id!r}"
            )
        if self.weight <= 0:
            raise ValueError(
                f"tenant {self.id}: weight must be > 0, got {self.weight}"
            )
        if self.forecast_history < 2:
            raise ValueError(
                f"tenant {self.id}: forecastHistory must be >= 2, got "
                f"{self.forecast_history}"
            )
        if self.pool_group is not None and not self.pool_group:
            raise ValueError(
                f"tenant {self.id}: poolGroup must be a non-empty id "
                f"or omitted"
            )


def load_tenant_config(path: str) -> List[TenantSpec]:
    """Parse --tenant-config: a JSON/YAML file holding either a bare
    list of tenant specs or {"tenants": [...]}. Ids must be unique."""
    from karpenter_tpu.api.serialization import from_dict
    from karpenter_tpu.utils.configfile import load_json_or_yaml

    doc = load_json_or_yaml(path)
    if isinstance(doc, dict):
        doc = doc.get("tenants", doc)
    if not isinstance(doc, list):
        raise ValueError(
            f"--tenant-config {path}: expected a LIST of tenant specs "
            f"(or {{'tenants': [...]}}), got {type(doc).__name__}"
        )
    specs = [from_dict(TenantSpec, entry) for entry in doc]
    seen = set()
    for spec in specs:
        spec.validate()
        if spec.id in seen:
            raise ValueError(
                f"--tenant-config {path}: duplicate tenant id {spec.id!r}"
            )
        seen.add(spec.id)
    return specs


class TenantMetrics:
    """The karpenter_tenant_* surface (module docstring): shared by the
    registry (membership) and the scheduler (traffic), so retirement on
    tenant deletion covers every family from one place."""

    def __init__(self, registry=None):
        self._per_tenant = []
        if registry is None:
            self.active = self.rounds = None
            self.weight = self.degraded = self.backlog = None
            self.decisions = self.dispatches = None
            self.mirror = self.fallback = None
            self.trips = self.deferrals = None
            return
        reg = registry.register
        # fleet-level
        self.active = reg(SUBSYSTEM, "active")
        self.rounds = reg(SUBSYSTEM, "admission_rounds")
        self.dispatches = reg(SUBSYSTEM, "dispatches_total", kind="counter")
        # per-tenant (name=<tenant id>, namespace="-"): retired on
        # tenant deletion via retire()
        self.weight = reg(SUBSYSTEM, "weight")
        self.degraded = reg(SUBSYSTEM, "degraded")
        self.backlog = reg(SUBSYSTEM, "backlog_rows")
        self.decisions = reg(SUBSYSTEM, "decisions_total", kind="counter")
        self.mirror = reg(
            SUBSYSTEM, "mirror_served_total", kind="counter"
        )
        # fallback ≠ mirror: a mirror serve is bit-identical device
        # math on host; a fallback serve is the synthesized never-block
        # floor (hold / cost-blind / invalid forecast) — dashboards
        # must be able to tell real answers from do-nothing ones
        self.fallback = reg(
            SUBSYSTEM, "fallback_served_total", kind="counter"
        )
        self.trips = reg(SUBSYSTEM, "breaker_trips_total", kind="counter")
        self.deferrals = reg(SUBSYSTEM, "deferrals_total", kind="counter")
        self._per_tenant = [
            self.weight, self.degraded, self.backlog, self.decisions,
            self.mirror, self.fallback, self.trips, self.deferrals,
        ]

    @property
    def enabled(self) -> bool:
        return self.active is not None

    def retire(self, tenant: str) -> None:
        """Drop every per-tenant series for a deleted tenant — a frozen
        karpenter_tenant_* value for a cluster that no longer exists
        would mislead dashboards exactly like the karpenter_cost_*
        frozen-series bug did (docs/cost.md)."""
        for vec in self._per_tenant:
            vec.remove(tenant, "-")


@dataclass
class TenantContext:
    """One tenant's namespaced stack (module docstring). Fields are
    built by TenantRegistry; engines share the process SolverService."""

    spec: TenantSpec
    store: object = None
    forecaster: object = None
    cost_model: object = None
    cost_engine: object = None
    warmpool: object = None
    journal_dir: Optional[str] = None
    _recovery: object = field(default=None, repr=False)

    @property
    def id(self) -> str:
        return self.spec.id

    def recovery(self):
        """The tenant's own RecoveryManager, built lazily over its
        namespaced journal dir (None without one): per-tenant fence
        generations and crash-safe journals, independent of every
        other tenant's (module docstring FENCING)."""
        if self._recovery is None and self.journal_dir:
            from karpenter_tpu.recovery import RecoveryManager

            self._recovery = RecoveryManager(self.journal_dir)
        return self._recovery

    def close(self) -> None:
        if self._recovery is not None:
            self._recovery.close()
            self._recovery = None


class TenantRegistry:
    """Tenant membership + per-tenant stack construction (module
    docstring). `service` is the shared SolverService every tenant's
    engines dispatch through; `registry` the shared GaugeRegistry;
    `journal_dir` the root under which per-tenant fencing/journal
    subdirectories are created."""

    def __init__(
        self,
        service=None,
        registry=None,
        journal_dir: Optional[str] = None,
        clock: Optional[Callable[[], float]] = None,
        specs: Optional[List[TenantSpec]] = None,
    ):
        import time as _time

        self.service = service
        self.metrics_registry = registry
        self.journal_dir = journal_dir
        self.clock = clock or _time.time
        self.metrics = TenantMetrics(registry)
        self._tenants: Dict[str, TenantContext] = {}
        self._lock = threading.Lock()
        # deletion listeners (the scheduler registers one so breakers,
        # admission credit, and its own stats forget the tenant too)
        self._on_removed: List[Callable[[str], None]] = []
        for spec in specs or []:
            self.add(spec)

    def on_removed(self, hook: Callable[[str], None]) -> None:
        self._on_removed.append(hook)

    def tenants(self) -> List[str]:
        with self._lock:
            return sorted(self._tenants)

    def __contains__(self, tenant: str) -> bool:
        with self._lock:
            return tenant in self._tenants

    def __len__(self) -> int:
        with self._lock:
            return len(self._tenants)

    def weight(self, tenant: str) -> float:
        with self._lock:
            ctx = self._tenants.get(tenant)
        return ctx.spec.weight if ctx is not None else 1.0

    def weights(self) -> Dict[str, float]:
        with self._lock:
            return {t: c.spec.weight for t, c in self._tenants.items()}

    def pool_groups(self) -> Dict[str, str]:
        """Tenant -> PoolGroup coalition id, grouped tenants only: the
        admission policy coalesces these into indivisible rounds
        (tenancy/fairness.py module docstring)."""
        with self._lock:
            return {
                t: c.spec.pool_group
                for t, c in self._tenants.items()
                if c.spec.pool_group
            }

    def journal_dir_for(self, tenant: str) -> Optional[str]:
        """`<journal_dir>/tenants/<id>`, created on first ask — the
        per-tenant fencing namespace (module docstring)."""
        if not self.journal_dir:
            return None
        path = os.path.join(self.journal_dir, "tenants", tenant)
        os.makedirs(path, exist_ok=True)
        return path

    def get(self, tenant: str) -> Optional[TenantContext]:
        with self._lock:
            return self._tenants.get(tenant)

    def get_or_create(self, tenant: str) -> TenantContext:
        ctx = self.get(tenant)
        if ctx is not None:
            return ctx
        return self.add(TenantSpec(id=tenant))

    def add(self, spec: TenantSpec) -> TenantContext:
        """Build and register one tenant's stack. Idempotent on id (the
        existing context wins — live state must not be silently
        rebuilt); publishes the membership gauges."""
        spec.validate()
        with self._lock:
            existing = self._tenants.get(spec.id)
            if existing is not None:
                return existing
        ctx = self._build(spec)
        discarded = None
        with self._lock:
            # re-check under the lock: two concurrent get_or_create
            # calls may both have built — the FIRST registration wins
            # (live state must never be silently replaced) and the
            # loser's freshly built, never-used stack is discarded
            existing = self._tenants.get(spec.id)
            if existing is not None:
                discarded, ctx = ctx, existing
            else:
                self._tenants[spec.id] = ctx
            n = len(self._tenants)
        if discarded is not None:
            discarded.close()
            return ctx
        if self.metrics.enabled:
            self.metrics.active.set("-", "-", float(n))
            self.metrics.weight.set(spec.id, "-", float(spec.weight))
            self.metrics.degraded.set(spec.id, "-", 0.0)
        return ctx

    def remove(self, tenant: str) -> None:
        """Delete a tenant: close its stack, retire every per-tenant
        gauge series, and notify listeners (scheduler breakers and
        admission credit forget it too)."""
        with self._lock:
            ctx = self._tenants.pop(tenant, None)
            n = len(self._tenants)
        if ctx is None:
            return
        ctx.close()
        if self.metrics.enabled:
            self.metrics.active.set("-", "-", float(n))
            self.metrics.retire(tenant)
        for hook in self._on_removed:
            hook(tenant)

    def close(self) -> None:
        for tenant in self.tenants():
            self.remove(tenant)

    # -- stack construction ------------------------------------------------

    def _build(self, spec: TenantSpec) -> TenantContext:
        from karpenter_tpu.cost import CostEngine, CostModel, WarmPoolEngine
        from karpenter_tpu.cost.pricing import pricing_source_for
        from karpenter_tpu.forecast import FleetForecaster
        from karpenter_tpu.store import Store

        store = Store()
        forecast_fn = (
            self.service.forecast if self.service is not None else None
        )
        cost_fn = self.service.cost if self.service is not None else None
        forecaster = FleetForecaster(
            forecast_fn=forecast_fn,
            clock=self.clock,
            capacity=spec.forecast_history,
        )
        cost_model = CostModel(
            default_hourly=spec.cost_default_hourly,
            spot_multiplier=spec.cost_spot_multiplier,
            pricing=pricing_source_for(spec.pricing_file),
        )
        cost_engine = CostEngine(
            store=store,
            cost_fn=cost_fn,
            model=cost_model,
            forecaster=forecaster,
        )
        warmpool = WarmPoolEngine(headroom_source=cost_engine.headroom)
        return TenantContext(
            spec=spec,
            store=store,
            forecaster=forecaster,
            cost_model=cost_model,
            cost_engine=cost_engine,
            warmpool=warmpool,
            journal_dir=self.journal_dir_for(spec.id),
        )
