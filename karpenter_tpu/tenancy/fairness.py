"""Weighted fair admission for the multi-tenant scheduler.

One solver service fronting a thousand tenant clusters has a classic
serving problem: a single noisy tenant — a cluster mid-incident
submitting 100x everyone else's rows — must not starve the shared
dispatch pipeline. The admission policy here is DEFICIT-WEIGHTED ROUND
ROBIN over tenants: each admission round carries a row budget, every
tenant accrues credit proportional to its configured weight, and the
round admits tenants (whole — a tenant's per-tick matrix is indivisible)
in credit order until the budget is spent. Credit is SPENT on admission
and CARRIES OVER when a tenant is deferred, so a deferred tenant's claim
on the next round grows instead of resetting — over consecutive rounds
every tenant's admitted-row share converges to its weight share, the
deficit-round-robin guarantee.

Two deliberate floors keep the policy safe at the edges:

  * every round admits AT LEAST one tenant, even when that tenant's
    matrix alone exceeds the budget — an oversized tenant is admitted
    ALONE (its rows become their own dispatch) rather than deadlocking;
  * a tenant's credit is capped at a few rounds' worth of its share, so
    an idle tenant cannot bank unbounded credit and then monopolize the
    pipeline when it returns.

PoolGroups add one constraint on top: tenants hosting member pools of
the same group (TenantSpec.poolGroup) must land in the SAME round — the
joint allocator (ops/poolgroup.py) scores a group's pools against each
other, so splitting its members across rounds would hand it a partial
view. Grouped tenants are admitted as one INDIVISIBLE COALITION:
combined demand, combined credit, admitted or deferred together. The
oversized-tenant floor applies to the coalition as a whole, and
ungrouped tenants are scheduled exactly as before (a singleton is a
coalition of one — same credit math, same order, same rounds).

The policy is host-side bookkeeping only (a dict of floats); the row
budget bounds each concatenated device program's leading axis, which is
what actually bounds a dispatch's latency and memory.
"""

from __future__ import annotations

from typing import Dict, List, Optional

# credit cap, in multiples of a tenant's per-round fair share: enough to
# absorb a couple of deferred rounds, small enough that a returning idle
# tenant cannot monopolize the pipeline
_CREDIT_CAP_ROUNDS = 4.0


def effective_weight(weights: Dict[str, float], tenant: str) -> float:
    """One tenant's normalized admission weight: missing defaults to
    1.0, zero/negative clamp to 1.0 (a weightless tenant must neither
    divide by zero nor be starved outright). THE single definition —
    round admission and the deadline budgets (scheduler._deadline_
    budgets) must normalize identically, or a zero-weight tenant would
    get one effective weight for rows and another for its wait bound."""
    return max(float(weights.get(tenant, 1.0)), 0.0) or 1.0


class WeightedAdmission:
    """Deficit-weighted round-robin admission (module docstring).

    `budget_rows` bounds the total rows admitted per round; weights come
    from the caller (TenantRegistry.weight in production). Stateful:
    deficits persist across rounds so deferral debts are honored."""

    def __init__(self, budget_rows: int = 4096):
        if budget_rows < 1:
            raise ValueError(f"budget_rows must be >= 1, got {budget_rows}")
        self.budget_rows = budget_rows
        self._credit: Dict[str, float] = {}

    def forget(self, tenant: str) -> None:
        """Drop a deleted tenant's carried credit."""
        self._credit.pop(tenant, None)

    def rounds(
        self,
        demand: Dict[str, int],
        weights: Dict[str, float],
        groups: Optional[Dict[str, str]] = None,
    ) -> List[List[str]]:
        """Partition tenants with pending rows into admission rounds.

        Returns the full schedule for this batch (every tenant appears
        exactly once): round k+1's tenants were deferred behind round
        k's by the weighted deficit. Tenants whose demand fits one
        budget together ride one round — the common small-fleet case
        collapses to a single concatenated dispatch.

        `groups` maps tenant id -> pool-group id: tenants sharing an id
        are admitted as one indivisible coalition (module docstring) so
        the joint allocator always sees a whole group in one round."""
        pending = {t: int(n) for t, n in demand.items() if n > 0}
        units = _coalitions(pending, groups)
        schedule: List[List[str]] = []
        while pending:
            admitted = self._admit_round(pending, weights, units)
            schedule.append(admitted)
            for tenant in admitted:
                del pending[tenant]
            units = [u for u in units if u[0] not in admitted]
        return schedule

    def _admit_round(
        self,
        pending: Dict[str, int],
        weights: Dict[str, float],
        units: List[List[str]],
    ) -> List[str]:
        total_weight = sum(
            effective_weight(weights, t) for t in pending
        )
        for tenant in pending:
            weight = effective_weight(weights, tenant)
            share = self.budget_rows * weight / total_weight
            credit = self._credit.get(tenant, 0.0) + share
            self._credit[tenant] = min(credit, _CREDIT_CAP_ROUNDS * share)
        # highest accrued credit first (a coalition's is its members'
        # combined, matching its combined row demand); the first member
        # id breaks ties so the schedule is deterministic under equal
        # weights — for singletons this is exactly the old ordering
        order = sorted(
            units,
            key=lambda u: (
                -sum(self._credit.get(t, 0.0) for t in u),
                u[0],
            ),
        )
        admitted: List[str] = []
        spent = 0
        for unit in order:
            rows = sum(pending[t] for t in unit)
            if admitted and spent + rows > self.budget_rows:
                continue  # deferred whole: credit carries to next round
            admitted.extend(unit)
            spent += rows
            # admission spends the credit (floored at 0 so an oversized
            # tenant admitted alone doesn't go unboundedly negative and
            # starve ITSELF forever)
            for tenant in unit:
                self._credit[tenant] = max(
                    0.0, self._credit.get(tenant, 0.0) - pending[tenant]
                )
        return admitted


def _coalitions(
    pending: Dict[str, int], groups: Optional[Dict[str, str]]
) -> List[List[str]]:
    """Pending tenants as indivisible admission units: tenants sharing
    a pool-group id ride together, everyone else is a singleton.
    Members are sorted so a coalition's identity (and the tie-break on
    its first member) is deterministic regardless of dict order."""
    if not groups:
        return [[t] for t in sorted(pending)]
    by_group: Dict[str, List[str]] = {}
    units: List[List[str]] = []
    for tenant in sorted(pending):
        gid = groups.get(tenant)
        if gid:
            by_group.setdefault(gid, []).append(tenant)
        else:
            units.append([tenant])
    units.extend(by_group.values())
    units.sort(key=lambda u: u[0])
    return units
