"""Per-tenant failure isolation: breakers generalizing the fault ladder.

The solver's backend-health FSM (solver/service.py) answers "is the
DEVICE sick" — one verdict for the whole process. With a thousand
tenants behind one service that is the wrong granularity: one tenant's
poisoned operands (a corrupt snapshot, a fault-injected feed) must not
degrade the other 999. The TenantBreakerBoard here is the per-tenant
generalization of the fault registry's per-object circuit breakers
(resilience.py): K consecutive per-tenant failures OPEN that tenant's
breaker — its rows stop entering the shared concatenated dispatch and
serve from the family's bit-identical numpy mirror instead — while
healthy tenants keep riding the device batch. An open breaker admits
one PROBE attempt per reset window; a probe success closes it.

This is the isolation half of docs/multitenancy.md's contract; the
fencing half (per-tenant journal dirs and actuation generations) lives
in registry.py — a tenant's crash-recovery state is namespaced the same
way its dispatch health is.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import Callable, Dict

CLOSED = "closed"
OPEN = "open"
# gate() verdicts: a PROBING tenant is dispatched in ISOLATION — its
# probe must never re-enter the shared batch, or the exact failure that
# opened the breaker would re-break every healthy tenant's round once
# per reset window
PROBE = "probe"


@dataclass
class _BreakerState:
    consecutive_failures: int = 0
    state: str = CLOSED
    next_probe: float = 0.0
    trips: int = 0


@dataclass
class TenantBreakerBoard:
    """One breaker per tenant id (module docstring).

    `threshold` consecutive failures open a tenant's breaker;
    `reset_s` is the open window before a probe attempt is admitted."""

    threshold: int = 3
    reset_s: float = 30.0
    clock: Callable[[], float] = _time.monotonic
    _tenants: Dict[str, _BreakerState] = field(default_factory=dict)

    def _state(self, tenant: str) -> _BreakerState:
        state = self._tenants.get(tenant)
        if state is None:
            state = self._tenants[tenant] = _BreakerState()
        return state

    def gate(self, tenant: str) -> str:
        """This tenant's admission verdict for one round: CLOSED (full
        shared-batch member), PROBE (breaker open but the probe window
        elapsed — ONE isolated recovery attempt; the next probe is
        scheduled immediately so consecutive rounds don't all probe),
        or OPEN (serve from the mirror, no attempt)."""
        state = self._state(tenant)
        if state.state == CLOSED:
            return CLOSED
        now = self.clock()
        if now >= state.next_probe:
            state.next_probe = now + self.reset_s
            return PROBE
        return OPEN

    def allow(self, tenant: str) -> bool:
        """Convenience over gate(): may this tenant attempt ANY device
        work this round (shared membership or an isolated probe)?"""
        return self.gate(tenant) != OPEN

    def record_failure(self, tenant: str) -> bool:
        """Count one per-tenant failure; returns True when this failure
        TRIPPED the breaker (closed -> open)."""
        state = self._state(tenant)
        state.consecutive_failures += 1
        if (
            state.state == CLOSED
            and state.consecutive_failures >= self.threshold
        ):
            state.state = OPEN
            state.next_probe = self.clock() + self.reset_s
            state.trips += 1
            return True
        return False

    def record_success(self, tenant: str) -> bool:
        """Reset the failure run; returns True when this success CLOSED
        an open breaker (a probe recovered the tenant)."""
        state = self._state(tenant)
        state.consecutive_failures = 0
        recovered = state.state == OPEN
        state.state = CLOSED
        return recovered

    def is_open(self, tenant: str) -> bool:
        state = self._tenants.get(tenant)
        return state is not None and state.state == OPEN

    def trips(self, tenant: str) -> int:
        state = self._tenants.get(tenant)
        return 0 if state is None else state.trips

    def forget(self, tenant: str) -> None:
        """Drop a deleted tenant's breaker state."""
        self._tenants.pop(tenant, None)
