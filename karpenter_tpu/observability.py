"""Observability: /metrics + /healthz HTTP endpoints and solver tracing.

reference: the manager serves controller metrics on :8080
(cmd/controller/main.go:52,61) scraped by a dedicated Prometheus via a 5s
ServiceMonitor (config/prometheus/monitor.yaml:10-14); health/readiness come
from the manager. The reference has NO tracing/profiling (OTel is future
work, docs/designs/DESIGN.md) — the solver trace hooks here are an addition
the TPU build needs: device-side timelines via the JAX profiler (xprof), so
a 200 ms budget regression is attributable to feed vs compile vs compute.
"""

from __future__ import annotations

import contextlib
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import urlsplit

from karpenter_tpu.metrics.registry import GaugeRegistry


class MetricsServer:
    """Serves the gauge registry in Prometheus text exposition format.

    port=0 binds an ephemeral port (tests); `port` attribute holds the bound
    port after start().
    """

    def __init__(self, registry: GaugeRegistry, port: int = 8080,
                 host: str = "0.0.0.0"):
        self.registry = registry
        self.host = host
        self.port = port
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        registry = self.registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                path = urlsplit(self.path).path.rstrip("/")
                if path in ("", "/healthz", "/readyz"):
                    body = b"ok"
                    content_type = "text/plain"
                elif path == "/metrics":
                    body = registry.expose_text().encode()
                    content_type = "text/plain; version=0.0.4"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # quiet: scrapes every 5s
                pass

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None


@contextlib.contextmanager
def solver_trace(name: str):
    """Annotate a host span so it shows up on the device timeline. No-op
    when the profiler is unavailable. Only annotation SETUP is guarded —
    exceptions from the traced block itself must propagate unchanged."""
    annotation = None
    try:
        import jax.profiler

        annotation = jax.profiler.TraceAnnotation(name)
        annotation.__enter__()
    except Exception:  # noqa: BLE001 — tracing must never break the solve
        annotation = None
    try:
        yield
    finally:
        if annotation is not None:
            try:
                annotation.__exit__(None, None, None)
            except Exception:  # noqa: BLE001
                pass


def start_profiler_server(port: int = 9999) -> bool:
    """Expose the JAX profiler so xprof/tensorboard can attach and capture
    device traces of the solver. Returns False if unavailable."""
    try:
        import jax.profiler

        jax.profiler.start_server(port)
        return True
    except Exception:  # noqa: BLE001
        return False
