"""Sharding-API compat surface — honest about the pinned JAX.

Every sharding import the repo takes rides through here, so exactly one
module knows which JAX era we target (`pyproject.toml` pins
``jax>=0.4.30``) and what that era actually provides:

  * ``PartitionSpec`` / ``Mesh`` / ``NamedSharding`` — stable under
    ``jax.sharding`` since 0.4.x. There is NO fallback rung reaching
    back to ``from jax.interpreters.sharded_jit import PartitionSpec``:
    that module was deleted from JAX years before the pin (it predates
    pjit/GSPMD), the import is unreachable on every version the
    dependency spec admits, and carrying it as a dead ``except
    ImportError`` arm would only misrepresent what this repo supports.
  * ``shard_map`` — promoted to ``jax.shard_map`` in newer releases;
    the pinned floor still spells it ``jax.experimental.shard_map``.
    Both are the SAME implementation, so the ladder here is a rename
    shim, not a behavior fork.
  * ``pjit`` — retained for callers that want explicit in/out shardings
    on a mesh program; on the pinned JAX ``jax.jit`` + ``NamedSharding``
    inputs is the equivalent (and preferred) spelling, which is what
    `mesh.py`/`solver/service.py` use. ``pjit`` is exported so embedders
    following the SNIPPETS idiom find it in one place.

If a future JAX bump breaks an import below, fix it HERE (and only
here) — do not grow per-module try/except ladders.
"""

from __future__ import annotations

from jax.sharding import Mesh, NamedSharding, PartitionSpec

try:  # jax >= 0.6 spelling
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # the pinned 0.4.x floor
    from jax.experimental.shard_map import shard_map

try:
    from jax.experimental.pjit import pjit
except ImportError:  # pjit folded into jax.jit
    from jax import jit as pjit

__all__ = [
    "Mesh",
    "NamedSharding",
    "PartitionSpec",
    "pjit",
    "shard_map",
]
