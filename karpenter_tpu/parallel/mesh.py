"""Multi-chip scale-out of the solver: mesh, shardings, fleet step.

The reference is a SINGLETON, leader-elected control plane (reference:
cmd/controller/main.go:58-59) whose design doc concedes the pending-pods
analysis "requires global analysis ... breaks down as the cluster scales"
(docs/designs/DESIGN.md "Pending Pods") and leaves sharding as future work
(pkg/controllers/horizontalautoscaler/v1alpha1/controller.go:45-46). The TPU
build answers that axis here: the pods×groups constraint matrix is sharded
over a 2D `jax.sharding.Mesh`:

- axis "pods"   — rows: pending pods / autoscaler fleet (the DP/SP analog;
  each chip owns a slab of pods and a slab of the autoscaler fleet)
- axis "groups" — columns: node groups / instance types (the TP analog;
  each chip owns a slab of the type universe)

Nothing below hand-schedules a collective. We annotate input shardings with
`NamedSharding` and let GSPMD partition the jitted solver: the feasibility
bitset matmuls become local [P/p, K] @ [K, T/g] blocks, the per-group
histogram reduction over pods becomes a psum over the "pods" axis, and the
shelf-BFD scan runs fully parallel across the "groups" shards. Collectives
ride ICI within a slice; cross-slice deployments put the "pods" axis on DCN
(pod slabs are independent until the histogram reduction, one all-reduce per
tick).

Divisibility: GSPMD requires dimension sizes divisible by their mesh axis;
`pad_*_for_mesh` grow the padded buckets (invalid rows/columns are masked,
never truncated — same policy as producers/pendingcapacity.py).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_tpu.parallel.compat import Mesh, NamedSharding
from karpenter_tpu.parallel.compat import PartitionSpec as P

from karpenter_tpu.ops.binpack import BinPackInputs, BinPackOutputs, binpack
from karpenter_tpu.ops.decision import (
    DecisionInputs,
    DecisionOutputs,
    decide,
)
from karpenter_tpu.utils.functional import pad_to_multiple as _pad_dim

AXIS_PODS = "pods"
AXIS_GROUPS = "groups"
AXIS_SLICE = "slice"  # cross-slice (DCN) axis in multi-host deployments


def factorize(n: int) -> Tuple[int, int]:
    """Split n devices into (pods, groups) mesh extents, pods-major.

    Rows (pods) dominate the problem size (100k pods vs 300 types at the
    bench scale), so the pods axis gets the larger factor.
    """
    best = (n, 1)
    for g in range(1, int(np.sqrt(n)) + 1):
        if n % g == 0:
            best = (n // g, g)
    return best


def build_mesh(
    n_devices: Optional[int] = None,
    devices: Optional[Sequence] = None,
    slices: int = 1,
    shape: Optional[Tuple[int, int]] = None,
) -> Mesh:
    """2D pods×groups mesh, or 3D slice×pods×groups when slices > 1.

    The slice axis models multi-host scale-out across TPU slices: pod
    rows shard over (slice, pods) — the per-tick histogram reduction is
    the ONE collective that crosses slices, so it rides DCN exactly once
    per solve, while the groups axis (feasibility matmul partners) stays
    inside a slice on ICI. On a single slice, pass slices=1 (default;
    identical to the 2D mesh). jax.distributed deployments hand the
    flattened global device list here; virtual CPU devices stand in for
    tests and the driver dryrun.

    `shape` overrides the pods-major factorization with explicit
    (pods, groups) extents — the SolverService mesh-shape knob for
    operators whose problem aspect ratio disagrees with the default
    split. Mutually exclusive with slices > 1.
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    n = len(devices)
    if shape is not None:
        if slices > 1:
            raise ValueError("shape= and slices>1 are mutually exclusive")
        pods, groups = shape
        if pods * groups > n:
            raise ValueError(
                f"mesh shape {shape} needs {pods * groups} devices, "
                f"have {n}"
            )
        dev_array = np.array(devices[: pods * groups]).reshape(pods, groups)
        return Mesh(dev_array, (AXIS_PODS, AXIS_GROUPS))
    if slices > 1:
        if n % slices:
            raise ValueError(f"{n} devices not divisible into {slices} slices")
        pods, groups = factorize(n // slices)
        dev_array = np.array(devices).reshape(slices, pods, groups)
        return Mesh(dev_array, (AXIS_SLICE, AXIS_PODS, AXIS_GROUPS))
    pods, groups = factorize(n)
    dev_array = np.array(devices).reshape(pods, groups)
    return Mesh(dev_array, (AXIS_PODS, AXIS_GROUPS))


def _row_axes(mesh: Mesh):
    """The mesh axes the row (pods / fleet) dimension shards over."""
    return (
        (AXIS_SLICE, AXIS_PODS)
        if AXIS_SLICE in mesh.shape
        else AXIS_PODS
    )


def mesh_extents(mesh: Mesh) -> Tuple[int, int]:
    """(row extent, group extent): the divisibility the pod and group
    axes must satisfy on this mesh — rows fold the slice axis in on a
    3D multi-host mesh. This pair is what the SolverService folds into
    its compile-cache key (the padded shape is a deterministic function
    of bucket shape × extents)."""
    return (
        mesh.shape[AXIS_PODS] * mesh.shape.get(AXIS_SLICE, 1),
        mesh.shape[AXIS_GROUPS],
    )


# ---------------------------------------------------------------------------
# Sharding specs
# ---------------------------------------------------------------------------


def binpack_shardings(  # lint: allow-complexity — one sharding rule per operand, optional operands included
    mesh: Mesh,
    with_weight: bool = False,
    with_forbidden: bool = False,
    with_score: bool = False,
    with_exclusive: bool = False,
    with_priority: bool = False,
    with_tier: bool = False,
    with_claim: bool = False,
    with_reservation: bool = False,
    with_pack_class: bool = False,
    with_spread_slot: bool = False,
    with_domain: bool = False,
    with_spread_cap: bool = False,
    batch: bool = False,
) -> BinPackInputs:
    """A BinPackInputs-shaped pytree of NamedShardings.

    Pod-major arrays shard their leading dim over "pods"; group-major arrays
    over "groups". Constraint-universe axes (R, K, L) are small and
    replicated. pod_weight (present only for deduplicated inputs) rides the
    pods axis like every other row-major array; pod_group_forbidden and
    pod_group_score are the 2D pods x groups arrays and shard over BOTH
    mesh axes — the same tiling the feasibility matrix they mask/steer
    gets from GSPMD. pod_priority rides the pods axis, group_tier the
    groups axis (the PR 6 steering operands).

    batch=True prepends a REPLICATED leading axis to every spec: the
    shardings for a SolverService-coalesced stack [B, ...] — each device
    holds every batch item's slab of its pod/group shard, so the
    lax.map/vmap batched programs partition exactly like the single-item
    program.
    """
    lead = (None,) if batch else ()
    s = lambda *spec: NamedSharding(mesh, P(*lead, *spec))
    rows = _row_axes(mesh)  # (slice, pods) on a 3D multi-host mesh
    return BinPackInputs(
        pod_requests=s(rows, None),
        pod_valid=s(rows),
        pod_intolerant=s(rows, None),
        pod_required=s(rows, None),
        group_allocatable=s(AXIS_GROUPS, None),
        group_taints=s(AXIS_GROUPS, None),
        group_labels=s(AXIS_GROUPS, None),
        pod_weight=s(rows) if with_weight else None,
        pod_group_forbidden=s(rows, AXIS_GROUPS) if with_forbidden else None,
        pod_group_score=s(rows, AXIS_GROUPS) if with_score else None,
        pod_exclusive=s(rows) if with_exclusive else None,
        pod_priority=s(rows) if with_priority else None,
        group_tier=s(AXIS_GROUPS) if with_tier else None,
        # constraint-plane operands (PR 6 pattern): pod-side vectors ride
        # the rows axis, group-side vectors the groups axis; the pack-
        # class one-hot's C axis and the [S, D] cap table are constraint-
        # universe-sized and replicate. The spread rank is an integer
        # cumsum over the pods axis — exact under any GSPMD collective
        # decomposition, so sharded == single-device stays bitwise.
        pod_claim=s(rows) if with_claim else None,
        group_reservation=s(AXIS_GROUPS) if with_reservation else None,
        pod_pack_class=s(rows, None) if with_pack_class else None,
        pod_spread_slot=s(rows) if with_spread_slot else None,
        group_domain=s(AXIS_GROUPS) if with_domain else None,
        spread_cap=s(None, None) if with_spread_cap else None,
    )


def stacked_binpack_shardings(
    mesh: Mesh, presence: Tuple[bool, ...]
) -> BinPackInputs:
    """binpack_shardings for a coalesced batch stack, keyed by the
    solver service's operand-presence tuple (solver/bucketing.presence:
    weight, forbidden, score, exclusive, priority, tier, claim,
    reservation, pack_class, spread_slot, domain, spread_cap)."""
    w, f, sc, e, pr, ti, cl, rv, pcls, ss, dom, cap = presence
    return binpack_shardings(
        mesh,
        with_weight=w,
        with_forbidden=f,
        with_score=sc,
        with_exclusive=e,
        with_priority=pr,
        with_tier=ti,
        with_claim=cl,
        with_reservation=rv,
        with_pack_class=pcls,
        with_spread_slot=ss,
        with_domain=dom,
        with_spread_cap=cap,
        batch=True,
    )


def forecast_shardings(mesh: Mesh):
    """ForecastInputs-shaped pytree of NamedShardings: the SERIES axis
    S rides the mesh rows (every series' recurrence is independent —
    the scans run over the replicated T axis per series, so the sharded
    program is bitwise equal to the single-device one; the forecast
    parity contract carries over unchanged)."""
    from karpenter_tpu.forecast.models import ForecastInputs

    s = lambda *spec: NamedSharding(mesh, P(*spec))
    rows = _row_axes(mesh)
    mat = s(rows, None)
    vec = s(rows)
    return ForecastInputs(
        values=mat, valid=mat, times=mat, weights=mat,
        horizon=vec, step_s=vec, model=vec, season=vec,
        alpha=vec, beta=vec, gamma=vec,
    )


def preempt_shardings(mesh: Mesh):
    """PreemptInputs-shaped pytree of NamedShardings: the CANDIDATE
    axis C — the data-parallel one (ops/preempt.py plans candidates
    independently) — rides the mesh rows; nodes and victims are
    replicated so the within-node victim prefix scans stay local. The
    only cross-candidate aggregate (`unplaceable`, an integer sum)
    reduces exactly, so sharded == single-device == numpy bitwise."""
    from karpenter_tpu.ops.preempt import PreemptInputs

    s = lambda *spec: NamedSharding(mesh, P(*spec))
    rows = _row_axes(mesh)
    cand = s(rows)
    cand2 = s(rows, None)
    rep = s(None)
    rep2 = s(None, None)
    return PreemptInputs(
        pod_requests=cand2,
        pod_priority=cand,
        pod_valid=cand,
        pod_node_forbidden=cand2,
        node_free=rep2,
        node_tier=rep,
        victim_requests=rep2,
        victim_priority=rep,
        victim_node=rep,
        victim_valid=rep,
        victim_evictable=rep,
    )


def decision_shardings(mesh: Mesh) -> DecisionInputs:
    """DecisionInputs-shaped pytree of NamedShardings: the autoscaler fleet
    axis N rides the "pods" mesh axis (the fleet is row-parallel; M metric
    columns are small and replicated)."""
    s = lambda *spec: NamedSharding(mesh, P(*spec))
    rows = _row_axes(mesh)
    row = s(rows)
    mat = s(rows, None)
    return DecisionInputs(
        metric_value=mat,
        target_value=mat,
        target_type=mat,
        metric_valid=mat,
        spec_replicas=row,
        status_replicas=row,
        min_replicas=row,
        max_replicas=row,
        up_window=row,
        down_window=row,
        up_policy=row,
        down_policy=row,
        last_scale_time=row,
        has_last_scale=row,
        now=s(),
        up_ptype=mat,
        up_pvalue=mat,
        up_pperiod=mat,
        up_pvalid=mat,
        down_ptype=mat,
        down_pvalue=mat,
        down_pperiod=mat,
        down_pvalid=mat,
    )




def pad_binpack_inputs_for_mesh(  # lint: allow-complexity — one inert-padding rule per operand, optional operands included
    inputs: BinPackInputs, mesh: Mesh
) -> BinPackInputs:
    """Grow P to a multiple of the pods axis and T of the groups axis.

    Padding rows carry pod_valid=False; padding columns carry zero
    allocatable, which `_feasibility` already rejects — masked, never
    truncated.
    """
    p_extent = mesh.shape[AXIS_PODS] * mesh.shape.get(AXIS_SLICE, 1)
    g_extent = mesh.shape[AXIS_GROUPS]
    P0 = inputs.pod_requests.shape[0]
    T0 = inputs.group_allocatable.shape[0]
    P1, T1 = _pad_dim(P0, p_extent), _pad_dim(T0, g_extent)
    if P1 == P0 and T1 == T0:
        return inputs

    def pad0(x, n):
        if x.shape[0] == n:
            return x
        widths = [(0, n - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
        return jnp.pad(x, widths)

    return BinPackInputs(
        pod_requests=pad0(inputs.pod_requests, P1),
        pod_valid=pad0(inputs.pod_valid, P1),
        pod_intolerant=pad0(inputs.pod_intolerant, P1),
        pod_required=pad0(inputs.pod_required, P1),
        group_allocatable=pad0(inputs.group_allocatable, T1),
        group_taints=pad0(inputs.group_taints, T1),
        group_labels=pad0(inputs.group_labels, T1),
        pod_weight=(
            None
            if inputs.pod_weight is None
            else pad0(inputs.pod_weight, P1)  # zero weight: inert rows
        ),
        pod_group_forbidden=(
            None
            if inputs.pod_group_forbidden is None
            # padding rows are invalid and padding columns zero-alloc, so
            # False (= not forbidden) padding stays inert on both axes
            else jnp.pad(
                inputs.pod_group_forbidden,
                [
                    (0, P1 - inputs.pod_group_forbidden.shape[0]),
                    (0, T1 - inputs.pod_group_forbidden.shape[1]),
                ],
            )
        ),
        pod_group_score=(
            None
            if inputs.pod_group_score is None
            # zero-score padding: padded columns are infeasible anyway
            else jnp.pad(
                inputs.pod_group_score,
                [
                    (0, P1 - inputs.pod_group_score.shape[0]),
                    (0, T1 - inputs.pod_group_score.shape[1]),
                ],
            )
        ),
        pod_exclusive=(
            None
            if inputs.pod_exclusive is None
            # False padding: padded rows are invalid, never bucketed
            else pad0(inputs.pod_exclusive, P1)
        ),
        pod_priority=(
            None
            if inputs.pod_priority is None
            # priority 0 = no steering; padded rows are invalid anyway
            else pad0(inputs.pod_priority, P1)
        ),
        group_tier=(
            None
            if inputs.group_tier is None
            # tier 0 = on-demand; padded columns are zero-alloc/infeasible
            else pad0(inputs.group_tier, T1)
        ),
        # constraint-plane operands — every one carried through (the PR 8
        # silent-drop bug class): claim/slot pad 0 (unclaimed /
        # unconstrained on invalid rows — zero spread-rank contribution),
        # reservation/domain pad 0 on zero-alloc columns nothing fits,
        # pack-class rows pad all-false (invalid, never histogrammed),
        # and the [S, D] cap table has no pod/group axis to pad
        pod_claim=(
            None
            if inputs.pod_claim is None
            else pad0(inputs.pod_claim, P1)
        ),
        group_reservation=(
            None
            if inputs.group_reservation is None
            else pad0(inputs.group_reservation, T1)
        ),
        pod_pack_class=(
            None
            if inputs.pod_pack_class is None
            else pad0(inputs.pod_pack_class, P1)
        ),
        pod_spread_slot=(
            None
            if inputs.pod_spread_slot is None
            else pad0(inputs.pod_spread_slot, P1)
        ),
        group_domain=(
            None
            if inputs.group_domain is None
            else pad0(inputs.group_domain, T1)
        ),
        spread_cap=inputs.spread_cap,
    )


def pad_decision_inputs_for_mesh(
    inputs: DecisionInputs, mesh: Mesh
) -> DecisionInputs:
    """Grow the fleet axis N to a multiple of the pods mesh axis. Padding
    rows have no valid metrics, so they decide spec_replicas (a no-op) and
    max_replicas=0 keeps every derived flag benign."""
    extent = mesh.shape[AXIS_PODS] * mesh.shape.get(AXIS_SLICE, 1)
    N0 = inputs.spec_replicas.shape[0]
    N1 = _pad_dim(N0, extent)
    if N1 == N0:
        return inputs

    def pad0(x):
        if x.ndim == 0:
            return x
        widths = [(0, N1 - N0)] + [(0, 0)] * (x.ndim - 1)
        return jnp.pad(x, widths)

    return jax.tree_util.tree_map(pad0, inputs)


def pad_forecast_inputs_for_mesh(inputs, mesh: Mesh):
    """Grow the series axis S to a multiple of the mesh rows. Padding
    series are all-invalid (valid=False everywhere), so every recurrence
    sees no samples and their output rows — sliced off by
    sharded_forecast — are well-defined and inert."""
    extent = mesh.shape[AXIS_PODS] * mesh.shape.get(AXIS_SLICE, 1)
    S0 = int(np.asarray(inputs.values).shape[0])
    S1 = _pad_dim(S0, extent)
    if S1 == S0:
        return inputs

    def pad0(x):
        x = jnp.asarray(x)
        widths = [(0, S1 - S0)] + [(0, 0)] * (x.ndim - 1)
        return jnp.pad(x, widths)

    return jax.tree_util.tree_map(pad0, inputs)


def pad_preempt_inputs_for_mesh(inputs, mesh: Mesh):
    """Grow the candidate axis C to a multiple of the mesh rows.
    Padding candidates are invalid (never counted unplaceable) and
    forbidden on every node (never placed); victims/nodes are untouched
    so the quantization scales — a pure function of the node and victim
    maxima — are identical to the unpadded problem."""
    import dataclasses

    extent = mesh.shape[AXIS_PODS] * mesh.shape.get(AXIS_SLICE, 1)
    C0 = int(np.asarray(inputs.pod_requests).shape[0])
    C1 = _pad_dim(C0, extent)
    if C1 == C0:
        return inputs

    def pad0(x, fill=0):
        x = jnp.asarray(x)
        widths = [(0, C1 - C0)] + [(0, 0)] * (x.ndim - 1)
        return jnp.pad(x, widths, constant_values=fill)

    return dataclasses.replace(
        inputs,
        pod_requests=pad0(inputs.pod_requests),
        pod_priority=pad0(inputs.pod_priority),
        pod_valid=pad0(inputs.pod_valid),
        pod_node_forbidden=pad0(inputs.pod_node_forbidden, fill=True),
    )


def shard_forecast_inputs(mesh: Mesh, inputs):
    return jax.device_put(
        pad_forecast_inputs_for_mesh(inputs, mesh),
        forecast_shardings(mesh),
    )


def shard_preempt_inputs(mesh: Mesh, inputs):
    return jax.device_put(
        pad_preempt_inputs_for_mesh(inputs, mesh),
        preempt_shardings(mesh),
    )


_forecast_jit = None


def sharded_forecast(mesh: Mesh, inputs):
    """Run the batched forecast kernel with its series axis partitioned
    over the mesh; outputs slice back to the caller's S. Bitwise equal
    to the single-device kernel (and therefore to forecast_numpy — the
    parity chain composes)."""
    global _forecast_jit
    from karpenter_tpu.forecast import models as FM

    if _forecast_jit is None:
        _forecast_jit = jax.jit(FM.forecast)
    n = int(np.asarray(inputs.values).shape[0])
    out = _forecast_jit(shard_forecast_inputs(mesh, inputs))
    return FM.ForecastOutputs(
        point=out.point[:n],
        sigma2=out.sigma2[:n],
        n_valid=out.n_valid[:n],
    )


def sharded_preempt(mesh: Mesh, inputs):
    """Run the eviction-planning kernel with its candidate axis
    partitioned over the mesh; outputs slice back to the caller's C.
    Bitwise equal to the single-device kernel (integer capacity
    arithmetic — ops/preempt.py parity contract)."""
    from karpenter_tpu.ops.preempt import PreemptOutputs, preempt_plan

    C0 = int(np.asarray(inputs.pod_requests).shape[0])
    V = int(np.asarray(inputs.victim_requests).shape[0])
    out = preempt_plan(shard_preempt_inputs(mesh, inputs))
    return PreemptOutputs(
        chosen_node=out.chosen_node[:C0],
        evict_count=out.evict_count[:C0],
        evict_mask=out.evict_mask[:C0, :V],
        unplaceable=out.unplaceable,  # padding candidates are invalid
    )


def shard_binpack_inputs(mesh: Mesh, inputs: BinPackInputs) -> BinPackInputs:
    inputs = pad_binpack_inputs_for_mesh(inputs, mesh)
    return jax.device_put(
        inputs,
        binpack_shardings(
            mesh,
            with_weight=inputs.pod_weight is not None,
            with_forbidden=inputs.pod_group_forbidden is not None,
            with_score=inputs.pod_group_score is not None,
            with_exclusive=inputs.pod_exclusive is not None,
            with_priority=inputs.pod_priority is not None,
            with_tier=inputs.group_tier is not None,
            with_claim=inputs.pod_claim is not None,
            with_reservation=inputs.group_reservation is not None,
            with_pack_class=inputs.pod_pack_class is not None,
            with_spread_slot=inputs.pod_spread_slot is not None,
            with_domain=inputs.group_domain is not None,
            with_spread_cap=inputs.spread_cap is not None,
        ),
    )


def shard_decision_inputs(
    mesh: Mesh, inputs: DecisionInputs
) -> DecisionInputs:
    inputs = pad_decision_inputs_for_mesh(inputs, mesh)
    return jax.device_put(inputs, decision_shardings(mesh))


# ---------------------------------------------------------------------------
# Sharded entry points
# ---------------------------------------------------------------------------


def sharded_binpack(
    mesh: Mesh, inputs: BinPackInputs, buckets: int = 32
) -> BinPackOutputs:
    """Run the bin-pack solver partitioned over the mesh. Inputs are
    device_put with NamedShardings; `binpack` is already jitted, so GSPMD
    propagates the input shardings through the whole program. Outputs are
    sliced back to the caller's P/T — mesh padding is an implementation
    detail, and padded rows (assigned=-1) must not leak into consumers that
    count unschedulable pods."""
    n_pods = inputs.pod_requests.shape[0]
    n_groups = inputs.group_allocatable.shape[0]
    out = binpack(shard_binpack_inputs(mesh, inputs), buckets=buckets)
    return BinPackOutputs(
        assigned=out.assigned[:n_pods],
        assigned_count=out.assigned_count[:n_groups],
        nodes_needed=out.nodes_needed[:n_groups],
        lp_bound=out.lp_bound[:n_groups],
        unschedulable=out.unschedulable,  # padding rows are ~pod_valid
    )


def sharded_decide(mesh: Mesh, inputs: DecisionInputs) -> DecisionOutputs:
    from karpenter_tpu.ops.decision import decide_jit

    n = inputs.spec_replicas.shape[0]
    out = decide_jit(shard_decision_inputs(mesh, inputs))
    return jax.tree_util.tree_map(lambda x: x[:n] if x.ndim else x, out)


@partial(jax.jit, static_argnames=("buckets",))
def fleet_step(
    decision_inputs: DecisionInputs,
    binpack_inputs: BinPackInputs,
    buckets: int = 32,
) -> Tuple[DecisionOutputs, BinPackOutputs]:
    """ONE tick of the whole control plane's device math: every autoscaler's
    HPA decision + the global pending-pods bin-pack, as a single XLA program.
    This is the framework's 'training step' analog — the thing a multi-chip
    deployment jits over the mesh.
    """
    return decide(decision_inputs), binpack(binpack_inputs, buckets=buckets)


# ---------------------------------------------------------------------------
# Tiny-shape builders (dryrun + tests)
# ---------------------------------------------------------------------------


def example_binpack_inputs(
    P_: int = 32, T: int = 8, R: int = 3, K: int = 8, L: int = 8, seed: int = 0
) -> BinPackInputs:
    rng = np.random.default_rng(seed)
    req = rng.uniform(0.1, 4.0, (P_, R)).astype(np.float32)
    alloc = rng.uniform(4.0, 16.0, (T, R)).astype(np.float32)
    intol = rng.random((P_, K)) < 0.2
    taints = rng.random((T, K)) < 0.2
    required = rng.random((P_, L)) < 0.15
    labels = rng.random((T, L)) < 0.7
    return BinPackInputs(
        pod_requests=jnp.asarray(req),
        pod_valid=jnp.ones((P_,), bool),
        pod_intolerant=jnp.asarray(intol),
        pod_required=jnp.asarray(required),
        group_allocatable=jnp.asarray(alloc),
        group_taints=jnp.asarray(taints),
        group_labels=jnp.asarray(labels),
    )


def example_decision_inputs(N: int = 16, M: int = 4, seed: int = 1) -> DecisionInputs:
    rng = np.random.default_rng(seed)
    return DecisionInputs(
        metric_value=jnp.asarray(
            rng.uniform(0.0, 100.0, (N, M)).astype(np.float32)
        ),
        target_value=jnp.asarray(
            rng.uniform(1.0, 60.0, (N, M)).astype(np.float32)
        ),
        target_type=jnp.asarray(rng.integers(0, 3, (N, M), dtype=np.int32)),
        metric_valid=jnp.asarray(rng.random((N, M)) < 0.8),
        spec_replicas=jnp.asarray(
            rng.integers(0, 20, (N,), dtype=np.int32)
        ),
        status_replicas=jnp.asarray(
            rng.integers(0, 20, (N,), dtype=np.int32)
        ),
        min_replicas=jnp.asarray(rng.integers(0, 3, (N,), dtype=np.int32)),
        max_replicas=jnp.asarray(
            rng.integers(10, 40, (N,), dtype=np.int32)
        ),
        up_window=jnp.zeros((N,), jnp.int32),
        down_window=jnp.full((N,), 300, jnp.int32),
        up_policy=jnp.zeros((N,), jnp.int32),
        down_policy=jnp.zeros((N,), jnp.int32),
        last_scale_time=jnp.asarray(
            rng.uniform(0.0, 100.0, (N,)).astype(np.float32)
        ),
        has_last_scale=jnp.asarray(rng.random((N,)) < 0.5),
        now=jnp.float32(250.0),
        # K=2 policy slots, mixed Count/Percent, some invalid — so the
        # sharded program exercises the policy clamp too
        up_ptype=jnp.asarray(rng.integers(0, 2, (N, 2), dtype=np.int32)),
        up_pvalue=jnp.asarray(rng.integers(1, 10, (N, 2), dtype=np.int32)),
        up_pperiod=jnp.asarray(
            rng.integers(30, 300, (N, 2), dtype=np.int32)
        ),
        up_pvalid=jnp.asarray(rng.random((N, 2)) < 0.5),
        down_ptype=jnp.asarray(rng.integers(0, 2, (N, 2), dtype=np.int32)),
        down_pvalue=jnp.asarray(rng.integers(1, 10, (N, 2), dtype=np.int32)),
        down_pperiod=jnp.asarray(
            rng.integers(30, 300, (N, 2), dtype=np.int32)
        ),
        down_pvalid=jnp.asarray(rng.random((N, 2)) < 0.5),
    )


def dryrun_fleet_step(n_devices: int) -> None:
    """Compile + execute one full sharded tick on an n-device mesh, and
    prove it EQUALS the single-device program element for element.

    Used by __graft_entry__.dryrun_multichip: proves the pods×groups
    shardings compile and run without n real chips. The inputs carry the
    WIDEST operand set the production encoder can emit — pod_weight
    (deduplicated shape rows), pod_group_forbidden (required node
    affinity), pod_group_score (preferred node affinity) — because the
    artifact must certify the program that actually ships: the affinity
    masks shard over BOTH mesh axes, exactly the case worth proving
    (VERDICT r2 item 3) — plus pod_exclusive (hostname self-anti-
    affinity). P=33 is deliberately NOT a multiple of any mesh row
    extent, so pad_binpack_inputs_for_mesh runs and a padding path that
    dropped an optional operand would break the equality below. When
    the device count allows, the same program is re-certified on a 3D
    slice×pods×groups mesh (the multi-slice deployment shape, one
    cross-slice reduction on DCN).
    """
    import dataclasses

    rng = np.random.default_rng(7)
    weights = np.ones(33, np.int32)
    weights[:4] = 5  # a few multiplied shape rows: 49 pods in 33 rows
    d_ref_in = example_decision_inputs(N=16, M=4)
    pack_class = np.zeros((33, 3), bool)
    pack_class[np.arange(33), rng.integers(0, 3, 33)] = True
    b_ref_in = dataclasses.replace(
        example_binpack_inputs(P_=33, T=8, K=8, L=8),
        pod_weight=jnp.asarray(weights),
        pod_group_forbidden=jnp.asarray(rng.random((33, 8)) < 0.3),
        pod_group_score=jnp.asarray(
            rng.integers(0, 100, (33, 8)).astype(np.float32)
        ),
        pod_exclusive=jnp.asarray(rng.random(33) < 0.25),
        # constraint-plane operands (this PR's widest set): claims,
        # isolation pack classes, and a spread slot with per-domain
        # caps — the padding path that dropped any of them would break
        # the bitwise equality below
        pod_claim=jnp.asarray(rng.integers(0, 2, 33, dtype=np.int32)),
        group_reservation=jnp.asarray(
            rng.integers(0, 2, 8, dtype=np.int32)
        ),
        pod_pack_class=jnp.asarray(pack_class),
        pod_spread_slot=jnp.asarray(
            rng.integers(0, 3, 33, dtype=np.int32)
        ),
        group_domain=jnp.asarray(rng.integers(0, 2, 8, dtype=np.int32)),
        spread_cap=jnp.asarray(
            rng.integers(1, 30, (2, 2), dtype=np.int32)
        ),
    )
    # single-device reference: same jitted program, no mesh
    d_ref, b_ref = jax.device_get(fleet_step(d_ref_in, b_ref_in, buckets=8))
    assert int(np.sum(b_ref.assigned_count)) + int(b_ref.unschedulable) == 49
    assert d_ref.desired.shape[0] == 16

    meshes = [build_mesh(n_devices=n_devices)]
    if n_devices % 2 == 0 and n_devices >= 4:
        meshes.append(build_mesh(n_devices=n_devices, slices=2))
    for mesh in meshes:
        d_in = shard_decision_inputs(mesh, d_ref_in)
        b_in = shard_binpack_inputs(mesh, b_ref_in)
        d_out, b_out = jax.device_get(fleet_step(d_in, b_in, buckets=8))
        # sharded == single-device, bitwise, after stripping mesh padding
        np.testing.assert_array_equal(b_out.assigned[:33], b_ref.assigned)
        np.testing.assert_array_equal(
            b_out.assigned_count[:8], b_ref.assigned_count
        )
        np.testing.assert_array_equal(
            b_out.nodes_needed[:8], b_ref.nodes_needed
        )
        assert int(b_out.unschedulable) == int(b_ref.unschedulable)
        np.testing.assert_array_equal(d_out.desired[:16], d_ref.desired)

    # the PRODUCTION route onto the same mesh: a SolverService with the
    # shard threshold forced low must route this solve through its
    # sharded dispatch strategy (docs/solver-service.md "Sharded
    # dispatch") and answer bit-identically to the single-device
    # program — certifying the seam every caller actually takes, not
    # just the raw helpers above
    from karpenter_tpu.metrics.registry import GaugeRegistry
    from karpenter_tpu.solver import SolverService

    service = SolverService(
        registry=GaugeRegistry(),
        shard_threshold=1,
        shard_devices=n_devices,
    )
    try:
        svc_out = service.solve(b_ref_in, buckets=8, backend="xla")
        # a 1-device dryrun has no mesh to build: the service must fall
        # through to the single-device program (still parity-checked)
        expected = 1 if n_devices >= 2 else 0
        assert service.stats.shard_dispatches == expected, service.stats
        np.testing.assert_array_equal(svc_out.assigned, b_ref.assigned)
        np.testing.assert_array_equal(
            svc_out.assigned_count, b_ref.assigned_count
        )
        np.testing.assert_array_equal(
            svc_out.nodes_needed, b_ref.nodes_needed
        )
        assert int(svc_out.unschedulable) == int(b_ref.unschedulable)
    finally:
        service.close()
