"""Multi-host initialization: the jax.distributed seam for multi-slice /
multi-host solver deployments.

reference analog: the reference's distributed backend is the
kube-apiserver bus + NCCL-less singleton control plane (SURVEY.md §2.2 —
it has no multi-node compute at all). The TPU build's compute CAN span
hosts: `parallel/mesh.py` builds 2D/3D meshes over whatever devices jax
exposes, and on a multi-host slice jax exposes the GLOBAL device set
only after `jax.distributed.initialize` — this module is the one place
that call lives.

Deployment contract (docs/OPERATIONS.md "Scaling past one chip"): run
one solver sidecar per host (`python -m karpenter_tpu.sidecar
--multihost`); on TPU pods the coordinator/process topology
auto-detects from the TPU environment, elsewhere it comes from the
standard env (JAX_COORDINATOR_ADDRESS, JAX_NUM_PROCESSES,
JAX_PROCESS_ID) or explicit arguments. After initialization,
`build_mesh(n_devices=jax.device_count())` spans the whole slice and
the sharded programs in parallel/mesh.py run unchanged — pod rows over
ICI, the one cross-slice reduction over DCN.
"""

from __future__ import annotations

import os
from typing import Optional

from karpenter_tpu.utils.log import logger

_initialized = False


def _resolve_topology(coordinator_address, num_processes, process_id):
    """Resolve each parameter (explicit argument, then standard env var)
    and enforce all-or-nothing: a PARTIAL explicit topology raises —
    silently degrading a mis-wired multi-host fleet to N independent
    single-host solvers would double-solve the fleet."""
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    env_processes = os.environ.get("JAX_NUM_PROCESSES")
    num_processes = (
        num_processes
        if num_processes is not None
        else (int(env_processes) if env_processes else None)
    )
    env_process_id = os.environ.get("JAX_PROCESS_ID")
    process_id = (
        process_id
        if process_id is not None
        else (int(env_process_id) if env_process_id else None)
    )
    explicit = (coordinator_address, num_processes, process_id)
    configured = [value for value in explicit if value is not None]
    if configured and len(configured) != len(explicit):
        raise ValueError(
            "partial multihost topology: coordinator_address, "
            f"num_processes, process_id must be set together (got "
            f"{explicit!r}); a half-configured host joining single-host "
            "would double-solve the fleet while the rest hang"
        )
    return explicit


def _auto_initialize(jax) -> bool:
    """The auto path: let jax's cluster detection decide. Attempted
    UNCONDITIONALLY (probing the backend first would itself initialize
    XLA and poison the join). Returns False only on the EXACT no-cluster
    sentinel: jax's cluster auto-detection found no cluster and fell
    through to the bare-args validation (jax._src.distributed raises
    RuntimeError 'coordinator_address should be defined.'). Anything
    else — a detected-but-unreachable coordinator, a partial detection,
    'must be called before any JAX calls' (an ordering bug in the
    caller) — is a REAL failure and raises: degrading a detected
    multi-host fleet to N independent solvers would double-solve the
    fleet while the other hosts hang in initialize. Substring matching
    here once misread real join failures (r3 code review)."""
    try:
        jax.distributed.initialize()
    except Exception as e:  # noqa: BLE001 — classified above
        if str(e).strip() == "coordinator_address should be defined.":
            # the normal single-host case
            logger().info("no multihost topology detected: %s", e)
            return False
        raise
    return True


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize jax.distributed for a multi-host deployment.

    MUST be called before anything initializes the in-process XLA
    backend (jax.devices(), any computation, even jax.default_backend())
    — jax.distributed.initialize refuses afterwards. The sidecar
    therefore joins BEFORE its backend probe.

    Resolution order per parameter: explicit argument, then standard env
    var. With a FULL explicit topology (all three of coordinator /
    num_processes / process_id) the join is mandatory and any failure
    raises. With NO explicit topology, jax's own cluster auto-detection
    runs (TPU pod metadata, GKE, Slurm); "no cluster found" returns
    False — the normal single-host case — while any other failure
    raises. A PARTIAL explicit topology always raises: silently
    degrading a mis-wired multi-host fleet to N independent single-host
    solvers would double-solve the fleet.

    Idempotent per process (jax.distributed.initialize is once-only).
    """
    global _initialized
    if _initialized:
        return True
    coordinator_address, num_processes, process_id = _resolve_topology(
        coordinator_address, num_processes, process_id
    )

    import jax

    if coordinator_address is None:
        if not _auto_initialize(jax):
            return False
        _initialized = True
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized = True
    logger().info(
        "multihost: process %d/%d, %d global device(s)",
        jax.process_index(),
        jax.process_count(),
        jax.device_count(),
    )
    return True
