"""What-if simulation: a dry-run pending-pods solve with per-pod detail.

The production tick computes per-row assignments on the device
(ops/binpack.BinPackOutputs.assigned) but only publishes per-group
aggregates through the MetricsProducer status. This module surfaces the
rows: which pod shapes land where, what stays unschedulable and why the
operator should care — and answers "what would ADDING node group X
change?" by re-running the identical solve with hypothetical groups
appended to the group axis.

reference anchor: the reference has no simulation surface at all (its
pending-capacity producer is a stub, pendingcapacity/producer.go:29-31);
the intent served here is DESIGN.md "Pending Pods" — operators sizing a
scale-up want to see the placement the signal is promising.

Nothing here mutates the store or any status object: the solve runs on a
detached snapshot, making it safe against a live cluster.

Every `simulate_*` replay world in this module is registered as a
SimLab scenario (karpenter_tpu/simlab/builtin.py, docs/simulator.md):
the scenario registry owns the `--simulate` CLI dispatch (`--list`
prints the catalog, `--sim-seed` threads a seed through the seeded
worlds' RNG streams), and pairs each world with seeded trail generators
for the gym-style simulator core. The functions here stay the library
surface — call them directly for programmatic replays; their default
seeds reproduce the digests the acceptance tests pin.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_tpu.metrics.producers.pendingcapacity import (
    DomainCensus,
    encode_snapshot,
    group_profile,
)
from karpenter_tpu.store.columnar import (
    PendingPodCache,
    is_pending,
    occupancy_from_pods,
)


def _what_if_profile(spec: dict) -> Tuple[Dict[str, float], set, set]:
    """A hypothetical group declared the same way provider node templates
    are: the raw dict goes through cloudprovider.node_template_from_raw
    (quantity parsing, cloud-API taint-effect dialect) and then the SAME
    template->profile conversion the scale-from-zero resolver uses —
    including the pods-resource default, so a spec that only declares
    cpu/memory is not silently infeasible for every pod."""
    from karpenter_tpu.cloudprovider import node_template_from_raw
    from karpenter_tpu.metrics.producers import profile_from_template

    template = node_template_from_raw(
        {
            "allocatable": spec.get("allocatable") or {},
            "labels": spec.get("labels") or {},
            "taints": spec.get("taints") or [],
        }
    )
    return profile_from_template(template)


def simulate(  # lint: allow-complexity — report assembly: one guard per optional report field
    store,
    what_if_groups: Optional[List[dict]] = None,
    solver=None,
    template_resolver=None,
    cost_model=None,
) -> dict:
    """One dry-run solve over the store's pendingCapacity producers plus
    `what_if_groups` (each {"name", "allocatable", "labels", "taints"}).

    Returns a JSON-shaped report:
      groups: per group {pending_pods, additional_nodes_needed,
              lp_lower_bound, what_if: bool, error?: str}
      rows:   per distinct pod shape {pod (ns/name of a representative),
              pods (count), assigned (group name or null)}
      unschedulable_pods: total weight with no feasible group

    `template_resolver` is the scale-from-zero seam solve_pending takes
    (producers.Factory.template_resolver): without it, empty groups with
    a nodeGroupRef encode as infeasible and the baseline drifts from the
    production solve. Per-producer failures are row-isolated exactly
    like the production path — a poisoned selector reports an `error` on
    its own group, never crashes the report.

    Hypothetical groups are appended AFTER the real ones, so first-
    feasible assignment only routes pods to them when no real group
    is feasible earlier in the order — the delta a what-if run shows is
    capacity the existing fleet genuinely lacks."""
    if solver is None:
        # the process-default solve service (solver/service.py): a
        # standalone simulation gets bucketing/backpressure/fallback for
        # free, and callers co-resident with other default-service users
        # (the sidecar server's RPCs) share one queue. A control plane
        # passes its runtime's own service here (__main__.py does).
        from karpenter_tpu.solver import default_service

        solver = default_service().solve

    producers = sorted(
        (
            mp
            for mp in store.list("MetricsProducer")
            if mp.spec.pending_capacity is not None
        ),
        key=lambda mp: (mp.metadata.namespace, mp.metadata.name),
    )
    nodes = store.list("Node")
    names: List[str] = []
    profiles = []
    what_if_names = set()
    group_errors: Dict[str, str] = {}
    for mp in producers:
        # namespace-qualified like the production solve's (ns, name) keys:
        # same-named producers in different namespaces must not collapse
        names.append(f"{mp.metadata.namespace}/{mp.metadata.name}")
        try:
            profile = group_profile(
                nodes, mp.spec.pending_capacity.node_selector
            )
            if not profile[0] and template_resolver is not None:
                ref = getattr(
                    mp.spec.pending_capacity, "node_group_ref", ""
                )
                if ref:
                    resolved = template_resolver(
                        mp.metadata.namespace, ref
                    )
                    if resolved is not None:
                        profile = resolved
        except Exception as e:  # noqa: BLE001 — row-isolated like
            # solve_pending: the dry-run tool must not crash on the
            # degraded clusters an operator most wants to inspect
            group_errors[names[-1]] = f"{type(e).__name__}: {e}"
            profile = ({}, set(), set())
        profiles.append(profile)
    for spec in what_if_groups or []:
        name = spec.get("name") or f"what-if-{len(what_if_names)}"
        n = 2
        while name in names:  # a colliding spec must not overwrite a row
            name = f"{spec.get('name') or 'what-if'}#{n}"
            n += 1
        names.append(name)
        what_if_names.add(name)
        profiles.append(_what_if_profile(spec))

    # detached encode with a slot -> pod-name map for per-row reporting
    # (snapshot rows are arena slots; snapshot_from_pods hides the map)
    all_pods = store.list("Pod")
    pods = [pod for pod in all_pods if is_pending(pod)]
    cache = PendingPodCache(store=None, capacity=max(16, len(pods)))
    slot_pod: Dict[int, str] = {}
    for pod in pods:
        key = (pod.metadata.namespace, pod.metadata.name)
        cache._upsert(key, pod)
        slot_pod[cache._slot[key]] = f"{key[0]}/{key[1]}"
    snap = cache.snapshot()

    # existing-pod domain occupancy, exactly like the production solve:
    # census nodes are the REAL ones (a what-if group's domains hold no
    # existing pods by construction)
    census = DomainCensus(occupancy_from_pods(all_pods), lambda: nodes)
    census.set_namespaces(store.list("Namespace"))
    inputs, row_idx, row_weight = encode_snapshot(
        snap, profiles, with_rows=True, census=census
    )
    if what_if_names and inputs.pod_group_score is not None:
        # preferred node affinity must not STEER pods into hypothetical
        # groups (the solver argmaxes score among feasible groups, which
        # would let a what-if group steal pods a real group serves): zero
        # their score columns, so they absorb only what no real group
        # can take — the invariant the delta report documents
        import dataclasses

        score = np.array(inputs.pod_group_score)
        score[:, len(profiles) - len(what_if_names): len(profiles)] = 0.0
        inputs = dataclasses.replace(inputs, pod_group_score=score)
    # per-group node pricing (cost/model.py): the columnar cost face of
    # the SAME profiles the solve encodes, so the report prices what a
    # scale-up signal would actually cost per hour (`cost_model` lets
    # the CLI's --cost-default-hourly/--cost-spot-multiplier knobs
    # reach the dry-run report)
    if cost_model is None:
        from karpenter_tpu.cost import CostModel

        cost_model = CostModel()
    group_cost = cost_model.group_costs(profiles)
    if len(row_idx) == 0:
        return {
            "groups": {
                name: {
                    "pending_pods": 0,
                    "additional_nodes_needed": 0,
                    "lp_lower_bound": 0,
                    "node_hourly_cost": round(float(group_cost[t]), 4),
                    "scale_up_hourly_cost": 0.0,
                    "what_if": name in what_if_names,
                    **(
                        {"error": group_errors[name]}
                        if name in group_errors
                        else {}
                    ),
                }
                for t, name in enumerate(names)
            },
            "rows": [],
            "unschedulable_pods": 0,
        }
    out = solver(inputs)
    assigned = np.asarray(out.assigned)
    assigned_count = np.asarray(out.assigned_count)
    nodes_needed = np.asarray(out.nodes_needed)
    lp_bound = np.asarray(out.lp_bound)

    rows = []
    for i in range(len(row_idx)):
        group = int(assigned[i])
        rows.append(
            {
                "pod": slot_pod.get(int(row_idx[i]), "<unknown>"),
                "pods": int(row_weight[i]),
                "assigned": (
                    names[group] if 0 <= group < len(names) else None
                ),
            }
        )
    return {
        "groups": {
            name: {
                "pending_pods": int(assigned_count[t]),
                "additional_nodes_needed": int(nodes_needed[t]),
                "lp_lower_bound": int(lp_bound[t]),
                "node_hourly_cost": round(float(group_cost[t]), 4),
                "scale_up_hourly_cost": round(
                    float(nodes_needed[t]) * float(group_cost[t]), 4
                ),
                "what_if": name in what_if_names,
                **(
                    {"error": group_errors[name]}
                    if name in group_errors
                    else {}
                ),
            }
            for t, name in enumerate(names)
        },
        "rows": rows,
        "unschedulable_pods": int(out.unschedulable),
    }


def simulate_consolidation(store, service=None, buckets: int = 32) -> dict:
    """Dry-run consolidation plan: which nodes' pods would re-pack onto
    the remainder of the cluster, and why the rest are ineligible.

    The same candidate generation and batched masked bin-pack the
    production engine runs (karpenter_tpu/consolidation), minus the
    runtime-state safety gates — cooldown clocks and in-flight budgets
    live in the long-running engine, so a fresh dry-run process reports
    STRUCTURAL drainability and leaves pacing to the engine. Nothing is
    cordoned, scaled, or otherwise mutated.

    Report shape:
      nodes: per node {group, pods, drainable | ineligible reason}
      drainable: [node names]
      candidates_evaluated: how many masked solves the batch carried
    """
    from karpenter_tpu.consolidation import (
        DO_NOT_DISRUPT,
        cluster_view,
        discover_groups,
        evaluate,
    )

    if service is None:
        from karpenter_tpu.solver import default_service

        service = default_service()

    def node_entry(nv) -> dict:
        entry: dict = {
            "group": (
                f"{nv.group[0]}/{nv.group[2]}"
                if nv.group is not None and nv.group[2]
                else None
            ),
            "pods": len(nv.pods),
        }
        if nv.group is None or not nv.group[2]:
            entry["ineligible"] = "no nodeGroupRef to actuate"
        elif not nv.receiver:
            entry["ineligible"] = "not ready/schedulable"
        elif nv.do_not_disrupt:
            entry["ineligible"] = f"{DO_NOT_DISRUPT} annotation"
        return entry

    groups = discover_groups(store)
    view = cluster_view(store, groups)
    report: Dict[str, dict] = {
        nv.name: node_entry(nv) for nv in view.nodes
    }
    candidates = [
        name for name, entry in report.items()
        if "ineligible" not in entry
    ]
    verdicts = evaluate(view, candidates, service, buckets=buckets)
    for name, verdict in verdicts.items():
        report[name]["drainable"] = verdict
    return {
        "nodes": report,
        "drainable": sorted(
            name for name, v in verdicts.items() if v
        ),
        "candidates_evaluated": len(candidates),
    }


def simulate_trace(export_path: Optional[str] = None) -> dict:  # lint: allow-complexity — scenario assembly: world build + FSM-phased replay + report
    """The traced end-to-end replay (docs/observability.md): a seeded
    consolidating world driven tick by tick with the reconcile tracer
    capturing every layer — tick entry, producer solves, the HA fleet
    decide, the COALESCED consolidation dispatch (one solver dispatch
    span linking every candidate request span that rode it), and the
    ScalableNodeGroup actuation that closes the event-observed ->
    actuation-acked window. `export_path` writes the capture as
    Chrome-trace/Perfetto JSONL; the report summarizes what the trace
    must contain (the acceptance pin in tests/test_observability.py).

    Nothing here touches a live store or provider: the world is
    self-contained (fake provider, scripted clock)."""
    from karpenter_tpu.api.core import (
        Container, Node, NodeCondition, NodeSpec, NodeStatus,
        ObjectMeta, Pod, PodSpec, resource_list,
    )
    from karpenter_tpu.api.horizontalautoscaler import (
        CrossVersionObjectReference, HorizontalAutoscaler,
        HorizontalAutoscalerSpec, Metric, MetricTarget,
        PrometheusMetricSource,
    )
    from karpenter_tpu.api.metricsproducer import (
        MetricsProducer, MetricsProducerSpec, PendingCapacitySpec,
    )
    from karpenter_tpu.api.scalablenodegroup import (
        FAKE_NODE_GROUP, ScalableNodeGroup, ScalableNodeGroupSpec,
    )
    from karpenter_tpu.cloudprovider.fake import FakeFactory
    from karpenter_tpu.observability import default_tracer
    from karpenter_tpu.runtime import KarpenterRuntime, Options
    from karpenter_tpu.utils.quantity import Quantity

    tracer = default_tracer()
    tracer.clear()
    clock = {"now": 1_000_000.0}
    provider = FakeFactory()
    provider.node_replicas["grp-id"] = 3
    runtime = KarpenterRuntime(
        Options(consolidate=True),
        cloud_provider_factory=provider,
        clock=lambda: clock["now"],
    )
    store = runtime.store
    for i in range(3):
        store.create(Node(
            metadata=ObjectMeta(name=f"n{i}", labels={"pool": "a"}),
            spec=NodeSpec(),
            status=NodeStatus(
                allocatable=resource_list(
                    cpu="8", memory="16Gi", pods="16"
                ),
                conditions=[NodeCondition("Ready", "True")],
            ),
        ))
    for i in range(3):
        # one small pod per node: every candidate needs a REAL masked
        # bin-pack (empty nodes short-circuit as trivially drainable and
        # would never ride the coalesced dispatch this replay exists to
        # trace)
        store.create(Pod(
            metadata=ObjectMeta(name=f"p{i}"),
            spec=PodSpec(
                node_name=f"n{i}",
                containers=[Container(requests={
                    "cpu": Quantity.parse("1"),
                    "memory": Quantity.parse("1Gi"),
                })],
            ),
        ))
    store.create(MetricsProducer(
        metadata=ObjectMeta(name="pending"),
        spec=MetricsProducerSpec(
            pending_capacity=PendingCapacitySpec(
                node_selector={"pool": "a"}, node_group_ref="grp",
            )
        ),
    ))
    store.create(ScalableNodeGroup(
        metadata=ObjectMeta(name="grp"),
        spec=ScalableNodeGroupSpec(
            replicas=3, type=FAKE_NODE_GROUP, id="grp-id",
        ),
    ))
    store.create(HorizontalAutoscaler(
        metadata=ObjectMeta(name="ha"),
        spec=HorizontalAutoscalerSpec(
            scale_target_ref=CrossVersionObjectReference(
                kind="ScalableNodeGroup", name="grp"
            ),
            min_replicas=2, max_replicas=100,
            metrics=[Metric(prometheus=PrometheusMetricSource(
                query='karpenter_queue_length{name="q"}',
                target=MetricTarget(type="AverageValue", value=4),
            ))],
        ),
    ))
    # queue length 8 / target 4 -> the HA computes desired 2 against the
    # observed 3: the decide patches spec.replicas, the watch event
    # stamps the e2e observation, and the next tick's SNG reconcile
    # actuates — the event-observed -> actuation-acked chain the trace
    # and karpenter_reconcile_e2e_seconds must both capture
    runtime.registry.register("queue", "length").set("q", "default", 8.0)

    engine = runtime.consolidation
    e2e_before = tracer.e2e_observed
    try:
        # tick through the consolidation FSM: first sight starts the
        # churn clock, cooldown expiry plans (the COALESCED candidate
        # dispatch), verify soaks, drain decrements spec.replicas, and
        # the watch-requeued SNG reconcile actuates the provider write
        runtime.manager.converge(1)
        clock["now"] += engine.config.cooldown_s + 1
        runtime.manager.converge(1)
        clock["now"] += engine.config.verify_s + 1
        runtime.manager.converge(1)
        runtime.manager.converge(2)
        actuated = provider.node_replicas["grp-id"]
    finally:
        runtime.close()

    spans = tracer.snapshot()
    dispatches = [
        s for s in spans if s["name"].startswith("solver.dispatch")
    ]
    max_links = max((len(s["links"]) for s in dispatches), default=0)
    report = {
        "replicas_after": actuated,
        "spans": len(spans),
        "traces": len({s["trace"] for s in spans}),
        "dispatch_spans": len(dispatches),
        "max_dispatch_links": max_links,
        "actuation_spans": sum(
            1 for s in spans if s["name"] == "actuate.set_replicas"
        ),
        "tick_spans": sum(
            1 for s in spans if s["name"] == "reconcile.tick"
        ),
        "e2e_samples": tracer.e2e_observed - e2e_before,
    }
    if export_path:
        report["trace_export"] = export_path
        report["trace_events"] = tracer.export_jsonl(export_path)
    return report


def _recording_provider():
    """FakeFactory that records every provider write as (group_id,
    count) in `.writes` — the shared actuation ledger of the eventloop
    and restart-storm replays (one definition, so the two replays'
    write accounting can never drift apart)."""
    from karpenter_tpu.cloudprovider.fake import (
        FakeFactory, FakeNodeGroup,
    )

    class _RecordingGroup(FakeNodeGroup):
        def set_replicas(self, count, token=None):
            super().set_replicas(count, token=token)
            self._factory.writes.append((self._id, count))

    class _RecordingFactory(FakeFactory):
        def __init__(self):
            super().__init__()
            self.writes = []

        def node_group_for(self, spec):
            return _RecordingGroup(self, spec.id)

    return _RecordingFactory()


def _eventloop_world(event_driven: bool, debounce_s: float, clock_fn):
    """One seeded autoscaling world for the event-loop replay: a node
    pool, a pendingCapacity producer, a queue-driven autoscaler, and a
    fake provider. `event_thread=False` — the replay drives event
    passes itself on the scripted clock, so both arms are wall-free."""
    from karpenter_tpu.api.core import (
        Node, NodeCondition, NodeSpec, NodeStatus, ObjectMeta,
        resource_list,
    )
    from karpenter_tpu.api.horizontalautoscaler import (
        CrossVersionObjectReference, HorizontalAutoscaler,
        HorizontalAutoscalerSpec, Metric, MetricTarget,
        PrometheusMetricSource,
    )
    from karpenter_tpu.api.metricsproducer import (
        MetricsProducer, MetricsProducerSpec, PendingCapacitySpec,
    )
    from karpenter_tpu.api.scalablenodegroup import (
        FAKE_NODE_GROUP, ScalableNodeGroup, ScalableNodeGroupSpec,
    )
    from karpenter_tpu.runtime import KarpenterRuntime, Options

    provider = _recording_provider()
    provider.node_replicas["grp-id"] = 3
    runtime = KarpenterRuntime(
        Options(
            event_driven=event_driven,
            event_debounce_s=debounce_s,
            event_thread=False,
        ),
        cloud_provider_factory=provider,
        clock=clock_fn,
    )
    store = runtime.store
    store.create(Node(
        metadata=ObjectMeta(name="n0", labels={"pool": "a"}),
        spec=NodeSpec(),
        status=NodeStatus(
            allocatable=resource_list(cpu="8", memory="16Gi", pods="16"),
            conditions=[NodeCondition("Ready", "True")],
        ),
    ))
    store.create(MetricsProducer(
        metadata=ObjectMeta(name="pending"),
        spec=MetricsProducerSpec(
            pending_capacity=PendingCapacitySpec(
                node_selector={"pool": "a"}, node_group_ref="grp",
            )
        ),
    ))
    store.create(ScalableNodeGroup(
        metadata=ObjectMeta(name="grp"),
        spec=ScalableNodeGroupSpec(
            replicas=3, type=FAKE_NODE_GROUP, id="grp-id",
        ),
    ))
    store.create(HorizontalAutoscaler(
        metadata=ObjectMeta(name="ha"),
        spec=HorizontalAutoscalerSpec(
            scale_target_ref=CrossVersionObjectReference(
                kind="ScalableNodeGroup", name="grp"
            ),
            min_replicas=2, max_replicas=400,
            metrics=[Metric(prometheus=PrometheusMetricSource(
                query='karpenter_queue_length{name="q"}',
                target=MetricTarget(type="AverageValue", value=4),
            ))],
        ),
    ))
    gauge = runtime.registry.register("queue", "length")
    gauge.set("q", "default", 12.0)
    return runtime, provider, gauge


def simulate_eventloop(  # lint: allow-complexity — scenario assembly: two arms + churn-storm arm + report
    ticks: int = 40,
    interval_s: float = 10.0,
    arrivals: int = 60,
    storm_events: int = 1000,
    debounce_s: float = 0.05,
    demand_step: float = 4.0,
    seed: int = 0,
) -> dict:
    """The event-driven-reconcile proof replay (docs/solver-service.md
    "Event-driven reconcile"): ONE seeded pod-arrival trace — `arrivals`
    pending pods at uniform-random times over `ticks` backstop
    intervals, each bumping queue demand by `demand_step` — replayed
    through two otherwise-identical worlds:

      tick-paced    the pre-PR loop: watch events mark objects due-now
                    but every reconcile waits for the next `interval_s`
                    tick, so the karpenter_reconcile_e2e_seconds sample
                    for each actuation is ~one full interval;
      event-driven  watch events cascade through debounced coalesced
                    event passes (pod -> producer solve -> autoscaler
                    decide -> node-group actuation), each hop one
                    `debounce_s` window — sub-second end to end.

    Both arms read e2e p50/p99 off the SAME histogram the live plane
    serves (HistogramVec.percentile — the number an operator's
    histogram_quantile() shows), count their solver work (bin-pack
    requests + fleet decides) for the amplification column, and must
    land on the SAME fleet fixed point. The event world then takes a
    CHURN STORM — `storm_events` pod events inside one debounce window —
    which must coalesce into a handful of passes (not one per event)
    with solve amplification bounded vs one backstop tick's work.

    Wall-clock-free and fully deterministic under `seed`: scripted
    clock, manual event passes (Options.event_thread=False), seeded
    arrival times."""
    from karpenter_tpu.api.core import ObjectMeta, Pod, PodSpec
    from karpenter_tpu.observability import (
        Tracer, reset_default_tracer, set_default_tracer,
    )

    rng = np.random.RandomState(seed)
    times = np.sort(
        rng.uniform(0.0, ticks * interval_s, size=arrivals)
    ).tolist()
    epoch = 1_000_000.0

    def replay(event_driven: bool) -> dict:
        clock = {"now": epoch}
        # the e2e histogram must measure SIMULATED lead time (ticks are
        # replayed far faster than the interval they model), so the
        # tracer runs on the scripted clock for this arm
        set_default_tracer(Tracer(clock=lambda: clock["now"]))
        runtime, provider, gauge = _eventloop_world(
            event_driven, debounce_s, lambda: clock["now"]
        )
        manager = runtime.manager
        store = runtime.store
        stats = runtime.solver_service.stats

        def solves() -> int:
            return stats.requests + stats.decide_calls

        def passes() -> float:
            value = runtime.registry.gauge(
                "runtime", "event_passes_total"
            ).get("manager", "-")
            return float(value or 0.0)

        def drain(limit: int = 6) -> None:
            """The debounce thread's job, on the scripted clock: each
            pending pass costs one debounce window of simulated time."""
            for _ in range(limit):
                if manager.dirty_count() == 0:
                    return
                clock["now"] += debounce_s
                manager.run_event_pass()

        demand = 12.0
        next_arrival = 0
        try:
            for k in range(1, ticks + 1):
                while (
                    next_arrival < len(times)
                    and times[next_arrival] < k * interval_s
                ):
                    clock["now"] = max(
                        clock["now"], epoch + times[next_arrival]
                    )
                    demand += demand_step
                    gauge.set("q", "default", demand)
                    store.create(Pod(
                        metadata=ObjectMeta(
                            name=f"arrival-{next_arrival}"
                        ),
                        spec=PodSpec(),
                    ))
                    if event_driven:
                        drain()
                    next_arrival += 1
                clock["now"] = max(clock["now"], epoch + k * interval_s)
                manager.reconcile_all()
                if event_driven:
                    drain()
            # settle: the trace's tail actuations need one more hop
            for _ in range(3):
                clock["now"] += interval_s
                manager.reconcile_all()
                if event_driven:
                    drain()
            trace_solves = solves()
            hist = runtime.registry.gauge("reconcile", "e2e_seconds")
            arm = {
                "e2e_seconds": {
                    "p50_s": hist.percentile(
                        "ScalableNodeGroup", "-", 50
                    ),
                    "p99_s": hist.percentile(
                        "ScalableNodeGroup", "-", 99
                    ),
                    "n": hist.count("ScalableNodeGroup", "-"),
                },
                "solves": trace_solves,
                "replicas_after": provider.node_replicas["grp-id"],
                "provider_writes": len(provider.writes),
            }
            if not event_driven:
                return arm
            arm["event_passes"] = passes()
            # -- churn-storm arm: storm_events pod events, ONE window --
            storm_t0 = clock["now"]
            s0, p0 = solves(), passes()
            for i in range(storm_events):
                store.create(Pod(
                    metadata=ObjectMeta(name=f"storm-{i}"),
                    spec=PodSpec(),
                ))
            drain(limit=8)
            storm_solves = solves() - s0
            storm_passes = passes() - p0
            # measured BEFORE the comparator tick advances the clock:
            # this is the simulated time the storm's passes spanned
            storm_window = round(clock["now"] - storm_t0, 3)
            # the tick-paced comparator: ONE backstop tick over a
            # freshly-churned world is the work a tick-paced loop would
            # have spent reacting to the storm (the extra pod keeps the
            # encoder's unchanged-cluster memo from eliding the tick's
            # solve, which would flatter the storm ratio)
            s1 = solves()
            store.create(Pod(
                metadata=ObjectMeta(name="storm-comparator"),
                spec=PodSpec(),
            ))
            clock["now"] += interval_s
            manager.reconcile_all()
            tick_solves = max(1, solves() - s1)
            arm["storm"] = {
                "events": storm_events,
                "passes": storm_passes,
                "solves": storm_solves,
                "window_s": storm_window,
                "amplification": round(storm_solves / tick_solves, 2),
            }
            return arm
        finally:
            runtime.close()

    try:
        tick_arm = replay(False)
        event_arm = replay(True)
    finally:
        # never leak a scripted-clock tracer into the process default
        reset_default_tracer()

    tick_p99 = tick_arm["e2e_seconds"]["p99_s"] or 0.0
    event_p99 = event_arm["e2e_seconds"]["p99_s"] or 0.0
    return {
        "config": {
            "ticks": ticks,
            "interval_s": interval_s,
            "arrivals": arrivals,
            "storm_events": storm_events,
            "debounce_s": debounce_s,
            "demand_step": demand_step,
            "seed": seed,
        },
        "tick_paced": tick_arm,
        "event_driven": event_arm,
        "e2e_p99_s": {
            "tick_paced": tick_p99,
            "event_driven": event_p99,
            "speedup": round(tick_p99 / event_p99, 1)
            if event_p99 else None,
        },
        "solve_amplification": round(
            event_arm["solves"] / max(1, tick_arm["solves"]), 2
        ),
        "fixed_point_match": (
            tick_arm["replicas_after"] == event_arm["replicas_after"]
        ),
    }


def simulate_forecast(  # lint: allow-complexity — scenario assembly: world build + two replays + report
    ticks: int = 90,
    interval_s: float = 10.0,
    horizon_s: float = 60.0,
    model: str = "holt-winters",
    base: float = 8.0,
    amplitude: float = 120.0,
    ramp_start: int = 10,
    ramp_ticks: int = 24,
    target: float = 4.0,
    min_samples: int = 4,
    seed: int = 0,
    backend: str = "xla",
) -> dict:
    """Dry-run the predictive subsystem against a synthetic diurnal
    ramp (docs/forecasting.md "Dry-running"): the same scripted metric —
    flat overnight base, a smooth morning surge of `amplitude` over
    `ramp_ticks`, then a daytime plateau — is replayed through two
    otherwise-identical autoscalers, one with spec.behavior.forecast and
    one reactive-only, and the report quantifies the PROVISIONING LEAD:
    how many ticks earlier the forecast-enabled autoscaler reached each
    capacity milestone, i.e. how much node-provisioning latency a real
    node group would have hidden. Nothing here touches a store or a
    cloud provider — both worlds are built from scratch in memory.
    """
    import math as _math

    from karpenter_tpu.api.core import ObjectMeta
    from karpenter_tpu.api.horizontalautoscaler import (
        Behavior,
        CrossVersionObjectReference,
        ForecastSpec,
        HorizontalAutoscaler,
        HorizontalAutoscalerSpec,
        Metric,
        MetricTarget,
        PrometheusMetricSource,
    )
    from karpenter_tpu.api.scalablenodegroup import (
        ScalableNodeGroup,
        ScalableNodeGroupSpec,
    )
    from karpenter_tpu.autoscaler import BatchAutoscaler
    from karpenter_tpu.forecast import FleetForecaster
    from karpenter_tpu.metrics.clients import MetricsClientFactory
    from karpenter_tpu.metrics.registry import GaugeRegistry
    from karpenter_tpu.solver import SolverService

    rng = np.random.RandomState(seed)
    noise = rng.normal(0.0, 0.01 * amplitude, size=ticks)

    def metric_at(tick: int) -> float:
        # the morning side of a diurnal wave: smooth cosine S-ramp from
        # base to base+amplitude, then plateau
        progress = min(max(tick - ramp_start, 0) / max(ramp_ticks, 1), 1.0)
        level = base + amplitude * 0.5 * (1.0 - _math.cos(_math.pi * progress))
        return max(0.0, level + float(noise[tick]))

    def replay(forecast_spec):
        from karpenter_tpu.store import Store as _Store

        store = _Store()
        registry = GaugeRegistry()
        gauge = registry.register("queue", "length")
        store.create(
            ScalableNodeGroup(
                metadata=ObjectMeta(name="g"),
                spec=ScalableNodeGroupSpec(
                    replicas=1, type="FakeNodeGroup", id="g"
                ),
            )
        )
        store.create(
            HorizontalAutoscaler(
                metadata=ObjectMeta(name="ha"),
                spec=HorizontalAutoscalerSpec(
                    scale_target_ref=CrossVersionObjectReference(
                        kind="ScalableNodeGroup", name="g"
                    ),
                    min_replicas=1,
                    max_replicas=10_000,
                    metrics=[
                        Metric(
                            prometheus=PrometheusMetricSource(
                                query='karpenter_queue_length{name="q"}',
                                target=MetricTarget(
                                    type="AverageValue", value=target
                                ),
                            )
                        )
                    ],
                    behavior=Behavior(forecast=forecast_spec),
                ),
            )
        )
        clock = {"now": 1_000_000.0}
        service = SolverService(backend=backend)
        forecaster = (
            FleetForecaster(
                forecast_fn=service.forecast,
                clock=lambda: clock["now"],
                capacity=64,
            )
            if forecast_spec is not None
            else None
        )
        autoscaler = BatchAutoscaler(
            MetricsClientFactory(registry=registry),
            store,
            clock=lambda: clock["now"],
            decider=service.decide,
            forecaster=forecaster,
        )
        desired: List[int] = []
        try:
            for tick in range(ticks):
                gauge.set("q", "default", metric_at(tick))
                ha = store.get("HorizontalAutoscaler", "default", "ha")
                errors = autoscaler.reconcile_batch([ha])
                error = errors[("default", "ha")]
                if error is not None:
                    raise error
                store.patch_status(ha)
                desired.append(
                    store.get_scale(
                        "ScalableNodeGroup", "default", "g"
                    ).spec_replicas
                )
                clock["now"] += interval_s
        finally:
            service.close()
        dispatches = (
            service.stats.forecast_dispatches if forecaster else 0
        )
        return desired, dispatches

    spec = ForecastSpec(
        horizon_seconds=horizon_s, model=model, min_samples=min_samples
    )
    proactive, dispatches = replay(spec)
    reactive, _ = replay(None)

    peak = max(reactive)

    def first_at(seq, level):
        return next(
            (i for i, v in enumerate(seq) if v is not None and v >= level),
            None,
        )

    milestones = {}
    leads = []
    for pct in (25, 50, 75, 100):
        level = max(1, int(round(peak * pct / 100.0)))
        p, r = first_at(proactive, level), first_at(reactive, level)
        milestones[f"{pct}%"] = {
            "replicas": level,
            "proactive_tick": p,
            "reactive_tick": r,
            "lead_ticks": (r - p) if p is not None and r is not None else None,
        }
        if p is not None and r is not None:
            leads.append(r - p)
    mean_lead = (sum(leads) / len(leads)) if leads else 0.0
    return {
        "config": {
            "ticks": ticks,
            "interval_s": interval_s,
            "horizon_s": horizon_s,
            "model": model,
            "ramp": f"{base} -> {base + amplitude} over ticks "
                    f"[{ramp_start}, {ramp_start + ramp_ticks}]",
            "target": target,
            "seed": seed,
        },
        "proactive_desired": proactive,
        "reactive_desired": reactive,
        "milestones": milestones,
        "mean_lead_ticks": round(mean_lead, 2),
        "mean_lead_seconds": round(mean_lead * interval_s, 1),
        "fixed_point": {
            "proactive": proactive[-1],
            "reactive": reactive[-1],
            "identical": proactive[-1] == reactive[-1],
        },
        "forecast_dispatches": dispatches,
    }


# -- spot-reclaim storm replay (--simulate --preempt) -------------------------


def _storm_world(
    on_demand_nodes: int, spot_nodes: int, node_cpu: float,
    default_priority: int,
):
    """The pre-storm fleet: an on-demand pool and a spot pool, each with
    its own pendingCapacity producer + ScalableNodeGroup; spot nodes run
    priority-0 batch, on-demand nodes run priority-100 services beside
    some batch, everything ~75% utilized."""
    from karpenter_tpu.api.core import (
        Container,
        Node,
        NodeCondition,
        NodeSpec,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from karpenter_tpu.api.metricsproducer import (
        MetricsProducer,
        MetricsProducerSpec,
        PendingCapacitySpec,
    )
    from karpenter_tpu.api.scalablenodegroup import (
        ScalableNodeGroup,
        ScalableNodeGroupSpec,
    )
    from karpenter_tpu.store import Store
    from karpenter_tpu.utils.quantity import Quantity

    q = lambda v: Quantity.parse(str(v))  # noqa: E731

    def make_node(name, labels):
        return Node(
            metadata=ObjectMeta(name=name, labels=dict(labels)),
            spec=NodeSpec(),
            status=NodeStatus(
                allocatable={
                    "cpu": q(node_cpu),
                    "memory": q(f"{int(node_cpu * 2)}Gi"),
                    "pods": q(64),
                },
                conditions=[NodeCondition("Ready", "True")],
            ),
        )

    def make_pod(name, node_name, priority):
        return Pod(
            metadata=ObjectMeta(name=name),
            spec=PodSpec(
                node_name=node_name,
                priority=priority,
                containers=[
                    Container(
                        requests={"cpu": q(1), "memory": q("1Gi")}
                    )
                ],
            ),
        )

    store = Store()
    pools = {
        "od": ({"pool": "od"}, on_demand_nodes),
        "spot": (
            {"pool": "spot", "karpenter.sh/capacity-type": "spot"},
            spot_nodes,
        ),
    }
    per_node = max(2, int(node_cpu))  # fully-packed nodes: the storm
    # must CONTEND — free slack would let the bind pass absorb the
    # displaced services and the eviction path would never exercise
    for pool, (labels, count) in pools.items():
        store.create(
            MetricsProducer(
                metadata=ObjectMeta(name=pool),
                spec=MetricsProducerSpec(
                    pending_capacity=PendingCapacitySpec(
                        node_selector={"pool": pool},
                        node_group_ref=f"{pool}-group",
                    )
                ),
            )
        )
        store.create(
            ScalableNodeGroup(
                metadata=ObjectMeta(name=f"{pool}-group"),
                spec=ScalableNodeGroupSpec(
                    replicas=count, type="FakeNodeGroup",
                    id=f"{pool}-group",
                    preemptible=(pool == "spot"),
                ),
            )
        )
        for n in range(count):
            node_name = f"{pool}-{n:03d}"
            store.create(make_node(node_name, labels))
            for i in range(per_node):
                # on-demand nodes run mostly services (2/3) over batch;
                # spot nodes run a couple of cost-optimized services
                # (the pods the storm displaces and preemption rescues)
                # over batch
                if pool == "od":
                    is_service = i < (2 * per_node) // 3
                else:
                    is_service = i < 2
                priority = 100 if is_service else default_priority
                store.create(
                    make_pod(
                        f"{pool}-{n:03d}-p{i}", node_name, priority
                    )
                )
    return store, pools, make_node


def _reclaim_wave(store, spot_nodes: int, fraction: float, rng):
    """Seeded spot reclaim: the provider takes `fraction` of the spot
    pool; each taken node vanishes and its pods go pending (the
    workload controllers re-create them unbound)."""
    taken = sorted(
        rng.choice(
            spot_nodes,
            size=max(1, int(round(spot_nodes * fraction))),
            replace=False,
        )
    )
    displaced = 0
    for n in taken:
        name = f"spot-{int(n):03d}"
        for pod in store.pods_on_node(name):
            pod.spec.node_name = ""
            pod.status.phase = "Pending"
            store.update(pod)
            displaced += 1
        key = next(
            (k for k in store.keys("Node") if k[2] == name), None
        )
        if key is not None:
            store.delete(*key)
    return len(taken), displaced


def _node_takes(labels: dict, cap: dict, pod, needs: dict) -> bool:
    """One (pod, node) first-fit check: selector match + capacity."""
    selector = pod.spec.node_selector
    if selector and any(
        labels.get(k) != v for k, v in selector.items()
    ):
        return False
    return all(cap.get(r, 0.0) >= v for r, v in needs.items())


def _bind_state(store, default_priority: int):
    """(free capacity by node, labels by node, pending pods in
    priority-then-name order) — the deterministic inputs of one bind
    pass."""
    from karpenter_tpu.api.core import effective_priority
    from karpenter_tpu.consolidation.planner import cluster_view

    view = cluster_view(store)
    free = {
        nv.name: dict(nv.free) for nv in view.nodes if nv.receiver
    }
    labels = {
        nv.name: dict(nv.node.metadata.labels) for nv in view.nodes
    }
    pending = sorted(
        (p for p in store.list("Pod") if is_pending(p)),
        key=lambda p: (
            -effective_priority(p, default=default_priority),
            p.metadata.name,
        ),
    )
    return free, labels, pending


def _bind_pending(store, default_priority: int) -> int:
    """Toy first-fit scheduler pass: bind pending pods (highest
    priority first) onto pool-matching nodes with free capacity —
    deterministic, so the replay's recovery ticks are reproducible."""
    free, labels, pending = _bind_state(store, default_priority)
    bound = 0
    for pod in pending:
        needs = {
            r: quant.to_float()
            for r, quant in pod.effective_requests().items()
        }
        needs["pods"] = 1.0
        for name in sorted(free):
            if not _node_takes(labels[name], free[name], pod, needs):
                continue
            for r, v in needs.items():
                free[name][r] = free[name].get(r, 0.0) - v
            pod.spec.node_name = name
            pod.status.phase = "Running"
            store.update(pod)
            bound += 1
            break
    return bound


def simulate_preempt(  # lint: allow-complexity — scenario assembly: storm + replay loop + report
    on_demand_nodes: int = 4,
    spot_nodes: int = 8,
    node_cpu: float = 8.0,
    ticks: int = 24,
    interval_s: float = 10.0,
    reclaim_tick: int = 3,
    reclaim_fraction: float = 0.5,
    provision_lag: int = 4,
    preempt_budget: int = 8,
    default_priority: int = 0,
    seed: int = 0,
    backend: str = "xla",
) -> dict:
    """Seeded spot-reclaim-storm replay (docs/preemption.md
    "Dry-running"): a mixed on-demand/spot fleet loses a seeded
    fraction of its spot pool in one wave; displaced priority-100
    services and priority-0 batch go pending together. Each tick runs
    the REAL preemption engine (budgeted evictions through
    SolverService.preempt), a deterministic first-fit bind pass (the
    scheduler stand-in), and the pending-capacity scale-up signal with
    `provision_lag`-tick node arrivals — so the report shows the
    trade the subsystem exists for: services recover via eviction in
    ~1 tick while batch waits for provisioned capacity. Self-contained
    and mutation-free toward any real cluster (own in-memory store)."""
    from karpenter_tpu.preemption import (
        PreemptionConfig,
        PreemptionEngine,
    )
    from karpenter_tpu.solver import SolverService

    rng = np.random.RandomState(seed)
    store, pools, make_node = _storm_world(
        on_demand_nodes, spot_nodes, node_cpu, default_priority
    )
    clock = {"now": 1_000_000.0}
    service = SolverService(backend=backend)
    engine = PreemptionEngine(
        store,
        service,
        config=PreemptionConfig(
            plan_interval_s=0.0,
            budget_per_group=preempt_budget,
            hold_s=2 * interval_s,
            default_priority=default_priority,
            backend=backend,
        ),
        clock=lambda: clock["now"],
    )
    trail = []
    evictions_total = 0
    scale_ups_total = 0
    arrivals = []  # (due_tick, pool)
    reclaimed = displaced = 0
    service_recovery = full_recovery = None
    try:
        for tick in range(ticks):
            if tick == reclaim_tick:
                reclaimed, displaced = _reclaim_wave(
                    store, spot_nodes, reclaim_fraction, rng
                )
            for due, pool in [a for a in arrivals if a[0] == tick]:
                labels, _ = pools[pool]
                scale_ups_total += 1
                store.create(
                    make_node(f"{pool}-new-{tick:02d}-{scale_ups_total:03d}", labels)
                )
            arrivals = [a for a in arrivals if a[0] > tick]

            bound_before = {
                (p.metadata.namespace, p.metadata.name): p
                for p in store.list("Pod")
                if p.spec.node_name
            }
            plans = engine.plan(clock["now"])
            evicted_keys = [
                key
                for p in plans.values()
                if p
                for key in p["evictions"]
            ]
            evictions_total += len(evicted_keys)
            # the workload-controller analog: an evicted pod's owner
            # re-creates it unbound — it re-enters the pending set and
            # rides the ordinary bind/scale-up path
            import dataclasses as _dc

            for key in evicted_keys:
                old = bound_before[key]
                replacement = _dc.replace(old)
                replacement.metadata = _dc.replace(
                    old.metadata, name=f"{key[1]}-r{tick}",
                    resource_version="",
                )
                replacement.spec = _dc.replace(
                    old.spec, node_name=""
                )
                replacement.status = _dc.replace(
                    old.status, phase="Pending"
                )
                store.create(replacement)
            _bind_pending(store, default_priority)

            report = simulate(store, solver=service.solve)
            needed = {
                pool: report["groups"][f"default/{pool}"][
                    "additional_nodes_needed"
                ]
                for pool in pools
            }
            for pool, n in needed.items():
                outstanding = sum(1 for _, p in arrivals if p == pool)
                for _ in range(max(0, n - outstanding)):
                    arrivals.append((tick + provision_lag, pool))

            pending = [
                p for p in store.list("Pod") if is_pending(p)
            ]
            high = sum(
                1 for p in pending if (p.spec.priority or 0) > 0
            )
            if service_recovery is None and tick >= reclaim_tick and high == 0:
                service_recovery = tick
            if full_recovery is None and tick >= reclaim_tick and not pending:
                full_recovery = tick
            trail.append(
                {
                    "tick": tick,
                    "pending": len(pending),
                    "pending_high_priority": high,
                    "evictions": len(evicted_keys),
                    "scale_up_signal": dict(needed),
                }
            )
            clock["now"] += interval_s
    finally:
        service.close()
    return {
        "config": {
            "on_demand_nodes": on_demand_nodes,
            "spot_nodes": spot_nodes,
            "node_cpu": node_cpu,
            "reclaim": f"{reclaimed} spot nodes at tick {reclaim_tick} "
                       f"({displaced} pods displaced)",
            "provision_lag_ticks": provision_lag,
            "preempt_budget": preempt_budget,
            "seed": seed,
        },
        "ticks": trail,
        "evictions_total": evictions_total,
        "scale_ups_total": scale_ups_total,
        "service_recovery_tick": service_recovery,
        "full_recovery_tick": full_recovery,
        "recovery_ticks_after_reclaim": (
            None
            if full_recovery is None
            else full_recovery - reclaim_tick
        ),
        "preempt_dispatches": service.stats.preempt_dispatches,
    }


def simulate_restart_storm(  # lint: allow-complexity — scenario assembly: crash/reboot cycles + convergence + report
    nodes: int = 5,
    crashes: int = 3,
    seed: int = 0,
    journal_dir: Optional[str] = None,
    warmup_ticks: int = 1,
) -> dict:
    """Seeded restart-storm replay (docs/resilience.md "Crash
    recovery"): a consolidating fleet is repeatedly SIGKILLed
    mid-drain — alternating (seeded) between a kill after the replica
    decrement landed and a kill inside actuation before it — and
    rebooted from the protective-state journal each time. The report
    pins the crash-safety contract end to end: every completed drain
    actuated EXACTLY once across all incarnations (no duplicate cloud
    writes), restored nodes resumed their FSM phase instead of being
    re-cordoned, the fence generation climbed once per boot, and a
    stale-incarnation replay probe at the end was fence-rejected
    instead of applied. Self-contained: own in-memory store, fake
    provider, fake clock, and (by default) a temporary journal dir."""
    import shutil
    import tempfile

    from karpenter_tpu.api.core import (
        Container,
        Node,
        NodeCondition,
        NodeSpec,
        NodeStatus,
        ObjectMeta,
        Pod,
        PodSpec,
    )
    from karpenter_tpu.api.metricsproducer import (
        MetricsProducer,
        MetricsProducerSpec,
        PendingCapacitySpec,
    )
    from karpenter_tpu.api.scalablenodegroup import (
        ScalableNodeGroup,
        ScalableNodeGroupSpec,
    )
    from karpenter_tpu.faults import (
        FaultRegistry,
        ProcessCrash,
        install,
        uninstall,
    )
    from karpenter_tpu.runtime import KarpenterRuntime, Options
    from karpenter_tpu.store import Store
    from karpenter_tpu.utils.quantity import Quantity

    rng = np.random.RandomState(seed)
    own_dir = journal_dir is None
    journal_dir = journal_dir or tempfile.mkdtemp(prefix="karpenter-storm-")

    q = Quantity.parse
    store = Store()
    provider = _recording_provider()
    provider.node_replicas["grp-id"] = nodes
    clock = {"now": 1_000_000.0}
    store.create(
        MetricsProducer(
            metadata=ObjectMeta(name="pc"),
            spec=MetricsProducerSpec(
                pending_capacity=PendingCapacitySpec(
                    node_selector={"pool": "a"}, node_group_ref="grp"
                )
            ),
        )
    )
    store.create(
        ScalableNodeGroup(
            metadata=ObjectMeta(name="grp"),
            spec=ScalableNodeGroupSpec(
                replicas=nodes, type="FakeNodeGroup", id="grp-id"
            ),
        )
    )
    for i in range(nodes):
        store.create(
            Node(
                metadata=ObjectMeta(name=f"n{i}", labels={"pool": "a"}),
                spec=NodeSpec(),
                status=NodeStatus(
                    allocatable={
                        "cpu": q("8"), "memory": q("16Gi"),
                        "pods": q("16"),
                    },
                    conditions=[NodeCondition("Ready", "True")],
                ),
            )
        )
    store.create(  # one bound pod anchors n0: only empty nodes drain
        Pod(
            metadata=ObjectMeta(name="p0"),
            spec=PodSpec(
                node_name="n0",
                containers=[Container(requests={"cpu": q("1")})],
            ),
        )
    )

    def boot():
        return KarpenterRuntime(
            Options(
                consolidate=True,
                journal_dir=journal_dir,
                recovery_warmup_ticks=warmup_ticks,
            ),
            store=store,
            cloud_provider_factory=provider,
            clock=lambda: clock["now"],
        )

    def kill(rt):  # SIGKILL analog: no graceful checkpoint
        rt.solver_service.close()
        rt.recovery.journal.close()

    def tick(rt, advance=61.0):
        clock["now"] += advance
        rt.manager._due = {k: 0.0 for k in rt.manager._due}
        rt.manager.reconcile_all()

    cordons_planned = 0  # across ALL incarnations (re-cordon detector)
    crash_sites = []
    rt = boot()
    try:
        for crash in range(crashes):
            engine = rt.consolidation
            engine.plan(clock["now"])  # first sight starts churn clocks
            clock["now"] += engine.config.cooldown_s + 1
            engine.plan(clock["now"])
            clock["now"] += engine.config.verify_s + 1
            site = rng.choice(["after-decrement", "mid-actuate"])
            crash_sites.append(str(site))
            if site == "mid-actuate":
                install(FaultRegistry(seed=seed + crash))
                from karpenter_tpu.faults import active

                active().plan(
                    "process.crash.drain", mode="crash", times=1
                )
                try:
                    engine.plan(clock["now"])
                except ProcessCrash:
                    pass
                uninstall()
            else:
                engine.plan(clock["now"])  # decrement lands, then "die"
            cordons_planned += int(
                rt.registry.gauge(
                    "consolidation", "drains_planned_total"
                ).get("-", "-")
                or 0
            )
            kill(rt)
            rt = boot()
            # drain the FULL warm-up (however many ticks were asked
            # for) so the next cycle's planning is actually admitted
            for _ in range(max(1, warmup_ticks)):
                tick(rt)
            if site == "mid-actuate":
                # the decrement never landed: the restored DRAINING
                # entry times out and the node returns to service
                clock["now"] += rt.consolidation.config.drain_timeout_s + 1
            tick(rt)
        # run the final incarnation clean to convergence: every empty
        # node drains, only the pod's node remains
        for _ in range(8 * nodes):
            if provider.node_replicas["grp-id"] <= 1:
                break
            engine = rt.consolidation
            clock["now"] += engine.config.cooldown_s + 1
            engine.plan(clock["now"])
            clock["now"] += engine.config.verify_s + 1
            engine.plan(clock["now"])
            tick(rt)
        cordons_planned += int(
            rt.registry.gauge(
                "consolidation", "drains_planned_total"
            ).get("-", "-")
            or 0
        )

        drains_completed = nodes - provider.node_replicas["grp-id"]

        # stale-incarnation probe: a NEW incarnation boots (bumping the
        # fence) and actuates a fresh decision; then the prior
        # incarnation — now a split-brain zombie — replays a dead one.
        # The provider must fence-reject the stale stamp, not apply it.
        successor = boot()  # `rt` is now the stale incarnation
        fresh = store.get("ScalableNodeGroup", "default", "grp")
        fresh.spec.replicas = provider.node_replicas["grp-id"] + 1
        store.update(fresh)
        tick(successor)  # the successor's write records its generation
        replicas_after_successor = provider.node_replicas["grp-id"]
        stale_ctrl = rt.manager._controllers[1]
        probe = store.get("ScalableNodeGroup", "default", "grp")
        probe.spec.replicas = nodes  # a long-dead scale-up decision
        try:
            stale_ctrl.reconcile(probe)
        except Exception:  # noqa: BLE001 — the rejection surfaces as a
            pass  # reconcile failure; the provider state is the proof
        stale_applied = (
            provider.node_replicas["grp-id"] != replicas_after_successor
        )
        fence_generation = successor.recovery.fence.generation
        successor.close()
        return {
            "config": {
                "nodes": nodes,
                "crashes": crashes,
                "seed": seed,
                "warmup_ticks": warmup_ticks,
            },
            "crash_sites": crash_sites,
            "restarts": crashes + 1,
            "fence_generation": fence_generation,
            "fence_rejections": provider.fence_validator.rejections,
            "stale_replay_applied": stale_applied,
            "actuations": list(provider.writes),
            # a duplicate is the SAME (group, count) write landing again
            # with no other transition in between — a replayed decision,
            # not a later legitimate return to a previous size
            "duplicate_actuations": sum(
                1
                for a, b in zip(
                    provider.writes, provider.writes[1:]
                )
                if a == b
            ),
            "drains_completed": drains_completed,
            "cordons_planned": cordons_planned,
            "resumed_not_recordoned": cordons_planned == drains_completed
            + sum(1 for s in crash_sites if s == "mid-actuate"),
            "final_replicas": provider.node_replicas["grp-id"],
            "nodes_remaining": sorted(
                n.metadata.name for n in store.list("Node")
            ),
        }
    finally:
        with __import__("contextlib").suppress(Exception):
            rt.close()
        if own_dir:
            shutil.rmtree(journal_dir, ignore_errors=True)


def simulate_failover(  # lint: allow-complexity — scenario assembly: replica fleet + leader kill + handoff audit + report
    tenants: int = 16,
    replicas: int = 3,
    partitions: Optional[int] = None,
    ticks: int = 40,
    kill_tick: int = 12,
    seed: int = 0,
    lease_duration: float = 5.0,
    tick_s: float = 1.0,
    warmup_ticks: int = 1,
    journal_dir: Optional[str] = None,
) -> dict:
    """Seeded leader-kill failover replay (docs/resilience.md
    "Replicated control plane"): N tenants partitioned across R
    leader-elected replicas, each tenant's demand a seeded random walk,
    each owner journaling its scale intent and actuating through a
    fence-validated per-tenant cloud. Mid-storm the biggest owner (the
    "leader") is SIGKILLed via the `replica.crash.*` chaos point — no
    graceful release, its leases must expire. The report pins the
    failover contract end to end: survivors adopt the victim's
    partitions (fenced handoff: fence generation bump + journal replay
    + per-tenant warm-up), every tenant reconverges to the no-fault
    fixed point (demand is a pure function of the tick, so the
    no-fault state IS the desired trace), zero duplicate and zero lost
    `set_replicas` writes across the handoff (journal-audited), and
    the deposed replica's late write is fence-rejected — not applied.
    Self-contained: own store, scripted clock, temp journal root."""
    import contextlib
    import hashlib
    import json
    import shutil
    import tempfile

    from karpenter_tpu.faults import (
        FaultRegistry,
        ProcessCrash,
        install,
        uninstall,
    )
    from karpenter_tpu.recovery.fence import (
        FenceRejectedError,
        FenceValidator,
    )
    from karpenter_tpu.replication import (
        ReplicatedControlPlane,
        crash_plan,
    )
    from karpenter_tpu.store import Store

    partitions = partitions or max(4, 2 * replicas)
    rng = np.random.RandomState(seed)
    own_dir = journal_dir is None
    journal_root = journal_dir or tempfile.mkdtemp(
        prefix="karpenter-failover-"
    )

    tenant_ids = [f"t{i:03d}" for i in range(tenants)]
    replica_ids = [f"replica-{i}" for i in range(replicas)]
    # seeded per-tenant demand walk: desired[tenant][tick], the pure
    # function both arms (and the convergence check) share
    desired = {}
    for tenant in tenant_ids:
        level = int(rng.randint(1, 9))
        walk = []
        for _ in range(ticks + 1):
            if rng.rand() < 0.35:
                level = int(np.clip(level + rng.randint(-2, 3), 1, 12))
            walk.append(level)
        desired[tenant] = walk

    class _TenantCloud:
        """One tenant's provider edge: fence-validated writes, the
        exactly-once ledger the audit reads."""

        def __init__(self):
            self.validator = FenceValidator()
            self.replicas = 0
            self.writes = []

        def set_replicas(self, count, token=None):
            self.validator.admit(token)
            self.replicas = count
            self.writes.append(count)

    clouds = {tenant: _TenantCloud() for tenant in tenant_ids}
    clock = {"now": 1_000_000.0}

    def journal_dir_for(tenant):
        import os as _os

        path = _os.path.join(journal_root, "tenants", tenant)
        _os.makedirs(path, exist_ok=True)
        return path

    def build_plane(replica_id):
        return ReplicatedControlPlane(
            store,
            replica_id=replica_id,
            partitions=partitions,
            lease_duration=lease_duration,
            tenants_source=lambda: tenant_ids,
            journal_dir_for=journal_dir_for,
            validator_for=lambda tenant: clouds[tenant].validator,
            warmup_ticks=warmup_ticks,
            clock=lambda: clock["now"],
        )

    store = Store()
    planes = {rid: build_plane(rid) for rid in replica_ids}
    dead = set()
    registry = FaultRegistry(seed=seed)
    install(registry)

    def serve(plane, tick):
        """One replica's serving pass: journal intent, then actuate
        every owned tenant toward this tick's desired level. Reading
        the cloud before writing is the exactly-once seam: a handoff
        adopter skips writes its predecessor already landed."""
        for tenant in tenant_ids:
            handoff = plane.handoff_for(tenant)
            if handoff is None or handoff.released:
                continue
            want = desired[tenant][tick]
            cloud = clouds[tenant]
            if cloud.replicas == want:
                continue
            if handoff.recovery is not None:
                handoff.recovery.handle("intent").set(
                    (tenant,), {"desired": int(want)}
                )
            cloud.set_replicas(want, token=handoff.token())

    victim = None
    victim_partitions = []
    victim_tenants = []
    victim_handoffs = {}
    adoption_tick = {}  # tenant -> first tick a survivor adopted it
    recovered_tick = {}  # tenant -> first post-kill tick back at desired
    stale_probe = {"done": False, "rejected": False, "applied": False}
    fence_rejections = 0
    try:
        for tick in range(1, ticks + 1):
            clock["now"] += tick_s
            if tick == kill_tick:
                # the leader: the replica owning the most partitions
                victim = max(
                    (rid for rid in replica_ids if rid not in dead),
                    key=lambda rid: (
                        len(planes[rid].leases.owned), rid
                    ),
                )
                victim_partitions = sorted(planes[victim].leases.owned)
                victim_tenants = sorted(
                    t for t in tenant_ids if planes[victim].owns(t)
                )
                # retain the victim's handoffs: the zombie's stale
                # fence tokens are the late-write probe's ammunition
                victim_handoffs = dict(planes[victim].handoffs)
                crash_plan(registry, victim, times=1)
            for rid in replica_ids:
                if rid in dead:
                    continue
                try:
                    planes[rid].on_tick()
                except ProcessCrash:
                    dead.add(rid)  # SIGKILL: no release, no checkpoint
                    continue
                serve(planes[rid], tick)
                for tenant in victim_tenants:
                    if (
                        tenant not in adoption_tick
                        and planes[rid].handoff_for(tenant) is not None
                    ):
                        adoption_tick[tenant] = tick
            # blackout ends when a survivor has adopted the tenant AND
            # its cloud is back at this tick's desired level
            for tenant in victim_tenants:
                if (
                    tenant not in recovered_tick
                    and tenant in adoption_tick
                    and clouds[tenant].replicas == desired[tenant][tick]
                ):
                    recovered_tick[tenant] = tick
            # the deposed replica's in-flight write lands AFTER a
            # survivor claimed the tenant's fence generation: it must
            # be rejected, not applied
            if (
                victim_tenants
                and not stale_probe["done"]
                and victim_tenants[0] in adoption_tick
            ):
                stale_probe["done"] = True
                probe_tenant = victim_tenants[0]
                cloud = clouds[probe_tenant]
                before = cloud.replicas
                stale = victim_handoffs.get(probe_tenant)
                try:
                    cloud.set_replicas(
                        desired[probe_tenant][kill_tick],
                        token=stale.token() if stale else None,
                    )
                except FenceRejectedError:
                    stale_probe["rejected"] = True
                    if stale is not None and stale.recovery is not None:
                        stale.recovery.count_fence_rejection()
                stale_probe["applied"] = cloud.replicas != before

        # -- audits --------------------------------------------------------
        from karpenter_tpu.recovery.journal import key_str

        fence_rejections = sum(
            cloud.validator.rejections for cloud in clouds.values()
        )
        converged = all(
            clouds[t].replicas == desired[t][ticks] for t in tenant_ids
        )
        # journal audit: every tenant's LAST journaled intent must have
        # landed exactly once — the live owner's replayed+mirrored table
        # IS what a successor would replay, so compare it to the cloud
        lost = 0
        for tenant in tenant_ids:
            owner = next(
                (
                    rid for rid in replica_ids
                    if rid not in dead
                    and planes[rid].handoff_for(tenant) is not None
                ),
                None,
            )
            if owner is None:
                lost += 1  # nobody serves this tenant: its writes stop
                continue
            recovery = planes[owner].handoffs[tenant].recovery
            if recovery is None:
                continue  # unfenced world: no journal to audit
            intent = recovery.table("intent").get(key_str((tenant,)))
            if intent is None:
                continue
            if clouds[tenant].replicas != intent["desired"]:
                lost += 1
        duplicates = sum(
            sum(1 for a, b in zip(c.writes, c.writes[1:]) if a == b)
            for c in clouds.values()
        )
        digest = hashlib.sha256(
            json.dumps(
                {t: clouds[t].writes for t in tenant_ids},
                sort_keys=True,
            ).encode()
        ).hexdigest()
        blackouts = sorted(
            recovered_tick.get(t, ticks) - kill_tick
            for t in victim_tenants
        ) or [0]
        p99_idx = max(0, int(np.ceil(0.99 * len(blackouts))) - 1)
        return {
            "config": {
                "tenants": tenants,
                "replicas": replicas,
                "partitions": partitions,
                "ticks": ticks,
                "kill_tick": kill_tick,
                "seed": seed,
                "lease_duration_s": lease_duration,
                "tick_s": tick_s,
                "warmup_ticks": warmup_ticks,
            },
            "victim": victim,
            "victim_partitions": victim_partitions,
            "victim_tenants": victim_tenants,
            "tenants_reassigned": sorted(adoption_tick),
            "adopters": {
                tenant: next(
                    (
                        rid for rid in replica_ids
                        if rid not in dead
                        and planes[rid].handoff_for(tenant) is not None
                    ),
                    None,
                )
                for tenant in sorted(adoption_tick)
            },
            "reconverge_ticks": (
                max(blackouts)
                if converged
                and len(recovered_tick) == len(victim_tenants)
                else None
            ),
            "converged": converged,
            "blackout_ticks_p99": blackouts[p99_idx],
            "blackout_s_p99": blackouts[p99_idx] * tick_s,
            "duplicate_actuations": duplicates,
            "lost_actuations": lost,
            "fence_rejections": fence_rejections,
            "stale_write_rejected": stale_probe["rejected"],
            "stale_write_applied": stale_probe["applied"],
            "handoffs_after_kill": len(adoption_tick),
            "fence_generations": {
                tenant: max(
                    (
                        planes[rid].handoffs[tenant].generation
                        for rid in replica_ids
                        if rid not in dead
                        and tenant in planes[rid].handoffs
                    ),
                    default=0,
                )
                for tenant in victim_tenants
            },
            "writes_digest": digest,
        }
    finally:
        uninstall(registry)
        for rid, plane in planes.items():
            with contextlib.suppress(Exception):
                if rid in dead:
                    # the zombie's open journals: close without the
                    # graceful release path (its successors own the
                    # fence now; close() would checkpoint over them)
                    for handoff in plane.handoffs.values():
                        if handoff.recovery is not None:
                            handoff.recovery.journal.close()
                else:
                    plane.close()
        if own_dir:
            shutil.rmtree(journal_root, ignore_errors=True)


def _why_report(ledger, sample: int = 8) -> dict:
    """The WHY column of a provenance-recording replay
    (docs/observability.md "Decision provenance"): stage totals over
    every recorded decision plus compact rows — the first record of
    each distinct winning stage and the last `sample` records — each
    answering "why did this group scale to N" in one line."""
    records = ledger.query()

    def row(index: int, record: dict) -> dict:
        return {
            "tick_record": index,
            "tenant": record["tenant"] or None,
            "group": record["group"],
            "why": record["winning_stage"],
            "desired": record["final_desired"],
            "base": record["base_desired"],
            "observed": record["observed"],
            "forecast": record["forecast_value"],
            "rung": record["solver_rung"] or None,
            "trace": record["trace"] or None,
        }

    by_stage: Dict[str, int] = {}
    firsts: Dict[str, dict] = {}
    for index, record in enumerate(records):
        stage = record["winning_stage"]
        by_stage[stage] = by_stage.get(stage, 0) + 1
        if stage not in firsts:
            firsts[stage] = row(index, record)
    tail = [
        row(len(records) - len(records[-sample:]) + i, record)
        for i, record in enumerate(records[-sample:])
    ]
    return {
        "records": len(records),
        "dropped": ledger.records_dropped,
        "by_stage": by_stage,
        "first_by_stage": firsts,
        "why": tail,
    }


# -- cost / warm-pool replay (--simulate --cost) ------------------------------


def _cost_world(
    warm_on: bool, initial: int, target: float, provision_lag: int,
    horizon_s: float, min_samples: int, violation_weight: float,
    max_hourly_cost: float, min_warm: int, max_warm: int, clock, backend,
    options=None,
):
    """One self-contained cost-replay world: a spot-tier node group
    behind a LAGGED provider (resizes ack immediately, PROVISIONED
    capacity trails scale-ups by `provision_lag` ticks — the lead time
    warm pools exist to hide), an SLO- and forecast-enabled autoscaler,
    and a full KarpenterRuntime so the warm target rides the real
    fenced SNG actuation path and the reconcile tracer's e2e histogram
    fills. Returns (runtime, provider, group_id)."""
    from karpenter_tpu.api.core import ObjectMeta
    from karpenter_tpu.api.horizontalautoscaler import (
        Behavior,
        CrossVersionObjectReference,
        ForecastSpec,
        HorizontalAutoscaler,
        HorizontalAutoscalerSpec,
        Metric,
        MetricTarget,
        PrometheusMetricSource,
        SLOSpec,
    )
    from karpenter_tpu.api.scalablenodegroup import (
        ScalableNodeGroup,
        ScalableNodeGroupSpec,
        WarmPoolSpec,
    )
    from karpenter_tpu.cloudprovider.fake import FakeFactory, FakeNodeGroup
    from karpenter_tpu.cost import INSTANCE_TYPE_ANNOTATION
    from karpenter_tpu.runtime import KarpenterRuntime, Options
    from karpenter_tpu.store import Store

    class _LaggedGroup(FakeNodeGroup):
        def set_replicas(self, count, token=None):
            super().set_replicas(count, token=token)
            f = self._factory
            f.writes.append((f.tick_now, self._id, count))
            have = f.provisioned.get(self._id, 0)
            # ANY write supersedes in-flight grows above its target —
            # including a shrink that still lands above provisioned
            # capacity, which must not leave a larger stale grow alive
            # to overshoot later
            f.pending = [
                p for p in f.pending
                if p[1] != self._id or p[2] <= count
            ]
            if count <= have:
                # shrinks release capacity immediately
                f.provisioned[self._id] = count
            else:
                f.pending.append((f.tick_now + f.lag, self._id, count))

    class _LaggedFactory(FakeFactory):
        def __init__(self, lag):
            super().__init__()
            self.lag = lag
            self.tick_now = 0
            self.provisioned = {}
            self.pending = []  # (due_tick, group_id, count)
            self.writes = []

        def node_group_for(self, spec):
            return _LaggedGroup(self, spec.id)

        def advance(self):
            self.tick_now += 1
            for due, gid, count in list(self.pending):
                if due <= self.tick_now:
                    self.provisioned[gid] = max(
                        self.provisioned.get(gid, 0), count
                    )
            self.pending = [
                p for p in self.pending if p[0] > self.tick_now
            ]

    gid = "cost-group"
    store = Store()
    provider = _LaggedFactory(provision_lag)
    provider.node_replicas[gid] = initial
    provider.provisioned[gid] = initial
    store.create(ScalableNodeGroup(
        metadata=ObjectMeta(
            name="grp",
            # spot-tier m5.xlarge pricing (cost/model.py): the replay's
            # spot-price step multiplies the model's spot multiplier
            annotations={INSTANCE_TYPE_ANNOTATION: "m5.xlarge"},
        ),
        spec=ScalableNodeGroupSpec(
            replicas=initial, type="FakeNodeGroup", id=gid,
            preemptible=True,
            warm_pool=(
                WarmPoolSpec(min_warm=min_warm, max_warm=max_warm)
                if warm_on
                else None
            ),
        ),
    ))
    store.create(HorizontalAutoscaler(
        metadata=ObjectMeta(name="ha"),
        spec=HorizontalAutoscalerSpec(
            scale_target_ref=CrossVersionObjectReference(
                kind="ScalableNodeGroup", name="grp"
            ),
            min_replicas=1,
            max_replicas=10_000,
            metrics=[Metric(prometheus=PrometheusMetricSource(
                query='karpenter_queue_length{name="q"}',
                target=MetricTarget(type="AverageValue", value=target),
            ))],
            behavior=Behavior(
                forecast=ForecastSpec(
                    horizon_seconds=horizon_s, model="linear",
                    min_samples=min_samples,
                ),
                slo=SLOSpec(
                    violation_cost_weight=violation_weight,
                    max_hourly_cost=max_hourly_cost,
                ),
            ),
        ),
    ))
    runtime = KarpenterRuntime(
        options if options is not None else Options(),
        store=store, cloud_provider_factory=provider,
        clock=clock,
    )
    runtime.solver_service.backend = backend
    return runtime, provider, gid


def simulate_cost(  # lint: allow-complexity — scenario assembly: two replays + milestone/violation/e2e accounting
    ticks: int = 110,
    interval_s: float = 10.0,
    horizon_s: float = 60.0,
    target: float = 4.0,
    base: float = 8.0,
    amplitude: float = 120.0,
    ramp_start: int = 25,
    ramp_ticks: int = 20,
    spot_step_tick: int = 70,
    spot_step_factor: float = 3.0,
    provision_lag: int = 6,
    min_warm: int = 2,
    max_warm: int = 8,
    violation_weight: float = 50.0,
    max_hourly_cost: float = 0.0,
    min_samples: int = 4,
    seed: int = 0,
    backend: str = "xla",
    default_hourly: float = 1.0,
    spot_multiplier: float = 0.35,
    provenance: bool = False,
) -> dict:
    """Seeded cost/warm-pool replay (docs/cost.md "Dry-running"): the
    same scripted load — flat overnight base, a diurnal morning ramp,
    a mid-run SPOT-PRICE STEP (the model's spot multiplier jumps
    `spot_step_factor`x) — is driven through two otherwise-identical
    cost-aware worlds, warm pool ON vs OFF, behind a provider whose
    provisioned capacity trails accepted resizes by `provision_lag`
    ticks. The report quantifies the trade the subsystem exists for:
    the warm pool's extra hourly cost vs the PROVISIONING LEAD TIME it
    removes (capacity-coverage milestones) at equal-or-lower
    SLO-violation count, plus the karpenter_reconcile_e2e_seconds
    p50/p99 each world measured. Self-contained and mutation-free
    toward any real cluster (own stores, fake lagged provider).

    `provenance=True` additionally records the decision ledger
    (observability/provenance.py) through the warm-on world and renders
    the WHY column: per recorded tick, the winning stage (reactive /
    forecast_blend / cost_raise / cost_clamp / ...), the chosen count,
    and the solver rung — the operator-facing answer `/debug/decisions`
    serves on a live process."""
    import math as _math

    from karpenter_tpu.observability import reset_default_tracer

    rng = np.random.RandomState(seed)
    noise = rng.normal(0.0, 0.01 * amplitude, size=ticks)

    def metric_at(tick: int) -> float:
        progress = min(
            max(tick - ramp_start, 0) / max(ramp_ticks, 1), 1.0
        )
        level = base + amplitude * 0.5 * (
            1.0 - _math.cos(_math.pi * progress)
        )
        return max(0.0, level + float(noise[tick]))

    initial = max(1, int(_math.ceil(base / target)))

    def replay(warm_on: bool) -> dict:
        from karpenter_tpu.observability import reset_default_ledger
        from karpenter_tpu.runtime import Options

        reset_default_tracer()
        # the WHY column rides the warm-on world only (one ledger, one
        # narrative); provenance=False never touches the ledger, so the
        # replay stays byte-identical to previous releases
        record_why = provenance and warm_on
        if provenance:
            reset_default_ledger(enabled=record_why)
        clock = {"now": 1_000_000.0}
        runtime, provider, gid = _cost_world(
            warm_on, initial, target, provision_lag, horizon_s,
            min_samples, violation_weight, max_hourly_cost,
            min_warm, max_warm, lambda: clock["now"], backend,
            options=Options(
                cost_default_hourly=default_hourly,
                cost_spot_multiplier=spot_multiplier,
            ),
        )
        gauge = runtime.registry.register("queue", "length")
        sng = runtime.store.get("ScalableNodeGroup", "default", "grp")
        provisioned_trail, hourly_trail = [], []
        violations = shortfall = 0
        try:
            for tick in range(ticks):
                if tick == spot_step_tick:
                    runtime.cost_model.spot_multiplier *= spot_step_factor
                demand = metric_at(tick)
                gauge.set("q", "default", demand)
                runtime.manager._due = {
                    k: 0.0 for k in runtime.manager._due
                }
                runtime.manager.reconcile_all()
                provider.advance()
                clock["now"] += interval_s
                have = provider.provisioned[gid]
                provisioned_trail.append(have)
                hourly_trail.append(
                    have * runtime.cost_model.unit_cost(sng)
                )
                if have * target < demand:
                    violations += 1
                    # replica-ticks of uncovered demand: a finer,
                    # deterministic lead measure than tick counts
                    shortfall += int(
                        _math.ceil(demand / target)
                    ) - have
            hist = runtime.registry.gauge("reconcile", "e2e_seconds")
            e2e = {
                "p50_s": hist.percentile("ScalableNodeGroup", "-", 50),
                "p99_s": hist.percentile("ScalableNodeGroup", "-", 99),
                "n": hist.count("ScalableNodeGroup", "-"),
            }
            stats = runtime.solver_service.stats
            report = {
                "provisioned": provisioned_trail,
                "mean_hourly_cost": round(
                    float(np.mean(hourly_trail)), 4
                ),
                "slo_violation_ticks": violations,
                "shortfall_replica_ticks": shortfall,
                "e2e_seconds": e2e,
                "cost_dispatches": stats.cost_dispatches,
                "provider_writes": len(provider.writes),
            }
            if record_why:
                report["provenance"] = _why_report(
                    runtime.decision_ledger
                )
            return report
        finally:
            runtime.close()

    # restore the process-default ledger even if a replay raises: an
    # ENABLED default leaking out would turn on provenance for a
    # co-resident runtime that never opted in (simulate_multitenant
    # takes the same care)
    saved_ledger = None
    if provenance:
        from karpenter_tpu.observability import (
            default_ledger,
            set_default_ledger,
        )

        saved_ledger = default_ledger()
    try:
        on = replay(True)
        off = replay(False)
    finally:
        if saved_ledger is not None:
            set_default_ledger(saved_ledger)

    # capacity-coverage milestones: how many ticks after demand reached
    # a level did PROVISIONED capacity cover it — the end-to-end
    # provisioning lead the warm pool attacks
    demand_trail = [metric_at(t) for t in range(ticks)]
    peak_needed = int(_math.ceil(max(demand_trail) / target))

    def coverage_lag(provisioned, pct: int):
        level = max(1, int(round(peak_needed * pct / 100.0)))
        demand_tick = next(
            (
                t for t, d in enumerate(demand_trail)
                if _math.ceil(d / target) >= level
            ),
            None,
        )
        cover_tick = next(
            (t for t, p in enumerate(provisioned) if p >= level), None
        )
        if demand_tick is None or cover_tick is None:
            return None
        return max(0, cover_tick - demand_tick)

    milestones, lags_on, lags_off = {}, [], []
    for pct in range(10, 101, 10):
        lag_on = coverage_lag(on["provisioned"], pct)
        lag_off = coverage_lag(off["provisioned"], pct)
        milestones[f"{pct}%"] = {
            "warm_on_lag_ticks": lag_on,
            "warm_off_lag_ticks": lag_off,
        }
        if lag_on is not None and lag_off is not None:
            lags_on.append(lag_on)
            lags_off.append(lag_off)
    mean_on = (sum(lags_on) / len(lags_on)) if lags_on else 0.0
    mean_off = (sum(lags_off) / len(lags_off)) if lags_off else 0.0
    return {
        "config": {
            "ticks": ticks,
            "interval_s": interval_s,
            "horizon_s": horizon_s,
            "target": target,
            "ramp": f"{base} -> {base + amplitude} over ticks "
                    f"[{ramp_start}, {ramp_start + ramp_ticks}]",
            "spot_step": f"x{spot_step_factor} at tick {spot_step_tick}",
            "provision_lag_ticks": provision_lag,
            "warm_pool": f"[{min_warm}, {max_warm}]",
            "violation_cost_weight": violation_weight,
            "max_hourly_cost": max_hourly_cost,
            "seed": seed,
        },
        "runs": {"warm_on": on, "warm_off": off},
        "hourly_cost": {
            "warm_on_mean": on["mean_hourly_cost"],
            "warm_off_mean": off["mean_hourly_cost"],
            "warm_premium": round(
                on["mean_hourly_cost"] - off["mean_hourly_cost"], 4
            ),
        },
        "slo_violations": {
            "warm_on": on["slo_violation_ticks"],
            "warm_off": off["slo_violation_ticks"],
            "warm_on_shortfall_replica_ticks": on[
                "shortfall_replica_ticks"
            ],
            "warm_off_shortfall_replica_ticks": off[
                "shortfall_replica_ticks"
            ],
        },
        "provisioning_lead": {
            "milestones": milestones,
            "warm_on_mean_lag_ticks": round(mean_on, 2),
            "warm_off_mean_lag_ticks": round(mean_off, 2),
            "reduction_ticks": round(mean_off - mean_on, 2),
            "reduction_seconds": round(
                (mean_off - mean_on) * interval_s, 1
            ),
        },
        "e2e_seconds": {
            "warm_on": on["e2e_seconds"],
            "warm_off": off["e2e_seconds"],
        },
    }


def _poolgroup_world(grouped: bool, target: float, budget: float, clock,
                     backend: str):
    """One self-contained disaggregated-serving world: a prefill pool
    and a decode pool (two SNG/HA pairs), one PoolGroup declaring the
    decode:prefill ratio band [2:1, 4:1] and a shared hourly budget,
    behind a full KarpenterRuntime. `grouped` toggles --poolgroups; the
    PoolGroup object is created either way, so the uncoordinated arm is
    the exact byte-identical ungrouped plane ignoring it. Returns
    (runtime, ratio) where ratio is the declared band for the caller's
    violation accounting."""
    from karpenter_tpu.api.core import ObjectMeta
    from karpenter_tpu.api.horizontalautoscaler import (
        Behavior,
        CrossVersionObjectReference,
        HorizontalAutoscaler,
        HorizontalAutoscalerSpec,
        Metric,
        MetricTarget,
        PrometheusMetricSource,
        SLOSpec,
    )
    from karpenter_tpu.api.poolgroup import (
        PoolGroup,
        PoolGroupSpec,
        PoolMember,
        RatioConstraint,
    )
    from karpenter_tpu.api.scalablenodegroup import (
        ScalableNodeGroup,
        ScalableNodeGroupSpec,
    )
    from karpenter_tpu.cloudprovider.fake import FakeFactory
    from karpenter_tpu.runtime import KarpenterRuntime, Options
    from karpenter_tpu.store import Store

    store = Store()
    provider = FakeFactory()
    for name, queue, initial in (("prefill", "qp", 10), ("decode", "qd", 20)):
        gid = f"g-{name}"
        provider.node_replicas[gid] = initial
        store.create(ScalableNodeGroup(
            metadata=ObjectMeta(name=gid),
            spec=ScalableNodeGroupSpec(
                replicas=initial, type="FakeNodeGroup", id=gid,
            ),
        ))
        store.create(HorizontalAutoscaler(
            metadata=ObjectMeta(name=name),
            spec=HorizontalAutoscalerSpec(
                scale_target_ref=CrossVersionObjectReference(
                    kind="ScalableNodeGroup", name=gid
                ),
                min_replicas=1,
                max_replicas=10_000,
                metrics=[Metric(prometheus=PrometheusMetricSource(
                    query=f'karpenter_queue_length{{name="{queue}"}}',
                    target=MetricTarget(type="AverageValue", value=target),
                ))],
                behavior=Behavior(slo=SLOSpec(violation_cost_weight=100.0)),
            ),
        ))
    ratio = RatioConstraint(
        numerator="decode", denominator="prefill",
        min_numerator=2, min_denominator=1,
        max_numerator=4, max_denominator=1,
    )
    store.create(PoolGroup(
        metadata=ObjectMeta(name="serving"),
        spec=PoolGroupSpec(
            pools=[
                PoolMember(name="prefill", role="prefill"),
                PoolMember(name="decode", role="decode"),
            ],
            ratios=[ratio],
            max_hourly_cost=budget,
        ),
    ))
    runtime = KarpenterRuntime(
        Options(poolgroups=grouped),
        store=store, cloud_provider_factory=provider, clock=clock,
    )
    runtime.solver_service.backend = backend
    return runtime, ratio


def simulate_poolgroups(  # lint: allow-complexity — scenario assembly: two replays + band/budget accounting
    ticks: int = 60,
    interval_s: float = 10.0,
    target: float = 4.0,
    prefill_queue: float = 40.0,
    decode_base: float = 80.0,
    decode_peak: float = 240.0,
    ramp_start: int = 15,
    ramp_ticks: int = 20,
    budget: float = 90.0,
    seed: int = 0,
    backend: str = "xla",
) -> dict:
    """Seeded traffic-mix-shift replay (docs/poolgroups.md): the same
    scripted DECODE-HEAVY STORM — prefill demand flat, decode demand
    ramping 3x over `ramp_ticks` ticks, the disaggregated-serving mix
    shift "Taming the Chaos" studies — is driven through two otherwise
    identical prefill/decode worlds, --poolgroups ON vs OFF. The
    declared coupling is a decode:prefill ratio band [2:1, 4:1] plus a
    shared hourly budget: the coordinated arm's joint allocator
    rebalances pool-to-pool (raising prefill beyond what its own flat
    queue asks, because decode's storm pulls the ratio toward the upper
    bound) and must HOLD the band through the storm under the cap; the
    uncoordinated arm scales each pool from its own queue alone and
    violates the band for the storm's whole plateau. Violations are
    counted by exact integer cross-multiplication on the actuated
    per-tick replica counts — the same arithmetic the joint kernel
    enforces on device. Self-contained and mutation-free toward any
    real cluster (own stores, fake provider); the storm's per-tick
    drift stays inside the joint candidate ladder's reach, so the
    coordinated arm repairs within the tick the drift lands."""
    import math as _math

    rng = np.random.RandomState(seed)
    noise_p = rng.normal(0.0, 0.25, size=ticks)
    noise_d = rng.normal(0.0, 0.25, size=ticks)

    def queues_at(tick: int):
        progress = min(
            max(tick - ramp_start, 0) / max(ramp_ticks, 1), 1.0
        )
        qd = decode_base + (decode_peak - decode_base) * 0.5 * (
            1.0 - _math.cos(_math.pi * progress)
        )
        return (
            max(0.0, prefill_queue + float(noise_p[tick])),
            max(0.0, qd + float(noise_d[tick])),
        )

    def replay(grouped: bool) -> dict:
        clock = {"now": 1_000_000.0}
        runtime, ratio = _poolgroup_world(
            grouped, target, budget, lambda: clock["now"], backend
        )
        gauge = runtime.registry.register("queue", "length")
        trail_p, trail_d, spend_trail = [], [], []
        violations = coordinated_ticks = 0
        try:
            for tick in range(ticks):
                qp, qd = queues_at(tick)
                gauge.set("qp", "default", qp)
                gauge.set("qd", "default", qd)
                runtime.manager._due = {
                    k: 0.0 for k in runtime.manager._due
                }
                runtime.manager.reconcile_all()
                clock["now"] += interval_s
                p = runtime.store.get_scale(
                    "ScalableNodeGroup", "default", "g-prefill"
                ).spec_replicas
                d = runtime.store.get_scale(
                    "ScalableNodeGroup", "default", "g-decode"
                ).spec_replicas
                trail_p.append(p)
                trail_d.append(d)
                # exact integer band check, the kernel's arithmetic:
                # min_num*p <= d*min_den and d*max_den <= max_num*p
                if (
                    d * ratio.min_denominator
                    < ratio.min_numerator * p
                    or d * ratio.max_denominator
                    > ratio.max_numerator * p
                ):
                    violations += 1
                spend_trail.append(float(p + d))  # default $1/replica-hour
                group = runtime.store.get(
                    "PoolGroup", "default", "serving"
                )
                if group.status.coordinated:
                    coordinated_ticks += 1
            stats = runtime.solver_service.stats
            return {
                "prefill": trail_p,
                "decode": trail_d,
                "ratio_violation_ticks": violations,
                "coordinated_ticks": coordinated_ticks,
                "max_hourly_spend": round(max(spend_trail), 2),
                "mean_hourly_spend": round(
                    float(np.mean(spend_trail)), 2
                ),
                "poolgroup_dispatches": stats.poolgroup_dispatches,
                "cost_dispatches": stats.cost_dispatches,
            }
        finally:
            runtime.close()

    on = replay(True)
    off = replay(False)
    return {
        "config": {
            "ticks": ticks,
            "interval_s": interval_s,
            "target": target,
            "prefill_queue": prefill_queue,
            "decode_storm": f"{decode_base} -> {decode_peak} over ticks "
                            f"[{ramp_start}, {ramp_start + ramp_ticks}]",
            "ratio_band": "2:1 <= decode:prefill <= 4:1",
            "max_hourly_cost": budget,
            "seed": seed,
        },
        "runs": {"coordinated": on, "uncoordinated": off},
        "band": {
            "coordinated_violation_ticks": on["ratio_violation_ticks"],
            "uncoordinated_violation_ticks": off[
                "ratio_violation_ticks"
            ],
            "held_through_storm": on["ratio_violation_ticks"] == 0,
        },
        "budget": {
            "declared_hourly": budget,
            "coordinated_max_spend": on["max_hourly_spend"],
            "under_cap": on["max_hourly_spend"] <= budget,
        },
        "dispatch_collapse": {
            # grouped rows leave the per-pool cost ladder and ride ONE
            # joint dispatch per tick (the acceptance criterion's
            # karpenter_solver_dispatches_per_tick collapse)
            "coordinated_poolgroup_dispatches": on[
                "poolgroup_dispatches"
            ],
            "coordinated_cost_dispatches": on["cost_dispatches"],
            "uncoordinated_cost_dispatches": off["cost_dispatches"],
        },
    }


def simulate_delta(
    store, what_if_groups: List[dict], solver=None,
    template_resolver=None, cost_model=None,
) -> dict:
    """Baseline solve vs what-if solve, with the per-group delta: the
    operator's 'what would adding node group X change?'."""
    baseline = simulate(
        store, solver=solver, template_resolver=template_resolver,
        cost_model=cost_model,
    )
    with_groups = simulate(
        store, what_if_groups, solver=solver,
        template_resolver=template_resolver, cost_model=cost_model,
    )
    delta = {}
    for name, after in with_groups["groups"].items():
        before = baseline["groups"].get(
            name,
            {"pending_pods": 0, "additional_nodes_needed": 0},
        )
        delta[name] = {
            "pending_pods": after["pending_pods"]
            - before["pending_pods"],
            "additional_nodes_needed": after["additional_nodes_needed"]
            - before["additional_nodes_needed"],
        }
    return {
        "baseline": baseline,
        "what_if": with_groups,
        "delta": {
            "groups": delta,
            "unschedulable_pods": with_groups["unschedulable_pods"]
            - baseline["unschedulable_pods"],
        },
    }


# -- constraint-plane replay (docs/constraints.md) ---------------------------


def simulate_constraints(  # lint: allow-complexity — scenario assembly: world build + outage replay + before/after report
    ticks: int = 3,
    zones: int = 3,
    nodes_per_zone: int = 2,
    web_pods: int = 6,
    gold_pods: int = 2,
    plain_pods: int = 4,
    seed: int = 7,
) -> dict:
    """The --simulate --constraints replay (docs/constraints.md): a
    spread-constrained serving fleet with a gold reservation, driven
    through the REAL producer/encoder/solver path, then hit with a
    seeded zonal outage. The report shows per-group spread skew and
    reservation fill BEFORE and AFTER the outage — the constrained
    re-solve must rebalance onto the surviving zones without dropping
    the reservation fence — plus deterministic per-phase digests the
    acceptance test pins (tests/test_simulate.py).

    Nothing here touches a live store or provider: the world is
    self-contained (fake provider, scripted clock)."""
    from karpenter_tpu.api.core import (
        Container, Node, NodeCondition, NodeSpec, NodeStatus,
        ObjectMeta, Pod, PodSpec, RESERVATION_LABEL, ZONE_LABEL,
        resource_list,
    )
    from karpenter_tpu.api.metricsproducer import (
        MetricsProducer, MetricsProducerSpec, PendingCapacitySpec,
    )
    from karpenter_tpu.cloudprovider.fake import FakeFactory
    from karpenter_tpu.constraints import ConstraintGroup, SpreadSpec
    from karpenter_tpu.metrics.producers.pendingcapacity import (
        CONSTRAINTS_SUBSYSTEM, RESERVATION_FILL, SPREAD_SKEW,
    )
    from karpenter_tpu.metrics.producers.pendingcapacity import (
        encoder as _pc_encoder,
    )
    from karpenter_tpu.runtime import KarpenterRuntime, Options

    rng = np.random.default_rng(seed)
    _pc_encoder.reset_constraint_state()
    clock = {"now": 1_000_000.0}
    runtime = KarpenterRuntime(
        Options(),
        cloud_provider_factory=FakeFactory(),
        clock=lambda: clock["now"],
    )
    store = runtime.store
    zone_names = [f"z{i + 1}" for i in range(zones)]
    for z, zone in enumerate(zone_names):
        for i in range(nodes_per_zone):
            store.create(Node(
                metadata=ObjectMeta(
                    name=f"{zone}-n{i}",
                    labels={"pool": "serving", ZONE_LABEL: zone},
                ),
                spec=NodeSpec(),
                status=NodeStatus(
                    allocatable=resource_list(
                        cpu="8", memory="32Gi", pods="32"
                    ),
                    conditions=[NodeCondition("Ready", "True")],
                ),
            ))
    store.create(Node(
        metadata=ObjectMeta(
            name="reserved-0",
            labels={"pool": "reserved", RESERVATION_LABEL: "gold"},
        ),
        spec=NodeSpec(),
        status=NodeStatus(
            allocatable=resource_list(cpu="8", memory="32Gi", pods="32"),
            conditions=[NodeCondition("Ready", "True")],
        ),
    ))
    # one producer per zone plus the reserved pool — the group axis.
    # A single producer spanning zones would profile as the label
    # INTERSECTION of its nodes (encoder._group_profile) and lose the
    # zone domain the spread constraint needs, exactly like real node
    # groups that are zonal by construction. The constraint groups ride
    # the first producer; solve_pending merges them across the axis.
    constraints = [
        ConstraintGroup(
            name="web", pod_selector={"app": "web"}, spread=SpreadSpec()
        ),
        ConstraintGroup(
            name="gold", pod_selector={"tier": "gold"},
            reservation="gold",
        ),
    ]
    for z, zone in enumerate(zone_names):
        store.create(MetricsProducer(
            metadata=ObjectMeta(name=f"serving-{zone}"),
            spec=MetricsProducerSpec(
                pending_capacity=PendingCapacitySpec(
                    node_selector={
                        "pool": "serving", ZONE_LABEL: zone
                    },
                    constraints=constraints if z == 0 else [],
                )
            ),
        ))
    store.create(MetricsProducer(
        metadata=ObjectMeta(name="serving-reserved"),
        spec=MetricsProducerSpec(
            pending_capacity=PendingCapacitySpec(
                node_selector={"pool": "reserved"},
            )
        ),
    ))
    specs = (
        [("web", {"app": "web"})] * web_pods
        + [("gold", {"tier": "gold"})] * gold_pods
        + [("plain", {})] * plain_pods
    )
    for i, (kind, labels) in enumerate(specs):
        store.create(Pod(
            metadata=ObjectMeta(name=f"{kind}-{i}", labels=dict(labels)),
            spec=PodSpec(
                node_name="",
                containers=[Container(requests=resource_list(
                    cpu=str(int(rng.integers(1, 3))), memory="1Gi",
                ))],
            ),
        ))

    def _phase() -> dict:
        skew = {}
        fill = {}
        for sub, name, metric in (
            (CONSTRAINTS_SUBSYSTEM, SPREAD_SKEW, skew),
            (CONSTRAINTS_SUBSYSTEM, RESERVATION_FILL, fill),
        ):
            # register() returns the existing vec (or an empty one if
            # the solve never published — gauge() would KeyError)
            vec = runtime.registry.register(sub, name)
            for sample in vec.samples():
                metric[sample.labels["name"]] = sample.value
        groups = {}
        unschedulable = -1
        for mp in store.list("MetricsProducer", "default"):
            status = mp.status.pending_capacity
            if status is None:
                continue
            groups[mp.metadata.name] = {
                "pending_pods": status.pending_pods,
                "nodes_needed": status.additional_nodes_needed,
            }
            unschedulable = status.unschedulable_pods
        return {
            "spread_skew": skew,
            "reservation_fill": fill,
            "groups": groups,
            "unschedulable": unschedulable,
        }

    def _digest(phase: dict) -> int:
        # zlib.crc32 over canonical JSON, NOT hash(): str hashing is
        # salted per process and the acceptance test pins these values
        import json
        import zlib

        return zlib.crc32(
            json.dumps(phase, sort_keys=True).encode()
        )

    try:
        for _ in range(ticks):
            clock["now"] += 10.0
            runtime.manager.converge(1)
        before = _phase()
        # the seeded zonal outage: one zone's nodes disappear — its
        # zone drops out of the spread domain universe and the
        # constrained re-solve must rebalance the quotas over the
        # survivors (NotReady alone wouldn't do it: an all-NotReady
        # group still profiles via the scaled-to-zero fallback)
        dead_zone = zone_names[int(rng.integers(0, zones))]
        for i in range(nodes_per_zone):
            store.delete("Node", "default", f"{dead_zone}-n{i}")
        for _ in range(ticks):
            clock["now"] += 10.0
            runtime.manager.converge(1)
        after = _phase()
        stats = dict(_pc_encoder.constraint_stats)
    finally:
        runtime.close()

    return {
        "config": {
            "ticks": ticks, "zones": zones,
            "nodes_per_zone": nodes_per_zone, "web_pods": web_pods,
            "gold_pods": gold_pods, "plain_pods": plain_pods,
            "seed": seed,
        },
        "dead_zone": dead_zone,
        "before": before,
        "after": after,
        "digests": {
            "before": _digest(before),
            "after": _digest(after),
        },
        "constraint_health": {
            "compiles": stats["compiles"],
            "fallbacks": stats["fallbacks"],
            "degraded": stats["degraded"],
        },
    }


# -- multi-tenant lockstep replay (docs/multitenancy.md) ---------------------


def multitenant_fleet_inputs(
    tenant: int,
    rows: int,
    metrics: int,
    seed: int,
    tick: int,
    spec_replicas: np.ndarray,
    now: float,
):
    """One tenant cluster's DecisionInputs for one lockstep tick:
    AverageValue metrics riding a seeded diurnal ramp whose phase and
    amplitude differ per tenant (tenant fleets are NOT in phase — the
    fairness and batching machinery must handle skewed demand), with
    the previous tick's desired fed back as spec/status replicas.
    Deterministic in (tenant, tick, seed); shared with `bench.py
    --multitenant` so the bench times exactly the matrices the
    simulator steps."""
    import math as _math

    from karpenter_tpu.ops import decision as D

    rng = np.random.RandomState(seed * 100_003 + tenant * 1_009 + tick)
    phase = (tenant % 7) / 7.0 * 2.0 * _math.pi
    level = 40.0 + 30.0 * _math.sin(tick / 12.0 * 2.0 * _math.pi + phase)
    values = np.maximum(
        0.0, level + rng.normal(0.0, 2.0, (rows, metrics))
    ).astype(np.float32)
    spec = np.asarray(spec_replicas, np.int32)
    return D.DecisionInputs(
        metric_value=values,
        target_value=np.full((rows, metrics), 4.0, np.float32),
        target_type=np.full(
            (rows, metrics), D.TYPE_AVERAGE_VALUE, np.int32
        ),
        metric_valid=np.ones((rows, metrics), bool),
        spec_replicas=spec,
        status_replicas=spec.copy(),
        min_replicas=np.ones(rows, np.int32),
        max_replicas=np.full(rows, 10_000, np.int32),
        up_window=np.zeros(rows, np.int32),
        down_window=np.zeros(rows, np.int32),
        up_policy=np.full(rows, D.POLICY_MAX, np.int32),
        down_policy=np.full(rows, D.POLICY_MAX, np.int32),
        last_scale_time=np.zeros(rows, np.float32),
        has_last_scale=np.zeros(rows, bool),
        now=np.float32(now),
        up_ptype=np.zeros((rows, 1), np.int32),
        up_pvalue=np.zeros((rows, 1), np.int32),
        up_pperiod=np.ones((rows, 1), np.int32),
        up_pvalid=np.zeros((rows, 1), bool),
        down_ptype=np.zeros((rows, 1), np.int32),
        down_pvalue=np.zeros((rows, 1), np.int32),
        down_pperiod=np.ones((rows, 1), np.int32),
        down_pvalid=np.zeros((rows, 1), bool),
    )


def multitenant_cost_inputs(decide_inputs, desired: np.ndarray):
    """The tenant's CostInputs for the same tick: every row SLO-opted,
    demand = the observed metric values, a per-row unit-cost spread so
    the budget/risk trade is live. Deterministic companion of
    multitenant_fleet_inputs."""
    from karpenter_tpu.ops.cost import CostInputs

    rows = int(np.asarray(desired).shape[0])
    values = np.asarray(decide_inputs.metric_value, np.float32)
    unit = np.asarray(
        [0.19 + 0.27 * (i % 4) for i in range(rows)], np.float32
    )
    return CostInputs(
        base_desired=np.asarray(desired, np.int32),
        min_replicas=np.asarray(decide_inputs.min_replicas, np.int32),
        max_replicas=np.asarray(decide_inputs.max_replicas, np.int32),
        unit_cost=unit,
        slo_weight=np.full(rows, 50.0, np.float32),
        max_hourly_cost=np.zeros(rows, np.float32),
        slo_valid=np.ones(rows, bool),
        slo_target=np.asarray(decide_inputs.target_value, np.float32),
        demand_mu=values,
        demand_sigma=np.full(values.shape, 1.5, np.float32),
        demand_valid=np.ones(values.shape, bool),
    )


def simulate_multitenant(  # lint: allow-complexity — scenario assembly: lockstep replay + provenance/trace exports + report
    tenants: int = 16,
    ticks: int = 12,
    rows: int = 4,
    metrics: int = 2,
    seed: int = 0,
    backend: str = "xla",
    tenant_config: Optional[str] = None,
    provenance: bool = False,
    trace_export: Optional[str] = None,
) -> dict:
    """Step N seeded tenant clusters in LOCKSTEP through one
    MultiTenantScheduler (docs/multitenancy.md): every tick, all
    tenants' fleet matrices concatenate into shared decide + cost
    dispatches, the refined desired feeds back as the next tick's
    replicas, and the report quantifies the amortization — actual
    shared dispatches vs the 2-per-tenant-per-tick a sequential loop
    would pay — plus deterministic aggregate-replica digests the
    regression tests pin. Self-contained: no store, no provider.

    `provenance=True` records the decision ledger through the replay
    and adds the per-tenant WHY view (winning stage, cost ladder,
    solver rung, admission round) for a pinned mid-run tick — the
    `--simulate --cost --multitenant --provenance` acceptance surface.
    `trace_export=FILE` additionally mints one reconcile trace per tick
    (so ledger records carry trace-id backlinks), exporting the trace
    JSONL to FILE and the decision JSONL next to it
    (provenance.decisions_export_path)."""
    from karpenter_tpu.metrics.registry import GaugeRegistry
    from karpenter_tpu.observability import (
        default_ledger,
        default_tracer,
        reset_default_ledger,
        reset_default_tracer,
        set_default_ledger,
    )
    from karpenter_tpu.solver import SolverService
    from karpenter_tpu.tenancy import (
        MultiTenantScheduler,
        TenantRegistry,
        TenantSpec,
        load_tenant_config,
    )

    if tenant_config:
        specs = load_tenant_config(tenant_config)
        tenants = len(specs)
    else:
        specs = [
            TenantSpec(id=f"t{i:04d}", weight=1.0 + (i % 3))
            for i in range(tenants)
        ]
    # the replay records into its OWN ledger and restores the process
    # default afterwards: an enabled default leaking out would turn on
    # provenance for a co-resident runtime that never opted in
    saved_ledger = None
    ledger = None
    if provenance:
        saved_ledger = default_ledger()
        ledger = reset_default_ledger(enabled=True)
    if trace_export:
        reset_default_tracer()
    service = SolverService(backend=backend, registry=GaugeRegistry())
    registry = TenantRegistry(
        service=service, registry=GaugeRegistry(), specs=specs
    )
    scheduler = MultiTenantScheduler(registry, service)
    replicas = {
        spec.id: np.full(rows, 2, np.int32) for spec in specs
    }
    digests = {}
    pinned_tick = ticks // 2
    pinned_records: List[dict] = []
    try:
        for tick in range(ticks):
            now = 1_000_000.0 + tick * 10.0
            batch = {
                spec.id: multitenant_fleet_inputs(
                    i, rows, metrics, seed, tick, replicas[spec.id], now
                )
                for i, spec in enumerate(specs)
            }
            with default_tracer().trace(
                "simulate.multitenant.tick", tick=tick
            ):
                decided = scheduler.decide_all(batch)
                cost_batch = {
                    tid: multitenant_cost_inputs(
                        batch[tid], decided[tid].desired
                    )
                    for tid in decided
                }
                refined = scheduler.cost_all(cost_batch, backend=backend)
            for tid in refined:
                replicas[tid] = np.asarray(refined[tid].desired, np.int32)
            if tick in (0, ticks // 2, ticks - 1):
                digests[f"tick_{tick}"] = int(
                    sum(int(r.sum()) for r in replicas.values())
                )
            if ledger is not None and tick == pinned_tick:
                # the tick's records are exactly the newest commit
                pinned_records = ledger.query(kind="tenant")[
                    -(len(refined) * rows):
                ]
    finally:
        service.close()
        if saved_ledger is not None:
            set_default_ledger(saved_ledger)
    stats = scheduler.stats
    shared = stats.decide_dispatches + stats.cost_dispatches
    isolated = stats.isolated_dispatches
    sequential_equiv = tenants * ticks * 2
    report = {
        "tenants": tenants,
        "ticks": ticks,
        "rows_per_tenant": rows,
        "metrics_per_row": metrics,
        "decisions": stats.decide_rows,
        "decide_dispatches": stats.decide_dispatches,
        "cost_dispatches": stats.cost_dispatches,
        "isolated_dispatches": isolated,
        "admission_rounds": stats.admission_rounds,
        "mirror_served": stats.mirror_served,
        "fallback_served": stats.fallback_served,
        "sequential_equivalent_dispatches": sequential_equiv,
        "dispatch_amortization": round(
            sequential_equiv / max(shared + isolated, 1), 1
        ),
        "aggregate_replicas": digests,
        "solver": {
            "requests": service.stats.requests,
            "dispatches": service.stats.dispatches,
        },
    }
    if ledger is not None:
        why = _why_report(ledger)
        why["pinned_tick"] = pinned_tick
        why["pinned"] = [
            {
                "tenant": r["tenant"],
                "row": r["name"],
                "why": r["winning_stage"],
                "desired": r["final_desired"],
                "base": r["base_desired"],
                "risk": r["cost_risk"],
                "hourly": r["cost_hourly"],
                "rung": r["solver_rung"] or None,
                "admission_round": r["admission_round"],
                "trace": r["trace"] or None,
            }
            for r in pinned_records
        ]
        report["provenance"] = why
    if trace_export:
        report["trace_export"] = trace_export
        report["trace_events"] = default_tracer().export_jsonl(
            trace_export
        )
        if ledger is not None:
            from karpenter_tpu.observability.provenance import (
                export_next_to_trace,
            )

            path, count = export_next_to_trace(ledger, trace_export)
            report["decisions_export"] = path
            report["decision_records"] = count
    return report
