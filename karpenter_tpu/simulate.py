"""What-if simulation: a dry-run pending-pods solve with per-pod detail.

The production tick computes per-row assignments on the device
(ops/binpack.BinPackOutputs.assigned) but only publishes per-group
aggregates through the MetricsProducer status. This module surfaces the
rows: which pod shapes land where, what stays unschedulable and why the
operator should care — and answers "what would ADDING node group X
change?" by re-running the identical solve with hypothetical groups
appended to the group axis.

reference anchor: the reference has no simulation surface at all (its
pending-capacity producer is a stub, pendingcapacity/producer.go:29-31);
the intent served here is DESIGN.md "Pending Pods" — operators sizing a
scale-up want to see the placement the signal is promising.

Nothing here mutates the store or any status object: the solve runs on a
detached snapshot, making it safe against a live cluster.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_tpu.metrics.producers.pendingcapacity import (
    DomainCensus,
    encode_snapshot,
    group_profile,
)
from karpenter_tpu.store.columnar import (
    PendingPodCache,
    is_pending,
    occupancy_from_pods,
)


def _what_if_profile(spec: dict) -> Tuple[Dict[str, float], set, set]:
    """A hypothetical group declared the same way provider node templates
    are: the raw dict goes through cloudprovider.node_template_from_raw
    (quantity parsing, cloud-API taint-effect dialect) and then the SAME
    template->profile conversion the scale-from-zero resolver uses —
    including the pods-resource default, so a spec that only declares
    cpu/memory is not silently infeasible for every pod."""
    from karpenter_tpu.cloudprovider import node_template_from_raw
    from karpenter_tpu.metrics.producers import profile_from_template

    template = node_template_from_raw(
        {
            "allocatable": spec.get("allocatable") or {},
            "labels": spec.get("labels") or {},
            "taints": spec.get("taints") or [],
        }
    )
    return profile_from_template(template)


def simulate(  # lint: allow-complexity — report assembly: one guard per optional report field
    store,
    what_if_groups: Optional[List[dict]] = None,
    solver=None,
    template_resolver=None,
) -> dict:
    """One dry-run solve over the store's pendingCapacity producers plus
    `what_if_groups` (each {"name", "allocatable", "labels", "taints"}).

    Returns a JSON-shaped report:
      groups: per group {pending_pods, additional_nodes_needed,
              lp_lower_bound, what_if: bool, error?: str}
      rows:   per distinct pod shape {pod (ns/name of a representative),
              pods (count), assigned (group name or null)}
      unschedulable_pods: total weight with no feasible group

    `template_resolver` is the scale-from-zero seam solve_pending takes
    (producers.Factory.template_resolver): without it, empty groups with
    a nodeGroupRef encode as infeasible and the baseline drifts from the
    production solve. Per-producer failures are row-isolated exactly
    like the production path — a poisoned selector reports an `error` on
    its own group, never crashes the report.

    Hypothetical groups are appended AFTER the real ones, so first-
    feasible assignment only routes pods to them when no real group
    is feasible earlier in the order — the delta a what-if run shows is
    capacity the existing fleet genuinely lacks."""
    if solver is None:
        # the process-default solve service (solver/service.py): a
        # standalone simulation gets bucketing/backpressure/fallback for
        # free, and callers co-resident with other default-service users
        # (the sidecar server's RPCs) share one queue. A control plane
        # passes its runtime's own service here (__main__.py does).
        from karpenter_tpu.solver import default_service

        solver = default_service().solve

    producers = sorted(
        (
            mp
            for mp in store.list("MetricsProducer")
            if mp.spec.pending_capacity is not None
        ),
        key=lambda mp: (mp.metadata.namespace, mp.metadata.name),
    )
    nodes = store.list("Node")
    names: List[str] = []
    profiles = []
    what_if_names = set()
    group_errors: Dict[str, str] = {}
    for mp in producers:
        # namespace-qualified like the production solve's (ns, name) keys:
        # same-named producers in different namespaces must not collapse
        names.append(f"{mp.metadata.namespace}/{mp.metadata.name}")
        try:
            profile = group_profile(
                nodes, mp.spec.pending_capacity.node_selector
            )
            if not profile[0] and template_resolver is not None:
                ref = getattr(
                    mp.spec.pending_capacity, "node_group_ref", ""
                )
                if ref:
                    resolved = template_resolver(
                        mp.metadata.namespace, ref
                    )
                    if resolved is not None:
                        profile = resolved
        except Exception as e:  # noqa: BLE001 — row-isolated like
            # solve_pending: the dry-run tool must not crash on the
            # degraded clusters an operator most wants to inspect
            group_errors[names[-1]] = f"{type(e).__name__}: {e}"
            profile = ({}, set(), set())
        profiles.append(profile)
    for spec in what_if_groups or []:
        name = spec.get("name") or f"what-if-{len(what_if_names)}"
        n = 2
        while name in names:  # a colliding spec must not overwrite a row
            name = f"{spec.get('name') or 'what-if'}#{n}"
            n += 1
        names.append(name)
        what_if_names.add(name)
        profiles.append(_what_if_profile(spec))

    # detached encode with a slot -> pod-name map for per-row reporting
    # (snapshot rows are arena slots; snapshot_from_pods hides the map)
    all_pods = store.list("Pod")
    pods = [pod for pod in all_pods if is_pending(pod)]
    cache = PendingPodCache(store=None, capacity=max(16, len(pods)))
    slot_pod: Dict[int, str] = {}
    for pod in pods:
        key = (pod.metadata.namespace, pod.metadata.name)
        cache._upsert(key, pod)
        slot_pod[cache._slot[key]] = f"{key[0]}/{key[1]}"
    snap = cache.snapshot()

    # existing-pod domain occupancy, exactly like the production solve:
    # census nodes are the REAL ones (a what-if group's domains hold no
    # existing pods by construction)
    census = DomainCensus(occupancy_from_pods(all_pods), lambda: nodes)
    census.set_namespaces(store.list("Namespace"))
    inputs, row_idx, row_weight = encode_snapshot(
        snap, profiles, with_rows=True, census=census
    )
    if what_if_names and inputs.pod_group_score is not None:
        # preferred node affinity must not STEER pods into hypothetical
        # groups (the solver argmaxes score among feasible groups, which
        # would let a what-if group steal pods a real group serves): zero
        # their score columns, so they absorb only what no real group
        # can take — the invariant the delta report documents
        import dataclasses

        score = np.array(inputs.pod_group_score)
        score[:, len(profiles) - len(what_if_names): len(profiles)] = 0.0
        inputs = dataclasses.replace(inputs, pod_group_score=score)
    if len(row_idx) == 0:
        return {
            "groups": {
                name: {
                    "pending_pods": 0,
                    "additional_nodes_needed": 0,
                    "lp_lower_bound": 0,
                    "what_if": name in what_if_names,
                    **(
                        {"error": group_errors[name]}
                        if name in group_errors
                        else {}
                    ),
                }
                for name in names
            },
            "rows": [],
            "unschedulable_pods": 0,
        }
    out = solver(inputs)
    assigned = np.asarray(out.assigned)
    assigned_count = np.asarray(out.assigned_count)
    nodes_needed = np.asarray(out.nodes_needed)
    lp_bound = np.asarray(out.lp_bound)

    rows = []
    for i in range(len(row_idx)):
        group = int(assigned[i])
        rows.append(
            {
                "pod": slot_pod.get(int(row_idx[i]), "<unknown>"),
                "pods": int(row_weight[i]),
                "assigned": (
                    names[group] if 0 <= group < len(names) else None
                ),
            }
        )
    return {
        "groups": {
            name: {
                "pending_pods": int(assigned_count[t]),
                "additional_nodes_needed": int(nodes_needed[t]),
                "lp_lower_bound": int(lp_bound[t]),
                "what_if": name in what_if_names,
                **(
                    {"error": group_errors[name]}
                    if name in group_errors
                    else {}
                ),
            }
            for t, name in enumerate(names)
        },
        "rows": rows,
        "unschedulable_pods": int(out.unschedulable),
    }


def simulate_consolidation(store, service=None, buckets: int = 32) -> dict:
    """Dry-run consolidation plan: which nodes' pods would re-pack onto
    the remainder of the cluster, and why the rest are ineligible.

    The same candidate generation and batched masked bin-pack the
    production engine runs (karpenter_tpu/consolidation), minus the
    runtime-state safety gates — cooldown clocks and in-flight budgets
    live in the long-running engine, so a fresh dry-run process reports
    STRUCTURAL drainability and leaves pacing to the engine. Nothing is
    cordoned, scaled, or otherwise mutated.

    Report shape:
      nodes: per node {group, pods, drainable | ineligible reason}
      drainable: [node names]
      candidates_evaluated: how many masked solves the batch carried
    """
    from karpenter_tpu.consolidation import (
        DO_NOT_DISRUPT,
        cluster_view,
        discover_groups,
        evaluate,
    )

    if service is None:
        from karpenter_tpu.solver import default_service

        service = default_service()

    def node_entry(nv) -> dict:
        entry: dict = {
            "group": (
                f"{nv.group[0]}/{nv.group[2]}"
                if nv.group is not None and nv.group[2]
                else None
            ),
            "pods": len(nv.pods),
        }
        if nv.group is None or not nv.group[2]:
            entry["ineligible"] = "no nodeGroupRef to actuate"
        elif not nv.receiver:
            entry["ineligible"] = "not ready/schedulable"
        elif nv.do_not_disrupt:
            entry["ineligible"] = f"{DO_NOT_DISRUPT} annotation"
        return entry

    groups = discover_groups(store)
    view = cluster_view(store, groups)
    report: Dict[str, dict] = {
        nv.name: node_entry(nv) for nv in view.nodes
    }
    candidates = [
        name for name, entry in report.items()
        if "ineligible" not in entry
    ]
    verdicts = evaluate(view, candidates, service, buckets=buckets)
    for name, verdict in verdicts.items():
        report[name]["drainable"] = verdict
    return {
        "nodes": report,
        "drainable": sorted(
            name for name, v in verdicts.items() if v
        ),
        "candidates_evaluated": len(candidates),
    }


def simulate_delta(
    store, what_if_groups: List[dict], solver=None, template_resolver=None
) -> dict:
    """Baseline solve vs what-if solve, with the per-group delta: the
    operator's 'what would adding node group X change?'."""
    baseline = simulate(
        store, solver=solver, template_resolver=template_resolver
    )
    with_groups = simulate(
        store, what_if_groups, solver=solver,
        template_resolver=template_resolver,
    )
    delta = {}
    for name, after in with_groups["groups"].items():
        before = baseline["groups"].get(
            name,
            {"pending_pods": 0, "additional_nodes_needed": 0},
        )
        delta[name] = {
            "pending_pods": after["pending_pods"]
            - before["pending_pods"],
            "additional_nodes_needed": after["additional_nodes_needed"]
            - before["additional_nodes_needed"],
        }
    return {
        "baseline": baseline,
        "what_if": with_groups,
        "delta": {
            "groups": delta,
            "unschedulable_pods": with_groups["unschedulable_pods"]
            - baseline["unschedulable_pods"],
        },
    }
