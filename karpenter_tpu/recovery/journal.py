"""Write-ahead journal + checkpoint for controller protective state.

The store's own DurableStore (store/persistence.py) is the etcd analog:
it persists API OBJECTS. This journal persists what etcd never sees —
the in-process protective state controllers build ON TOP of those
objects (FSM phases, holds, budget spend, breaker/backoff state,
forecast rings). Same durability discipline, different payload:

  * records append as JSONL, flushed to the OS per append (survives
    process crash — the failure mode that matters for a leader-elected
    control plane); `fsync=True` additionally fsyncs, BATCHED every
    `fsync_every` appends so the sync cost stays off the per-append
    hot path;
  * every `compact_every` appends the journal checkpoints: the full
    current state (gathered from a provider callable) is written
    atomically and the journal truncates, so on-disk size is bounded
    by fleet size, not uptime;
  * recovery tolerates a TORN final record (crash mid-append): the
    fragment is discarded and the file truncated back to a record
    boundary, exactly like the store WAL.

Replay is a PURE FOLD (`replay`) over a tiny op vocabulary every
subsystem shares — `set`/`del` on a keyed table, bounded `append` for
ring samples — so determinism properties are structural: replaying the
same journal twice yields identical state, and checkpoint + journal
tail equals the full journal (both property-pinned in
tests/test_recovery.py).

Keys are tuples on the subsystem side and JSON strings on disk:
`key_str`/`key_tuple` round-trip them (nested tuples included).
"""

from __future__ import annotations

import copy
import json
import os
import threading
import time as _time
from typing import Callable, Dict, List, Optional, Tuple

from karpenter_tpu.faults import ProcessCrash, inject
from karpenter_tpu.utils.log import logger

_CHECKPOINT = "state-checkpoint.json"
_JOURNAL = "state-journal.jsonl"

OPS = ("set", "del", "append")

# appends between zombie self-fence polls (journal docstring): bounds a
# superseded incarnation's stale-append window without paying a FENCE
# file read on every hot-path append
_OWNER_CHECK_EVERY = 64


def atomic_write(path: str, text: str, dir_fsync: bool = True) -> None:
    """Durably replace `path` with `text`: tmp write + fsync + rename +
    directory fsync, so a crash at any point leaves either the old file
    or the new one, never a torn mix. Shared by the checkpoint writer
    and the fence-generation claim — one copy of the durability-critical
    sequence to keep correct."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    if dir_fsync:
        dir_fd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)


def key_str(key: tuple) -> str:
    """Tuple key -> canonical JSON string (nested tuples become lists)."""

    def listify(x):
        if isinstance(x, (tuple, list)):
            return [listify(e) for e in x]
        return x

    return json.dumps(listify(key), sort_keys=True, separators=(",", ":"))


def key_tuple(s: str) -> tuple:
    """JSON string key -> tuple (nested lists become tuples)."""

    def tupleize(x):
        if isinstance(x, list):
            return tuple(tupleize(e) for e in x)
        return x

    return tupleize(json.loads(s))


def apply_record(state: Dict[str, dict], record: dict) -> None:
    """One pure fold step. Unknown subsystems create their table on
    first sight; unknown ops are ignored (forward compatibility — an
    older binary replaying a newer journal keeps what it understands)."""
    op = record.get("op")
    if op not in OPS:
        return
    table = state.setdefault(record["sub"], {})
    k = record["k"]
    if op == "set":
        table[k] = record["v"]
    elif op == "del":
        table.pop(k, None)
    else:  # append: bounded ring sample [t, value]
        ring = table.get(k)
        if not isinstance(ring, list):
            ring = table[k] = []  # last-write-wins on a key whose type changed
        ring.append([record["t"], record["v"]])
        cap = int(record.get("cap", 0))
        if cap and len(ring) > cap:
            del ring[: len(ring) - cap]


def replay(
    checkpoint: Optional[dict], records: List[dict]
) -> Dict[str, dict]:
    """Pure replay: fold `records` over the checkpoint state. Inputs are
    not mutated, so replaying the same journal twice from the same
    checkpoint yields identical state by construction."""
    state: Dict[str, dict] = copy.deepcopy(
        (checkpoint or {}).get("state", {})
    )
    for record in records:
        apply_record(state, record)
    return state


class JournalHandle:
    """A subsystem's bound append surface: `set`/`delete`/`append_sample`
    against its own table, stamped with the subsystem name."""

    __slots__ = ("_journal", "_sub")

    def __init__(self, journal: "StateJournal", sub: str):
        self._journal = journal
        self._sub = sub

    def set(self, key: tuple, value) -> None:
        self._journal.record(
            {"sub": self._sub, "op": "set", "k": key_str(key), "v": value}
        )

    def delete(self, key: tuple) -> None:
        self._journal.record(
            {"sub": self._sub, "op": "del", "k": key_str(key)}
        )

    def append_sample(
        self, key: tuple, t: float, value: float, cap: int = 0
    ) -> None:
        self._journal.record(
            {
                "sub": self._sub,
                "op": "append",
                "k": key_str(key),
                "t": float(t),
                "v": float(value),
                "cap": int(cap),
            }
        )


class StateJournal:
    """Append-only protective-state journal with periodic checkpoints
    (module docstring). `record()` never raises on I/O failure — memory
    stays authoritative and the journal marks itself dirty, healing via
    a full checkpoint on the next successful write (the DurableStore
    posture). The one deliberate exception: an injected `process.crash`
    fault (faults/registry.py) propagates after flushing HALF the
    encoded record, producing a REAL torn tail for the kill-and-restart
    chaos suite to recover through."""

    def __init__(
        self,
        journal_dir: str,
        fsync: bool = False,
        fsync_every: int = 64,
        compact_every: int = 4096,
        compact_min_interval_s: float = 30.0,
    ):
        self.journal_dir = journal_dir
        self.fsync = fsync
        self.fsync_every = max(1, int(fsync_every))
        self.compact_every = max(1, int(compact_every))
        # auto-compaction floor: per-tick journal traffic scales with
        # fleet size, so a pure record-count trigger would checkpoint
        # every few ticks on a large fleet — serializing the FULL state
        # (all forecast rings) under the journal lock on the reconcile
        # hot path. Count AND interval must both be exceeded; explicit
        # checkpoint()/boot/shutdown compactions are not throttled.
        self.compact_min_interval_s = compact_min_interval_s
        self._last_checkpoint = float("-inf")
        # gathered at compaction time by the RecoveryManager: () -> the
        # full {sub: {key_str: value}} state to checkpoint
        self.checkpoint_provider: Optional[Callable[[], dict]] = None
        # optional live fold: every successful record also applies into
        # this state dict (the RecoveryManager points it at its replayed
        # state), so checkpoints capture subsystems that journal through
        # a handle without registering a snapshot provider
        self.mirror: Optional[Dict[str, dict]] = None
        # zombie self-fence (the RecoveryManager wires it): returns True
        # while this incarnation still owns the journal dir. A stale
        # incarnation overlapping a rolling restart must stop writing —
        # its appends would override the live journal's records and its
        # close-time checkpoint would overwrite live state with stale
        # state. Polled every _OWNER_CHECK_EVERY appends (bounding a
        # zombie's damage window) and at EVERY checkpoint (the
        # destructive operation is checked exactly).
        self.owner_check: Optional[Callable[[], bool]] = None
        self._superseded = False
        self._since_owner_check = 0
        self._lock = threading.Lock()
        self._count = 0  # records since the last checkpoint
        self._since_fsync = 0
        self._dirty = False
        self._bytes = 0
        self._closed = False
        os.makedirs(journal_dir, exist_ok=True)
        self._file = open(self._journal_path, "a", encoding="utf-8")

    # -- paths -------------------------------------------------------------

    @property
    def _journal_path(self) -> str:
        return os.path.join(self.journal_dir, _JOURNAL)

    @property
    def _checkpoint_path(self) -> str:
        return os.path.join(self.journal_dir, _CHECKPOINT)

    def handle(self, sub: str) -> JournalHandle:
        return JournalHandle(self, sub)

    def journal_bytes(self) -> int:
        """Bytes appended since the last checkpoint (the
        karpenter_recovery_journal_bytes gauge)."""
        with self._lock:
            return self._bytes

    # -- append ------------------------------------------------------------

    def record(self, record: dict) -> None:
        with self._lock:
            if self._closed or self._superseded:
                return  # dead incarnation's handle: no-op
            if not self._ensure_file_locked():
                return  # reopen failed; retried on the next record
            if self._owner_lost_locked():
                return  # superseded mid-life: zombie goes read-only
            line = json.dumps(record, sort_keys=True) + "\n"
            self._crash_point(line)
            if self.mirror is not None:
                # fold BEFORE the write attempt: memory stays
                # authoritative even when the append below fails (the
                # heal checkpoint then carries the mirrored state)
                apply_record(self.mirror, record)
            try:
                self._append_locked(line)
            except OSError:
                self._dirty = True
                logger().exception(
                    "state journal append failed — protective-state "
                    "durability degraded until the next checkpoint"
                )

    def _ensure_file_locked(self) -> bool:
        """Reopen the append handle if a previous checkpoint's reopen
        failed (fd exhaustion, late ENOSPC). Without this, one failed
        reopen would silently end ALL protective-state journaling for
        the process lifetime — each record retries instead."""
        if self._file is not None and not self._file.closed:
            return True
        try:
            self._file = open(self._journal_path, "a", encoding="utf-8")
            return True
        except OSError:
            self._dirty = True
            logger().exception(
                "state journal reopen failed — retrying on the next "
                "record"
            )
            return False

    def _owner_lost_locked(self) -> bool:
        """Poll the zombie self-fence every _OWNER_CHECK_EVERY appends
        (and on the first): once a newer incarnation owns the dir, this
        journal goes permanently read-only."""
        if self.owner_check is None:
            return False
        self._since_owner_check -= 1
        if self._since_owner_check > 0:
            return False
        self._since_owner_check = _OWNER_CHECK_EVERY
        if self.owner_check():
            return False
        self._superseded = True
        if self._file is not None and not self._file.closed:
            self._file.close()
        logger().warning(
            "state journal superseded by a newer incarnation; this "
            "(stale) incarnation stops journaling"
        )
        return True

    def _crash_point(self, line: str) -> None:
        """The kill-and-restart chaos point: an injected crash flushes a
        REAL torn half-record (what a kernel page flush mid-write leaves
        behind) before the 'process dies'."""
        try:
            inject("process.crash.journal")
        except ProcessCrash:
            try:
                self._file.write(line[: max(1, len(line) // 2)])
                self._file.flush()
            except OSError:
                pass
            raise

    def _heal_locked(self) -> bool:
        """A previous append failed: the journal has a gap, so only a
        full checkpoint restores integrity. Heal from the provider or
        the mirror fold; with NEITHER there is no full-state source —
        keep appending (recovery still folds what landed) and stay
        dirty rather than claiming a heal that never happened."""
        state = None
        if self.checkpoint_provider is not None:
            state = self.checkpoint_provider()
        elif self.mirror is not None:
            state = {
                sub: dict(table) for sub, table in self.mirror.items()
            }
        if state is None:
            return False
        self._checkpoint_locked(state)
        self._dirty = False
        logger().warning("state journal healed via full checkpoint")
        return True

    def _append_locked(self, line: str) -> None:
        if self._dirty and self._heal_locked():
            return
        self._file.write(line)
        self._file.flush()
        self._count += 1
        self._bytes += len(line)
        self._since_fsync += 1
        if self.fsync and self._since_fsync >= self.fsync_every:
            os.fsync(self._file.fileno())
            self._since_fsync = 0
        if (
            self._count >= self.compact_every
            and self.checkpoint_provider is not None
            and _time.monotonic() - self._last_checkpoint
            >= self.compact_min_interval_s
        ):
            self._checkpoint_locked()

    # -- checkpoint --------------------------------------------------------

    def checkpoint(self, state: Optional[dict] = None) -> None:
        """Write a full checkpoint (from `state`, or the provider) and
        truncate the journal."""
        with self._lock:
            self._checkpoint_locked(state)
            self._dirty = False

    def _checkpoint_locked(self, state: Optional[dict] = None) -> None:
        if self._superseded:
            return
        if self.owner_check is not None and not self.owner_check():
            # EXACT check before the destructive op: a zombie's
            # checkpoint would overwrite live state with stale state
            # and truncate the live incarnation's journal
            self._superseded = True
            logger().warning(
                "state journal superseded by a newer incarnation; "
                "skipping this (stale) incarnation's checkpoint"
            )
            return
        if state is None:
            if self.checkpoint_provider is None:
                return
            state = self.checkpoint_provider()
        # atomic_write makes the rename durable BEFORE the journal
        # truncation below (else a power loss could pair the OLD
        # checkpoint with an empty journal)
        atomic_write(
            self._checkpoint_path,
            json.dumps({"state": state}, sort_keys=True),
        )
        # post-mortem breadcrumb (observability.flightrecorder): a
        # compaction re-bounds the journal — record how much it folded
        # so a restart-storm timeline shows journal growth vs re-bounds
        from karpenter_tpu.observability import default_flight_recorder

        default_flight_recorder().record(
            "journal_compaction",
            records=self._count,
            bytes=self._bytes,
            tables=len(state),
        )
        self._last_checkpoint = _time.monotonic()
        if self._file is not None and not self._file.closed:
            self._file.close()
        try:
            self._file = open(self._journal_path, "w", encoding="utf-8")
        except OSError:
            # the truncating reopen failed: leave no handle and let the
            # next record() retry — journaling must not silently end
            self._file = None
            self._dirty = True
            logger().exception(
                "state journal reopen after checkpoint failed"
            )
            return
        if self.fsync:
            os.fsync(self._file.fileno())
        self._count = 0
        self._bytes = 0
        self._since_fsync = 0

    # -- recovery ----------------------------------------------------------

    def recover(self) -> Tuple[Optional[dict], List[dict]]:
        """(checkpoint doc or None, journal records) — torn-tail
        tolerant. Reads the files fresh, so it can be called on a
        journal another (crashed) incarnation wrote."""
        checkpoint = None
        if os.path.exists(self._checkpoint_path):
            with open(self._checkpoint_path, encoding="utf-8") as f:
                checkpoint = json.load(f)
        records = self._read_journal()
        return checkpoint, records

    def _read_journal(self) -> List[dict]:
        if not os.path.exists(self._journal_path):
            return []
        records: List[dict] = []
        valid_end = 0
        torn = False
        with open(self._journal_path, "rb") as f:
            for raw in f:
                line = raw.decode("utf-8", errors="replace").strip()
                if not line:
                    valid_end += len(raw)
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    # torn final append (crash mid-write): everything
                    # before it is intact — records are written whole
                    # under the journal lock
                    logger().warning(
                        "state journal: discarding torn record tail"
                    )
                    torn = True
                    break
                valid_end += len(raw)
        self._repair_tail(torn, valid_end)
        with self._lock:
            self._count = len(records)
            try:
                self._bytes = os.path.getsize(self._journal_path)
            except OSError:
                self._bytes = 0
            # reopen: recovery may have truncated under the append handle
            if self._file is not None and not self._file.closed:
                self._file.close()
            self._file = open(self._journal_path, "a", encoding="utf-8")
        return records

    def _repair_tail(self, torn: bool, valid_end: int) -> None:
        if torn:
            with open(self._journal_path, "rb+") as f:
                f.truncate(valid_end)
            return
        # repair a missing final newline (full record, torn terminator)
        # so the next append starts on a record boundary
        with open(self._journal_path, "rb+") as f:
            f.seek(0, os.SEEK_END)
            if f.tell() > 0:
                f.seek(-1, os.SEEK_END)
                if f.read(1) != b"\n":
                    f.write(b"\n")

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._file is not None and not self._file.closed:
                try:
                    self._file.flush()
                    if self.fsync:
                        os.fsync(self._file.fileno())
                except OSError:
                    pass
                self._file.close()
