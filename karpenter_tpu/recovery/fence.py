"""Actuation fencing: monotonic generation tokens on cloud writes.

The failure this closes: a controller crashes mid-actuation, restarts
(or worse, a split-brain duplicate keeps running), and REPLAYS a stale
scale decision against the cloud — undoing what the live incarnation
decided since. Borrowed from fencing tokens in distributed lock
services: every incarnation boots with a generation strictly greater
than any before it (persisted + fsynced in the journal dir BEFORE any
actuation), stamps that generation into every `set_replicas`/eviction
call, and the PROVIDER verifies the stamp before applying — a call
carrying a superseded generation is rejected with `FenceRejectedError`
instead of applied.

Two halves:

  * ActuationFence — controller side. One per incarnation; `token()`
    mints the stamp the ScalableNodeGroup controller passes to the
    provider. The generation is claimed durably at construction: a
    crash between boot and first actuation still burns the generation,
    so no later incarnation can ever be outranked by an earlier one.
  * FenceValidator — provider side (the fake, AWS, and TPU factories
    each own one). Tracks the highest generation it has admitted;
    `admit()` rejects anything older. Unstamped calls (token None)
    pass through — fencing is opt-in via `--journal-dir`, and an
    unfenced deployment keeps the old behavior.

`FenceRejectedError` is a RetryableError (code "FenceRejected"): the
stale incarnation's reconcile fails softly — the resource stays Active,
the breaker eventually opens on the zombie — while the live
incarnation, holding the newest generation, is never blocked.
"""

from __future__ import annotations

import fcntl
import os
import threading
from typing import NamedTuple, Optional

from karpenter_tpu.controllers.errors import RetryableError

_FENCE_FILE = "FENCE"
_FENCE_LOCK = "FENCE.lock"


def read_generation(journal_dir: str) -> int:
    """The generation currently claimed in `journal_dir` (0 when none).
    The journal's zombie self-fence polls this: a stale incarnation
    detects it has been superseded and stops writing."""
    try:
        with open(
            os.path.join(journal_dir, _FENCE_FILE), encoding="utf-8"
        ) as f:
            return int(f.read().strip() or 0)
    except (OSError, ValueError):
        return 0

FENCE_REJECTED_CODE = "FenceRejected"


class FenceToken(NamedTuple):
    """The stamp on one actuation: which incarnation decided it."""

    generation: int


class FenceRejectedError(RetryableError):
    """An actuation carried a superseded fence generation: a stale
    (restarted-over or split-brain) controller tried to replay a dead
    decision. The provider did NOT apply it."""

    def __init__(self, message: str):
        super().__init__(message, code=FENCE_REJECTED_CODE)


class ActuationFence:
    """Controller-side generation source (module docstring).

    With `journal_dir`, the generation is read from / persisted to
    `<dir>/FENCE` and fsynced before __init__ returns — claiming the
    generation is durable BEFORE any actuation can carry it. Without a
    dir (tests, ephemeral runs) the generation is whatever `generation`
    says (default 1)."""

    def __init__(
        self,
        journal_dir: Optional[str] = None,
        generation: Optional[int] = None,
    ):
        if generation is not None:
            self.generation = int(generation)
            self.path = None
            return
        if journal_dir is None:
            self.generation = 1
            self.path = None
            return
        os.makedirs(journal_dir, exist_ok=True)
        self.path = os.path.join(journal_dir, _FENCE_FILE)
        from karpenter_tpu.recovery.journal import atomic_write

        # the claim is a read-modify-write: serialize concurrent boots
        # under an exclusive flock, or two simultaneous starts would
        # claim EQUAL generations and neither would ever be fenced
        with open(
            os.path.join(journal_dir, _FENCE_LOCK), "w"
        ) as lock_file:
            fcntl.flock(lock_file, fcntl.LOCK_EX)
            self.generation = read_generation(journal_dir) + 1
            atomic_write(self.path, str(self.generation))
            # flock releases when lock_file closes

    def token(self) -> FenceToken:
        return FenceToken(generation=self.generation)


class FenceValidator:
    """Provider-side fence enforcement (module docstring). One per
    provider factory — the cloud is shared infrastructure, so every
    controller incarnation actuating through one factory races against
    the same highest-seen generation.

    Scope: the validator's memory is per factory INSTANCE, so
    cross-process enforcement requires either a shared factory (the
    in-process store-as-apiserver deployment and the chaos harness) or
    seeding: the runtime calls `observe(generation)` with its freshly
    claimed fence generation at boot, so a restarted process's own
    provider immediately outranks every earlier incarnation without
    waiting for a first actuation. A REAL cloud binding that spans
    machines should additionally translate the token into the cloud's
    own conditional-write/lease primitive; the SPI carries the token to
    the provider edge exactly so a binding can."""

    def __init__(self):
        self._lock = threading.Lock()
        self.highest_seen = 0
        self.rejections = 0

    def observe(self, generation: int) -> None:
        """Record a known-live generation WITHOUT an actuation: raises
        the floor so stamps older than `generation` are rejected even
        before the new incarnation's first provider write."""
        with self._lock:
            self.highest_seen = max(self.highest_seen, int(generation))

    def admit(self, token: Optional[FenceToken]) -> None:
        """Verify one actuation's stamp BEFORE applying it. Raises
        FenceRejectedError for a superseded generation; records the
        generation otherwise. token=None (unfenced caller) is admitted
        unchecked."""
        if token is None:
            return
        with self._lock:
            if token.generation < self.highest_seen:
                self.rejections += 1
                raise FenceRejectedError(
                    f"actuation fence rejected generation "
                    f"{token.generation} (provider has admitted "
                    f"generation {self.highest_seen}): stale controller "
                    "incarnation replaying a dead decision"
                )
            self.highest_seen = token.generation
