"""RecoveryManager: boot-time replay, warm-up, and the checkpoint loop.

One per runtime (built when `--journal-dir` is set). Construction does
the crash-recovery boot sequence:

  1. claim a fresh fence generation (durably, BEFORE anything can
     actuate — fence.py);
  2. replay checkpoint + journal into per-subsystem state tables (the
     pure fold in journal.py), timing it for the
     karpenter_recovery_replay_seconds gauge;
  3. if anything was recovered (or the fence shows a prior
     incarnation), arm the WARM-UP: `allow_disruption()` stays False
     until `warmup_ticks` full manager ticks have completed — the
     consolidation and preemption engines gate their planning on it, so
     a freshly restarted controller confirms fleet state before any
     scale-down or eviction.

The runtime then hands each subsystem its table (`table(sub)`) to
restore from, registers live-state snapshot providers
(`register_snapshot`), and calls `finish_boot()` — which writes a
compacted checkpoint of the replayed state, so a restart STORM cannot
grow the journal (every boot re-bounds it).

Subsystems not running this incarnation (e.g. consolidation toggled
off) keep their replayed tables verbatim in every checkpoint — their
state survives a feature toggle across restarts instead of being
silently dropped.
"""

from __future__ import annotations

import time as _time
from typing import Callable, Dict, Optional

from karpenter_tpu.metrics.registry import GaugeRegistry
from karpenter_tpu.recovery.fence import ActuationFence, read_generation
from karpenter_tpu.recovery.journal import JournalHandle, StateJournal, replay
from karpenter_tpu.utils.log import logger

SUBSYSTEM = "recovery"

REPLAY_SECONDS = "replay_seconds"
JOURNAL_BYTES = "journal_bytes"
WARMUP_TICKS_REMAINING = "warmup_ticks_remaining"
FENCE_REJECTIONS = "fence_rejections_total"


class RecoveryManager:
    def __init__(
        self,
        journal_dir: str,
        registry: Optional[GaugeRegistry] = None,
        clock: Callable[[], float] = _time.time,
        warmup_ticks: int = 1,
        fsync: bool = False,
        compact_every: int = 4096,
    ):
        self.clock = clock
        self.journal = StateJournal(
            journal_dir, fsync=fsync, compact_every=compact_every
        )
        self.fence = ActuationFence(journal_dir)
        # zombie self-fence: once a NEWER incarnation claims the dir,
        # this journal goes read-only — a stale overlapping incarnation
        # (rolling restart, split brain) cannot override the live
        # incarnation's records or overwrite its checkpoint at close
        generation = self.fence.generation
        self.journal.owner_check = (
            lambda: read_generation(journal_dir) == generation
        )
        t0 = _time.perf_counter()
        checkpoint, records = self.journal.recover()
        self.state: Dict[str, dict] = replay(checkpoint, records)
        self.replay_seconds = _time.perf_counter() - t0
        # a prior incarnation existed iff there was anything to replay
        # or the fence file already carried a generation; first boot
        # (nothing recovered) skips the warm-up — there is no pre-crash
        # state whose confirmation could be pending
        self.recovered = bool(
            checkpoint is not None or records or self.fence.generation > 1
        )
        self.warmup_total = max(0, int(warmup_ticks))
        self.warmup_remaining = self.warmup_total if self.recovered else 0
        # live-state snapshot providers: sub -> () -> {key_str: value};
        # checkpoints merge these OVER the replayed tables
        self._snapshots: Dict[str, Callable[[], dict]] = {}
        self.journal.checkpoint_provider = self._gather_state
        # every journaled record also folds into self.state, so
        # checkpoints capture live appends even for subsystems that
        # never register a snapshot provider
        self.journal.mirror = self.state
        self._g_replay = self._g_bytes = self._g_warmup = None
        self._c_fence_rejections = None
        if registry is not None:
            reg = registry.register
            self._g_replay = reg(SUBSYSTEM, REPLAY_SECONDS)
            self._g_bytes = reg(SUBSYSTEM, JOURNAL_BYTES)
            self._g_warmup = reg(SUBSYSTEM, WARMUP_TICKS_REMAINING)
            self._c_fence_rejections = reg(
                SUBSYSTEM, FENCE_REJECTIONS, kind="counter"
            )
            self._g_replay.set("-", "-", self.replay_seconds)
            self._g_warmup.set("-", "-", float(self.warmup_remaining))
        if self.recovered:
            logger().info(
                "recovery: replayed %d protective-state table(s) in "
                "%.3fs (fence generation %d); warm-up holds disruption "
                "for %d tick(s)",
                len(self.state), self.replay_seconds,
                self.fence.generation, self.warmup_remaining,
            )

    # -- state surface -----------------------------------------------------

    def handle(self, sub: str) -> JournalHandle:
        """The append surface a subsystem journals through."""
        return self.journal.handle(sub)

    def table(self, sub: str) -> dict:
        """The replayed {key_str: value} table a subsystem restores
        from (empty dict when nothing was journaled for it)."""
        return self.state.get(sub, {})

    def register_snapshot(self, sub: str, fn: Callable[[], dict]) -> None:
        """Register a live-state provider for checkpoints: `fn()`
        returns the subsystem's CURRENT full table."""
        self._snapshots[sub] = fn

    def _gather_state(self) -> dict:
        state = {
            sub: dict(table)
            for sub, table in self.state.items()
            if sub not in self._snapshots
        }
        for sub, fn in self._snapshots.items():
            try:
                state[sub] = fn()
            except Exception:  # noqa: BLE001 — a failing snapshot must
                # not lose the subsystem's previous state wholesale
                logger().exception(
                    "recovery: snapshot provider for %r failed; "
                    "checkpoint keeps the replayed table", sub,
                )
                state[sub] = dict(self.state.get(sub, {}))
        return state

    def finish_boot(self) -> None:
        """Compact after replay: every boot re-bounds the journal, so a
        restart storm cannot grow it without bound."""
        self.journal.checkpoint()
        if self._g_bytes is not None:
            self._g_bytes.set(
                "-", "-", float(self.journal.journal_bytes())
            )

    # -- warm-up -----------------------------------------------------------

    def allow_disruption(self) -> bool:
        """The disruption gate the consolidation and preemption engines
        consult: False while warm-up ticks remain."""
        return self.warmup_remaining <= 0

    def on_tick(self) -> None:
        """Manager tick hook: one full reconcile pass completed —
        advance the warm-up and refresh the point-in-time gauges."""
        if self.warmup_remaining > 0:
            self.warmup_remaining -= 1
            if self.warmup_remaining == 0:
                logger().info(
                    "recovery: warm-up complete; disruption "
                    "(consolidation/preemption) re-enabled"
                )
        if self._g_warmup is not None:
            self._g_warmup.set("-", "-", float(self.warmup_remaining))
        if self._g_bytes is not None:
            self._g_bytes.set(
                "-", "-", float(self.journal.journal_bytes())
            )

    def count_fence_rejection(self) -> None:
        """Fed by the ScalableNodeGroup controller when a provider
        rejects a stale stamp (karpenter_recovery_fence_rejections_total)."""
        if self._c_fence_rejections is not None:
            self._c_fence_rejections.inc("-", "-")
        # a fence rejection means THIS incarnation is the stale one —
        # exactly the post-mortem a flight-recorder dump should explain
        from karpenter_tpu.observability import default_flight_recorder

        default_flight_recorder().record(
            "fence_rejection", generation=self.fence.generation
        )

    def close(self) -> None:
        """Graceful shutdown: checkpoint the live state (a clean restart
        then replays one compact file) and release the journal."""
        try:
            self.journal.checkpoint()
        except Exception:  # noqa: BLE001 — closing must not raise past
            # the runtime teardown; the journal alone still recovers
            logger().exception("recovery: final checkpoint failed")
        self.journal.close()
