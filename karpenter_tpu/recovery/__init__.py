"""Crash-safe controller state (docs/resilience.md "Crash recovery").

The reference Karpenter keeps all reconcile state in the kube-apiserver,
so a controller restart is harmless. This build accumulated rich
in-process PROTECTIVE state — the consolidation cordon→verify→drain FSM,
preemption holds and eviction-budget spend, actuation circuit breakers,
per-object requeue backoff, and the forecast history/skill — that a
crash would erase, turning a restart into exactly the
disruption-amplification event those safety layers exist to prevent.
This package makes that state durable:

  * StateJournal (journal.py) — a write-ahead journal + periodic
    checkpoint for protective state, bounded by compaction, with a pure
    deterministic replay fold (property-pinned: replaying a journal
    twice is a no-op, and checkpoint+tail == full journal);
  * ActuationFence / FenceValidator (fence.py) — a monotonic generation
    token stamped into every cloud set_replicas call and verified by
    the provider before apply, so a restarted (or split-brain
    duplicate) controller cannot replay a stale decision;
  * RecoveryManager (manager.py) — boot orchestration: replay the
    journal, hand each subsystem its restored state, invalidate
    identity-keyed device caches, and hold a conservative WARM-UP
    (no scale-down or eviction) until one full reconcile tick has
    confirmed fleet state.

Wired through runtime.Options (`--journal-dir`,
`--recovery-warmup-ticks`) and exercised by the seeded kill-and-restart
chaos suite (`make test-recovery`).
"""

from karpenter_tpu.recovery.fence import (
    ActuationFence,
    FenceRejectedError,
    FenceToken,
    FenceValidator,
)
from karpenter_tpu.recovery.journal import (
    JournalHandle,
    StateJournal,
    key_str,
    key_tuple,
    replay,
)
from karpenter_tpu.recovery.manager import RecoveryManager

__all__ = [
    "ActuationFence",
    "FenceRejectedError",
    "FenceToken",
    "FenceValidator",
    "JournalHandle",
    "RecoveryManager",
    "StateJournal",
    "key_str",
    "key_tuple",
    "replay",
]
