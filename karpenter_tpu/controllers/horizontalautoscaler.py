"""HorizontalAutoscaler controller (reference:
pkg/controllers/horizontalautoscaler/v1alpha1/controller.go:40-50).

Unlike the reference's one-object-at-a-time Reconcile, the batch path hands
the whole fleet to the BatchAutoscaler for a single device evaluation — this
is the singleton-architecture note at controller.go:45-46 resolved the TPU
way: no sharded controllers, one array program.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from karpenter_tpu.api.horizontalautoscaler import HorizontalAutoscaler
from karpenter_tpu.autoscaler import BatchAutoscaler


class HorizontalAutoscalerController:
    """`solver_service` (solver/service.py) is the shared solve service:
    the BatchAutoscaler's decision kernel is already routed through it as
    the `decider` seam (runtime.py wiring); the controller additionally
    records each fleet evaluation into the service's latency surface so
    /metrics shows the decide stage next to the bin-pack stages."""

    def __init__(
        self, batch_autoscaler: BatchAutoscaler, solver_service=None
    ):
        self.autoscaler = batch_autoscaler
        self.solver_service = solver_service

    def kind(self) -> str:
        return HorizontalAutoscaler.KIND

    def interval(self) -> float:
        return 10.0

    @staticmethod
    def event_routes() -> tuple:
        """Event-driven mode (engine module docstring): autoscalers
        decide off producer-published gauges, and producers run first in
        tick order precisely so those signals are fresh — a refreshed
        producer status is therefore the 'new signal available' edge
        that should trigger a re-decide now, not at the next interval.
        Tick-paced mode never registers this watch."""
        return ("MetricsProducer",)

    def on_deleted(self, ha) -> None:
        """Engine pruning signal: drop the deleted autoscaler's metric
        history, skill state, and forecast gauges (forecast/engine.py) —
        the ring buffers are bounded, but a deleted object's series must
        not linger until eviction."""
        forecaster = getattr(self.autoscaler, "forecaster", None)
        if forecaster is not None:
            forecaster.prune(ha.metadata.namespace, ha.metadata.name)
        cost_engine = getattr(self.autoscaler, "cost_engine", None)
        if cost_engine is not None:
            cost_engine.prune(ha.metadata.namespace, ha.metadata.name)

    def reconcile(self, ha) -> None:
        error = self.reconcile_batch([ha]).get(
            (ha.metadata.namespace, ha.metadata.name)
        )
        if error is not None:
            raise error

    def reconcile_batch(
        self, has: List[HorizontalAutoscaler]
    ) -> Dict[tuple, Optional[Exception]]:
        """Keyed by (namespace, name)."""
        if self.solver_service is not None:
            with self.solver_service.track("reconcile_batch"):
                return self.autoscaler.reconcile_batch(has)
        return self.autoscaler.reconcile_batch(has)
