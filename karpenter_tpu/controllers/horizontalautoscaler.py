"""HorizontalAutoscaler controller (reference:
pkg/controllers/horizontalautoscaler/v1alpha1/controller.go:40-50).

Unlike the reference's one-object-at-a-time Reconcile, the batch path hands
the whole fleet to the BatchAutoscaler for a single device evaluation — this
is the singleton-architecture note at controller.go:45-46 resolved the TPU
way: no sharded controllers, one array program.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from karpenter_tpu.api.horizontalautoscaler import HorizontalAutoscaler
from karpenter_tpu.autoscaler import BatchAutoscaler


class HorizontalAutoscalerController:
    def __init__(self, batch_autoscaler: BatchAutoscaler):
        self.autoscaler = batch_autoscaler

    def kind(self) -> str:
        return HorizontalAutoscaler.KIND

    def interval(self) -> float:
        return 10.0

    def reconcile(self, ha) -> None:
        error = self.autoscaler.reconcile_batch([ha]).get(
            (ha.metadata.namespace, ha.metadata.name)
        )
        if error is not None:
            raise error

    def reconcile_batch(
        self, has: List[HorizontalAutoscaler]
    ) -> Dict[tuple, Optional[Exception]]:
        """Keyed by (namespace, name)."""
        return self.autoscaler.reconcile_batch(has)
