"""MetricsProducer controller (reference:
pkg/controllers/metricsproducer/v1alpha1/controller.go:40-47).

Batch hook: all pendingCapacity producers due in a tick are solved in ONE
device bin-pack call (the reference reconciles each producer independently;
pending-pods is inherently a global problem — DESIGN.md "Pending Pods").
"""

from __future__ import annotations

from typing import Dict, List, Optional

from karpenter_tpu.api.metricsproducer import MetricsProducer
from karpenter_tpu.metrics.producers.pendingcapacity import solve_pending

# every per-producer gauge SUBSYSTEM: the deletion hook below retires a
# deleted producer's {name, namespace} series from every vec registered
# under these — without this, a deleted queue's karpenter_queue_length
# (and the whole resources x metric-types reserved_capacity family)
# froze at its last value forever (the same frozen-series bug PR 10
# fixed for karpenter_cost_*). Subsystem-wide removal
# (GaugeRegistry.remove_series) so families added later are covered
# without re-enumerating metric names here.
_PRODUCER_SUBSYSTEMS = (
    "queue",
    "reserved_capacity",
    "scheduled_capacity",
    "pending_capacity",
)


class MetricsProducerController:
    def __init__(self, producer_factory):
        self.factory = producer_factory

    def kind(self) -> str:
        return MetricsProducer.KIND

    def interval(self) -> float:
        return 5.0

    @staticmethod
    def event_routes() -> tuple:
        """Event-driven mode (engine module docstring): a Pod appearing,
        binding, or vanishing — and a Node joining or draining — changes
        the very capacity picture pendingCapacity producers exist to
        measure, so those events pull every producer due-now into the
        next coalesced event pass instead of waiting out the interval.
        Tick-paced mode never registers these watches."""
        return ("Pod", "Node")

    def on_deleted(self, mp) -> None:
        """Retire a deleted producer's gauge series (module constant):
        series are keyed {name, namespace} per producer, so a deleted
        object's last values must leave /metrics with it."""
        for subsystem in _PRODUCER_SUBSYSTEMS:
            self.factory.registry.remove_series(
                subsystem, mp.metadata.name, mp.metadata.namespace
            )

    def reconcile(self, mp) -> None:
        self.factory.for_producer(mp).reconcile()

    def _solve_pending_batch(self, pending, key, results) -> None:
        """One device bin-pack call for every due pendingCapacity producer."""
        try:
            outcomes = solve_pending(
                self.factory.store,
                pending,
                self.factory.registry,
                solver=self.factory.solver,
                feed=self.factory.pending_feed(),
                template_resolver=self.factory.template_resolver(),
            )
            for mp in pending:
                # per-ROW outcome: a poisoned spec fails only itself
                results[key(mp)] = outcomes.get(key(mp))
        except Exception as e:  # noqa: BLE001 — global failure
            for mp in pending:
                results[key(mp)] = e

    def reconcile_batch(
        self, mps: List[MetricsProducer]
    ) -> Dict[tuple, Optional[Exception]]:
        key = lambda mp: (mp.metadata.namespace, mp.metadata.name)
        results: Dict[tuple, Optional[Exception]] = {}
        pending = [mp for mp in mps if mp.spec.pending_capacity is not None]
        others = [mp for mp in mps if mp.spec.pending_capacity is None]

        if pending:
            self._solve_pending_batch(pending, key, results)

        for mp in others:
            try:
                self.factory.for_producer(mp).reconcile()
                results[key(mp)] = None
            except Exception as e:  # noqa: BLE001
                results[key(mp)] = e
        return results
