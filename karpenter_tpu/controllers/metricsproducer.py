"""MetricsProducer controller (reference:
pkg/controllers/metricsproducer/v1alpha1/controller.go:40-47)."""

from __future__ import annotations

from karpenter_tpu.api.metricsproducer import MetricsProducer


class MetricsProducerController:
    def __init__(self, producer_factory):
        self.factory = producer_factory

    def kind(self) -> str:
        return MetricsProducer.KIND

    def interval(self) -> float:
        return 5.0

    def reconcile(self, mp) -> None:
        self.factory.for_producer(mp).reconcile()
