"""MetricsProducer controller (reference:
pkg/controllers/metricsproducer/v1alpha1/controller.go:40-47).

Batch hook: all pendingCapacity producers due in a tick are solved in ONE
device bin-pack call (the reference reconciles each producer independently;
pending-pods is inherently a global problem — DESIGN.md "Pending Pods").
"""

from __future__ import annotations

from typing import Dict, List, Optional

from karpenter_tpu.api.metricsproducer import MetricsProducer
from karpenter_tpu.metrics.producers.pendingcapacity import solve_pending


class MetricsProducerController:
    def __init__(self, producer_factory):
        self.factory = producer_factory

    def kind(self) -> str:
        return MetricsProducer.KIND

    def interval(self) -> float:
        return 5.0

    def reconcile(self, mp) -> None:
        self.factory.for_producer(mp).reconcile()

    def _solve_pending_batch(self, pending, key, results) -> None:
        """One device bin-pack call for every due pendingCapacity producer."""
        try:
            outcomes = solve_pending(
                self.factory.store,
                pending,
                self.factory.registry,
                solver=self.factory.solver,
                feed=self.factory.pending_feed(),
                template_resolver=self.factory.template_resolver(),
            )
            for mp in pending:
                # per-ROW outcome: a poisoned spec fails only itself
                results[key(mp)] = outcomes.get(key(mp))
        except Exception as e:  # noqa: BLE001 — global failure
            for mp in pending:
                results[key(mp)] = e

    def reconcile_batch(
        self, mps: List[MetricsProducer]
    ) -> Dict[tuple, Optional[Exception]]:
        key = lambda mp: (mp.metadata.namespace, mp.metadata.name)
        results: Dict[tuple, Optional[Exception]] = {}
        pending = [mp for mp in mps if mp.spec.pending_capacity is not None]
        others = [mp for mp in mps if mp.spec.pending_capacity is None]

        if pending:
            self._solve_pending_batch(pending, key, results)

        for mp in others:
            try:
                self.factory.for_producer(mp).reconcile()
                results[key(mp)] = None
            except Exception as e:  # noqa: BLE001
                results[key(mp)] = e
        return results
