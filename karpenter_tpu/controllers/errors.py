"""Error taxonomy for controllers (reference: pkg/controllers/errors.go:22-59).

RetryableError marks transient provider failures that should NOT deactivate a
resource; the short `code` surfaces in status conditions where long messages
won't fit.
"""

from __future__ import annotations


class RetryableError(RuntimeError):
    def __init__(self, message: str, code: str = "", retryable: bool = True):
        super().__init__(message)
        self.code = code
        self.retryable = retryable


def is_retryable(err: BaseException) -> bool:
    """reference: errors.go:41-47"""
    e = err
    while e is not None:
        if isinstance(e, RetryableError):
            return e.retryable
        e = e.__cause__
    return False


def error_code(err: BaseException) -> str:
    """reference: errors.go:53-59"""
    e = err
    while e is not None:
        if isinstance(e, RetryableError):
            return e.code
        e = e.__cause__
    return ""
