"""Reconcile engine: the standardized controller workflow + manager.

reference: pkg/controllers/controller.go:33-97 (Controller/Object interfaces,
GenericController workflow) and pkg/controllers/manager.go:40-79.

Workflow per object (controller.go:67-97): get fresh copy → keep persisted
base → validate (failure marks Active false but still patches status) →
domain reconcile (failure marks Active false; success true) → status
merge-patch → requeue after the controller's interval.

TPU redesign: the manager tick is BATCH-FIRST. A controller may implement
reconcile_batch(objects) → {name: error}; the manager then hands it every
due object of its kind in one call (the HA controller turns this into a
single device kernel invocation for the whole fleet). Controllers without a
batch path get the per-object workflow. Watch events requeue immediately
(the reference's watch-driven actuation, DESIGN.md:435).

Failure ladder (docs/resilience.md): the fixed-interval requeue applies
only to SUCCESSFUL reconciles. A failed one is classified through
errors.is_retryable —

  retryable     → requeue on per-object decorrelated-jitter exponential
                  backoff (monotone, bounded by backoff_cap_s): a flaky
                  dependency is retried promptly at first, then ever
                  slower, and the jitter keeps a fleet of failers from
                  herding the dependency's recovery;
  non-retryable → DEACTIVATE: Active=False is persisted and the object
                  is not requeued at all until a watch event (spec edit,
                  external patch) revives it — a poisoned spec stops
                  consuming ticks instead of failing forever.

A failed status patch itself backs off too (the store is a dependency
like any other).
"""

from __future__ import annotations

import time as _time
from typing import Dict, List, Optional, Protocol

from karpenter_tpu.api import conditions as cond
from karpenter_tpu.controllers.errors import is_retryable
from karpenter_tpu.observability import default_tracer
from karpenter_tpu.resilience import DecorrelatedJitterBackoff
from karpenter_tpu.store import Store
from karpenter_tpu.utils.log import logger

_NEVER = float("inf")  # the deactivated requeue time


class Controller(Protocol):
    def kind(self) -> str:
        """Kind of the resource this controller owns."""
        ...

    def interval(self) -> float:
        """Seconds between reconciles (reference: controller.go:37-41)."""
        ...

    def reconcile(self, obj) -> None:
        """Domain reconcile; raise to mark the resource not Active."""
        ...


class Manager:
    def __init__(
        self, store: Store, clock=_time.time, registry=None,
        solver_service=None,
        backoff_base_s: float = 1.0,
        backoff_cap_s: float = 60.0,
        backoff_seed: int = 0,
        tick_hook=None,
        recovery_journal=None,
    ):
        self.store = store
        self.clock = clock
        # crash safety (karpenter_tpu/recovery): `recovery_journal` is a
        # JournalHandle persisting per-object backoff state — without
        # it, a crash-looping object restarts its ladder at the base
        # delay every controller restart, defeating the backoff exactly
        # when it matters. `tick_hook` fires after each full
        # reconcile_all pass (the recovery warm-up counts ticks on it).
        self._tick_hook = tick_hook
        self._journal = recovery_journal
        # shared solve service (solver/service.py): the manager refreshes
        # its point-in-time gauges (queue depth, coalesce factor, stage
        # percentiles) every tick, so /metrics shows them alongside the
        # runtime series with no extra wiring in __main__.py
        self._solver_service = solver_service
        self._controllers: List[Controller] = []
        # kinds whose controller ACKS the e2e lead-time mark
        # (`acks_e2e = True`, the SNG controller): marks are only
        # stamped for these — stamping every kind would be per-object
        # tracer-lock traffic on the hot path that no ack ever reads
        self._e2e_kinds: set = set()
        # (kind, namespace, name) -> next due time; 0 = due now,
        # inf = deactivated (revived only by a watch event)
        self._due: Dict[tuple, float] = {}
        # per-object retryable-failure ladder: key -> previous delay
        self._backoff = DecorrelatedJitterBackoff(
            base_s=backoff_base_s, cap_s=backoff_cap_s, seed=backoff_seed
        )
        self._backoff_prev: Dict[tuple, float] = {}
        # self-observability (the reference gets controller-runtime's
        # metrics for free; here the manager publishes its own):
        # karpenter_runtime_{tick_seconds,reconciles_total,
        # reconcile_errors_total}{name=<kind>|manager}
        self._tick_gauge = self._count_gauge = self._error_gauge = None
        self._backoff_gauge = self._deactivated_gauge = None
        if registry is not None:
            self._tick_gauge = registry.register("runtime", "tick_seconds")
            self._count_gauge = registry.register(
                "runtime", "reconciles_total", kind="counter"
            )
            self._error_gauge = registry.register(
                "runtime", "reconcile_errors_total", kind="counter"
            )
            # ladder observability: the last requeue backoff per kind and
            # how many objects have been deactivated by non-retryable
            # errors (karpenter_resilience_* — docs/resilience.md)
            self._backoff_gauge = registry.register(
                "resilience", "requeue_backoff_seconds"
            )
            self._deactivated_gauge = registry.register(
                "resilience", "deactivated_total", kind="counter"
            )

    def _count(self, gauge, name: str, delta: float = 1.0) -> None:
        # process-level series: namespace "-" keeps them distinct from
        # object-namespace-labeled producer gauges on dashboards
        if gauge is not None:
            gauge.inc(name, "-", delta)

    def register(self, *controllers: Controller) -> "Manager":
        """reference: manager.go:59-71"""
        for controller in controllers:
            self._controllers.append(controller)
            if getattr(controller, "acks_e2e", False):
                self._e2e_kinds.add(controller.kind())
            self.store.watch(controller.kind(), self._on_event)
        return self

    def _on_event(self, event: str, obj) -> None:
        key = (obj.KIND, obj.metadata.namespace, obj.metadata.name)
        if event == "Deleted":
            self._due.pop(key, None)
            self._drop_backoff(key)
            default_tracer().drop_observed(key)
            # controllers may keep per-object state of their own (the
            # SNG controller's circuit breakers + gauge series): give
            # them the same pruning signal the engine's maps get
            for controller in self._controllers:
                hook = getattr(controller, "on_deleted", None)
                if hook is not None and controller.kind() == obj.KIND:
                    hook(obj)
        else:
            # watch events trigger immediate reconcile on the next tick,
            # overriding any scheduled requeue (the reference's watch-driven
            # actuation, DESIGN.md:435) — including the inf requeue of a
            # DEACTIVATED object: an external edit is the revival signal
            self._due[key] = 0.0
            # event-observed stamp for the end-to-end lead-time
            # histogram (karpenter_reconcile_e2e_seconds), only for
            # kinds whose controller acks it. overwrite=False: EVERY
            # store write notifies here — including this engine's own
            # per-reconcile status patches — so a pending mark must
            # survive re-notification or a multi-tick actuation would
            # be measured from its last self-patch (~one tick) instead
            # of the triggering event. The earliest stamp since the
            # mark was last retired IS the divergence observation: the
            # SNG controller acks the mark on actuation and drops it
            # on every converged reconcile, and the validation/
            # deactivation paths drop it too, so a stamp never
            # predates the divergence by more than one reconcile
            # interval
            if obj.KIND in self._e2e_kinds:
                default_tracer().mark_observed(key, overwrite=False)

    # -- the generic workflow (reference: controller.go:67-97) -------------

    def _finish(self, controller, obj, error: Optional[Exception]) -> None:
        mgr = obj.status_conditions()
        if error is not None and obj.KIND in self._e2e_kinds:
            # a failed reconcile proved nothing about convergence: keep
            # the mark and a converged-but-flapping object would carry
            # it into a much later actuation's karpenter_reconcile_e2e_
            # seconds sample. Dropping under-reports lead during fault
            # windows instead — the conservative direction (degraded-
            # path visibility is the flight recorder's job)
            default_tracer().drop_observed(self._key_of(obj))
        if error is not None:
            mgr.mark_false(cond.ACTIVE, "", str(error))
            logger().error(
                "Controller failed to reconcile kind %s %s: %s",
                obj.KIND,
                obj.metadata.name,
                error,
            )
        else:
            mgr.mark_true(cond.ACTIVE)
        self._count(self._count_gauge, obj.KIND)
        if error is not None:
            self._count(self._error_gauge, obj.KIND)
        try:
            patched = self.store.patch_status(obj)
        except KeyError:
            return  # deleted mid-reconcile
        except Exception as patch_error:  # noqa: BLE001 — store hiccup
            # the store is a dependency like the provider: a failed
            # status write requeues on the retryable ladder (the write
            # is redone wholesale by the next reconcile) and NEVER
            # deactivates — the conditions were not persisted, so a
            # deactivation here would strand the object invisibly
            logger().warning(
                "status patch failed for %s %s: %s; requeueing with "
                "backoff", obj.KIND, obj.metadata.name, patch_error,
            )
            self._count(self._error_gauge, obj.KIND)
            self._requeue_backoff(self._key_of(obj))
            return
        self._requeue(controller, self._key_of(obj), error, patched)

    @staticmethod
    def _key_of(obj) -> tuple:
        return (obj.KIND, obj.metadata.namespace, obj.metadata.name)

    def _requeue(
        self, controller, key, error: Optional[Exception], patched=None
    ) -> None:
        """The supervised requeue ladder: interval on success, jittered
        backoff on retryable failure, deactivation on non-retryable."""
        if error is None:
            self._drop_backoff(key)
            self._due[key] = self.clock() + controller.interval()
        elif is_retryable(error):
            self._requeue_backoff(key)
        else:
            self._deactivate(key, patched)

    def _deactivate(self, key, patched) -> None:
        """DEACTIVATE: no requeue until a watch event revives the
        object (_on_event). Exactly-once by construction — the object
        is never due again, so _finish cannot re-run. Concurrency
        guard: an EXTERNAL write landing during this reconcile fired
        its revival event before we got here and due=inf would silently
        discard it — detectable because the stored resourceVersion has
        moved past our own status patch. Reconcile once more instead of
        deactivating."""
        current = self.store.try_get(*key)
        if (
            current is not None
            and patched is not None
            and current.metadata.resource_version
            != patched.metadata.resource_version
        ):
            self._due[key] = 0.0
            return
        # the journaled ladder is dropped too: a crash-restart must not
        # revive a DEACTIVATED object through a stale finite due time
        # restored from the journal
        self._drop_backoff(key)
        # a deactivated object will not actuate until revived: retire
        # any pending e2e mark so the revival's actuation measures from
        # the reviving edit, not from before the deactivation
        default_tracer().drop_observed(key)
        self._due[key] = _NEVER
        if self._deactivated_gauge is not None:
            self._deactivated_gauge.inc(key[0], "-")

    def _drop_backoff(self, key) -> None:
        """Retire an object's backoff ladder, in memory AND in the
        journal (one idiom for success, deletion, and deactivation)."""
        if (
            self._backoff_prev.pop(key, None) is not None
            and self._journal is not None
        ):
            self._journal.delete(key)

    def _requeue_backoff(self, key) -> None:
        delay = self._backoff.next(self._backoff_prev.get(key, 0.0))
        self._backoff_prev[key] = delay
        self._due[key] = self.clock() + delay
        if self._journal is not None:
            self._journal.set(
                key, {"prev": delay, "due": self._due[key]}
            )
        if self._backoff_gauge is not None:
            self._backoff_gauge.set(key[0], "-", delay)

    def snapshot_backoff(self) -> Dict[str, dict]:
        """Live backoff table for the recovery checkpoint."""
        from karpenter_tpu.recovery.journal import key_str

        return {
            key_str(key): {"prev": prev, "due": self._due.get(key, 0.0)}
            for key, prev in self._backoff_prev.items()
        }

    def restore_backoff(self, entries: dict) -> None:
        """Rebuild the per-object backoff ladder from a replayed journal
        table, so a crash-looping object cannot reset its ladder by
        crashing the controller. Restored due times are CAPPED at
        now + backoff cap: an object journaled long before the outage
        ended must come due within one max-backoff window, never stay
        parked on a stale far-future (or inf) stamp."""
        from karpenter_tpu.recovery.journal import key_tuple

        now = self.clock()
        restored = 0
        # snapshot the items: `entries` aliases the journal's live
        # mirror table, and the delete below folds back into it —
        # iterating the dict itself would crash the recovery boot
        for k, doc in list(entries.items()):
            key = key_tuple(k)
            if self.store.try_get(*key) is None:
                # deleted while we were down: no Deleted event will
                # ever fire for it — drop the entry now or it would
                # re-persist through every future checkpoint
                if self._journal is not None:
                    self._journal.delete(key)
                continue
            prev = min(float(doc["prev"]), self._backoff.cap_s)
            self._backoff_prev[key] = prev
            self._due[key] = min(
                float(doc["due"]), now + self._backoff.cap_s
            )
            restored += 1
        if restored:
            logger().info(
                "engine: restored backoff state for %d object(s) from "
                "the journal", restored,
            )

    def _validate(self, obj) -> Optional[Exception]:
        try:
            obj.validate()
            return None
        except Exception as e:  # noqa: BLE001
            return e

    def _reconcile_controller(self, controller, now: float) -> None:
        """One controller's slice of the tick: collect due objects,
        validate, dispatch."""
        kind = controller.kind()
        # dueness is decided on keys so idle ticks never deep-copy the
        # fleet; only due objects are fetched
        due_objs = [
            obj
            for key in self.store.keys(kind)
            if self._due.get(key, 0.0) <= now
            and (obj := self.store.try_get(*key)) is not None
        ]
        if not due_objs:
            return

        tracer = default_tracer()
        e2e = kind in self._e2e_kinds
        valid_objs = []
        for obj in due_objs:
            error = self._validate(obj)
            if error is not None:
                # _finish retires any pending e2e mark on the error
                # path: an invalid object cannot actuate, and an
                # hours-later revival must not measure its lead time
                # from a stamp that predates the fix
                self._finish(controller, obj, error)
            else:
                if e2e:
                    # interval-driven reconciles have no watch event:
                    # the tick entry IS the observation point for the
                    # e2e lead time (stamped AFTER validation — a
                    # failing object never accrues a mark). setdefault
                    # semantics — an earlier event stamp wins.
                    tracer.mark_observed(
                        self._key_of(obj), overwrite=False
                    )
                valid_objs.append(obj)
        with tracer.span(
            f"reconcile.{kind}", objects=len(valid_objs)
        ):
            self._dispatch(controller, valid_objs)

    def _dispatch(self, controller, valid_objs) -> None:
        """Batch path when the controller offers one, else per-object."""
        batch = getattr(controller, "reconcile_batch", None)
        if batch is not None and valid_objs:
            obj_key = lambda o: (o.metadata.namespace, o.metadata.name)
            try:
                errors = batch(valid_objs)
            except Exception as e:  # noqa: BLE001 - batch-wide failure
                errors = {obj_key(o): e for o in valid_objs}
            for obj in valid_objs:
                self._finish(controller, obj, errors.get(obj_key(obj)))
        else:
            for obj in valid_objs:
                try:
                    controller.reconcile(obj)
                    error = None
                except Exception as e:  # noqa: BLE001
                    error = e
                self._finish(controller, obj, error)

    def reconcile_all(self) -> None:
        """One manager tick: every due object of every controller.

        The tick is a reconcile-trace entry point (docs/observability.md):
        a trace id is minted here and every span opened inside — the
        per-kind reconcile, the HA fleet decide, solver requests, SNG
        actuation — inherits it through the tracer's thread-local
        stack, so one trace connects a watch event to the coalesced
        dispatch to the provider write it caused."""
        start = _time.perf_counter()
        now = self.clock()
        with default_tracer().trace("reconcile.tick"):
            for controller in self._controllers:
                self._reconcile_controller(controller, now)
        if self._solver_service is not None:
            self._solver_service.publish_gauges()
        if self._tick_hook is not None:
            self._tick_hook()
        if self._tick_gauge is not None:
            self._tick_gauge.set(
                "manager", "-", _time.perf_counter() - start
            )

    def run(self, duration: float, tick: float = 0.1) -> None:
        """Drive reconcile_all on a wall-clock loop for `duration` seconds."""
        deadline = self.clock() + duration
        while self.clock() < deadline:
            self.reconcile_all()
            _time.sleep(tick)

    def converge(self, ticks: int = 5) -> None:
        """Run N immediate ticks ignoring intervals (test convergence helper,
        the ExpectEventuallyHappy analog — expectations.go:51-61)."""
        for _ in range(ticks):
            self._due = {k: 0.0 for k in self._due}
            self.reconcile_all()
