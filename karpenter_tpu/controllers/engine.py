"""Reconcile engine: the standardized controller workflow + manager.

reference: pkg/controllers/controller.go:33-97 (Controller/Object interfaces,
GenericController workflow) and pkg/controllers/manager.go:40-79.

Workflow per object (controller.go:67-97): get fresh copy → keep persisted
base → validate (failure marks Active false but still patches status) →
domain reconcile (failure marks Active false; success true) → status
merge-patch → requeue after the controller's interval.

TPU redesign: the manager tick is BATCH-FIRST. A controller may implement
reconcile_batch(objects) → {name: error}; the manager then hands it every
due object of its kind in one call (the HA controller turns this into a
single device kernel invocation for the whole fleet). Controllers without a
batch path get the per-object workflow. Watch events requeue immediately
(the reference's watch-driven actuation, DESIGN.md:435).

Failure ladder (docs/resilience.md): the fixed-interval requeue applies
only to SUCCESSFUL reconciles. A failed one is classified through
errors.is_retryable —

  retryable     → requeue on per-object decorrelated-jitter exponential
                  backoff (monotone, bounded by backoff_cap_s): a flaky
                  dependency is retried promptly at first, then ever
                  slower, and the jitter keeps a fleet of failers from
                  herding the dependency's recovery;
  non-retryable → DEACTIVATE: Active=False is persisted and the object
                  is not requeued at all until a watch event (spec edit,
                  external patch) revives it — a poisoned spec stops
                  consuming ticks instead of failing forever.

A failed status patch itself backs off too (the store is a dependency
like any other).

EVENT-DRIVEN RECONCILE (docs/solver-service.md "Event-driven
reconcile"): with `event_driven=True` a watch event no longer waits for
the next tick. `_on_event` marks the key DIRTY and schedules a
COALESCED EVENT PASS — after a short debounce window (so an event storm
batches into one pass, not one pass per event) a partial reconcile_all
runs over only the dirty keys that are actually due. The periodic tick
is demoted to a RESYNC BACKSTOP: it still runs every interval for drift
repair, interval-driven requeues, backoff/deactivation revival, the
tick hook consumers (recovery warm-up counting, self-SLO evaluation)
and gauge publication — none of which fire per event. Invariants:

  * one pass at a time: ticks and event passes serialize on one lock,
    and dueness is re-checked under it, so a key reconciled by the tick
    (and requeued at now+interval) is skipped by a racing event pass —
    never double-reconciled;
  * the ladder holds: a key parked on retryable backoff or DEACTIVATED
    is revived by a DIRECT watch event exactly as before (due=0), and a
    failure inside an event pass walks the same _requeue ladder a tick
    failure does;
  * the watch callback thread never blocks: marking dirty is a set-add
    + signal; the pass itself runs on the manager's event thread (or
    whoever calls run_event_pass in simulated-time harnesses);
  * controllers may additionally declare `event_routes() -> (kinds,)`:
    events on those kinds mark the controller's OWN objects dirty
    (a pending Pod wakes the pendingCapacity producers; a refreshed
    producer wakes the autoscalers) — routed dirtying never revives
    backoff/deactivated keys, only a direct event does.

With event_driven=False (the default) none of this machinery is built
and the loop is byte-identical to the tick-paced engine.
"""

from __future__ import annotations

import threading
import time as _time
from typing import Dict, List, Optional, Protocol

from karpenter_tpu.api import conditions as cond
from karpenter_tpu.controllers.errors import is_retryable
from karpenter_tpu.observability import default_tracer
from karpenter_tpu.resilience import DecorrelatedJitterBackoff
from karpenter_tpu.store import Store
from karpenter_tpu.utils.log import logger

_NEVER = float("inf")  # the deactivated requeue time


class Controller(Protocol):
    def kind(self) -> str:
        """Kind of the resource this controller owns."""
        ...

    def interval(self) -> float:
        """Seconds between reconciles (reference: controller.go:37-41)."""
        ...

    def reconcile(self, obj) -> None:
        """Domain reconcile; raise to mark the resource not Active."""
        ...


class Manager:
    def __init__(
        self, store: Store, clock=_time.time, registry=None,
        solver_service=None,
        backoff_base_s: float = 1.0,
        backoff_cap_s: float = 60.0,
        backoff_seed: int = 0,
        tick_hook=None,
        recovery_journal=None,
        event_driven: bool = False,
        event_debounce_s: float = 0.05,
        event_thread: bool = True,
    ):
        self.store = store
        self.clock = clock
        # crash safety (karpenter_tpu/recovery): `recovery_journal` is a
        # JournalHandle persisting per-object backoff state — without
        # it, a crash-looping object restarts its ladder at the base
        # delay every controller restart, defeating the backoff exactly
        # when it matters. `tick_hook` fires after each full
        # reconcile_all pass (the recovery warm-up counts ticks on it).
        self._tick_hook = tick_hook
        self._journal = recovery_journal
        # shared solve service (solver/service.py): the manager refreshes
        # its point-in-time gauges (queue depth, coalesce factor, stage
        # percentiles) every tick, so /metrics shows them alongside the
        # runtime series with no extra wiring in __main__.py
        self._solver_service = solver_service
        self._controllers: List[Controller] = []
        # kinds whose controller ACKS the e2e lead-time mark
        # (`acks_e2e = True`, the SNG controller): marks are only
        # stamped for these — stamping every kind would be per-object
        # tracer-lock traffic on the hot path that no ack ever reads
        self._e2e_kinds: set = set()
        # (kind, namespace, name) -> next due time; 0 = due now,
        # inf = deactivated (revived only by a watch event)
        self._due: Dict[tuple, float] = {}
        # per-object retryable-failure ladder: key -> previous delay
        self._backoff = DecorrelatedJitterBackoff(
            base_s=backoff_base_s, cap_s=backoff_cap_s, seed=backoff_seed
        )
        self._backoff_prev: Dict[tuple, float] = {}
        # event-driven reconcile (module docstring): the dirty-key set
        # feeding coalesced event passes, the lock serializing passes
        # (tick AND event) and the debounce/scheduler state. All of it
        # is inert when event_driven is False.
        self.event_driven = event_driven
        self.event_debounce_s = event_debounce_s
        self._own_event_thread = event_thread
        self._dirty: set = set()
        self._dirty_lock = threading.Lock()
        # mid-reconcile event race (event-driven mode only): a watch
        # event landing while a pass is BETWEEN fetching a key's object
        # and requeueing it acted on state the reconcile never saw —
        # and the interval requeue would overwrite the event's due-now
        # stamp, parking the key until the backstop tick. Each external
        # event bumps the key's sequence; the pass snapshots it at
        # collection time and a changed sequence at requeue time keeps
        # the key due-now + dirty instead.
        self._event_seq: Dict[tuple, int] = {}
        self._pass_seq: Dict[tuple, int] = {}
        self._dirty_since: Optional[float] = None
        self._pass_lock = threading.Lock()
        self._event_signal = threading.Event()
        self._event_worker: Optional[threading.Thread] = None
        self._closed = False
        # the key whose status THIS thread is currently patching inside
        # _finish: its own watch echo must not re-stamp a just-retired
        # e2e mark (or schedule an event pass) — see _on_event
        self._patching = threading.local()
        # self-observability (the reference gets controller-runtime's
        # metrics for free; here the manager publishes its own):
        # karpenter_runtime_{tick_seconds,reconciles_total,
        # reconcile_errors_total}{name=<kind>|manager}
        self._tick_gauge = self._count_gauge = self._error_gauge = None
        self._backoff_gauge = self._deactivated_gauge = None
        self._event_pass_gauge = self._event_keys_gauge = None
        self._event_debounce_gauge = None
        if registry is not None:
            self._tick_gauge = registry.register("runtime", "tick_seconds")
            self._count_gauge = registry.register(
                "runtime", "reconciles_total", kind="counter"
            )
            self._error_gauge = registry.register(
                "runtime", "reconcile_errors_total", kind="counter"
            )
            # event-pass observability (module docstring): passes that
            # dispatched >= 1 due key, the keys they carried, and the
            # last pass's measured debounce gather (first dirty mark ->
            # pass start) — the coalescing signal an operator tunes
            # --event-debounce against
            self._event_pass_gauge = registry.register(
                "runtime", "event_passes_total", kind="counter"
            )
            self._event_keys_gauge = registry.register(
                "runtime", "event_pass_keys_total", kind="counter"
            )
            self._event_debounce_gauge = registry.register(
                "runtime", "event_debounce_ms"
            )
            # ladder observability: the last requeue backoff per kind and
            # how many objects have been deactivated by non-retryable
            # errors (karpenter_resilience_* — docs/resilience.md)
            self._backoff_gauge = registry.register(
                "resilience", "requeue_backoff_seconds"
            )
            self._deactivated_gauge = registry.register(
                "resilience", "deactivated_total", kind="counter"
            )

    def _count(self, gauge, name: str, delta: float = 1.0) -> None:
        # process-level series: namespace "-" keeps them distinct from
        # object-namespace-labeled producer gauges on dashboards
        if gauge is not None:
            gauge.inc(name, "-", delta)

    def register(self, *controllers: Controller) -> "Manager":
        """reference: manager.go:59-71"""
        for controller in controllers:
            self._controllers.append(controller)
            if getattr(controller, "acks_e2e", False):
                self._e2e_kinds.add(controller.kind())
            self.store.watch(controller.kind(), self._on_event)
            self._register_routes(controller)
        return self

    def _register_routes(self, controller) -> None:
        """Event-driven mode only: a controller's `event_routes()` names
        EXTRA kinds whose events make the controller's own objects dirty
        (module docstring). Tick-paced mode registers nothing — routed
        kinds see zero new callbacks and behavior stays byte-identical."""
        if not self.event_driven:
            return
        routes = getattr(controller, "event_routes", None)
        if routes is None:
            return
        from functools import partial

        for kind in routes():
            self.store.watch(
                kind, partial(self._on_routed_event, controller)
            )

    def _on_routed_event(self, controller, event: str, obj) -> None:
        """A routed kind changed (a Pod appeared, a producer refreshed):
        mark the controller's own objects dirty so the next event pass
        re-evaluates them against the fresh signal. Routed dirtying is
        WEAKER than a direct watch event: it only pulls due times
        FORWARD for keys on the plain interval schedule — keys riding
        the retryable-backoff ladder or DEACTIVATED stay parked (only a
        direct event on the object itself revives, preserving the
        failure ladder under routed churn). Deletes route too — a
        removed pod frees capacity, which is as much a signal as a new
        one. The ladder guard and the due-now stamp are ONE critical
        section on the dirty lock — the ladder's own due writes
        (_requeue_backoff, _deactivate) take the same lock, so this
        check can never interleave with a parking write and erase it."""
        keys = self.store.keys(controller.kind())
        marked = False
        with self._dirty_lock:
            for key in keys:
                if key in self._backoff_prev:
                    continue  # parked on the retryable ladder
                if self._due.get(key, 0.0) == _NEVER:
                    continue  # deactivated
                self._due[key] = 0.0
                self._event_seq[key] = self._event_seq.get(key, 0) + 1
                self._mark_dirty_locked(key)
                marked = True
        if marked:
            self._wake_event_worker()

    def _on_event(self, event: str, obj) -> None:
        key = (obj.KIND, obj.metadata.namespace, obj.metadata.name)
        if event == "Deleted":
            self._due.pop(key, None)
            self._drop_backoff(key)
            self._event_seq.pop(key, None)
            self._pass_seq.pop(key, None)
            default_tracer().drop_observed(key)
            # controllers may keep per-object state of their own (the
            # SNG controller's circuit breakers + gauge series): give
            # them the same pruning signal the engine's maps get
            for controller in self._controllers:
                hook = getattr(controller, "on_deleted", None)
                if hook is not None and controller.kind() == obj.KIND:
                    hook(obj)
        elif (
            self.event_driven
            and getattr(self._patching, "key", None) == key
        ):
            # the engine's OWN status-patch echo (fired synchronously
            # from inside _finish, on this thread): _requeue — running
            # immediately after — owns this key's due time, and
            # re-stamping the e2e mark the reconcile just retired would
            # measure the NEXT divergence from our own write instead of
            # from its triggering event (the staleness that dominated
            # sub-second event passes). External writes racing the
            # patch arrive on other threads and are untouched. Gated on
            # event_driven: tick-paced mode keeps the pre-PR echo
            # semantics byte for byte (the wire-compat contract).
            return
        else:
            # watch events trigger immediate reconcile on the next tick,
            # overriding any scheduled requeue (the reference's watch-driven
            # actuation, DESIGN.md:435) — including the inf requeue of a
            # DEACTIVATED object: an external edit is the revival signal
            self._due[key] = 0.0
            # event-observed stamp for the end-to-end lead-time
            # histogram (karpenter_reconcile_e2e_seconds), only for
            # kinds whose controller acks it. overwrite=False: EVERY
            # store write notifies here — including this engine's own
            # per-reconcile status patches — so a pending mark must
            # survive re-notification or a multi-tick actuation would
            # be measured from its last self-patch (~one tick) instead
            # of the triggering event. The earliest stamp since the
            # mark was last retired IS the divergence observation: the
            # SNG controller acks the mark on actuation and drops it
            # on every converged reconcile, and the validation/
            # deactivation paths drop it too, so a stamp never
            # predates the divergence by more than one reconcile
            # interval
            if obj.KIND in self._e2e_kinds:
                default_tracer().mark_observed(key, overwrite=False)
            # event-driven mode: schedule the coalesced event pass and
            # bump the key's event sequence so a reconcile racing this
            # event detects it at requeue time (_note_event and the
            # _requeue re-check serialize on the dirty lock, so the
            # bump and the due-now stamp are atomic vs the re-check)
            if self.event_driven:
                self._note_event(key)

    # -- event passes (module docstring) -----------------------------------

    def _mark_dirty_locked(self, key: tuple) -> None:
        """Set-add only (caller holds the dirty lock): the writer
        thread never waits on reconcile work."""
        self._dirty.add(key)
        if self._dirty_since is None:
            self._dirty_since = self.clock()

    def _wake_event_worker(self) -> None:
        self._event_signal.set()
        if self._own_event_thread:
            self._ensure_event_worker()

    def _note_event(self, key: tuple) -> None:
        """Record one external event on `key`: due-now stamp, sequence
        bump, dirty mark — all under the dirty lock, so the bump can
        never land between _requeue's staleness comparison and its
        interval due-write (which would let the interval overwrite the
        event's due-now stamp and park the key until the backstop)."""
        with self._dirty_lock:
            self._due[key] = 0.0
            self._event_seq[key] = self._event_seq.get(key, 0) + 1
            self._mark_dirty_locked(key)
        self._wake_event_worker()

    def _ensure_event_worker(self) -> None:
        if self._event_worker is not None or self._closed:
            return
        with self._dirty_lock:
            if self._event_worker is not None or self._closed:
                return
            self._event_worker = threading.Thread(
                target=self._event_loop,
                name="manager-event-pass",
                daemon=True,
            )
            self._event_worker.start()

    def _event_loop(self) -> None:
        """The debounced scheduler: wake on the first dirty mark, sleep
        the debounce window out (events landing meanwhile join the same
        pass), run ONE coalesced pass, repeat."""
        while not self._closed:
            self._event_signal.wait()
            if self._closed:
                return
            self._event_signal.clear()
            _time.sleep(self.event_debounce_s)
            try:
                self.run_event_pass()
            except Exception:  # noqa: BLE001 — the backstop tick repairs
                logger().exception("event pass failed; tick will resync")

    def dirty_count(self) -> int:
        """Keys awaiting an event pass (simulated-time harnesses poll
        this to drive run_event_pass without the wall-clock thread)."""
        with self._dirty_lock:
            return len(self._dirty)

    def run_event_pass(self) -> int:
        """One coalesced event pass: swap out the dirty set, reconcile
        the dirty keys that are DUE, return how many were dispatched.

        Dueness is re-checked under the pass lock — a key the tick (or
        a previous pass) just reconciled was requeued at now+interval
        and is skipped here, which is the no-double-reconcile guarantee.
        Tick consumers (tick_hook, solver gauge publication) explicitly
        do NOT run: they stay on the tick cadence."""
        with self._dirty_lock:
            if not self._dirty:
                return 0
            dirty, self._dirty = self._dirty, set()
            since, self._dirty_since = self._dirty_since, None
        with self._pass_lock:
            now = self.clock()
            # a dirty key only ever becomes due via an event's due-now
            # stamp — a MISSING entry means the object was deleted after
            # dirtying (the Deleted handler pops _due), so it must not
            # default to due-now and inflate the pass gauges
            due = {
                k for k in dirty
                if (d := self._due.get(k)) is not None and d <= now
            }
            if not due:
                return 0
            if self._event_debounce_gauge is not None and since is not None:
                self._event_debounce_gauge.set(
                    "manager", "-", max(0.0, now - since) * 1e3
                )
            with default_tracer().trace(
                "reconcile.event_pass", keys=len(due)
            ):
                for controller in self._controllers:
                    self._reconcile_controller(controller, now, keys=due)
        self._count(self._event_pass_gauge, "manager")
        self._count(self._event_keys_gauge, "manager", float(len(due)))
        return len(due)

    def close(self) -> None:
        """Stop the event-pass thread (idempotent; a tick-paced manager
        has nothing to stop)."""
        self._closed = True
        self._event_signal.set()
        worker = self._event_worker
        if worker is not None:
            worker.join(timeout=5.0)
            self._event_worker = None

    # -- the generic workflow (reference: controller.go:67-97) -------------

    def _finish(self, controller, obj, error: Optional[Exception]) -> None:
        mgr = obj.status_conditions()
        if error is not None and obj.KIND in self._e2e_kinds:
            # a failed reconcile proved nothing about convergence: keep
            # the mark and a converged-but-flapping object would carry
            # it into a much later actuation's karpenter_reconcile_e2e_
            # seconds sample. Dropping under-reports lead during fault
            # windows instead — the conservative direction (degraded-
            # path visibility is the flight recorder's job)
            default_tracer().drop_observed(self._key_of(obj))
        if error is not None:
            mgr.mark_false(cond.ACTIVE, "", str(error))
            logger().error(
                "Controller failed to reconcile kind %s %s: %s",
                obj.KIND,
                obj.metadata.name,
                error,
            )
        else:
            mgr.mark_true(cond.ACTIVE)
        self._count(self._count_gauge, obj.KIND)
        if error is not None:
            self._count(self._error_gauge, obj.KIND)
        self._patching.key = self._key_of(obj)
        try:
            patched = self.store.patch_status(obj)
        except KeyError:
            return  # deleted mid-reconcile
        except Exception as patch_error:  # noqa: BLE001 — store hiccup
            # the store is a dependency like the provider: a failed
            # status write requeues on the retryable ladder (the write
            # is redone wholesale by the next reconcile) and NEVER
            # deactivates — the conditions were not persisted, so a
            # deactivation here would strand the object invisibly
            logger().warning(
                "status patch failed for %s %s: %s; requeueing with "
                "backoff", obj.KIND, obj.metadata.name, patch_error,
            )
            self._count(self._error_gauge, obj.KIND)
            self._requeue_backoff(self._key_of(obj))
            return
        finally:
            self._patching.key = None
        self._requeue(controller, self._key_of(obj), error, patched)

    @staticmethod
    def _key_of(obj) -> tuple:
        return (obj.KIND, obj.metadata.namespace, obj.metadata.name)

    def _requeue(
        self, controller, key, error: Optional[Exception], patched=None
    ) -> None:
        """The supervised requeue ladder: interval on success, jittered
        backoff on retryable failure, deactivation on non-retryable."""
        observed_seq = self._pass_seq.pop(key, None)
        if error is None:
            self._drop_backoff(key)
            self._requeue_success(controller, key, observed_seq)
        elif is_retryable(error):
            self._requeue_backoff(key)
        else:
            self._deactivate(key, patched)

    def _requeue_success(self, controller, key, observed_seq) -> None:
        """Interval requeue after a successful reconcile — unless a
        watch event raced it (landed after the object was fetched): the
        state just acted on is already stale, so the key stays due-now
        + dirty and the next pass re-reconciles, instead of the
        interval requeue silently swallowing the event until the
        backstop tick (the _deactivate resourceVersion re-check,
        generalized to the success path). Comparison and due-write are
        one critical section with _note_event: a bump can never land
        between them unseen."""
        if not self.event_driven:
            self._due[key] = self.clock() + controller.interval()
            return
        with self._dirty_lock:
            if (
                observed_seq is not None
                and self._event_seq.get(key, 0) != observed_seq
            ):
                self._due[key] = 0.0
                self._mark_dirty_locked(key)
                raced = True
            else:
                self._due[key] = self.clock() + controller.interval()
                raced = False
        if raced:
            self._wake_event_worker()

    def _deactivate(self, key, patched) -> None:
        """DEACTIVATE: no requeue until a watch event revives the
        object (_on_event). Exactly-once by construction — the object
        is never due again, so _finish cannot re-run. Concurrency
        guard: an EXTERNAL write landing during this reconcile fired
        its revival event before we got here and due=inf would silently
        discard it — detectable because the stored resourceVersion has
        moved past our own status patch. Reconcile once more instead of
        deactivating."""
        current = self.store.try_get(*key)
        if (
            current is not None
            and patched is not None
            and current.metadata.resource_version
            != patched.metadata.resource_version
        ):
            self._due[key] = 0.0
            return
        # the journaled ladder is dropped too: a crash-restart must not
        # revive a DEACTIVATED object through a stale finite due time
        # restored from the journal
        self._drop_backoff(key)
        # a deactivated object will not actuate until revived: retire
        # any pending e2e mark so the revival's actuation measures from
        # the reviving edit, not from before the deactivation. The due
        # write takes the dirty lock so the routed-event guard cannot
        # interleave and erase the inf stamp (_on_routed_event).
        default_tracer().drop_observed(key)
        with self._dirty_lock:
            self._due[key] = _NEVER
        if self._deactivated_gauge is not None:
            self._deactivated_gauge.inc(key[0], "-")

    def _drop_backoff(self, key) -> None:
        """Retire an object's backoff ladder, in memory AND in the
        journal (one idiom for success, deletion, and deactivation)."""
        if (
            self._backoff_prev.pop(key, None) is not None
            and self._journal is not None
        ):
            self._journal.delete(key)

    def _requeue_backoff(self, key) -> None:
        delay = self._backoff.next(self._backoff_prev.get(key, 0.0))
        # under the dirty lock: the routed-event guard reads the ladder
        # (_on_routed_event) and must never interleave between these
        # two writes — it would revive a key the ladder is parking
        with self._dirty_lock:
            self._backoff_prev[key] = delay
            self._due[key] = self.clock() + delay
        if self._journal is not None:
            self._journal.set(
                key, {"prev": delay, "due": self._due[key]}
            )
        if self._backoff_gauge is not None:
            self._backoff_gauge.set(key[0], "-", delay)

    def snapshot_backoff(self) -> Dict[str, dict]:
        """Live backoff table for the recovery checkpoint."""
        from karpenter_tpu.recovery.journal import key_str

        return {
            key_str(key): {"prev": prev, "due": self._due.get(key, 0.0)}
            for key, prev in self._backoff_prev.items()
        }

    def restore_backoff(self, entries: dict) -> None:
        """Rebuild the per-object backoff ladder from a replayed journal
        table, so a crash-looping object cannot reset its ladder by
        crashing the controller. Restored due times are CAPPED at
        now + backoff cap: an object journaled long before the outage
        ended must come due within one max-backoff window, never stay
        parked on a stale far-future (or inf) stamp."""
        from karpenter_tpu.recovery.journal import key_tuple

        now = self.clock()
        restored = 0
        # snapshot the items: `entries` aliases the journal's live
        # mirror table, and the delete below folds back into it —
        # iterating the dict itself would crash the recovery boot
        for k, doc in list(entries.items()):
            key = key_tuple(k)
            if self.store.try_get(*key) is None:
                # deleted while we were down: no Deleted event will
                # ever fire for it — drop the entry now or it would
                # re-persist through every future checkpoint
                if self._journal is not None:
                    self._journal.delete(key)
                continue
            prev = min(float(doc["prev"]), self._backoff.cap_s)
            self._backoff_prev[key] = prev
            self._due[key] = min(
                float(doc["due"]), now + self._backoff.cap_s
            )
            restored += 1
        if restored:
            logger().info(
                "engine: restored backoff state for %d object(s) from "
                "the journal", restored,
            )

    def _due_objects(self, kind: str, now: float, keys) -> list:
        """Due objects of `kind`: the full key sweep on a tick, the
        dirty-key slice on an event pass. Dueness is decided on keys so
        idle ticks never deep-copy the fleet; only due objects are
        fetched."""
        candidates = (
            self.store.keys(kind)
            if keys is None
            else [k for k in keys if k[0] == kind]
        )
        due_objs = []
        for key in candidates:
            if self._due.get(key, 0.0) > now:
                continue
            if self.event_driven:
                # snapshot the event sequence BEFORE fetching: an event
                # landing after the snapshot (even mid-fetch) shows up
                # as a seq change at requeue time and re-reconciles. The
                # reverse order would fold a mid-collection event into
                # the snapshot and let the interval requeue swallow it —
                # spurious re-reconciles are safe, swallowed events are
                # not (_requeue_success re-checks).
                self._pass_seq[key] = self._event_seq.get(key, 0)
            obj = self.store.try_get(*key)
            if obj is not None:
                due_objs.append(obj)
        return due_objs

    def _validate(self, obj) -> Optional[Exception]:
        try:
            obj.validate()
            return None
        except Exception as e:  # noqa: BLE001
            return e

    def _reconcile_controller(
        self, controller, now: float, keys=None
    ) -> None:
        """One controller's slice of the pass: collect due objects,
        validate, dispatch. `keys=None` is the full tick sweep; an event
        pass restricts the sweep to its dirty keys (already filtered for
        dueness, re-filtered here for the kind)."""
        kind = controller.kind()
        due_objs = self._due_objects(kind, now, keys)
        if not due_objs:
            return

        tracer = default_tracer()
        e2e = kind in self._e2e_kinds
        valid_objs = []
        for obj in due_objs:
            error = self._validate(obj)
            if error is not None:
                # _finish retires any pending e2e mark on the error
                # path: an invalid object cannot actuate, and an
                # hours-later revival must not measure its lead time
                # from a stamp that predates the fix
                self._finish(controller, obj, error)
            else:
                if e2e:
                    # interval-driven reconciles have no watch event:
                    # the tick entry IS the observation point for the
                    # e2e lead time (stamped AFTER validation — a
                    # failing object never accrues a mark). setdefault
                    # semantics — an earlier event stamp wins.
                    tracer.mark_observed(
                        self._key_of(obj), overwrite=False
                    )
                valid_objs.append(obj)
        with tracer.span(
            f"reconcile.{kind}", objects=len(valid_objs)
        ):
            self._dispatch(controller, valid_objs)

    def _dispatch(self, controller, valid_objs) -> None:
        """Batch path when the controller offers one, else per-object."""
        batch = getattr(controller, "reconcile_batch", None)
        if batch is not None and valid_objs:
            obj_key = lambda o: (o.metadata.namespace, o.metadata.name)
            try:
                errors = batch(valid_objs)
            except Exception as e:  # noqa: BLE001 - batch-wide failure
                errors = {obj_key(o): e for o in valid_objs}
            for obj in valid_objs:
                self._finish(controller, obj, errors.get(obj_key(obj)))
        else:
            for obj in valid_objs:
                try:
                    controller.reconcile(obj)
                    error = None
                except Exception as e:  # noqa: BLE001
                    error = e
                self._finish(controller, obj, error)

    def reconcile_all(self) -> None:
        """One manager tick: every due object of every controller.

        The tick is a reconcile-trace entry point (docs/observability.md):
        a trace id is minted here and every span opened inside — the
        per-kind reconcile, the HA fleet decide, solver requests, SNG
        actuation — inherits it through the tracer's thread-local
        stack, so one trace connects a watch event to the coalesced
        dispatch to the provider write it caused."""
        start = _time.perf_counter()
        # one pass at a time: a tick and an event pass must never
        # reconcile concurrently (run_event_pass holds the same lock);
        # with event_driven off the lock is always uncontended
        with self._pass_lock:
            now = self.clock()
            with default_tracer().trace("reconcile.tick"):
                for controller in self._controllers:
                    self._reconcile_controller(controller, now)
        if self._solver_service is not None:
            # per-tick dispatch accounting BEFORE the gauges publish:
            # note_tick closes this tick's window (dispatches since the
            # last tick -> karpenter_solver_dispatches_per_tick), the
            # number the fused tick collapses from 3+ to 1
            self._solver_service.note_tick()
            self._solver_service.publish_gauges()
        if self._tick_hook is not None:
            self._tick_hook()
        if self._tick_gauge is not None:
            self._tick_gauge.set(
                "manager", "-", _time.perf_counter() - start
            )

    def run(self, duration: float, tick: float = 0.1) -> None:
        """Drive reconcile_all on a wall-clock loop for `duration` seconds."""
        deadline = self.clock() + duration
        while self.clock() < deadline:
            self.reconcile_all()
            _time.sleep(tick)

    def converge(self, ticks: int = 5) -> None:
        """Run N immediate ticks ignoring intervals (test convergence helper,
        the ExpectEventuallyHappy analog — expectations.go:51-61)."""
        for _ in range(ticks):
            self._due = {k: 0.0 for k in self._due}
            self.reconcile_all()
