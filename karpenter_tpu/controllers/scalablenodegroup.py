"""ScalableNodeGroup controller: actuate replicas against the cloud provider.

reference: pkg/controllers/scalablenodegroup/v1alpha1/controller.go:48-95 —
stabilization check, observe replicas into status, set replicas when spec
diverges; retryable provider errors mark AbleToScale false WITHOUT
deactivating the resource (the next loop will likely succeed).

Consolidation (karpenter_tpu/consolidation) rides this controller: the
engine plans drains on this controller's cadence (`maybe_plan`), expresses
an approved drain as a spec.replicas decrement through the scale
subresource, and this controller's ordinary spec-vs-observed loop performs
the provider call — consolidation never bypasses the one actuation door.
When the scale-down lands, the engine is told (`on_scale_down`) so it can
finalize the drained nodes.
"""

from __future__ import annotations

from karpenter_tpu.api import conditions as cond
from karpenter_tpu.api.scalablenodegroup import ScalableNodeGroup
from karpenter_tpu.controllers.errors import error_code, is_retryable
from karpenter_tpu.utils.log import logger


class ScalableNodeGroupController:
    def __init__(self, cloud_provider_factory, consolidator=None):
        self.cloud_provider = cloud_provider_factory
        # ConsolidationEngine (or None): planning is bounded by the
        # engine's own interval, so calling it every reconcile is cheap
        self.consolidator = consolidator

    def kind(self) -> str:
        return ScalableNodeGroup.KIND

    def interval(self) -> float:
        return 60.0

    def _reconcile(self, resource) -> None:
        if self.consolidator is not None:
            # plan before observing: an approved drain decrements
            # spec.replicas via the scale subresource, and the resulting
            # watch event requeues this resource immediately — the
            # actuation lands on the very next tick
            self.consolidator.maybe_plan()
        node_group = self.cloud_provider.node_group_for(resource.spec)
        mgr = resource.status_conditions()

        # 1. stabilization state -> condition
        stable, message = node_group.stabilized()
        if stable:
            mgr.mark_true(cond.STABILIZED)
        else:
            mgr.mark_false(cond.STABILIZED, "", message)

        # 2. observe replicas
        observed = node_group.get_replicas()
        resource.status.replicas = observed

        # 3. actuate when spec diverges from observation. Scale-UPS never
        # pile onto a group mid-change: overlapping grow resizes against a
        # pool whose previous resize is in flight can strand partial TPU
        # slices (tpu.py module doc); the next loop grows once stable.
        # Scale-DOWNS actuate even while unstable — when a group is stuck
        # converging (e.g. an ASG capped below desired by a capacity
        # shortage, permanently un-stable under the healthy==desired
        # check), the corrective shrink is exactly the action that
        # unsticks it, and blocking it would deadlock the resource.
        if resource.spec.replicas is None or resource.spec.replicas == observed:
            return
        if not stable and resource.spec.replicas > observed:
            return
        node_group.set_replicas(resource.spec.replicas)
        logger().debug(
            "ScalableNodeGroup %s updated nodes %d -> %d",
            resource.spec.id,
            observed,
            resource.spec.replicas,
        )
        if resource.spec.replicas < observed:
            self._finish_scale_down(
                resource, mgr, observed, stable, message
            )

    def _finish_scale_down(
        self, resource, mgr, observed: int, stable: bool, message: str
    ) -> None:
        """Post-actuation bookkeeping for a shrink: let the consolidation
        engine finalize any drains this scale-down carries, and surface a
        disruption-under-instability as a STRUCTURED condition (reason +
        transition timestamp) on the API object, not just a log line —
        operators watching the resource see WHY a shrinking group moved
        while unconverged."""
        drained = []
        if self.consolidator is not None:
            drained = self.consolidator.on_scale_down(
                resource.metadata.namespace,
                resource.metadata.name,
                observed - resource.spec.replicas,
            )
        if not stable:
            detail = (
                f"scale-down {observed}->{resource.spec.replicas} "
                f"actuated while unstable: {message}"
            )
            if drained:
                detail += f" (consolidation drained {', '.join(drained)})"
            mgr.mark_false(
                cond.STABILIZED, "ScaleDownWhileUnstable", detail
            )

    def reconcile(self, resource) -> None:
        mgr = resource.status_conditions()
        try:
            self._reconcile(resource)
        except Exception as e:  # noqa: BLE001
            if is_retryable(e):
                # stay Active; just flag the transient inability to scale
                # (reference: controller.go:83-95)
                mgr.mark_false(cond.ABLE_TO_SCALE, "", error_code(e) or str(e))
                return
            raise
        mgr.mark_true(cond.ABLE_TO_SCALE)
