"""ScalableNodeGroup controller: actuate replicas against the cloud provider.

reference: pkg/controllers/scalablenodegroup/v1alpha1/controller.go:48-95 —
stabilization check, observe replicas into status, set replicas when spec
diverges; retryable provider errors mark AbleToScale false WITHOUT
deactivating the resource (the next loop will likely succeed).
"""

from __future__ import annotations

from karpenter_tpu.api import conditions as cond
from karpenter_tpu.api.scalablenodegroup import ScalableNodeGroup
from karpenter_tpu.controllers.errors import error_code, is_retryable
from karpenter_tpu.utils.log import logger


class ScalableNodeGroupController:
    def __init__(self, cloud_provider_factory):
        self.cloud_provider = cloud_provider_factory

    def kind(self) -> str:
        return ScalableNodeGroup.KIND

    def interval(self) -> float:
        return 60.0

    def _reconcile(self, resource) -> None:
        node_group = self.cloud_provider.node_group_for(resource.spec)
        mgr = resource.status_conditions()

        # 1. stabilization state -> condition
        stable, message = node_group.stabilized()
        if stable:
            mgr.mark_true(cond.STABILIZED)
        else:
            mgr.mark_false(cond.STABILIZED, "", message)

        # 2. observe replicas
        observed = node_group.get_replicas()
        resource.status.replicas = observed

        # 3. actuate when spec diverges from observation. Scale-UPS never
        # pile onto a group mid-change: overlapping grow resizes against a
        # pool whose previous resize is in flight can strand partial TPU
        # slices (tpu.py module doc); the next loop grows once stable.
        # Scale-DOWNS actuate even while unstable — when a group is stuck
        # converging (e.g. an ASG capped below desired by a capacity
        # shortage, permanently un-stable under the healthy==desired
        # check), the corrective shrink is exactly the action that
        # unsticks it, and blocking it would deadlock the resource.
        if resource.spec.replicas is None or resource.spec.replicas == observed:
            return
        if not stable and resource.spec.replicas > observed:
            return
        node_group.set_replicas(resource.spec.replicas)
        logger().debug(
            "ScalableNodeGroup %s updated nodes %d -> %d",
            resource.spec.id,
            observed,
            resource.spec.replicas,
        )

    def reconcile(self, resource) -> None:
        mgr = resource.status_conditions()
        try:
            self._reconcile(resource)
        except Exception as e:  # noqa: BLE001
            if is_retryable(e):
                # stay Active; just flag the transient inability to scale
                # (reference: controller.go:83-95)
                mgr.mark_false(cond.ABLE_TO_SCALE, "", error_code(e) or str(e))
                return
            raise
        mgr.mark_true(cond.ABLE_TO_SCALE)
