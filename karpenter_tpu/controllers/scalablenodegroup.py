"""ScalableNodeGroup controller: actuate replicas against the cloud provider.

reference: pkg/controllers/scalablenodegroup/v1alpha1/controller.go:48-95 —
stabilization check, observe replicas into status, set replicas when spec
diverges; retryable provider errors mark AbleToScale false WITHOUT
deactivating the resource (the next loop will likely succeed).

Consolidation (karpenter_tpu/consolidation) rides this controller: the
engine plans drains on this controller's cadence (`maybe_plan`), expresses
an approved drain as a spec.replicas decrement through the scale
subresource, and this controller's ordinary spec-vs-observed loop performs
the provider call — consolidation never bypasses the one actuation door.
When the scale-down lands, the engine is told (`on_scale_down`) so it can
finalize the drained nodes.

Circuit breaker (docs/resilience.md): each node group carries its own
breaker around the provider calls. After `circuit_failure_threshold`
consecutive provider failures the circuit OPENS: reconciles stop
touching the provider entirely (a flapping cloud API no longer eats the
tick) and the resource reports AbleToScale=False with the structured
ActuationCircuitOpen reason, the last RetryableError.code, and the
next-probe ETA. After `circuit_reset_s` one half-open probe reconcile is
admitted; success closes the circuit, failure re-opens it for a fresh
window.
"""

from __future__ import annotations

from typing import Dict

from karpenter_tpu.api import conditions as cond
from karpenter_tpu.api.scalablenodegroup import ScalableNodeGroup
from karpenter_tpu.controllers.errors import error_code, is_retryable
from karpenter_tpu.resilience import CLOSED as resilience_CLOSED
from karpenter_tpu.resilience import CircuitBreaker
from karpenter_tpu.utils.log import logger


class ScalableNodeGroupController:
    def __init__(
        self,
        cloud_provider_factory,
        consolidator=None,
        preemptor=None,
        registry=None,
        circuit_failure_threshold: int = 5,
        circuit_reset_s: float = 120.0,
        clock=None,
    ):
        import time as _time

        self.cloud_provider = cloud_provider_factory
        # ConsolidationEngine (or None): planning is bounded by the
        # engine's own interval, so calling it every reconcile is cheap
        self.consolidator = consolidator
        # PreemptionEngine (or None): same cadence door — eviction
        # planning rides the reconcile loop, interval-bounded in-engine
        self.preemptor = preemptor
        self.circuit_failure_threshold = circuit_failure_threshold
        self.circuit_reset_s = circuit_reset_s
        self.clock = clock or _time.monotonic
        # one breaker per resource (namespace, name): group A's flapping
        # ASG must not trip group B's actuation
        self._breakers: Dict[tuple, CircuitBreaker] = {}
        self._g_circuit = self._c_opens = None
        if registry is not None:
            self._g_circuit = registry.register(
                "resilience", "circuit_state"
            )
            self._c_opens = registry.register(
                "resilience", "circuit_open_total", kind="counter"
            )

    def kind(self) -> str:
        return ScalableNodeGroup.KIND

    def interval(self) -> float:
        return 60.0

    def on_deleted(self, resource) -> None:
        """Engine deletion hook: drop the per-object breaker and its
        gauge series — a recreated group with the same name must start
        with a CLOSED circuit, not inherit a dead group's open one."""
        self._breakers.pop(
            (resource.metadata.namespace, resource.metadata.name), None
        )
        if self._g_circuit is not None:
            self._g_circuit.remove(
                resource.metadata.name, resource.metadata.namespace
            )

    def _breaker(self, resource) -> CircuitBreaker:
        key = (resource.metadata.namespace, resource.metadata.name)
        breaker = self._breakers.get(key)
        if breaker is None:
            breaker = self._breakers[key] = CircuitBreaker(
                failure_threshold=self.circuit_failure_threshold,
                reset_s=self.circuit_reset_s,
                clock=self.clock,
            )
        return breaker

    def _publish_circuit(self, resource, breaker: CircuitBreaker) -> None:
        if self._g_circuit is not None:
            self._g_circuit.set(
                resource.metadata.name,
                resource.metadata.namespace,
                breaker.state_value(),
            )

    def _reconcile(self, resource) -> None:
        if self.preemptor is not None:
            # preemption plans BEFORE consolidation: admitting a
            # high-priority pending pod may consume the very free
            # capacity a drain was counting on — planning order makes
            # the preemption hold visible to this tick's drain gate
            self.preemptor.maybe_plan()
        if self.consolidator is not None:
            # plan before observing: an approved drain decrements
            # spec.replicas via the scale subresource, and the resulting
            # watch event requeues this resource immediately — the
            # actuation lands on the very next tick
            self.consolidator.maybe_plan()
        node_group = self.cloud_provider.node_group_for(resource.spec)
        mgr = resource.status_conditions()

        # 1. stabilization state -> condition
        stable, message = node_group.stabilized()
        if stable:
            mgr.mark_true(cond.STABILIZED)
        else:
            mgr.mark_false(cond.STABILIZED, "", message)

        # 2. observe replicas
        observed = node_group.get_replicas()
        resource.status.replicas = observed

        # 3. actuate when spec diverges from observation. Scale-UPS never
        # pile onto a group mid-change: overlapping grow resizes against a
        # pool whose previous resize is in flight can strand partial TPU
        # slices (tpu.py module doc); the next loop grows once stable.
        # Scale-DOWNS actuate even while unstable — when a group is stuck
        # converging (e.g. an ASG capped below desired by a capacity
        # shortage, permanently un-stable under the healthy==desired
        # check), the corrective shrink is exactly the action that
        # unsticks it, and blocking it would deadlock the resource.
        if resource.spec.replicas is None or resource.spec.replicas == observed:
            return
        if not stable and resource.spec.replicas > observed:
            return
        node_group.set_replicas(resource.spec.replicas)
        logger().debug(
            "ScalableNodeGroup %s updated nodes %d -> %d",
            resource.spec.id,
            observed,
            resource.spec.replicas,
        )
        if resource.spec.replicas < observed:
            self._finish_scale_down(
                resource, mgr, observed, stable, message
            )

    def _finish_scale_down(
        self, resource, mgr, observed: int, stable: bool, message: str
    ) -> None:
        """Post-actuation bookkeeping for a shrink: let the consolidation
        engine finalize any drains this scale-down carries, and surface a
        disruption-under-instability as a STRUCTURED condition (reason +
        transition timestamp) on the API object, not just a log line —
        operators watching the resource see WHY a shrinking group moved
        while unconverged."""
        drained = []
        if self.consolidator is not None:
            drained = self.consolidator.on_scale_down(
                resource.metadata.namespace,
                resource.metadata.name,
                observed - resource.spec.replicas,
            )
        if not stable:
            detail = (
                f"scale-down {observed}->{resource.spec.replicas} "
                f"actuated while unstable: {message}"
            )
            if drained:
                detail += f" (consolidation drained {', '.join(drained)})"
            mgr.mark_false(
                cond.STABILIZED, "ScaleDownWhileUnstable", detail
            )

    def _mark_circuit_open(self, resource, breaker: CircuitBreaker) -> None:
        """ActuationCircuitOpen condition: machine-readable reason, with
        the last RetryableError.code and next-probe ETA in the message —
        the operator sees WHY actuation is paused without log-diving."""
        resource.status_conditions().mark_false(
            cond.ABLE_TO_SCALE,
            cond.ACTUATION_CIRCUIT_OPEN,
            f"actuation circuit open for {resource.spec.id}: "
            f"{breaker.consecutive_failures} consecutive provider "
            f"failures (last code "
            f"{breaker.last_error_code or 'unknown'}); next probe in "
            f"{breaker.retry_in():.0f}s",
        )

    def _record_provider_failure(self, resource, breaker, err) -> None:
        opens_before = breaker.opens_total
        breaker.record_failure(error_code(err))
        if breaker.opens_total > opens_before:
            logger().warning(
                "actuation circuit OPENED for ScalableNodeGroup %s/%s "
                "after %d consecutive provider failures (last: %s)",
                resource.metadata.namespace, resource.metadata.name,
                breaker.consecutive_failures, err,
            )
            if self._c_opens is not None:
                self._c_opens.inc(
                    resource.metadata.name, resource.metadata.namespace
                )

    def reconcile(self, resource) -> None:
        mgr = resource.status_conditions()
        breaker = self._breaker(resource)
        if not breaker.allow():
            # open circuit: skip the provider ENTIRELY this tick — the
            # whole point of the breaker is that a flapping cloud API
            # stops consuming reconcile time. The resource stays Active
            # (this is a supervised degradation, not a resource fault).
            self._mark_circuit_open(resource, breaker)
            self._publish_circuit(resource, breaker)
            return
        try:
            self._reconcile(resource)
        except Exception as e:  # noqa: BLE001
            # EVERY failure feeds the breaker — in particular a
            # non-retryable one during a HALF_OPEN probe must record an
            # outcome, or the breaker wedges half-open (allow() False
            # forever) with no probe ever admitted again
            self._record_provider_failure(resource, breaker, e)
            self._publish_circuit(resource, breaker)
            if is_retryable(e):
                # stay Active; just flag the transient inability to scale
                # (reference: controller.go:83-95) — K consecutive
                # failures open the circuit
                if breaker.state != resilience_CLOSED:
                    self._mark_circuit_open(resource, breaker)
                else:
                    mgr.mark_false(
                        cond.ABLE_TO_SCALE, "", error_code(e) or str(e)
                    )
                return
            raise
        breaker.record_success()
        self._publish_circuit(resource, breaker)
        mgr.mark_true(cond.ABLE_TO_SCALE)
