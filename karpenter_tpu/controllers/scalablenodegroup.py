"""ScalableNodeGroup controller: actuate replicas against the cloud provider.

reference: pkg/controllers/scalablenodegroup/v1alpha1/controller.go:48-95 —
stabilization check, observe replicas into status, set replicas when spec
diverges; retryable provider errors mark AbleToScale false WITHOUT
deactivating the resource (the next loop will likely succeed).

Consolidation (karpenter_tpu/consolidation) rides this controller: the
engine plans drains on this controller's cadence (`maybe_plan`), expresses
an approved drain as a spec.replicas decrement through the scale
subresource, and this controller's ordinary spec-vs-observed loop performs
the provider call — consolidation never bypasses the one actuation door.
When the scale-down lands, the engine is told (`on_scale_down`) so it can
finalize the drained nodes.

Circuit breaker (docs/resilience.md): each node group carries its own
breaker around the provider calls. After `circuit_failure_threshold`
consecutive provider failures the circuit OPENS: reconciles stop
touching the provider entirely (a flapping cloud API no longer eats the
tick) and the resource reports AbleToScale=False with the structured
ActuationCircuitOpen reason, the last RetryableError.code, and the
next-probe ETA. After `circuit_reset_s` one half-open probe reconcile is
admitted; success closes the circuit, failure re-opens it for a fresh
window.

Crash safety (karpenter_tpu/recovery, docs/resilience.md "Crash
recovery"): with a RecoveryManager wired, every provider write is
FENCED — stamped with the incarnation's generation token, which the
provider verifies before applying, so a stale (restarted-over or
split-brain) controller cannot replay a dead decision — and journaled
as an intent/ack pair: an intent without an ack after a crash marks an
actuation of unknown fate, which the level-triggered spec-vs-observed
loop resolves idempotently (observed already at target → the write
landed; otherwise it is re-issued under a fresh token) — exactly-once
either way. Breaker state journals too: a provider that was flapping
before the crash is still circuit-broken after it.
"""

from __future__ import annotations

from typing import Dict

from karpenter_tpu.api import conditions as cond
from karpenter_tpu.api.scalablenodegroup import ScalableNodeGroup
from karpenter_tpu.controllers.errors import error_code, is_retryable
from karpenter_tpu.observability import (
    default_flight_recorder,
    default_tracer,
)
from karpenter_tpu.resilience import CLOSED as resilience_CLOSED
from karpenter_tpu.resilience import CircuitBreaker
from karpenter_tpu.utils.log import logger


def _serving_replicas(resource, observed: int, warm: int) -> int:
    """What status.replicas reports: SERVING replicas, warm headroom
    excluded. The scale subresource feeds the decision kernel's
    proportional math as current replicas (Value/Utilization targets),
    and counting warm nodes there would ratchet the fleet up by the
    warm amount every tick (spec rises to match the inflated status,
    warm rides on top, repeat until maxReplicas). Only nodes BEYOND
    spec.replicas are warm — mid-transition, everything observed up to
    spec is serving — and with warm 0 this is exactly `observed`
    (byte-identical pre-warm-pool behavior)."""
    if resource.spec.replicas is None or warm <= 0:
        return observed
    return min(observed, max(resource.spec.replicas, observed - warm))


class ScalableNodeGroupController:
    # this controller ACKS the e2e lead-time mark (ack_observed on the
    # provider-write return, drop_observed on convergence): the engine
    # only stamps marks for kinds that declare this — stamping kinds
    # nothing acks would be pure hot-path overhead (engine._on_event)
    acks_e2e = True

    def __init__(
        self,
        cloud_provider_factory,
        consolidator=None,
        preemptor=None,
        warmpool=None,
        registry=None,
        circuit_failure_threshold: int = 5,
        circuit_reset_s: float = 120.0,
        clock=None,
        recovery=None,
    ):
        import time as _time

        self.cloud_provider = cloud_provider_factory
        # ConsolidationEngine (or None): planning is bounded by the
        # engine's own interval, so calling it every reconcile is cheap
        self.consolidator = consolidator
        # PreemptionEngine (or None): same cadence door — eviction
        # planning rides the reconcile loop, interval-bounded in-engine
        self.preemptor = preemptor
        # WarmPoolEngine (or None): spec.warmPool groups actuate
        # spec.replicas + warm through this controller's one provider
        # door (docs/cost.md "Warm pools"); groups without the spec see
        # byte-identical behavior (warm == 0)
        self.warmpool = warmpool
        self.circuit_failure_threshold = circuit_failure_threshold
        self.circuit_reset_s = circuit_reset_s
        self.clock = clock or _time.monotonic
        # one breaker per resource (namespace, name): group A's flapping
        # ASG must not trip group B's actuation
        self._breakers: Dict[tuple, CircuitBreaker] = {}
        # crash safety (module docstring): the RecoveryManager supplies
        # the fence generation, the breaker/actuation journal handles,
        # and the replayed tables restored below
        self.recovery = recovery
        self.fence = recovery.fence if recovery is not None else None
        self._j_breaker = self._j_actuation = None
        # (namespace, name) -> un-acked intent: live during the provider
        # write, and restored from the journal after a crash
        self._intents: Dict[tuple, dict] = {}
        # breakers currently present in the journal table (avoids a
        # delete record per healthy reconcile)
        self._journaled_breakers: set = set()
        self._g_circuit = self._c_opens = None
        if registry is not None:
            self._g_circuit = registry.register(
                "resilience", "circuit_state"
            )
            self._c_opens = registry.register(
                "resilience", "circuit_open_total", kind="counter"
            )
        if recovery is not None:
            self._j_breaker = recovery.handle("breaker")
            self._j_actuation = recovery.handle("actuation")
            self._restore_recovery_state()
            recovery.register_snapshot("breaker", self.snapshot_breakers)
            recovery.register_snapshot("actuation", self._snapshot_intents)

    def kind(self) -> str:
        return ScalableNodeGroup.KIND

    def interval(self) -> float:
        return 60.0

    def on_deleted(self, resource) -> None:
        """Engine deletion hook: drop the per-object breaker and its
        gauge series — a recreated group with the same name must start
        with a CLOSED circuit, not inherit a dead group's open one."""
        key = (resource.metadata.namespace, resource.metadata.name)
        self._breakers.pop(key, None)
        if self.warmpool is not None:
            self.warmpool.on_deleted(resource)
        if self._j_breaker is not None and key in self._journaled_breakers:
            self._j_breaker.delete(key)
            self._journaled_breakers.discard(key)
        # a pending intent dies with its group too: a later group
        # RECREATED under the same name must not resolve a dead epoch's
        # actuation intent
        if self._intents.pop(key, None) is not None and (
            self._j_actuation is not None
        ):
            self._j_actuation.delete(key)
        if self._g_circuit is not None:
            self._g_circuit.remove(
                resource.metadata.name, resource.metadata.namespace
            )

    # -- crash-safe state (karpenter_tpu/recovery) -------------------------

    def _journal_breaker(self, key: tuple, breaker: CircuitBreaker) -> None:
        if self._j_breaker is None:
            return
        if breaker.state == resilience_CLOSED and (
            breaker.consecutive_failures == 0
        ):
            # a pristine breaker is the default: journal a delete (once)
            # instead of a set, so the table — and the per-tick journal
            # traffic of a HEALTHY fleet — stays proportional to sick
            # groups, not to fleet size
            if key in self._journaled_breakers:
                self._j_breaker.delete(key)
                self._journaled_breakers.discard(key)
            return
        self._j_breaker.set(key, self._breaker_doc(breaker))
        self._journaled_breakers.add(key)

    @staticmethod
    def _breaker_doc(breaker: CircuitBreaker) -> dict:
        return {
            "state": breaker.state,
            "failures": breaker.consecutive_failures,
            "opened_at": breaker.opened_at,
            "opens_total": breaker.opens_total,
            "code": breaker.last_error_code,
        }

    def snapshot_breakers(self) -> Dict[str, dict]:
        from karpenter_tpu.recovery.journal import key_str

        return {
            key_str(key): self._breaker_doc(breaker)
            for key, breaker in self._breakers.items()
            if not (
                breaker.state == resilience_CLOSED
                and breaker.consecutive_failures == 0
            )
        }

    def _snapshot_intents(self) -> Dict[str, dict]:
        from karpenter_tpu.recovery.journal import key_str

        return {key_str(k): v for k, v in self._intents.items()}

    def _restore_recovery_state(self) -> None:
        """Rebuild breakers and pending actuation intents from the
        replayed journal tables. A restored OPEN breaker keeps its
        window (opened_at capped at now — a skewed stamp must not
        shorten it); a pending intent marks a pre-crash provider write
        of unknown fate, resolved idempotently on first reconcile."""
        from karpenter_tpu.recovery.journal import key_tuple

        now = self.clock()
        for k, doc in self.recovery.table("breaker").items():
            key = key_tuple(k)
            breaker = CircuitBreaker(
                failure_threshold=self.circuit_failure_threshold,
                reset_s=self.circuit_reset_s,
                clock=self.clock,
            )
            breaker.state = doc["state"]
            breaker.consecutive_failures = int(doc["failures"])
            opened = doc.get("opened_at")
            breaker.opened_at = (
                None if opened is None else min(float(opened), now)
            )
            breaker.opens_total = int(doc.get("opens_total", 0))
            breaker.last_error_code = doc.get("code", "")
            self._breakers[key] = breaker
            self._journaled_breakers.add(key)
        for k, doc in self.recovery.table("actuation").items():
            # mark journal-restored intents: only THESE get the
            # crash-recovery log wording when resolved (an in-session
            # provider failure also leaves an un-acked intent, and
            # calling that "recovered" would send operators hunting
            # for restarts that never happened)
            self._intents[key_tuple(k)] = dict(doc, restored=True)
        if self._breakers or self._intents:
            logger().info(
                "scalablenodegroup: restored %d breaker(s) and %d "
                "pending actuation intent(s) from the journal",
                len(self._breakers), len(self._intents),
            )

    def prune_restored_missing(self, store) -> None:
        """Drop restored breakers/intents whose group was deleted while
        the controller was down — no Deleted event will ever fire for
        them, so without this sweep they would re-persist through every
        future checkpoint forever. The runtime calls this once after
        restore, against the re-listed store."""
        for key in list(self._breakers):
            if store.try_get("ScalableNodeGroup", *key) is None:
                self._breakers.pop(key, None)
                if key in self._journaled_breakers:
                    self._j_breaker.delete(key)
                    self._journaled_breakers.discard(key)
        for akey in list(self._intents):
            if store.try_get("ScalableNodeGroup", *akey) is None:
                self._intents.pop(akey, None)
                if self._j_actuation is not None:
                    self._j_actuation.delete(akey)

    def _breaker(self, resource) -> CircuitBreaker:
        key = (resource.metadata.namespace, resource.metadata.name)
        breaker = self._breakers.get(key)
        if breaker is None:
            breaker = self._breakers[key] = CircuitBreaker(
                failure_threshold=self.circuit_failure_threshold,
                reset_s=self.circuit_reset_s,
                clock=self.clock,
            )
        return breaker

    def _publish_circuit(self, resource, breaker: CircuitBreaker) -> None:
        if self._g_circuit is not None:
            self._g_circuit.set(
                resource.metadata.name,
                resource.metadata.namespace,
                breaker.state_value(),
            )

    def _reconcile(self, resource) -> None:
        if self.preemptor is not None:
            # preemption plans BEFORE consolidation: admitting a
            # high-priority pending pod may consume the very free
            # capacity a drain was counting on — planning order makes
            # the preemption hold visible to this tick's drain gate
            self.preemptor.maybe_plan()
        if self.consolidator is not None:
            # plan before observing: an approved drain decrements
            # spec.replicas via the scale subresource, and the resulting
            # watch event requeues this resource immediately — the
            # actuation lands on the very next tick
            self.consolidator.maybe_plan()
        node_group = self.cloud_provider.node_group_for(resource.spec)
        mgr = resource.status_conditions()

        # 1. stabilization state -> condition
        stable, message = node_group.stabilized()
        if stable:
            mgr.mark_true(cond.STABILIZED)
        else:
            mgr.mark_false(cond.STABILIZED, "", message)

        # 2. observe replicas
        warm = (
            self.warmpool.warm_for(resource)
            if self.warmpool is not None
            else 0
        )
        observed = node_group.get_replicas()
        resource.status.replicas = _serving_replicas(
            resource, observed, warm
        )

        self._resolve_pending_intent(resource, observed)

        # 3. actuate when the TARGET diverges from observation — target
        # = spec.replicas + warm headroom (docs/cost.md "Warm pools";
        # warm is 0 without spec.warmPool, keeping the pre-cost
        # divergence check byte for byte). Scale-UPS never pile onto a
        # group mid-change: overlapping grow resizes against a pool
        # whose previous resize is in flight can strand partial TPU
        # slices (tpu.py module doc); the next loop grows once stable.
        # Scale-DOWNS actuate even while unstable — when a group is
        # stuck converging (e.g. an ASG capped below desired by a
        # capacity shortage, permanently un-stable under the
        # healthy==desired check), the corrective shrink is exactly the
        # action that unsticks it, and blocking it would deadlock the
        # resource.
        if resource.spec.replicas is None:
            default_tracer().drop_observed(self._e2e_key(resource))
            return
        target = resource.spec.replicas + warm
        if target == observed:
            # converged, nothing to actuate: retire any e2e observation
            # mark — a stale stamp must not inflate a later ack's
            # karpenter_reconcile_e2e_seconds sample
            default_tracer().drop_observed(self._e2e_key(resource))
            return
        if not stable and target > observed:
            return
        self._set_replicas(node_group, resource, target)
        # the provider write returned: the actuation is ACKED — close
        # the event-observed -> actuation-acked window (the BLITZSCALE
        # lead-time observable, docs/observability.md)
        default_tracer().ack_observed(self._e2e_key(resource))
        logger().debug(
            "ScalableNodeGroup %s updated nodes %d -> %d (%d warm)",
            resource.spec.id,
            observed,
            target,
            warm,
        )
        if target < observed:
            self._finish_scale_down(
                resource, mgr, observed, target, stable, message
            )

    def _resolve_pending_intent(self, resource, observed: int) -> None:
        """Resolve a pre-crash actuation of unknown fate (an intent
        journaled without an ack): the fresh observation settles it —
        either the write landed before the crash (observed == target;
        nothing to redo) or it didn't and the level-triggered
        spec-vs-observed step re-issues it under a fresh fence token.
        Exactly-once by idempotent replay, never a blind redo."""
        akey = (resource.metadata.namespace, resource.metadata.name)
        intent = self._intents.pop(akey, None)
        if intent is None or self._j_actuation is None:
            return
        self._j_actuation.delete(akey)
        outcome = (
            "landed before the crash"
            if intent.get("target") == observed
            else "not applied; the reconcile loop re-issues it"
        )
        if intent.get("restored"):
            logger().info(
                "recovered actuation intent for %s/%s (target %s): "
                "observed %d — %s",
                akey[0], akey[1], intent.get("target"), observed, outcome,
            )
        else:
            # same-incarnation leftover of a raised provider call: the
            # ordinary retry path, not a crash recovery
            logger().debug(
                "unresolved actuation intent for %s/%s (target %s): "
                "observed %d — %s",
                akey[0], akey[1], intent.get("target"), observed, outcome,
            )

    @staticmethod
    def _e2e_key(resource) -> tuple:
        """The engine's object key — where the manager stamped the
        event-observed time this controller's ack closes."""
        return (
            resource.KIND,
            resource.metadata.namespace,
            resource.metadata.name,
        )

    def _set_replicas(self, node_group, resource, target: int) -> None:
        """The one provider-write door — `target` includes any warm-pool
        headroom on top of spec.replicas. Unfenced (no RecoveryManager):
        the plain call, byte-compatible with every existing provider
        fake. Fenced: journal the intent, stamp the incarnation's fence
        token (the provider verifies it before applying), ack on
        success. A raised provider call leaves the intent UN-acked —
        its fate is unknown (a timeout may have landed), and the next
        reconcile's observation resolves it idempotently."""
        with default_tracer().span(
            "actuate.set_replicas",
            group=resource.spec.id,
            target=target,
            fenced=self.fence is not None,
        ):
            if self.fence is None:
                node_group.set_replicas(target)
                return
            akey = (resource.metadata.namespace, resource.metadata.name)
            intent = {
                "target": target,
                "gen": self.fence.generation,
            }
            self._intents[akey] = intent
            if self._j_actuation is not None:
                self._j_actuation.set(akey, intent)
            node_group.set_replicas(target, token=self.fence.token())
            self._intents.pop(akey, None)
            if self._j_actuation is not None:
                self._j_actuation.delete(akey)

    def _finish_scale_down(
        self, resource, mgr, observed: int, target: int, stable: bool,
        message: str,
    ) -> None:
        """Post-actuation bookkeeping for a shrink: let the consolidation
        engine finalize any drains this scale-down carries, and surface a
        disruption-under-instability as a STRUCTURED condition (reason +
        transition timestamp) on the API object, not just a log line —
        operators watching the resource see WHY a shrinking group moved
        while unconverged."""
        drained = []
        if self.consolidator is not None:
            drained = self.consolidator.on_scale_down(
                resource.metadata.namespace,
                resource.metadata.name,
                observed - target,
            )
        if not stable:
            detail = (
                f"scale-down {observed}->{target} "
                f"actuated while unstable: {message}"
            )
            if drained:
                detail += f" (consolidation drained {', '.join(drained)})"
            mgr.mark_false(
                cond.STABILIZED, "ScaleDownWhileUnstable", detail
            )

    def _mark_circuit_open(self, resource, breaker: CircuitBreaker) -> None:
        """ActuationCircuitOpen condition: machine-readable reason, with
        the last RetryableError.code and next-probe ETA in the message —
        the operator sees WHY actuation is paused without log-diving."""
        resource.status_conditions().mark_false(
            cond.ABLE_TO_SCALE,
            cond.ACTUATION_CIRCUIT_OPEN,
            f"actuation circuit open for {resource.spec.id}: "
            f"{breaker.consecutive_failures} consecutive provider "
            f"failures (last code "
            f"{breaker.last_error_code or 'unknown'}); next probe in "
            f"{breaker.retry_in():.0f}s",
        )

    def _record_provider_failure(self, resource, breaker, err) -> None:
        opens_before = breaker.opens_total
        code = error_code(err)
        breaker.record_failure(code)
        key = (resource.metadata.namespace, resource.metadata.name)
        self._journal_breaker(key, breaker)
        if self.recovery is not None:
            from karpenter_tpu.recovery.fence import FENCE_REJECTED_CODE

            if code == FENCE_REJECTED_CODE:
                # a provider refused this incarnation's stamp: we are
                # the stale (restarted-over / split-brain) controller
                self.recovery.count_fence_rejection()
        if breaker.opens_total > opens_before:
            logger().warning(
                "actuation circuit OPENED for ScalableNodeGroup %s/%s "
                "after %d consecutive provider failures (last: %s)",
                resource.metadata.namespace, resource.metadata.name,
                breaker.consecutive_failures, err,
            )
            if self._c_opens is not None:
                self._c_opens.inc(
                    resource.metadata.name, resource.metadata.namespace
                )
            # flight-recorder event (trace id captured from the tick
            # span): which group's actuation went dark, and on what code
            default_flight_recorder().record(
                "circuit_open",
                group=f"{resource.metadata.namespace}/"
                      f"{resource.metadata.name}",
                failures=breaker.consecutive_failures,
                code=breaker.last_error_code or error_code(err) or "",
            )

    def reconcile(self, resource) -> None:
        mgr = resource.status_conditions()
        breaker = self._breaker(resource)
        if not breaker.allow():
            # open circuit: skip the provider ENTIRELY this tick — the
            # whole point of the breaker is that a flapping cloud API
            # stops consuming reconcile time. The resource stays Active
            # (this is a supervised degradation, not a resource fault).
            # Retire any pending e2e mark: convergence is UNKNOWABLE
            # without the provider, and a mark accrued on a converged
            # group during a flap would inflate the next real
            # actuation's lead time by the whole outage. Conservative
            # trade: lead during a circuit-open window is under-
            # reported (the flight recorder carries that story).
            default_tracer().drop_observed(self._e2e_key(resource))
            self._mark_circuit_open(resource, breaker)
            self._publish_circuit(resource, breaker)
            return
        try:
            self._reconcile(resource)
        except Exception as e:  # noqa: BLE001
            # EVERY failure feeds the breaker — in particular a
            # non-retryable one during a HALF_OPEN probe must record an
            # outcome, or the breaker wedges half-open (allow() False
            # forever) with no probe ever admitted again
            self._record_provider_failure(resource, breaker, e)
            self._publish_circuit(resource, breaker)
            if is_retryable(e):
                # stay Active; just flag the transient inability to scale
                # (reference: controller.go:83-95) — K consecutive
                # failures open the circuit
                if breaker.state != resilience_CLOSED:
                    self._mark_circuit_open(resource, breaker)
                else:
                    mgr.mark_false(
                        cond.ABLE_TO_SCALE, "", error_code(e) or str(e)
                    )
                return
            raise
        breaker.record_success()
        self._journal_breaker(
            (resource.metadata.namespace, resource.metadata.name), breaker
        )
        self._publish_circuit(resource, breaker)
        mgr.mark_true(cond.ABLE_TO_SCALE)
