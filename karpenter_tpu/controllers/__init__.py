from karpenter_tpu.controllers.engine import Controller, Manager
from karpenter_tpu.controllers.errors import (
    RetryableError,
    error_code,
    is_retryable,
)
from karpenter_tpu.controllers.horizontalautoscaler import (
    HorizontalAutoscalerController,
)
from karpenter_tpu.controllers.metricsproducer import MetricsProducerController
from karpenter_tpu.controllers.scalablenodegroup import (
    ScalableNodeGroupController,
)

__all__ = [
    "Controller",
    "Manager",
    "RetryableError",
    "error_code",
    "is_retryable",
    "HorizontalAutoscalerController",
    "MetricsProducerController",
    "ScalableNodeGroupController",
]
