"""CRD + deployment manifest generation from the API dataclasses.

The reference generates its CRD YAML with controller-gen from kubebuilder
markers on the Go structs (reference: Makefile:37-53 codegen target,
config/crd/*.yaml output, the scale-subresource marker at
pkg/apis/autoscaling/v1alpha1/scalablenodegroup.go:51). Here the Python
dataclasses ARE the schema source: this module reflects them into OpenAPI
v3 structural schemas so `config/crd/` can never drift from the types the
control plane actually validates — the same single-source-of-truth property
controller-gen gives the reference.

Run `python -m karpenter_tpu.codegen config/` (the Makefile's codegen
target) to regenerate; tests assert committed YAML == regenerated.
"""

from __future__ import annotations

import dataclasses
import sys
import typing
from typing import Any, Dict

import yaml

from karpenter_tpu.api.horizontalautoscaler import HorizontalAutoscaler
from karpenter_tpu.api.metricsproducer import MetricsProducer
from karpenter_tpu.api.poolgroup import PoolGroup
from karpenter_tpu.api.scalablenodegroup import ScalableNodeGroup
from karpenter_tpu.api.serialization import _FIELD_TO_KEY, snake_to_camel
from karpenter_tpu.utils.quantity import Quantity

GROUP = "autoscaling.karpenter.sh"
VERSION = "v1alpha1"

CRD_KINDS = {
    "HorizontalAutoscaler": {
        "cls": HorizontalAutoscaler,
        "plural": "horizontalautoscalers",
        "shortNames": ["ha"],
        "printcolumns": [
            # reference: kubectl printcolumn markers,
            # horizontalautoscaler.go:192-200
            {
                "name": "Min",
                "type": "integer",
                "jsonPath": ".spec.minReplicas",
            },
            {
                "name": "Desired",
                "type": "integer",
                "jsonPath": ".status.desiredReplicas",
            },
            {
                "name": "Max",
                "type": "integer",
                "jsonPath": ".spec.maxReplicas",
            },
            {
                "name": "Ready",
                "type": "string",
                "jsonPath": '.status.conditions[?(@.type=="Ready")].status',
            },
        ],
    },
    "MetricsProducer": {
        "cls": MetricsProducer,
        "plural": "metricsproducers",
        "shortNames": ["mp"],
        "printcolumns": [
            {
                "name": "Ready",
                "type": "string",
                "jsonPath": '.status.conditions[?(@.type=="Ready")].status',
            },
        ],
    },
    "PoolGroup": {
        "cls": PoolGroup,
        "plural": "poolgroups",
        "shortNames": ["pg"],
        "printcolumns": [
            {
                "name": "Coordinated",
                "type": "boolean",
                "jsonPath": ".status.coordinated",
            },
            {
                "name": "Hourly",
                "type": "number",
                "jsonPath": ".status.expectedHourly",
            },
        ],
    },
    "ScalableNodeGroup": {
        "cls": ScalableNodeGroup,
        "plural": "scalablenodegroups",
        "shortNames": ["sng"],
        # reference: scale-subresource kubebuilder marker,
        # scalablenodegroup.go:51
        "scale": {
            "specReplicasPath": ".spec.replicas",
            "statusReplicasPath": ".status.replicas",
        },
        "printcolumns": [
            {
                "name": "Replicas",
                "type": "integer",
                "jsonPath": ".status.replicas",
            },
            {
                "name": "Type",
                "type": "string",
                "jsonPath": ".spec.type",
            },
        ],
    },
}


def _unwrap_optional(tp: Any) -> Any:
    if typing.get_origin(tp) is typing.Union:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def schema_for_type(tp: Any) -> Dict[str, Any]:
    """Python type -> OpenAPI v3 structural schema node."""
    tp = _unwrap_optional(tp)
    origin = typing.get_origin(tp)
    if origin in (list, typing.List):
        (item,) = typing.get_args(tp) or (Any,)
        return {"type": "array", "items": schema_for_type(item)}
    if origin in (dict, typing.Dict):
        args = typing.get_args(tp)
        val = args[1] if len(args) == 2 else Any
        return {
            "type": "object",
            "additionalProperties": schema_for_type(val),
        }
    return _schema_for_scalar(tp)


def _schema_for_scalar(tp: Any) -> Dict[str, Any]:
    if tp is Quantity:
        # apimachinery resource.Quantity serializes as a string
        return {"type": "string"}
    if dataclasses.is_dataclass(tp):
        return schema_for_dataclass(tp)
    if tp is int:
        return {"type": "integer"}
    if tp is float:
        return {"type": "number"}
    if tp is bool:
        return {"type": "boolean"}
    if tp is str:
        return {"type": "string"}
    # Any / unknown: accept arbitrary structure
    return {"x-kubernetes-preserve-unknown-fields": True}


def schema_for_dataclass(cls: type) -> Dict[str, Any]:
    hints = typing.get_type_hints(cls)
    props = {}
    for f in dataclasses.fields(cls):
        key = _FIELD_TO_KEY.get(f.name, snake_to_camel(f.name))
        props[key] = schema_for_type(hints[f.name])
    return {"type": "object", "properties": props}


def crd_manifest(kind: str) -> Dict[str, Any]:
    info = CRD_KINDS[kind]
    cls = info["cls"]
    hints = typing.get_type_hints(cls)
    spec_schema = schema_for_type(hints["spec"])
    status_schema = schema_for_type(hints["status"])
    version: Dict[str, Any] = {
        "name": VERSION,
        "served": True,
        "storage": True,
        "schema": {
            "openAPIV3Schema": {
                "type": "object",
                "properties": {
                    "apiVersion": {"type": "string"},
                    "kind": {"type": "string"},
                    "metadata": {"type": "object"},
                    "spec": spec_schema,
                    "status": status_schema,
                },
            }
        },
        "subresources": {"status": {}},
        "additionalPrinterColumns": info["printcolumns"],
    }
    if "scale" in info:
        version["subresources"]["scale"] = info["scale"]
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {
            "name": f"{info['plural']}.{GROUP}",
            "annotations": {
                # cert-manager CA injection for conversion/admission,
                # reference: config/crd kustomize patches
                "cert-manager.io/inject-ca-from": (
                    "karpenter/karpenter-serving-cert"
                ),
            },
        },
        "spec": {
            "group": GROUP,
            "names": {
                "kind": kind,
                "listKind": f"{kind}List",
                "plural": info["plural"],
                "singular": kind.lower(),
                "shortNames": info["shortNames"],
            },
            "scope": "Namespaced",
            "versions": [version],
        },
    }


def crd_yaml(kind: str) -> str:
    return yaml.safe_dump(
        crd_manifest(kind), sort_keys=False, default_flow_style=False
    )


def write_crds(config_dir: str) -> list:
    import os

    crd_dir = os.path.join(config_dir, "crd")
    os.makedirs(crd_dir, exist_ok=True)
    # the Helm chart installs CRDs via the crds/ convention (applied
    # before templates, never templated); write the SAME content there so
    # the chart can't drift from the types — both copies are codegen
    # outputs, pinned equal by tests/test_codegen.py
    chart_crds = os.path.join(
        os.path.dirname(os.path.abspath(os.path.normpath(config_dir))),
        "charts",
        "karpenter-tpu",
        "crds",
    )
    chart_present = os.path.isdir(os.path.dirname(chart_crds))
    if chart_present:
        os.makedirs(chart_crds, exist_ok=True)
    written = []
    for kind, info in CRD_KINDS.items():
        content = crd_yaml(kind)
        path = os.path.join(crd_dir, f"{GROUP}_{info['plural']}.yaml")
        with open(path, "w") as f:
            f.write(content)
        written.append(path)
        if chart_present:
            chart_path = os.path.join(
                chart_crds, f"{GROUP}_{info['plural']}.yaml"
            )
            with open(chart_path, "w") as f:
                f.write(content)
            written.append(chart_path)
    return written


# ---------------------------------------------------------------------------
# API reference docs (the reference generates docs/README.md with
# gen-crd-api-reference-docs, Makefile:72-77; here the same reference is
# rendered straight from the dataclasses that ARE the schema)
# ---------------------------------------------------------------------------


def _type_label(tp: Any) -> str:
    tp = _unwrap_optional(tp)
    origin = typing.get_origin(tp)
    if origin is typing.Union:  # non-Optional unions, e.g. int | str
        # \| keeps the label inside one markdown table cell
        return " \\| ".join(
            _type_label(arg)
            for arg in typing.get_args(tp)
            if arg is not type(None)
        )
    if origin in (list, typing.List):
        (item,) = typing.get_args(tp) or (Any,)
        return f"[]{_type_label(item)}"
    if origin in (dict, typing.Dict):
        args = typing.get_args(tp)
        val = _type_label(args[1]) if len(args) == 2 else "object"
        return f"map[string]{val}"
    if dataclasses.is_dataclass(tp):
        return f"[{tp.__name__}](#{tp.__name__.lower()})"
    return getattr(tp, "__name__", str(tp))


def _field_default_label(f) -> str:
    if f.default is not dataclasses.MISSING:
        return repr(f.default)
    if f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
        return f.default_factory.__name__ + "()"
    return ""


def _render_class_docs(cls, queue: list) -> list:
    """Markdown section for one API dataclass; nested dataclass types are
    appended to `queue` for later sections."""
    lines = [f"## {cls.__name__}", ""]
    doc = (cls.__doc__ or "").strip()
    if doc and not doc.startswith(f"{cls.__name__}("):
        # real docstring (the auto-generated dataclass signature is noise)
        lines.append(doc.split("\n\n")[0])
        lines.append("")
    lines.append("| Field | Type | Default |")
    lines.append("|---|---|---|")
    hints = typing.get_type_hints(cls)
    for f in dataclasses.fields(cls):
        key = _FIELD_TO_KEY.get(f.name, snake_to_camel(f.name))
        tp = _unwrap_optional(hints[f.name])
        if dataclasses.is_dataclass(tp):
            queue.append(tp)
        else:
            for arg in typing.get_args(tp):
                arg = _unwrap_optional(arg)
                if dataclasses.is_dataclass(arg):
                    queue.append(arg)
        default = _field_default_label(f)
        lines.append(f"| `{key}` | {_type_label(hints[f.name])} | {default} |")
    lines.append("")
    return lines


def api_docs_markdown() -> str:
    """One markdown API reference for the three CRDs, generated from the
    API dataclasses (single source of truth with the CRD schemas above)."""
    lines = [
        "# API reference",
        "",
        f"Group `{GROUP}`, version `{VERSION}`. Generated by "
        "`make docs` from `karpenter_tpu/api/` — do not edit by hand.",
        "",
    ]
    rendered = set()
    queue = [CRD_KINDS[kind]["cls"] for kind in CRD_KINDS]
    while queue:
        cls = queue.pop(0)
        if cls.__name__ in rendered:
            continue
        rendered.add(cls.__name__)
        lines.extend(_render_class_docs(cls, queue))
    return "\n".join(lines)


def write_api_docs(path: str = "docs/API.md") -> str:
    with open(path, "w") as f:
        f.write(api_docs_markdown())
    return path


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    if args and args[0] == "--docs":
        print(f"wrote {write_api_docs(args[1] if len(args) > 1 else 'docs/API.md')}")
        return 0
    config_dir = args[0] if args else "config"
    for path in write_crds(config_dir):
        print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
