"""Observability HTTP surface: /metrics, health, and the debug endpoints.

reference: the manager serves controller metrics on :8080
(cmd/controller/main.go:52,61) scraped by a dedicated Prometheus via a 5s
ServiceMonitor (config/prometheus/monitor.yaml:10-14); health/readiness
come from the manager. Here the same server additionally serves:

  /healthz               liveness ONLY: the process is up and serving —
                         always "ok" (a degraded-but-supervising control
                         plane must NOT be restarted by its liveness
                         probe; degradation is what /readyz reports)
  /readyz                readiness wired to REAL state via the
                         `readiness` callable: 503 during recovery
                         warm-up ticks and while the solver backend
                         health FSM is tripped (__main__.py wires it)
  /metrics               Prometheus text exposition (gauges, counters,
                         and native histograms — metrics/registry.py)
  /debug/traces          recent reconcile spans as JSON (?limit=N;
                         ?tenant=ID keeps the traces that touched that
                         tenant), same records `--trace-export` writes
                         as Chrome-trace JSONL (observability.tracing)
  /debug/flightrecorder  the flight-recorder event ring as JSON
                         (?kind=fsm_trip and ?tenant=ID filter)
  /debug/decisions       the decision provenance ledger as JSON
                         (?kind=&tenant=&group=&name=&limit= filter) —
                         observability.provenance, --provenance to
                         enable recording
  /debug/selfslo         the self-SLO scoreboard: per-window burn
                         rates/budget + solver FSM + per-tenant breaker
                         degradation (observability.selfslo)
  /debug/replicas        the replicated-control-plane scoreboard: this
                         replica's identity, the live-replica set,
                         per-partition lease holders, and per-tenant
                         handoff state (replication/plane.py;
                         enabled: false without --partitions)
  /debug/solver          the full solver posture as ONE JSON document:
                         compile-cache rungs + hit/miss + the compile
                         ledger tail, resident LRU contents, shard
                         route + extents, backend FSM, queue/pipeline
                         depths (observability.devicetelemetry,
                         --introspect; ?limit=N bounds the ledger tail)
  /debug/profile?ms=N    one bounded single-flight jax.profiler capture
                         written atomically into --journal-dir next to
                         the flight-recorder dumps, stamped with the
                         active trace id; 503 when the profiler probe
                         failed, a capture is in flight, or no
                         --journal-dir is configured
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

from karpenter_tpu.metrics.registry import GaugeRegistry

# readiness callable contract: () -> (ready, reason)
ReadinessCheck = Callable[[], Tuple[bool, str]]


def _parse_limit(query: dict) -> Optional[int]:
    """?limit=N as an int, None when absent or malformed (a broken
    limit serves everything rather than erroring a debug page)."""
    try:
        if "limit" in query:
            return int(query["limit"][0])
    except (ValueError, IndexError):
        pass
    return None


class MetricsServer:
    """Serves the gauge registry in Prometheus text exposition format
    plus the health/debug endpoints (module docstring).

    port=0 binds an ephemeral port (tests); `port` attribute holds the
    bound port after start(). `readiness` gates /readyz (None = always
    ready); `tracer`/`recorder` back the debug endpoints (None = the
    process defaults).
    """

    def __init__(
        self,
        registry: GaugeRegistry,
        port: int = 8080,
        host: str = "0.0.0.0",
        readiness: Optional[ReadinessCheck] = None,
        tracer=None,
        recorder=None,
        ledger=None,
        selfslo=None,
        introspection=None,
        profile_dir: Optional[str] = None,
        replication=None,
    ):
        self.registry = registry
        self.host = host
        self.port = port
        self.readiness = readiness
        self._tracer = tracer
        self._recorder = recorder
        self._ledger = ledger
        self._selfslo = selfslo
        # the solver introspection plane backing /debug/solver
        # (observability.devicetelemetry; None = endpoint reports
        # enabled: false) and the directory /debug/profile captures
        # into (the runtime wires --journal-dir; None = 503)
        self._introspection = introspection
        self._profile_dir = profile_dir
        # the replicated control plane backing /debug/replicas
        # (replication/plane.py scoreboard; None = endpoint reports
        # enabled: false — the single-replica deployment)
        self._replication = replication
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def _tracer_or_default(self):
        if self._tracer is not None:
            return self._tracer
        from karpenter_tpu.observability.tracing import default_tracer

        return default_tracer()

    def _recorder_or_default(self):
        if self._recorder is not None:
            return self._recorder
        from karpenter_tpu.observability.flightrecorder import (
            default_flight_recorder,
        )

        return default_flight_recorder()

    def _ledger_or_default(self):
        if self._ledger is not None:
            return self._ledger
        from karpenter_tpu.observability.provenance import default_ledger

        return default_ledger()

    # -- responses ---------------------------------------------------------

    def _respond_ready(self) -> Tuple[int, bytes, str]:
        if self.readiness is None:
            return 200, b"ok", "text/plain"
        try:
            ready, reason = self.readiness()
        except Exception as error:  # noqa: BLE001 — a broken check is NOT ready
            ready, reason = False, f"readiness check failed: {error}"
        if ready:
            return 200, b"ok", "text/plain"
        return 503, reason.encode(), "text/plain"

    def _respond_traces(self, query: dict) -> Tuple[int, bytes, str]:
        limit = _parse_limit(query)
        tracer = self._tracer_or_default()
        tenant = query.get("tenant", [None])[0]
        if tenant is not None:
            # per-tenant view (docs/multitenancy.md): keep whole TRACES
            # that touched the tenant — any span stamped tenant=<id>
            # (the tenancy serve spans, tenant-stamped solver requests)
            # selects its trace, and every span of a selected trace is
            # returned so the tick context around the tenant's work
            # survives the filter. The limit applies AFTER filtering.
            spans = tracer.snapshot()
            traces = {
                span["trace"] for span in spans
                if span["args"].get("tenant") == tenant
            }
            spans = [s for s in spans if s["trace"] in traces]
            if limit is not None and limit >= 0:
                spans = spans[-limit:] if limit else []
        else:
            spans = tracer.snapshot(limit=limit)
        body = json.dumps({
            "epoch_unix": tracer.epoch_unix,
            "spans_total": tracer.spans_total,
            "spans_dropped": tracer.spans_dropped,
            "spans": spans,
        }, sort_keys=True).encode()
        return 200, body, "application/json"

    def _respond_flightrecorder(self, query: dict) -> Tuple[int, bytes, str]:
        kind = query.get("kind", [None])[0]
        tenant = query.get("tenant", [None])[0]
        events = self._recorder_or_default().events(kind=kind)
        if tenant is not None:
            events = [e for e in events if e.get("tenant") == tenant]
        body = json.dumps({
            "events": events,
        }, sort_keys=True).encode()
        return 200, body, "application/json"

    def _respond_decisions(self, query: dict) -> Tuple[int, bytes, str]:
        limit = _parse_limit(query)
        ledger = self._ledger_or_default()
        body = json.dumps({
            "enabled": ledger.enabled,
            "records_total": ledger.records_total,
            "records_dropped": ledger.records_dropped,
            "decisions": ledger.query(
                kind=query.get("kind", [None])[0],
                tenant=query.get("tenant", [None])[0],
                group=query.get("group", [None])[0],
                name=query.get("name", [None])[0],
                limit=limit,
            ),
        }, sort_keys=True).encode()
        return 200, body, "application/json"

    def _respond_solver(self, query: dict) -> Tuple[int, bytes, str]:
        if self._introspection is None:
            body = json.dumps({"enabled": False}).encode()
            return 200, body, "application/json"
        limit = _parse_limit(query)
        snapshot = self._introspection.snapshot(
            ledger_limit=limit if limit is not None else 32
        )
        body = json.dumps(snapshot, sort_keys=True).encode()
        return 200, body, "application/json"

    def _respond_profile(self, query: dict) -> Tuple[int, bytes, str]:
        """One on-demand jax.profiler capture (observability.profiler
        capture_profile): bounded, single-flight, written atomically
        into the journal dir; every no-can-do answers 503 with the
        reason so an operator's curl explains itself."""
        from karpenter_tpu.observability.profiler import (
            ProfileBusy,
            ProfileUnavailable,
            capture_profile,
        )

        if not self._profile_dir:
            return (
                503,
                b"no --journal-dir configured: nowhere to write the "
                b"capture",
                "text/plain",
            )
        try:
            ms = int(query.get("ms", ["100"])[0])
        except (ValueError, IndexError):
            return 400, b"?ms=N must be an integer", "text/plain"
        tracer = self._tracer_or_default()
        # the active trace id: the serving thread carries no span, so
        # fall back to the newest recorded span's trace — the tick the
        # operator is (almost certainly) asking about
        trace_id = tracer.current_trace_id()
        if trace_id is None:
            newest = tracer.snapshot(limit=1)
            trace_id = newest[0]["trace"] if newest else None
        try:
            report = capture_profile(
                ms, self._profile_dir, trace_id=trace_id
            )
        except (ProfileUnavailable, ProfileBusy) as error:
            return 503, str(error).encode(), "text/plain"
        except Exception as error:  # noqa: BLE001 — capture must not 500-loop
            return (
                503,
                f"profiler capture failed: {error}".encode(),
                "text/plain",
            )
        body = json.dumps(report, sort_keys=True).encode()
        return 200, body, "application/json"

    def _respond_selfslo(self) -> Tuple[int, bytes, str]:
        if self._selfslo is None:
            body = json.dumps({"enabled": False}).encode()
        else:
            body = json.dumps(
                {"enabled": True, **self._selfslo.scoreboard()},
                sort_keys=True,
            ).encode()
        return 200, body, "application/json"

    def _respond_replicas(self) -> Tuple[int, bytes, str]:
        if self._replication is None:
            body = json.dumps({"enabled": False}).encode()
        else:
            body = json.dumps(
                {"enabled": True, **self._replication.scoreboard()},
                sort_keys=True,
            ).encode()
        return 200, body, "application/json"

    def _route(self, path: str, query: dict) -> Optional[Tuple[int, bytes, str]]:
        """(status, body, content-type) or None for 404."""
        if path in ("", "/healthz"):
            return 200, b"ok", "text/plain"
        if path == "/metrics":
            return (
                200,
                self.registry.expose_text().encode(),
                "text/plain; version=0.0.4",
            )
        handlers = {
            "/readyz": lambda q: self._respond_ready(),
            "/debug/traces": self._respond_traces,
            "/debug/flightrecorder": self._respond_flightrecorder,
            "/debug/decisions": self._respond_decisions,
            "/debug/selfslo": lambda q: self._respond_selfslo(),
            "/debug/replicas": lambda q: self._respond_replicas(),
            "/debug/solver": self._respond_solver,
            "/debug/profile": self._respond_profile,
        }
        handler = handlers.get(path)
        return handler(query) if handler is not None else None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> int:
        route = self._route

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                split = urlsplit(self.path)
                response = route(
                    split.path.rstrip("/"), parse_qs(split.query)
                )
                if response is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                status, body, content_type = response
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # quiet: scrapes every 5s
                pass

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
