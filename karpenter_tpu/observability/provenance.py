"""Decision provenance ledger: WHY did this group scale to N this tick?

The control plane's decisions are multi-stage — reactive decide ->
forecast blend (docs/forecasting.md) -> cost/SLO refinement with
movement-bound clamps (docs/cost.md) -> warm pools -> per-tenant
admission and breaker rungs (docs/multitenancy.md) — but until this
layer an operator asking "why did tenant X's group scale to N?" had to
reconstruct the answer from trace spans and scattered gauges. The
DecisionLedger records, for every HorizontalAutoscaler decision, the
full input chain as ONE structured record:

  observed metric values | forecast value/skill + whether the blend won
  | the cost-ladder candidate chosen with its risk/cost score and any
  budget/movement-bound clamp | warm-pool headroom | the solver backend
  + degradation rung actually used (device/sidecar/shard/numpy/mirror/
  floor) | tenant id + admission round | the reconcile trace id as a
  backlink into --trace-export / /debug/traces.

Storage discipline is the forecast history store's (forecast/history.py):
a BOUNDED COLUMNAR RING — preallocated numpy arrays per column, batch
appends as O(columns) slice assignments per *batched dispatch*, never
O(decisions) Python objects on the reconcile hot path. Python dicts are
only built at QUERY time (/debug/decisions, the JSONL export, the
--simulate "why" report), off the hot path.

Annotation model (mirrors the tracer's TLS threading): the subsystem
that OWNS a batch begins a staging LedgerBatch — the BatchAutoscaler for
the single-tenant fleet pass, the MultiTenantScheduler for cross-tenant
batches — and every subsystem the batch flows through annotates its own
slice where the arrays already are: the decide kernel outputs, the
forecast pass, CostEngine.adjust, the SolverService dispatch, the
tenancy scatter. In-thread code reaches the current batch through
`default_ledger().current()` with no parameter threading.

Posture matches tracing: DEFAULT OFF (`--provenance` enables). A
disabled ledger costs one attribute read per site and records nothing —
decisions are byte-identical with the ledger on or off (the ledger only
observes; tests/test_provenance.py property-pins both), and `make
bench-provenance` publishes the enabled-vs-disabled tick overhead
(<=5% target, docs/BENCHMARKS.md).
"""

from __future__ import annotations

import json
import math
import threading
import time as _time
from typing import Dict, List, Optional, Sequence

import numpy as np

SUBSYSTEM = "provenance"

# fixed width of the per-record observed-metric-values slice: a columnar
# ring cannot carry ragged rows, and fleets past 4 metrics per HA are
# vanishingly rare (observed_n records how many were real)
OBSERVED_WIDTH = 4

# winning-stage vocabulary (docs/observability.md "Decision provenance"):
# the single stage that best explains the final desired count, computed
# at commit with this precedence (first match wins)
STAGE_COST_BLIND = "cost_blind"
STAGE_COST_RAISE = "cost_raise"
STAGE_COST_CLAMP = "cost_clamp"
STAGE_FORECAST_BLEND = "forecast_blend"
STAGE_DEGRADED_FLOOR = "degraded_floor"
STAGE_ADMISSION_DEFERRAL = "admission_deferral"
STAGE_REACTIVE = "reactive"
# the full vocabulary in precedence order — the stable label index
# space consumers (simlab/labels.py label_stream) encode against
STAGES = (
    STAGE_COST_BLIND,
    STAGE_COST_RAISE,
    STAGE_COST_CLAMP,
    STAGE_FORECAST_BLEND,
    STAGE_DEGRADED_FLOOR,
    STAGE_ADMISSION_DEFERRAL,
    STAGE_REACTIVE,
)

# column schema: name -> (dtype, fill). Object columns hold interned
# strings (names that already exist elsewhere); numeric fills mark
# "never annotated" (NaN / -1) so queries can render them as null.
_NUMERIC_COLUMNS = (
    ("ts", np.float64, 0.0),
    ("seq", np.int64, 0),
    ("observed_n", np.int16, 0),
    ("prev_replicas", np.int32, -1),
    ("base_desired", np.int32, -1),
    ("final_desired", np.int32, -1),
    ("forecast_value", np.float32, np.nan),
    ("forecast_skill", np.float32, np.nan),
    ("forecast_blend", bool, False),
    ("forecast_active", bool, False),
    ("slo_opted", bool, False),
    ("cost_candidate", np.int32, -1),
    ("cost_risk", np.float32, np.nan),
    ("cost_hourly", np.float32, np.nan),
    ("cost_score", np.float32, np.nan),
    ("budget_clamped", bool, False),
    ("movement_clamped", bool, False),
    ("cost_blind", bool, False),
    ("pool_grouped", bool, False),
    ("pool_joint_repair", bool, False),
    ("warm_headroom", np.int32, -1),
    ("admission_round", np.int16, -1),
    ("deferred", bool, False),
)
_OBJECT_COLUMNS = (
    ("kind", ""),
    ("tenant", ""),
    ("namespace", ""),
    ("name", ""),
    ("group", ""),
    ("trace", ""),
    ("solver_backend", ""),
    ("solver_rung", ""),
    ("winning_stage", ""),
)
_COLUMN_FILLS: Dict[str, object] = {
    **{name: fill for name, _dtype, fill in _NUMERIC_COLUMNS},
    **dict(_OBJECT_COLUMNS),
}


class LedgerBatch:
    """Staging area for one batched dispatch's records: plain numpy
    columns of length `n`, committed to the ring in O(columns) slice
    assignments. `autosolver=True` marks a batch whose solver
    backend/rung annotation comes from inside SolverService.decide/cost
    (the BatchAutoscaler flow); the MultiTenantScheduler stamps rungs
    per tenant slice itself and leaves it False."""

    __slots__ = ("n", "cols", "autosolver")

    def __init__(self, n: int, autosolver: bool = False):
        self.n = n
        self.cols: Dict[str, object] = {}
        self.autosolver = autosolver

    def annotate(self, **columns) -> None:
        """Set whole-batch columns: each value is a scalar (broadcast)
        or a length-n sequence/array."""
        self.cols.update(columns)

    def _materialize(self, name: str) -> np.ndarray:
        """The column as a writable length-n array: a scalar (or
        absent) column broadcasts into a full array first, so partial
        writes compose with whole-batch annotations in either order."""
        staged = self.cols.get(name)
        if isinstance(staged, np.ndarray) and staged.shape:
            return staged
        fill = staged if staged is not None else _COLUMN_FILLS.get(name, 0)
        if isinstance(fill, (list, tuple)):
            staged = np.asarray(
                fill, object if any(
                    isinstance(v, str) for v in fill
                ) else None
            )
        elif isinstance(fill, str):
            staged = np.empty(self.n, object)
            staged[:] = fill
        else:
            staged = np.full(self.n, fill)
        self.cols[name] = staged
        return staged

    def annotate_rows(self, rows: Sequence[int], **columns) -> None:
        """Scatter values into a subset of rows (e.g. the SLO-opted
        rows of a cost pass); `columns` values are scalars or arrays
        indexed LIKE THE BATCH (length n — the cost outputs are already
        row-aligned with the decide batch)."""
        idx = np.asarray(list(rows), np.int64)
        for name, value in columns.items():
            staged = self._materialize(name)
            value = np.asarray(value)
            staged[idx] = value[idx] if value.shape else value

    def annotate_slice(self, start: int, stop: int, **columns) -> None:
        """Set columns on a contiguous row slice (the tenancy scatter:
        one tenant's rows inside a concatenated batch); values are
        scalars or length-(stop-start) arrays."""
        for name, value in columns.items():
            self._materialize(name)[start:stop] = value


class DecisionLedger:
    """Bounded columnar provenance ring (module docstring)."""

    def __init__(
        self,
        capacity: int = 4096,
        clock=_time.time,
        enabled: bool = False,
    ):
        self.enabled = enabled
        self.capacity = capacity
        self._clock = clock
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._head = 0  # next write slot
        self._size = 0  # valid records in the ring
        self._seq = 0
        self.records_total = 0
        self.records_dropped = 0
        self._rings: Dict[str, np.ndarray] = {}
        for name, dtype, fill in _NUMERIC_COLUMNS:
            self._rings[name] = np.full(capacity, fill, dtype)
        for name, fill in _OBJECT_COLUMNS:
            ring = np.empty(capacity, object)
            ring[:] = fill
            self._rings[name] = ring
        self._rings["observed"] = np.zeros(
            (capacity, OBSERVED_WIDTH), np.float32
        )
        self._c_records = self._c_dropped = None

    def bind_registry(self, registry) -> None:
        """karpenter_provenance_{records,dropped}_total."""
        self._c_records = registry.register(
            SUBSYSTEM, "records_total", kind="counter"
        )
        self._c_dropped = registry.register(
            SUBSYSTEM, "dropped_total", kind="counter"
        )

    # -- staging -----------------------------------------------------------

    def begin(
        self,
        kind: str,
        count: int,
        autosolver: bool = False,
        **columns,
    ) -> Optional[LedgerBatch]:
        """Open the staging batch for one batched dispatch and make it
        this thread's CURRENT batch (annotation sites reach it through
        current()). None when disabled — callers guard on `enabled`
        first, so the disabled hot path is one attribute read."""
        if not self.enabled or count <= 0:
            return None
        batch = LedgerBatch(count, autosolver=autosolver)
        batch.annotate(kind=kind, **columns)
        trace = _current_trace_id()
        if trace and "trace" not in columns:
            batch.annotate(trace=trace)
        self._tls.batch = batch
        return batch

    def current(self) -> Optional[LedgerBatch]:
        if not self.enabled:
            return None
        return getattr(self._tls, "batch", None)

    def abort(self, batch: Optional[LedgerBatch] = None) -> None:
        if getattr(self._tls, "batch", None) is (batch or self.current()):
            self._tls.batch = None

    # -- commit (the columnar append) --------------------------------------

    def commit(self, batch: Optional[LedgerBatch] = None) -> int:  # lint: allow-complexity — the columnar append: one arm per column class (ts/seq/staged/fill)
        """Append the staged batch to the ring: one (wrap-aware) slice
        assignment per column. Returns the records written."""
        if batch is None:
            batch = self.current()
        if batch is None:
            return 0
        if getattr(self._tls, "batch", None) is batch:
            self._tls.batch = None
        n = batch.n
        cols = batch.cols
        if "winning_stage" not in cols:
            cols["winning_stage"] = self._winning_stages(batch)
        now = self._clock()
        if n == 1:
            # the common single-HA tick: per-item writes skip the
            # slice-assignment broadcast machinery (~4x cheaper per
            # column, and the bench-provenance <=5% budget is paid in
            # exactly this shape)
            return self._commit_single(cols, now)
        with self._lock:
            keep = min(n, self.capacity)
            skip = n - keep  # oversized batch: oldest rows drop
            head = self._head
            for name, ring in self._rings.items():
                if name == "ts":
                    self._ring_write(ring, head, keep, now)
                elif name == "seq":
                    self._ring_write(
                        ring, head, keep,
                        np.arange(
                            self._seq + 1 + skip,
                            self._seq + 1 + n,
                            dtype=np.int64,
                        ),
                    )
                else:
                    value = cols.get(name, _COLUMN_FILLS.get(name, 0))
                    if isinstance(value, (list, tuple, np.ndarray)):
                        value = np.asarray(value)
                        if value.shape and value.shape[0] == n and skip:
                            value = value[skip:]
                    self._ring_write(ring, head, keep, value)
            dropped = max(
                0, self._size + keep - self.capacity
            ) + skip
            self._head = (head + keep) % self.capacity
            self._size = min(self.capacity, self._size + keep)
            self._seq += n
            self.records_total += n
            self.records_dropped += dropped
        if self._c_records is not None:
            self._c_records.inc("-", "-", float(n))
            if dropped:
                self._c_dropped.inc("-", "-", float(dropped))
        return n

    def _commit_single(self, cols: Dict[str, object], now: float) -> int:  # lint: allow-complexity — per-item ring write: one guard per value class

        fills = _COLUMN_FILLS
        with self._lock:
            head = self._head
            for name, ring in self._rings.items():
                if name == "ts":
                    ring[head] = now
                    continue
                if name == "seq":
                    ring[head] = self._seq + 1
                    continue
                value = cols.get(name)
                if value is None:
                    value = fills.get(name, 0)
                elif isinstance(value, (list, tuple)):
                    value = value[0]
                elif isinstance(value, np.ndarray) and value.ndim >= 1:
                    value = value[0]
                ring[head] = value
            dropped = 1 if self._size == self.capacity else 0
            self._head = (head + 1) % self.capacity
            self._size = min(self.capacity, self._size + 1)
            self._seq += 1
            self.records_total += 1
            self.records_dropped += dropped
        if self._c_records is not None:
            self._c_records.inc("-", "-", 1.0)
            if dropped:
                self._c_dropped.inc("-", "-", 1.0)
        return 1

    @staticmethod
    def _ring_write(ring, head: int, n: int, value) -> None:
        """Write `value` (scalar broadcast or length-n array) into the
        ring at [head, head+n) with wraparound — at most two slice
        assignments."""
        cap = ring.shape[0]
        first = min(n, cap - head)
        scalar = not (
            isinstance(value, np.ndarray) and value.shape
        )
        if scalar:
            ring[head:head + first] = value
            if n > first:
                ring[: n - first] = value
        else:
            ring[head:head + first] = value[:first]
            if n > first:
                ring[: n - first] = value[first:]

    def _winning_stages(self, batch: LedgerBatch):
        """The single stage that best explains each final count
        (precedence in the module constants' order). Small batches take
        the scalar path: a typical tick commits a handful of rows, and
        a dozen tiny-array numpy ops cost ~100us of fixed overhead the
        <=5% bench budget cannot afford; the vectorized path serves the
        multi-tenant thousands-of-rows commits."""
        if batch.n <= 32:
            return self._winning_stages_scalar(batch)
        return self._winning_stages_vector(batch)

    @staticmethod
    def _winning_stages_scalar(batch: LedgerBatch) -> list:  # lint: allow-complexity — the stage-precedence ladder, one arm per stage
        cols = batch.cols

        def get(name, i, default):
            value = cols.get(name, default)
            if isinstance(value, (np.ndarray, list, tuple)):
                return value[i]
            return value

        stages = []
        for i in range(batch.n):
            base = int(get("base_desired", i, -1))
            final = int(get("final_desired", i, -1))
            if get("cost_blind", i, False):
                stages.append(STAGE_COST_BLIND)
            elif final >= 0 and base >= 0 and final > base:
                stages.append(STAGE_COST_RAISE)
            elif final >= 0 and base >= 0 and final < base:
                stages.append(STAGE_COST_CLAMP)
            elif get("forecast_blend", i, False):
                stages.append(STAGE_FORECAST_BLEND)
            elif get("solver_rung", i, "") == "floor":
                stages.append(STAGE_DEGRADED_FLOOR)
            elif get("deferred", i, False):
                stages.append(STAGE_ADMISSION_DEFERRAL)
            else:
                stages.append(STAGE_REACTIVE)
        return stages

    def _winning_stages_vector(self, batch: LedgerBatch) -> np.ndarray:
        n = batch.n

        def col(name):
            value = batch.cols.get(name, _COLUMN_FILLS.get(name))
            if isinstance(value, (list, tuple, np.ndarray)):
                return np.asarray(value)
            return np.full(n, value)

        base = col("base_desired").astype(np.int64)
        final = col("final_desired").astype(np.int64)
        delta = np.where((final >= 0) & (base >= 0), final - base, 0)
        rung = col("solver_rung").astype(object)
        stages = np.empty(n, object)
        stages[:] = STAGE_REACTIVE
        stages[col("deferred").astype(bool)] = STAGE_ADMISSION_DEFERRAL
        stages[rung == "floor"] = STAGE_DEGRADED_FLOOR
        stages[col("forecast_blend").astype(bool)] = STAGE_FORECAST_BLEND
        stages[delta < 0] = STAGE_COST_CLAMP
        stages[delta > 0] = STAGE_COST_RAISE
        stages[col("cost_blind").astype(bool)] = STAGE_COST_BLIND
        return stages

    # -- queries (off the hot path) ----------------------------------------

    def clear(self) -> None:
        with self._lock:
            self._head = 0
            self._size = 0

    def _order(self) -> np.ndarray:
        """Ring indices oldest-first (caller holds the lock)."""
        if self._size < self.capacity:
            return np.arange(self._size)
        return np.arange(self._head, self._head + self.capacity) % (
            self.capacity
        )

    def query(
        self,
        kind: Optional[str] = None,
        tenant: Optional[str] = None,
        group: Optional[str] = None,
        name: Optional[str] = None,
        limit: Optional[int] = None,
    ) -> List[dict]:
        """Filtered records, oldest-first (most recent last). Dicts are
        built HERE, not at record time — the hot path stays columnar."""
        with self._lock:
            order = self._order()
            snapshot = {
                col: ring[order] for col, ring in self._rings.items()
            }
        mask = np.ones(len(order), bool)
        for column, wanted in (
            ("kind", kind), ("tenant", tenant),
            ("group", group), ("name", name),
        ):
            if wanted is not None:
                mask &= snapshot[column] == wanted
        idx = np.nonzero(mask)[0]
        if limit is not None and limit >= 0:
            idx = idx[-limit:] if limit else idx[:0]
        return [self._record(snapshot, int(i)) for i in idx]

    @staticmethod
    def _record(snapshot: Dict[str, np.ndarray], i: int) -> dict:  # lint: allow-complexity — JSON shaping: one guard per value class

        record: dict = {}
        for column, values in snapshot.items():
            if column == "observed":
                n = int(snapshot["observed_n"][i])
                record["observed"] = [
                    round(float(v), 6) for v in values[i][:n]
                ]
                continue
            if column == "observed_n":
                continue
            value = values[i]
            if isinstance(value, (np.floating, float)):
                value = None if math.isnan(float(value)) else round(
                    float(value), 6
                )
            elif isinstance(value, (np.bool_, bool)):
                value = bool(value)
            elif isinstance(value, np.integer):
                value = int(value)
            record[column] = value
        # sentinel numerics render as null: "never annotated" must not
        # read as a real count of -1
        for column in (
            "prev_replicas", "base_desired", "final_desired",
            "cost_candidate", "warm_headroom", "admission_round",
        ):
            if record.get(column) == -1:
                record[column] = None
        return record

    def export_jsonl(self, path: str) -> int:
        """Dump the ring as JSONL (one record per line), crash-safely —
        the recovery journal's tmp + fsync + rename. Written next to
        the --trace-export trace by the runtime/simulate wiring; the
        `trace` field of each record backlinks into that file's span
        `cat` ids. Returns the record count."""
        from karpenter_tpu.recovery.journal import atomic_write

        records = self.query()
        atomic_write(
            path,
            "".join(
                json.dumps(record, sort_keys=True) + "\n"
                for record in records
            ),
        )
        return len(records)


def decisions_export_path(trace_export: str) -> str:
    """The ledger JSONL path derived from a --trace-export path:
    trace.jsonl -> trace.decisions.jsonl (same directory, so the trace
    and the decisions it backlinks travel together)."""
    import os.path

    root, ext = os.path.splitext(trace_export)
    return f"{root}.decisions{ext or '.jsonl'}"


def export_next_to_trace(ledger: DecisionLedger, trace_export: str):
    """Dump `ledger` as the decisions JSONL sibling of a trace export
    (the one export contract every caller shares — the CLI exit hook,
    the simulate replays). Returns (path, record_count)."""
    path = decisions_export_path(trace_export)
    return path, ledger.export_jsonl(path)


def _current_trace_id() -> Optional[str]:
    from karpenter_tpu.observability.tracing import default_tracer

    return default_tracer().current_trace_id()


# -- process default ----------------------------------------------------------
# One ledger per process like the tracer/flight recorder: annotation
# sites read it through default_ledger() so provenance context crosses
# module boundaries with no parameter threading. DEFAULT OFF — the
# runtime enables it under --provenance.

_default = DecisionLedger()


def default_ledger() -> DecisionLedger:
    return _default


def set_default_ledger(ledger: DecisionLedger) -> DecisionLedger:
    global _default
    _default = ledger
    return ledger


def reset_default_ledger(enabled: bool = False) -> DecisionLedger:
    """Swap in a fresh default ledger (test isolation / the simulate
    replays)."""
    return set_default_ledger(DecisionLedger(enabled=enabled))
