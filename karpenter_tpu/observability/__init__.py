"""Observability package: tracing, flight recorder, metrics/debug HTTP.

Grown from the single-module observability.py (which held MetricsServer
and the xprof hooks) into the correlation layer for the whole control
plane — docs/observability.md is the operator guide:

  tracing.py         per-reconcile trace IDs + spans, coalesced-dispatch
                     links, Chrome-trace/Perfetto JSONL export, and the
                     karpenter_reconcile_e2e_seconds lead-time histogram
  flightrecorder.py  bounded structured event ring (fault injections,
                     FSM trips, circuit opens, fence rejections, shard
                     fallbacks, journal compactions) with trace-ID
                     backlinks and crash-safe dumps into --journal-dir
  provenance.py      the decision provenance ledger — a bounded
                     columnar ring answering "why did this group scale
                     to N this tick" (/debug/decisions, JSONL export
                     next to --trace-export; default off, --provenance)
  selfslo.py         the control plane's self-SLO monitor: multi-window
                     burn rates over karpenter_reconcile_e2e_seconds +
                     solver FSM + tenant breakers (/debug/selfslo,
                     karpenter_selfslo_*, selfslo_burn auto-dump)
  server.py          /metrics, /healthz (liveness), /readyz (real
                     readiness), /debug/traces, /debug/flightrecorder,
                     /debug/decisions, /debug/selfslo
  profiler.py        device-timeline annotations (solver_trace, probed
                     once) + the xprof profiler server

The public names below are the pre-package import surface — existing
importers (`from karpenter_tpu.observability import MetricsServer,
solver_trace, start_profiler_server`) are unchanged.
"""

from karpenter_tpu.observability.flightrecorder import (
    FlightRecorder,
    default_flight_recorder,
    reset_default_flight_recorder,
    set_default_flight_recorder,
)
from karpenter_tpu.observability.profiler import (
    solver_trace,
    start_profiler_server,
)
from karpenter_tpu.observability.provenance import (
    DecisionLedger,
    default_ledger,
    reset_default_ledger,
    set_default_ledger,
)
from karpenter_tpu.observability.selfslo import SelfSLOMonitor
from karpenter_tpu.observability.server import MetricsServer
from karpenter_tpu.observability.tracing import (
    Tracer,
    default_tracer,
    reset_default_tracer,
    set_default_tracer,
)

__all__ = [
    "DecisionLedger",
    "FlightRecorder",
    "MetricsServer",
    "SelfSLOMonitor",
    "Tracer",
    "default_flight_recorder",
    "default_ledger",
    "default_tracer",
    "reset_default_flight_recorder",
    "reset_default_ledger",
    "reset_default_tracer",
    "set_default_flight_recorder",
    "set_default_ledger",
    "set_default_tracer",
    "solver_trace",
    "start_profiler_server",
]
