"""Observability package: tracing, flight recorder, metrics/debug HTTP.

Grown from the single-module observability.py (which held MetricsServer
and the xprof hooks) into the correlation layer for the whole control
plane — docs/observability.md is the operator guide:

  tracing.py         per-reconcile trace IDs + spans, coalesced-dispatch
                     links, Chrome-trace/Perfetto JSONL export, and the
                     karpenter_reconcile_e2e_seconds lead-time histogram
  flightrecorder.py  bounded structured event ring (fault injections,
                     FSM trips, circuit opens, fence rejections, shard
                     fallbacks, journal compactions) with trace-ID
                     backlinks and crash-safe dumps into --journal-dir
  provenance.py      the decision provenance ledger — a bounded
                     columnar ring answering "why did this group scale
                     to N this tick" (/debug/decisions, JSONL export
                     next to --trace-export; default off, --provenance)
  selfslo.py         the control plane's self-SLO monitor: multi-window
                     burn rates over karpenter_reconcile_e2e_seconds +
                     solver FSM + tenant breakers (/debug/selfslo,
                     karpenter_selfslo_*, selfslo_burn auto-dump)
  devicetelemetry.py the solver introspection plane: compile ledger
                     (every compile-cache miss with rung/extents/wall
                     time/trace ids + XLA cost attribution,
                     karpenter_solver_compile_seconds, compile_storm
                     trip-class events), device memory telemetry
                     (karpenter_device_*, resident-LRU byte accounting,
                     the self-SLO memory source), /debug/solver
                     (default off, --introspect)
  server.py          /metrics, /healthz (liveness), /readyz (real
                     readiness), /debug/traces, /debug/flightrecorder,
                     /debug/decisions, /debug/selfslo, /debug/solver,
                     /debug/profile
  profiler.py        device-timeline annotations (solver_trace, probed
                     once), the xprof profiler server, and the bounded
                     single-flight on-demand capture (/debug/profile)

The public names below are the pre-package import surface — existing
importers (`from karpenter_tpu.observability import MetricsServer,
solver_trace, start_profiler_server`) are unchanged.
"""

from karpenter_tpu.observability.devicetelemetry import (
    CompileLedger,
    SolverIntrospection,
)
from karpenter_tpu.observability.flightrecorder import (
    FlightRecorder,
    default_flight_recorder,
    reset_default_flight_recorder,
    set_default_flight_recorder,
)
from karpenter_tpu.observability.profiler import (
    solver_trace,
    start_profiler_server,
)
from karpenter_tpu.observability.provenance import (
    DecisionLedger,
    default_ledger,
    reset_default_ledger,
    set_default_ledger,
)
from karpenter_tpu.observability.selfslo import SelfSLOMonitor
from karpenter_tpu.observability.server import MetricsServer
from karpenter_tpu.observability.tracing import (
    Tracer,
    default_tracer,
    reset_default_tracer,
    set_default_tracer,
)

__all__ = [
    "CompileLedger",
    "DecisionLedger",
    "FlightRecorder",
    "MetricsServer",
    "SelfSLOMonitor",
    "SolverIntrospection",
    "Tracer",
    "default_flight_recorder",
    "default_ledger",
    "default_tracer",
    "reset_default_flight_recorder",
    "reset_default_ledger",
    "reset_default_tracer",
    "set_default_flight_recorder",
    "set_default_ledger",
    "set_default_tracer",
    "solver_trace",
    "start_profiler_server",
]
