"""JAX-profiler integration: host span annotations + the xprof server.

reference: the reference has NO tracing/profiling (OTel is future work,
docs/designs/DESIGN.md) — these hooks are an addition the TPU build
needs: device-side timelines via the JAX profiler (xprof), so a 200 ms
budget regression is attributable to feed vs compile vs compute. The
host-side reconcile spans live in observability.tracing; `solver_trace`
here only mirrors named hot sections onto the DEVICE timeline when a
profiler is attached.

Hot-path discipline: availability of `jax.profiler` is probed ONCE per
process and cached — the pre-package implementation re-ran the import
machinery and built a TraceAnnotation attempt on every call, a real
cost at thousands of dispatches/sec. The unavailable path now returns a
shared no-op context manager: zero allocations, one module-global read.
"""

from __future__ import annotations

import json
import os
import threading
import time as _time

from karpenter_tpu.observability.tracing import _NOOP_SPAN as _NOOP_TRACE

# probe cache: None = unprobed; False = unavailable; otherwise the
# jax.profiler.TraceAnnotation class itself
_ANNOTATION_CLS = None


class _GuardedAnnotation:
    """One TraceAnnotation whose SETUP/TEARDOWN failures are swallowed —
    tracing must never break the solve — while exceptions raised by the
    traced block itself propagate unchanged."""

    __slots__ = ("_cls", "_name", "_annotation")

    def __init__(self, cls, name: str):
        self._cls = cls
        self._name = name
        self._annotation = None

    def __enter__(self):
        try:
            self._annotation = self._cls(self._name)
            self._annotation.__enter__()
        except Exception:  # noqa: BLE001 — tracing must never break the solve
            self._annotation = None
        return None

    def __exit__(self, *exc):
        if self._annotation is not None:
            try:
                self._annotation.__exit__(None, None, None)
            except Exception:  # noqa: BLE001
                pass
        return False


def _probe():
    """One-time jax.profiler availability probe (cached)."""
    global _ANNOTATION_CLS
    if _ANNOTATION_CLS is None:
        try:
            import jax.profiler

            _ANNOTATION_CLS = jax.profiler.TraceAnnotation
        except Exception:  # noqa: BLE001 — no jax / broken profiler
            _ANNOTATION_CLS = False
    return _ANNOTATION_CLS


def reset_probe() -> None:
    """Forget the cached probe (test isolation)."""
    global _ANNOTATION_CLS
    _ANNOTATION_CLS = None


def solver_trace(name: str):
    """Annotate a host span so it shows up on the device timeline. With
    no profiler available this is the SHARED no-op context manager —
    allocation-free, probed once per process."""
    cls = _ANNOTATION_CLS if _ANNOTATION_CLS is not None else _probe()
    if cls is False:
        return _NOOP_TRACE
    return _GuardedAnnotation(cls, name)


def start_profiler_server(port: int = 9999) -> bool:
    """Expose the JAX profiler so xprof/tensorboard can attach and
    capture device traces of the solver. Returns False if unavailable —
    with the reason LOGGED (a silent False left operators staring at a
    missing :9999 with nothing in the logs to explain it)."""
    try:
        import jax.profiler

        jax.profiler.start_server(port)
        return True
    except Exception as error:  # noqa: BLE001
        from karpenter_tpu.utils.log import logger

        logger().warning(
            "jax profiler server failed to start on :%d (%s: %s); "
            "device-timeline capture unavailable",
            port, type(error).__name__, error,
        )
        return False


# -- on-demand capture (/debug/profile) ---------------------------------------

# bounds for one on-demand capture window: long enough to span several
# manager ticks, short enough that a fat-fingered query can't park the
# profiler (and its overhead) on a production plane for minutes
MIN_CAPTURE_MS = 1
MAX_CAPTURE_MS = 30_000

PROFILE_PREFIX = "profile-"

# single-flight: the jax profiler is a process-global singleton — two
# concurrent start_trace calls corrupt each other's sessions
_capture_lock = threading.Lock()
_capture_seq = 0


class ProfileBusy(RuntimeError):
    """A capture is already in flight (single-flight contract)."""


class ProfileUnavailable(RuntimeError):
    """The jax.profiler probe failed — no capture possible."""


def capture_profile(
    ms: int, out_dir: str, trace_id=None, sleep=_time.sleep
) -> dict:
    """One bounded on-demand jax.profiler capture (/debug/profile?ms=N):
    profile the process for `ms` milliseconds (clamped to
    [MIN_CAPTURE_MS, MAX_CAPTURE_MS]) into
    `out_dir/profile-<seq>-<stamp>/` — the runtime passes --journal-dir,
    so captures land next to the flight-recorder dumps an incident
    already wrote. The capture directory is written under a tmp name
    and renamed into place ATOMICALLY (the flight-recorder dump
    discipline: a crash mid-capture leaves a .tmp orphan, never a
    half-readable capture), with a manifest.json stamping the active
    trace id, window, and wall time.

    Raises ProfileUnavailable when the jax.profiler probe failed and
    ProfileBusy when a capture is already in flight (single-flight) —
    the HTTP surface maps both to 503."""
    if _probe() is False:
        raise ProfileUnavailable("jax.profiler unavailable")
    import jax.profiler

    global _capture_seq
    ms = max(MIN_CAPTURE_MS, min(MAX_CAPTURE_MS, int(ms)))
    if not _capture_lock.acquire(blocking=False):
        raise ProfileBusy("a profiler capture is already in flight")
    try:
        _capture_seq += 1
        stamp = _time.strftime("%Y%m%d-%H%M%S")
        final = os.path.join(
            out_dir, f"{PROFILE_PREFIX}{_capture_seq:04d}-{stamp}"
        )
        tmp = final + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        t0 = _time.perf_counter()
        jax.profiler.start_trace(tmp)
        try:
            sleep(ms / 1e3)
        finally:
            jax.profiler.stop_trace()
        elapsed_ms = (_time.perf_counter() - t0) * 1e3
        manifest = {
            "ms_requested": ms,
            "ms_captured": round(elapsed_ms, 3),
            "trace_id": trace_id,
            "captured_at": _time.time(),
        }
        with open(os.path.join(tmp, "manifest.json"), "w") as fh:
            json.dump(manifest, fh, sort_keys=True)
        os.rename(tmp, final)
        return {"path": final, **manifest}
    finally:
        _capture_lock.release()
