"""JAX-profiler integration: host span annotations + the xprof server.

reference: the reference has NO tracing/profiling (OTel is future work,
docs/designs/DESIGN.md) — these hooks are an addition the TPU build
needs: device-side timelines via the JAX profiler (xprof), so a 200 ms
budget regression is attributable to feed vs compile vs compute. The
host-side reconcile spans live in observability.tracing; `solver_trace`
here only mirrors named hot sections onto the DEVICE timeline when a
profiler is attached.

Hot-path discipline: availability of `jax.profiler` is probed ONCE per
process and cached — the pre-package implementation re-ran the import
machinery and built a TraceAnnotation attempt on every call, a real
cost at thousands of dispatches/sec. The unavailable path now returns a
shared no-op context manager: zero allocations, one module-global read.
"""

from __future__ import annotations

from karpenter_tpu.observability.tracing import _NOOP_SPAN as _NOOP_TRACE

# probe cache: None = unprobed; False = unavailable; otherwise the
# jax.profiler.TraceAnnotation class itself
_ANNOTATION_CLS = None


class _GuardedAnnotation:
    """One TraceAnnotation whose SETUP/TEARDOWN failures are swallowed —
    tracing must never break the solve — while exceptions raised by the
    traced block itself propagate unchanged."""

    __slots__ = ("_cls", "_name", "_annotation")

    def __init__(self, cls, name: str):
        self._cls = cls
        self._name = name
        self._annotation = None

    def __enter__(self):
        try:
            self._annotation = self._cls(self._name)
            self._annotation.__enter__()
        except Exception:  # noqa: BLE001 — tracing must never break the solve
            self._annotation = None
        return None

    def __exit__(self, *exc):
        if self._annotation is not None:
            try:
                self._annotation.__exit__(None, None, None)
            except Exception:  # noqa: BLE001
                pass
        return False


def _probe():
    """One-time jax.profiler availability probe (cached)."""
    global _ANNOTATION_CLS
    if _ANNOTATION_CLS is None:
        try:
            import jax.profiler

            _ANNOTATION_CLS = jax.profiler.TraceAnnotation
        except Exception:  # noqa: BLE001 — no jax / broken profiler
            _ANNOTATION_CLS = False
    return _ANNOTATION_CLS


def reset_probe() -> None:
    """Forget the cached probe (test isolation)."""
    global _ANNOTATION_CLS
    _ANNOTATION_CLS = None


def solver_trace(name: str):
    """Annotate a host span so it shows up on the device timeline. With
    no profiler available this is the SHARED no-op context manager —
    allocation-free, probed once per process."""
    cls = _ANNOTATION_CLS if _ANNOTATION_CLS is not None else _probe()
    if cls is False:
        return _NOOP_TRACE
    return _GuardedAnnotation(cls, name)


def start_profiler_server(port: int = 9999) -> bool:
    """Expose the JAX profiler so xprof/tensorboard can attach and
    capture device traces of the solver. Returns False if unavailable."""
    try:
        import jax.profiler

        jax.profiler.start_server(port)
        return True
    except Exception:  # noqa: BLE001
        return False
