"""Solver introspection plane: compile ledger, device memory telemetry,
and XLA cost attribution (docs/observability.md "Device telemetry &
introspection").

Why this exists: the north star is a sub-200 ms full-fleet solve on a
real accelerator, but nothing in the control plane could SEE the device
layer it is supposed to be fast on. A first-touch compile, a resident-
state re-upload, or an HBM high-watermark is indistinguishable from
"the solver is slow" without attribution — BLITZSCALE's observation
(PAPERS.md) is that autoscaler lead time is won or lost in exactly
these hidden device-side stalls, and the self-SLO monitor (PR 12)
burns budget on them without being able to say why. Three surfaces
close the gap:

  * COMPILE LEDGER — every compile-cache miss inside the SolverService
    is recorded as one columnar-ring row: kernel family, bucket rung,
    shard extents, wall compile seconds, the trace ids that paid for
    it, and the XLA cost analysis of the compiled program. Exported as
    `karpenter_solver_compile_seconds` (histogram, `name`=family).
    A COMPILE STORM — >= `storm_threshold` misses inside one manager
    tick window AFTER the plane reached steady state — records a
    `compile_storm` flight-recorder event, a trip-class kind
    (flightrecorder.DUMP_KINDS), so the surrounding event ring dumps
    crash-safely into --journal-dir with trace backlinks. Steady state
    is a tick with ZERO misses: a cold boot's taper (3 misses, 1, 0)
    never trips, a mid-run cache reset (recovery boot, jit-key
    regression) does — once per incident (hysteresis re-arms on the
    next zero-miss tick).
  * DEVICE MEMORY TELEMETRY — per tick, poll `device.memory_stats()`
    where the backend supports it (TPU/GPU; CPU reports none) into
    `karpenter_device_{bytes_in_use,bytes_limit}` (`name`=device), plus
    EXACT byte accounting of the ResidentFleetState LRU — per-entry
    bytes/rows/tenant/age as `karpenter_solver_resident_entry_bytes`
    (`name`=entry slot, `namespace`=tenant). A high-watermark breach
    (bytes_in_use/bytes_limit >= `watermark` on any device) feeds the
    self-SLO monitor as its FOURTH source (observability/selfslo.py
    `memory_source`): HBM pressure burns error budget like a degraded
    FSM does.
  * XLA COST ATTRIBUTION — at compile time (the only moment it is
    free: `Lowered.cost_analysis()` runs XLA's analytical model on the
    lowered HLO, no second backend compile) the plane captures flops
    and bytes-accessed per cache entry, so every subsequent dispatch
    span gains flops/bytes args and `/debug/solver` renders
    $/decision-grade cost next to the PR 12 cost model.

`/debug/solver` (observability/server.py) reports the full solver
posture in ONE JSON document: compile-cache rungs per family +
hit/miss counters + the ledger tail, resident LRU contents, shard
route + extents, backend FSM state, and queue/pipeline depths.

Posture (the tracing/provenance precedent): DEFAULT OFF behind
`--introspect`. Disabled, the hot path pays one attribute read per
compile miss and nothing else — decisions are property-pinned
byte-identical and the ledger stays mark-free
(tests/test_introspect.py). `make bench-introspect` publishes the
honest <=2% tick-overhead number.
"""

from __future__ import annotations

import collections
import threading
import time as _time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

SUBSYSTEM = "solver"
DEVICE_SUBSYSTEM = "device"

# metric names (module constants so the doc-drift lint's AST scan
# resolves them — tests/test_metrics.py TestMetricsDocDrift)
COMPILE_SECONDS = "compile_seconds"
COMPILE_STORMS = "compile_storms_total"
BYTES_IN_USE = "bytes_in_use"
BYTES_LIMIT = "bytes_limit"
RESIDENT_ENTRY_BYTES = "resident_entry_bytes"

# flight-recorder kind for a compile storm (a DUMP_KINDS member: the
# ring dumps crash-safely into --journal-dir when one lands)
STORM_EVENT = "compile_storm"

# compile wall times run from milliseconds (persistent-cache disk
# reads) to minutes (first-touch TPU solver programs: 20-40s)
_COMPILE_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 10.0, 30.0, 60.0, 120.0,
)

# ledger columns, in tail()-render order
_COLUMNS = (
    "seq", "ts", "family", "rung", "extents", "seconds",
    "trace_ids", "flops", "bytes_accessed",
)


def extract_cost(analysis) -> Tuple[Optional[float], Optional[float]]:
    """(flops, bytes accessed) out of a jax cost-analysis result, which
    is a dict on modern jax and a one-element list of dicts on older
    releases; (None, None) when the backend reported neither."""
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    if not isinstance(analysis, dict):
        return None, None
    flops = analysis.get("flops")
    bytes_accessed = analysis.get("bytes accessed")
    return (
        float(flops) if flops is not None else None,
        float(bytes_accessed) if bytes_accessed is not None else None,
    )


class CompileLedger:
    """Bounded COLUMNAR ring of compile-cache misses (the provenance-
    ledger discipline: parallel per-column deques, O(columns) slice
    work per record, dicts materialized only at query time)."""

    def __init__(self, capacity: int = 256, clock=_time.time):
        self.capacity = capacity
        self._clock = clock
        self._lock = threading.Lock()
        self._cols: Dict[str, collections.deque] = {
            name: collections.deque(maxlen=capacity) for name in _COLUMNS
        }
        self._seq = 0
        self.records_total = 0
        # per-family miss counters ({} when nothing recorded)
        self.by_family: Dict[str, int] = {}

    def record(
        self,
        family: str,
        rung: str,
        seconds: float,
        extents: Optional[tuple] = None,
        trace_ids: Sequence[str] = (),
        flops: Optional[float] = None,
        bytes_accessed: Optional[float] = None,
    ) -> int:
        with self._lock:
            self._seq += 1
            row = {
                "seq": self._seq,
                "ts": self._clock(),
                "family": family,
                "rung": rung,
                "extents": tuple(extents) if extents else None,
                "seconds": round(float(seconds), 6),
                "trace_ids": list(trace_ids),
                "flops": flops,
                "bytes_accessed": bytes_accessed,
            }
            for name in _COLUMNS:
                self._cols[name].append(row[name])
            self.records_total += 1
            self.by_family[family] = self.by_family.get(family, 0) + 1
            return self._seq

    def tail(self, limit: Optional[int] = None) -> List[dict]:
        """Newest-last row dicts (the /debug/solver ledger tail)."""
        with self._lock:
            rows = [list(self._cols[name]) for name in _COLUMNS]
        records = [
            dict(zip(_COLUMNS, values)) for values in zip(*rows)
        ]
        if limit is not None and limit >= 0:
            records = records[-limit:] if limit else []
        return records


class SolverIntrospection:
    """The introspection plane one SolverService carries (module
    docstring). Seams are injectable so tests compose pieces freely:

      service        the SolverService to snapshot (attach() wires the
                     back-pointer so dispatch sites can note compiles)
      stats_source   () -> [{"device", "bytes_in_use", "bytes_limit"}]
                     (default: jax.devices() memory_stats, skipping
                     devices that report none — the CPU backend)
      recorder       the flight recorder storm trips dump through
                     (default: the process default)

    DISABLED (the default) every entry point returns after one
    attribute read and records nothing — the mark-free off path the
    property pin holds to."""

    def __init__(
        self,
        service=None,
        enabled: bool = False,
        registry=None,
        clock=_time.time,
        recorder=None,
        stats_source: Optional[Callable[[], List[dict]]] = None,
        storm_threshold: int = 4,
        watermark: float = 0.9,
        ledger_capacity: int = 256,
    ):
        self.enabled = enabled
        self.service = service
        self._clock = clock
        self._recorder = recorder
        self._stats_source = stats_source
        # >= this many compile-cache misses inside ONE tick window,
        # after steady state, is a storm
        self.storm_threshold = storm_threshold
        # bytes_in_use/bytes_limit at or above this on ANY device is
        # the high-watermark trip the self-SLO memory source reports
        self.watermark = watermark
        self.ledger = CompileLedger(capacity=ledger_capacity, clock=clock)
        # (cache key) -> (flops, bytes) attribution captured at compile
        # time; bounded like the compile cache it mirrors
        self._cost_by_key: Dict[tuple, Tuple[float, float]] = {}
        self._cost_lock = threading.Lock()
        # storm detector: ARMED only after a zero-miss tick (a cold
        # boot's compile taper is not a storm; a mid-run cache reset
        # after steady state is), one trip per incident
        self._armed = False
        self._tripped = False
        self._misses_at_tick = 0
        self.storms_total = 0
        self.last_tick_misses = 0
        # device-memory high-watermark state (the self-SLO source)
        self.memory_high: Optional[bool] = None
        self._last_memory: List[dict] = []
        # resident-entry gauge series published last tick (retired when
        # the LRU churns them out — no frozen per-entry series)
        self._entry_series: set = set()
        self._h_compile = None
        self._c_storms = None
        self._g_bytes_in_use = None
        self._g_bytes_limit = None
        self._g_entry_bytes = None
        if registry is not None:
            self.bind_registry(registry)

    def bind_registry(self, registry) -> None:
        reg = registry.register
        self._h_compile = reg(
            SUBSYSTEM, COMPILE_SECONDS, kind="histogram",
            buckets=_COMPILE_BUCKETS,
        )
        self._c_storms = reg(SUBSYSTEM, COMPILE_STORMS, kind="counter")
        self._g_bytes_in_use = reg(DEVICE_SUBSYSTEM, BYTES_IN_USE)
        self._g_bytes_limit = reg(DEVICE_SUBSYSTEM, BYTES_LIMIT)
        self._g_entry_bytes = reg(SUBSYSTEM, RESIDENT_ENTRY_BYTES)

    def attach(self, service) -> "SolverIntrospection":
        """Wire the back-pointer both ways: the service's dispatch
        sites note compile misses here, and snapshot() reads the
        service's caches/FSM/queue."""
        self.service = service
        service.attach_introspection(self)
        return self

    def _recorder_or_default(self):
        if self._recorder is not None:
            return self._recorder
        from karpenter_tpu.observability.flightrecorder import (
            default_flight_recorder,
        )

        return default_flight_recorder()

    # -- compile ledger (called from SolverService dispatch sites) ---------

    def note_compile(
        self,
        family: str,
        key: tuple,
        seconds: float,
        trace_ids: Sequence[str] = (),
        extents: Optional[tuple] = None,
        cost_fn: Optional[Callable[[], object]] = None,
    ) -> None:
        """Record one compile-cache miss: the wall time the first
        dispatch paid, the trace ids riding it, and — via `cost_fn`, a
        lazy thunk so disabled planes never touch jax — the XLA cost
        analysis of the compiled program. Never raises into the
        dispatch path it observes."""
        if not self.enabled:
            return
        flops = bytes_accessed = None
        if cost_fn is not None:
            try:
                flops, bytes_accessed = extract_cost(cost_fn())
            except Exception:  # noqa: BLE001 — attribution is best-effort
                pass
            if flops is not None or bytes_accessed is not None:
                with self._cost_lock:
                    # bounded alongside the compile cache it mirrors
                    if len(self._cost_by_key) >= 512:
                        self._cost_by_key.clear()
                    self._cost_by_key[key] = (flops, bytes_accessed)
        from karpenter_tpu.solver.bucketing import rung_label

        self.ledger.record(
            family=family,
            rung=rung_label(key),
            seconds=seconds,
            extents=extents,
            trace_ids=trace_ids,
            flops=flops,
            bytes_accessed=bytes_accessed,
        )
        if self._h_compile is not None:
            self._h_compile.observe(family, "-", float(seconds))

    def dispatch_cost_args(self, key: tuple) -> dict:
        """{flops, bytes} span args for a dispatch riding `key`, {}
        when disabled or unattributed — the off path adds nothing to
        any span (the byte-identical pin)."""
        if not self.enabled:
            return {}
        cost = self._cost_by_key.get(key)
        if cost is None:
            return {}
        flops, bytes_accessed = cost
        args = {}
        if flops is not None:
            args["flops"] = flops
        if bytes_accessed is not None:
            args["bytes"] = bytes_accessed
        return args

    # -- the per-tick evaluation (manager tick hook) -----------------------

    def on_tick(self) -> None:
        """One evaluation pass: close the tick's compile-miss window
        (storm detection) and poll the device-memory surfaces. Runs on
        the manager tick hook; disabled planes return immediately."""
        if not self.enabled:
            return
        self._evaluate_storm()
        self._poll_memory()
        self._publish_resident_entries()

    def _evaluate_storm(self) -> None:
        total = self.ledger.records_total
        misses = total - self._misses_at_tick
        self._misses_at_tick = total
        self.last_tick_misses = misses
        if misses == 0:
            # steady state: arm the detector (and re-arm after a trip)
            self._armed = True
            self._tripped = False
            return
        if (
            self._armed
            and not self._tripped
            and misses >= self.storm_threshold
        ):
            self._tripped = True
            self.storms_total += 1
            if self._c_storms is not None:
                self._c_storms.inc("-", "-")
            tail = self.ledger.tail(limit=misses)
            trace_ids = [
                tid for row in tail for tid in row["trace_ids"]
            ]
            families = sorted({row["family"] for row in tail})
            # trip-class kind: the recorder auto-dumps the ring into
            # --journal-dir with the storm's rows still in context
            self._recorder_or_default().record(
                STORM_EVENT,
                trace_ids=list(dict.fromkeys(trace_ids)),
                subsystem="solver",
                misses=misses,
                threshold=self.storm_threshold,
                families=families,
            )

    def _device_stats(self) -> List[dict]:
        """[{device, bytes_in_use, bytes_limit}] for every device whose
        backend reports memory stats (TPU/GPU; the CPU backend returns
        none and contributes nothing)."""
        if self._stats_source is not None:
            return list(self._stats_source())
        stats = []
        try:
            import jax

            for device in jax.devices():
                try:
                    mem = device.memory_stats()
                except Exception:  # noqa: BLE001 — per-device probe
                    continue
                if not mem:
                    continue
                in_use = mem.get("bytes_in_use")
                limit = mem.get("bytes_limit")
                if in_use is None:
                    continue
                stats.append({
                    "device": str(device),
                    "bytes_in_use": int(in_use),
                    "bytes_limit": (
                        int(limit) if limit is not None else None
                    ),
                })
        except Exception:  # noqa: BLE001 — observation only
            pass
        return stats

    def _poll_memory(self) -> None:
        stats = self._device_stats()
        self._last_memory = stats
        high: Optional[bool] = None
        for entry in stats:
            if self._g_bytes_in_use is not None:
                self._g_bytes_in_use.set(
                    entry["device"], "-", float(entry["bytes_in_use"])
                )
            limit = entry.get("bytes_limit")
            if limit:
                if self._g_bytes_limit is not None:
                    self._g_bytes_limit.set(
                        entry["device"], "-", float(limit)
                    )
                breached = (
                    entry["bytes_in_use"] / limit >= self.watermark
                )
                high = breached if high is None else (high or breached)
        self.memory_high = high

    def _publish_resident_entries(self) -> None:
        """Exact per-entry byte accounting of the resident LRU:
        one series per live entry (`name`=slot, `namespace`=tenant),
        entries evicted since last tick RETIRED (no frozen series —
        the PR 11 gauge-retirement discipline)."""
        if self._g_entry_bytes is None or self.service is None:
            return
        entries = self._resident_entries()
        current = set()
        for entry in entries:
            series = (entry["slot"], entry["tenant"] or "-")
            current.add(series)
            self._g_entry_bytes.set(
                series[0], series[1], float(entry["bytes"])
            )
        for stale in self._entry_series - current:
            self._g_entry_bytes.remove(*stale)
        self._entry_series = current

    def _resident_entries(self) -> List[dict]:
        resident = getattr(self.service, "_resident", None)
        if resident is None:
            return []
        try:
            # ages must be computed on the SAME clock that stamped
            # created_at — the owning service's, not the plane's (the
            # runtime wires them differently: scripted vs monotonic)
            clock = getattr(self.service, "_clock", self._clock)
            return resident.entries(now=clock())
        except Exception:  # noqa: BLE001 — observation only
            return []

    # -- the self-SLO memory source ----------------------------------------

    def memory_source(self) -> Optional[bool]:
        """The self-SLO monitor's fourth source (selfslo.memory_source
        contract): True = high-watermark breached this tick (bad
        event), False = telemetry healthy (good event), None = no
        telemetry (disabled plane, or a backend with no memory stats)
        — quiet, no event either way."""
        if not self.enabled:
            return None
        return self.memory_high

    # -- /debug/solver ----------------------------------------------------

    def snapshot(self, ledger_limit: int = 32) -> dict:
        """The full solver posture as one JSON-ready document. A
        DISABLED plane reports only {"enabled": false} — --introspect
        is the opt-in for the whole surface (compile rungs, per-tenant
        resident entries, queue internals), not just the ledger."""
        if not self.enabled:
            return {"enabled": False}
        doc: dict = {
            "enabled": self.enabled,
            "compile": {
                "records_total": self.ledger.records_total,
                "by_family": dict(self.ledger.by_family),
                "storms_total": self.storms_total,
                "storm_threshold": self.storm_threshold,
                "storm_armed": self._armed,
                "last_tick_misses": self.last_tick_misses,
                "ledger_tail": self.ledger.tail(limit=ledger_limit),
            },
            "device_memory": {
                "devices": self._last_memory,
                "watermark": self.watermark,
                "high": self.memory_high,
            },
        }
        service = self.service
        if service is None:
            return doc
        from karpenter_tpu.solver.bucketing import rung_label

        with service._cond:
            seen = list(service._compile_seen)
            queue_depth = len(service._queue)
            inflight = len(service._inflight)
        rungs: Dict[str, List[str]] = {}
        for key in seen:
            family = (
                key[0] if key and key[0] in ("forecast", "preempt")
                else "solve"
            )
            rungs.setdefault(family, []).append(rung_label(key))
        for family in rungs:
            rungs[family].sort()
        stats = service.stats
        mesh = service._mesh
        doc["compile"]["cache"] = {
            "rungs": rungs,
            "hits": stats.compile_cache_hits,
            "misses": stats.compile_cache_misses,
        }
        doc["resident"] = {
            "bytes": service._resident.resident_bytes(),
            "rows": service._resident.resident_rows(),
            "entries": self._resident_entries(),
            "hits": stats.resident_hits,
            "scatters": stats.resident_scatters,
            "rebuilds": stats.resident_rebuilds,
            "drops": stats.resident_drops,
        }
        doc["shard"] = {
            "threshold": service.shard_threshold,
            "broken": service._shard_broken,
            "devices": (
                int(mesh.devices.size) if mesh is not None else 0
            ),
            "extents": (
                tuple(int(x) for x in mesh.devices.shape)
                if mesh is not None else None
            ),
            "requests": stats.shard_requests,
            "dispatches": stats.shard_dispatches,
            "fallbacks": stats.shard_fallbacks,
        }
        doc["backend"] = {
            "state": service.backend_health(),
            "device_failures": stats.device_failures,
            "fsm_trips": stats.fsm_trips,
            "fsm_recoveries": stats.fsm_recoveries,
            "watchdog_restarts": stats.watchdog_restarts,
        }
        doc["queue"] = {
            "depth": queue_depth,
            "inflight": inflight,
            "max_queue": service.max_queue,
            "pipeline_depth": service.pipeline_depth,
            "window_ms": service._window_now_s * 1e3,
            "requests": stats.requests,
            "dispatches": stats.dispatches,
            "fallbacks": stats.fallbacks,
        }
        return doc
