"""Self-SLO monitor: the control plane watches its OWN service level.

An SLO-driven autoscaler must account for its decisions AND notice when
it is the thing violating an SLO (PAPERS.md: "An SLO Driven and
Cost-Aware Autoscaling Framework for Kubernetes"); the lead-time
discipline of BLITZSCALE only pays off if a regression in
`karpenter_reconcile_e2e_seconds` is detected by the system itself, not
by a human reading dashboards after the fact. This module runs the
classic MULTI-WINDOW, MULTI-BURN-RATE evaluation (the SRE-workbook
alerting shape) over the control plane's own health signals:

  * the existing `karpenter_reconcile_e2e_seconds` histogram — each
    evaluation reads (samples <= objective, total samples) cumulatively
    (HistogramVec.le_totals) and the delta since the last evaluation is
    this tick's good/bad event stream;
  * the solver backend-health FSM — a degraded FSM contributes one BAD
    control-health event per evaluation (the plane is serving numpy-
    degraded decisions), a healthy one a good event. This is what lets
    a 100%-fault chaos run burn the budget even while no actuations
    complete;
  * per-tenant breakers (the MultiTenantScheduler board) — each OPEN
    breaker is a bad event per evaluation, each closed tenant a good
    one, and the per-tenant view feeds the /debug/selfslo scoreboard;
  * the device-memory high watermark (the solver introspection plane,
    observability/devicetelemetry.py, --introspect) — a tick whose
    bytes_in_use/bytes_limit crossed the watermark on any device is a
    bad event, a healthy poll a good one, and no telemetry (plane off,
    or a backend without memory stats) contributes nothing.

Each window (fast 5m/1h page pair + slow 6h/3d ladder) gets a BURN RATE
— (bad/total over the window) / error budget — published as
`karpenter_selfslo_burn_rate{name=<window>}` with
`karpenter_selfslo_budget_remaining{name=<window>}` (fraction of the
window's error budget unspent) and
`karpenter_selfslo_window_violations_total{name=<window>}`. When BOTH
fast windows exceed their threshold the monitor trips: it records a
`selfslo_burn` flight-recorder event — a trip-class kind, so the ring
auto-dumps into --journal-dir with trace backlinks (the PR 9 machinery)
— and `karpenter_selfslo_tripped` goes 1 until the fast window's burn
falls back under threshold (hysteresis: one dump per incident, not one
per tick). Budget RECOVERS as bad events age out of the sliding
windows; the chaos suite pins trip -> dump -> post-fault recovery.

State is a bounded list of cumulative (ts, good, bad) snapshots — one
tuple per evaluation (the manager tick), pruned past the longest
window; window deltas are bisect lookups. O(1) per tick, no per-event
Python objects.
"""

from __future__ import annotations

import bisect
import time as _time
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Sequence, Tuple

SUBSYSTEM = "selfslo"

# flight-recorder kind for a fast-burn trip (flightrecorder.DUMP_KINDS
# includes it: a burn trip is exactly the "degradation an operator wants
# the surrounding context for" the dump discipline exists for)
BURN_EVENT = "selfslo_burn"


@dataclass(frozen=True)
class BurnWindow:
    """One evaluation window: `threshold` is the burn rate that counts
    as a violation (SRE-workbook defaults: the page pair burns 14.4x —
    2% of a 30d budget in 1h — and the slow ladder 6x / 1x)."""

    name: str
    seconds: float
    threshold: float


DEFAULT_WINDOWS: Tuple[BurnWindow, ...] = (
    BurnWindow("5m", 300.0, 14.4),
    BurnWindow("1h", 3600.0, 14.4),
    BurnWindow("6h", 21600.0, 6.0),
    BurnWindow("3d", 259200.0, 1.0),
)


class SelfSLOMonitor:
    """One per runtime (module docstring); `evaluate()` runs on the
    manager tick hook.

    Seams (all optional, so tests compose pieces freely):
      histogram      the karpenter_reconcile_e2e_seconds HistogramVec
                     (anything with `.le_totals(bound) -> (good, total)`)
      fsm_source     () -> "healthy" | "degraded" (SolverService
                     .backend_health)
      tenant_source  () -> {tenant_id: breaker_open_bool}
      memory_source  () -> Optional[bool] — the device-memory
                     high-watermark trip from the solver introspection
                     plane (observability/devicetelemetry.py): True =
                     breached (bad event), False = healthy (good),
                     None = no telemetry (disabled plane or a backend
                     without memory stats) — contributes no event
      replica_source () -> Optional[bool] — the replicated control
                     plane's health (replication/plane.py slo_source):
                     True = mid-failover (lease renew failures or
                     tenants still warming; bad event), False =
                     serving steadily (good), None = replication off
                     or no lease round yet — contributes no event
      recorder       the flight recorder burn trips dump through
                     (default: the process default)
    """

    def __init__(
        self,
        registry=None,
        objective_s: float = 1.0,
        target: float = 0.99,
        clock=_time.time,
        histogram=None,
        fsm_source: Optional[Callable[[], str]] = None,
        tenant_source: Optional[Callable[[], Dict[str, bool]]] = None,
        memory_source: Optional[Callable[[], Optional[bool]]] = None,
        replica_source: Optional[Callable[[], Optional[bool]]] = None,
        recorder=None,
        windows: Sequence[BurnWindow] = DEFAULT_WINDOWS,
    ):
        if not 0.0 < target < 1.0:
            raise ValueError(f"selfslo target must be in (0, 1): {target}")
        self.objective_s = objective_s
        self.target = target
        self.error_budget = 1.0 - target
        self.clock = clock
        self.histogram = histogram
        self.fsm_source = fsm_source
        self.tenant_source = tenant_source
        self.memory_source = memory_source
        self.replica_source = replica_source
        self._recorder = recorder
        self.windows = tuple(windows)
        # cumulative snapshot series, one entry per evaluate(): parallel
        # lists (ts sorted ascending) pruned past the longest window
        self._ts: list = []
        self._good: list = []
        self._bad: list = []
        self._cum_good = 0
        self._cum_bad = 0
        self._last_hist: Tuple[int, int] = (0, 0)
        self.tripped = False
        self.trips_total = 0
        self._last_eval: Optional[dict] = None
        self._g_burn = self._g_budget = self._c_violations = None
        self._g_tripped = None
        if registry is not None:
            self._g_burn = registry.register(SUBSYSTEM, "burn_rate")
            self._g_budget = registry.register(
                SUBSYSTEM, "budget_remaining"
            )
            self._c_violations = registry.register(
                SUBSYSTEM, "window_violations_total", kind="counter"
            )
            self._g_tripped = registry.register(SUBSYSTEM, "tripped")
            self._g_tripped.set("-", "-", 0.0)

    def _recorder_or_default(self):
        if self._recorder is not None:
            return self._recorder
        from karpenter_tpu.observability.flightrecorder import (
            default_flight_recorder,
        )

        return default_flight_recorder()

    # -- the per-tick evaluation -------------------------------------------

    def _hist_events(self) -> Tuple[int, int]:
        if self.histogram is None:
            return 0, 0
        le, total = self.histogram.le_totals(self.objective_s)
        last_le, last_total = self._last_hist
        d_total = max(0, total - last_total)
        d_le = min(max(0, le - last_le), d_total)
        self._last_hist = (le, total)
        return d_le, d_total - d_le

    def _fsm_events(self) -> Tuple[int, int]:
        if self.fsm_source is None:
            return 0, 0
        if self.fsm_source() == "healthy":
            return 1, 0
        return 0, 1

    def _tenant_events(self) -> Tuple[int, int]:
        if self.tenant_source is None:
            return 0, 0
        opens = list(self.tenant_source().values())
        bad = sum(1 for is_open in opens if is_open)
        return len(opens) - bad, bad

    def _memory_events(self) -> Tuple[int, int]:
        """The FOURTH source (observability/devicetelemetry.py):
        device HBM pressure burns budget like a degraded FSM — None
        (no telemetry) stays quiet, contributing no event."""
        if self.memory_source is None:
            return 0, 0
        high = self.memory_source()
        if high is True:
            return 0, 1
        if high is False:
            return 1, 0
        return 0, 0

    def _replica_events(self) -> Tuple[int, int]:
        """The FIFTH source (karpenter_tpu/replication, the /debug/
        replicas scoreboard): a replica mid-failover — held-lease renew
        failures or tenants still in handoff warm-up — burns budget
        like a degraded FSM; None (replication off, or no lease round
        yet) stays quiet."""
        if self.replica_source is None:
            return 0, 0
        degraded = self.replica_source()
        if degraded is True:
            return 0, 1
        if degraded is False:
            return 1, 0
        return 0, 0

    def _collect(self) -> Tuple[int, int]:
        """(good, bad) increments for THIS evaluation across the five
        sources. Source failures degrade to 'no events', never raise —
        the monitor must not take the tick down with it."""
        good = bad = 0
        for source in (
            self._hist_events, self._fsm_events,
            self._tenant_events, self._memory_events,
            self._replica_events,
        ):
            try:
                d_good, d_bad = source()
            except Exception:  # noqa: BLE001 — observation only
                continue
            good += d_good
            bad += d_bad
        return good, bad

    def evaluate(self, now: Optional[float] = None) -> dict:
        """One monitoring pass: fold the sources' increments into the
        snapshot series, compute every window's burn rate, publish the
        gauges, and trip/recover the fast-burn alarm."""
        if now is None:
            now = self.clock()
        good, bad = self._collect()
        self._cum_good += good
        self._cum_bad += bad
        self._ts.append(now)
        self._good.append(self._cum_good)
        self._bad.append(self._cum_bad)
        self._prune(now)

        windows: Dict[str, dict] = {}
        for window in self.windows:
            burn, budget_remaining, d_bad, d_total = self._window_burn(
                now, window.seconds
            )
            violating = burn > window.threshold
            windows[window.name] = {
                "seconds": window.seconds,
                "burn_rate": round(burn, 4),
                "budget_remaining": round(budget_remaining, 4),
                "threshold": window.threshold,
                "violating": violating,
                "bad": d_bad,
                "total": d_total,
            }
            if self._g_burn is not None:
                self._g_burn.set(window.name, "-", burn)
                self._g_budget.set(window.name, "-", budget_remaining)
                if violating:
                    self._c_violations.inc(window.name, "-")
        self._update_trip(now, windows)
        self._last_eval = {
            "at": now,
            "objective_s": self.objective_s,
            "target": self.target,
            "tripped": self.tripped,
            "windows": windows,
        }
        return self._last_eval

    def _prune(self, now: float) -> None:
        horizon = now - max(w.seconds for w in self.windows) - 1.0
        cut = bisect.bisect_left(self._ts, horizon)
        # keep one snapshot BEFORE the horizon as the delta baseline
        cut = max(0, cut - 1)
        if cut:
            del self._ts[:cut]
            del self._good[:cut]
            del self._bad[:cut]

    def _window_burn(
        self, now: float, seconds: float
    ) -> Tuple[float, float, int, int]:
        """(burn_rate, budget_remaining, bad, total) over the trailing
        window: deltas against the newest snapshot at or before the
        window start (cumulative series, so this is exact)."""
        start = now - seconds
        i = bisect.bisect_right(self._ts, start) - 1
        base_good = self._good[i] if i >= 0 else 0
        base_bad = self._bad[i] if i >= 0 else 0
        d_good = self._cum_good - base_good
        d_bad = self._cum_bad - base_bad
        d_total = d_good + d_bad
        if d_total <= 0:
            return 0.0, 1.0, 0, 0
        ratio = d_bad / d_total
        burn = ratio / self.error_budget
        allowed = self.error_budget * d_total
        budget_remaining = max(0.0, 1.0 - d_bad / allowed)
        return burn, budget_remaining, d_bad, d_total

    def _update_trip(self, now: float, windows: Dict[str, dict]) -> None:
        """Page-pair trip with hysteresis: BOTH fast windows over
        threshold arms the trip (one selfslo_burn event + auto-dump per
        incident); the FAST window dropping back under re-arms."""
        fast = [windows[w.name] for w in self.windows[:2]]
        firing = len(fast) >= 2 and all(w["violating"] for w in fast)
        if firing and not self.tripped:
            self.tripped = True
            self.trips_total += 1
            if self._g_tripped is not None:
                self._g_tripped.set("-", "-", 1.0)
            self._recorder_or_default().record(
                BURN_EVENT,
                objective_s=self.objective_s,
                target=self.target,
                burn_fast=fast[0]["burn_rate"],
                burn_slow=fast[1]["burn_rate"],
                window_fast=self.windows[0].name,
                window_slow=self.windows[1].name,
            )
        elif self.tripped and not fast[0]["violating"]:
            self.tripped = False
            if self._g_tripped is not None:
                self._g_tripped.set("-", "-", 0.0)

    # -- the debug surface -------------------------------------------------

    def _board_solver_backend(self) -> str:
        try:
            return self.fsm_source()
        except Exception:  # noqa: BLE001 — observation only
            return "unknown"

    def _board_device_memory(self) -> str:
        try:
            high = self.memory_source()
        except Exception:  # noqa: BLE001 — observation only
            return "unknown"
        if high is None:
            return "off"
        return "high" if high else "ok"

    def _board_tenants(self) -> Dict[str, dict]:
        try:
            return {
                tenant: {
                    "breaker_open": bool(is_open),
                    "degraded": bool(is_open),
                }
                for tenant, is_open in sorted(
                    self.tenant_source().items()
                )
            }
        except Exception:  # noqa: BLE001 — observation only
            return {}

    def scoreboard(self) -> dict:
        """/debug/selfslo: the last evaluation plus the per-tenant
        degradation view (breaker state per tenant), the solver FSM,
        and the device-memory posture — the 'how degraded is the
        control plane, and for whom' page."""
        board = dict(self._last_eval or {
            "at": None,
            "objective_s": self.objective_s,
            "target": self.target,
            "tripped": self.tripped,
            "windows": {},
        })
        board["trips_total"] = self.trips_total
        if self.fsm_source is not None:
            board["solver_backend"] = self._board_solver_backend()
        if self.memory_source is not None:
            board["device_memory"] = self._board_device_memory()
        if self.tenant_source is not None:
            board["tenants"] = self._board_tenants()
        return board
