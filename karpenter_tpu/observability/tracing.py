"""Reconcile tracing: per-reconcile trace IDs, spans, Chrome-trace export.

The control plane is eight subsystems deep (solver, consolidation,
faults, forecast, preemption, recovery, sharding) but until this layer
the only correlation between them was log interleaving: when 8 coalesced
requests ride one sharded dispatch and a circuit opens two ticks later,
nothing connects the watch event to the dispatch to the actuation. This
module is the correlation layer:

  * TRACE IDS are minted at the reconcile entry points (the manager
    tick, the simulate replays) by `Tracer.trace(...)`; everything that
    runs inside — producer encodes, the HA fleet decide, solver
    requests, SNG actuation — opens child spans that inherit the trace
    ID through a thread-local span stack, so in-tick code needs no
    plumbing.
  * CROSS-THREAD WORK (the solver worker) cannot use the stack: a
    request captures the submitter's span with `begin()` (explicitly
    parented, no TLS), and the worker's coalesced dispatch span LINKS
    the N request spans that rode it — the one-to-many join the
    coalescing queue otherwise erases. Pipeline-split chunks and
    sharded dispatches carry the same links.
  * EXPORT is Chrome-trace/Perfetto JSONL (`export_jsonl`): one event
    object per line — complete ("X") events for spans, flow ("s"/"f")
    events for dispatch links — loadable in Perfetto/chrome://tracing
    next to an xprof device timeline captured over the same wall
    clock. `/debug/traces` (observability.server) serves the same
    spans as JSON for a live process.
  * END-TO-END LEAD TIME: the BLITZSCALE observable is
    event-observed -> actuation-acked, not solve latency. The tracer
    keeps per-object observation marks (`mark_observed` at watch/tick
    entry, `ack_observed` when the provider write returns) and
    publishes the distance as the `karpenter_reconcile_e2e_seconds`
    histogram (metrics/registry.py native histograms).

Overhead posture: the span ring is a bounded deque; a disabled tracer
(`enabled = False`) returns a shared no-op context manager and None
handles — the hot path pays one attribute read. `make bench-trace`
publishes the enabled-vs-disabled tick overhead (<5% target,
docs/BENCHMARKS.md); tests/test_observability.py pins a regression
ceiling.
"""

from __future__ import annotations

import collections
import itertools
import json
import threading
import time as _time
from typing import Dict, List, Optional, Sequence, Tuple

SUBSYSTEM = "trace"

# karpenter_reconcile_e2e_seconds ladder: watch-event -> actuation-ack
# spans sub-ms (in-process store, fake provider) through the tens of
# seconds a real cloud resize takes
E2E_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


class _NoopSpan:
    """Shared allocation-free no-op context manager: the disabled
    tracer's span AND (via observability.profiler) the profiler-less
    solver_trace — one class so the two no-op paths cannot diverge."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP_SPAN = _NoopSpan()


class OpenSpan:
    """A span in progress. Context-manager use (`with tracer.span(...)`)
    threads the TLS stack; `begin()`/`close()` use skips it (cross-thread
    spans must not corrupt another thread's stack)."""

    __slots__ = (
        "_tracer", "name", "trace_id", "span_id", "parent_id",
        "t0", "args", "links", "_on_stack", "_closed",
    )

    def __init__(self, tracer, name, trace_id, span_id, parent_id,
                 args, links, on_stack):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.args = args
        self.links = links
        self._on_stack = on_stack
        self._closed = False
        self.t0 = tracer._clock()

    def ref(self) -> Tuple[str, str]:
        return (self.trace_id, self.span_id)

    def close(self, **extra) -> None:
        """Finish the span (idempotent — the solver's first-finisher-wins
        request completion may race a stale worker)."""
        if self._closed:
            return
        self._closed = True
        if extra:
            self.args.update(extra)
        self._tracer._finish(self)

    def __enter__(self) -> "OpenSpan":
        if self._on_stack:
            self._tracer._stack().append(self)
        return self

    def __exit__(self, *exc) -> bool:
        if self._on_stack:
            stack = self._tracer._stack()
            if stack and stack[-1] is self:
                stack.pop()
        if exc and exc[0] is not None:
            self.close(error=exc[0].__name__)
        else:
            self.close()
        return False


class Tracer:
    """Bounded in-memory span collector (module docstring)."""

    def __init__(self, capacity: int = 8192, clock=_time.perf_counter):
        self.enabled = True
        self.capacity = capacity
        self._clock = clock
        self._epoch = clock()
        # wall-clock anchor of the epoch, so exported ts_us correlate
        # with xprof's wall-clock device timelines
        self.epoch_unix = _time.time()
        self._lock = threading.Lock()
        # itertools.count is atomic under the GIL: span-id minting needs
        # no lock on the hot path
        self._seq = itertools.count(1)
        self._spans: collections.deque = collections.deque(maxlen=capacity)
        self._tls = threading.local()
        self.spans_total = 0
        self.spans_dropped = 0
        # e2e lead-time marks: (kind, namespace, name) -> observed ts
        self._observed: Dict[tuple, float] = {}
        self.e2e_observed = 0
        self._c_spans = self._c_dropped = self._h_e2e = None

    # -- wiring ------------------------------------------------------------

    def bind_registry(self, registry) -> None:
        """Publish the tracer's own counters and the e2e histogram into
        a runtime's GaugeRegistry (karpenter_trace_*,
        karpenter_reconcile_e2e_seconds). The counters sync when a ROOT
        span closes (once per tick) rather than per span — per-span vec
        locking is measurable at the tick rate, and a scrape only needs
        counter freshness at tick granularity."""
        self._c_spans = registry.register(
            SUBSYSTEM, "spans_total", kind="counter"
        )
        self._c_dropped = registry.register(
            SUBSYSTEM, "spans_dropped_total", kind="counter"
        )
        self._h_e2e = registry.register(
            "reconcile", "e2e_seconds", kind="histogram",
            buckets=E2E_BUCKETS,
        )

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def current(self) -> Optional[OpenSpan]:
        """The innermost span open on THIS thread (None outside any)."""
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    def current_trace_id(self) -> Optional[str]:
        span = self.current()
        return span.trace_id if span is not None else None

    def _mint(self) -> int:
        return next(self._seq)

    @staticmethod
    def _resolve_parent(parent) -> Optional[Tuple[str, str]]:
        if parent is None:
            return None
        if isinstance(parent, OpenSpan):
            return parent.ref()
        trace_id, span_id = parent  # (trace_id, span_id) tuple
        return (trace_id, span_id)

    def _open(self, name, parent, new_trace, links, args, on_stack):
        seq = self._mint()
        span_id = f"s{seq:08x}"
        ref = self._resolve_parent(parent)
        if new_trace or ref is None:
            trace_id, parent_id = f"t{seq:08x}", None
        else:
            trace_id, parent_id = ref
        link_refs = [
            self._resolve_parent(link) for link in links
            if link is not None
        ] if links else []
        # args is the caller's fresh **kwargs dict — owned, no copy
        return OpenSpan(
            self, name, trace_id, span_id, parent_id,
            args, link_refs, on_stack,
        )

    # -- span API ----------------------------------------------------------

    def trace(self, name: str, **args):
        """Mint a NEW trace id and open its root span (the watch/tick
        entry points call this)."""
        if not self.enabled:
            return _NOOP_SPAN
        return self._open(name, None, True, (), args, on_stack=True)

    def span(self, name: str, parent=None, links: Sequence = (), **args):
        """Open a child span: of `parent` when given (an OpenSpan or a
        (trace_id, span_id) ref), else of this thread's current span;
        with neither, a fresh trace (orphan work is still captured).
        `links` joins other spans' refs — the coalesced-dispatch
        one-to-many edge."""
        if not self.enabled:
            return _NOOP_SPAN
        if parent is None:
            parent = self.current()
        return self._open(
            name, parent, False, links, args, on_stack=True
        )

    def begin(self, name: str, parent=None, **args) -> Optional[OpenSpan]:
        """Open a span WITHOUT touching the TLS stack — for spans closed
        on another thread (solver requests). Close with `.close()`."""
        if not self.enabled:
            return None
        if parent is None:
            parent = self.current()
        return self._open(
            name, parent, False, (), args, on_stack=False
        )

    def _finish(self, span: OpenSpan) -> None:
        now = self._clock()
        args = span.args
        if args:
            args = {k: v for k, v in args.items() if v is not None}
        record = {
            "name": span.name,
            "trace": span.trace_id,
            "id": span.span_id,
            "parent": span.parent_id,
            "ts_us": (span.t0 - self._epoch) * 1e6,
            "dur_us": max(0.0, (now - span.t0) * 1e6),
            "tid": threading.get_ident() & 0xFFFF,
            "args": args,
            "links": [sid for (_tid, sid) in span.links],
        }
        with self._lock:
            dropped = len(self._spans) >= self.capacity
            self._spans.append(record)
            self.spans_total += 1
            if dropped:
                self.spans_dropped += 1
        # counters sync on ROOT closes (bind_registry docstring): a
        # monotone set() at tick granularity instead of a vec-locked
        # inc() per span
        if span.parent_id is None and self._c_spans is not None:
            self._c_spans.set("-", "-", float(self.spans_total))
            self._c_dropped.set("-", "-", float(self.spans_dropped))

    # -- e2e lead time (BLITZSCALE observable) -----------------------------

    def mark_observed(self, key: tuple, overwrite: bool = True) -> None:
        """Stamp WHEN work for an object was observed. The engine passes
        overwrite=False everywhere (watch events AND tick entries):
        marks are retired on ack/convergence, so the earliest stamp
        since retirement is the observation of the CURRENT divergence —
        overwriting would let the engine's own status-patch
        notifications re-stamp a pending mark every tick and
        under-report multi-tick actuations. Disabled tracer: no-op
        (the marks are O(objects)/tick on the reconcile hot path, and
        the e2e histogram is trace-derived telemetry)."""
        if not self.enabled:
            return
        now = self._clock()
        with self._lock:
            if overwrite or key not in self._observed:
                self._observed[key] = now

    def drop_observed(self, key: tuple) -> None:
        """Retire a mark without an actuation: the object converged (or
        was deleted) — a stale stamp must not inflate a later ack.
        Runs even when disabled (clears marks left by a mid-flight
        toggle), but skips the lock when there is nothing to drop."""
        if not self._observed:
            return  # racy read is fine: empty means nothing to drop
        with self._lock:
            self._observed.pop(key, None)

    def ack_observed(self, key: tuple) -> Optional[float]:
        """Actuation acked for `key`: observe event->ack lead time into
        karpenter_reconcile_e2e_seconds and return it (None without a
        mark)."""
        if not self._observed:
            return None
        now = self._clock()
        with self._lock:
            t0 = self._observed.pop(key, None)
        if t0 is None:
            return None
        lead = max(0.0, now - t0)
        self.e2e_observed += 1
        if self._h_e2e is not None:
            self._h_e2e.observe(key[0], "-", lead)
        return lead

    # -- export ------------------------------------------------------------

    def snapshot(self, limit: Optional[int] = None) -> List[dict]:
        """Most-recent-last copy of the finished-span ring."""
        with self._lock:
            spans = list(self._spans)
        if limit is not None and limit >= 0:
            # limit=0 means NONE (spans[-0:] would be the whole ring)
            spans = spans[-limit:] if limit else []
        return spans

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def chrome_events(self) -> List[dict]:
        """Chrome-trace event objects: one complete ("X") event per
        span, plus flow ("s"/"f") event pairs rendering dispatch links
        as arrows in Perfetto."""
        events: List[dict] = []
        spans = self.snapshot()
        by_id = {span["id"]: span for span in spans}
        for span in spans:
            args = dict(span["args"])
            args["trace_id"] = span["trace"]
            if span["parent"]:
                args["parent_id"] = span["parent"]
            if span["links"]:
                args["links"] = list(span["links"])
            events.append({
                "ph": "X",
                "name": span["name"],
                "cat": span["trace"],
                "pid": 1,
                "tid": span["tid"],
                "ts": round(span["ts_us"], 3),
                "dur": round(span["dur_us"], 3),
                "id": span["id"],
                "args": args,
            })
            for linked_id in span["links"]:
                linked = by_id.get(linked_id)
                if linked is None:
                    continue  # the linked span aged out of the ring
                # flow ids are PER EDGE (src>dst): two dispatches
                # linking the same request (the sharded->single-device
                # retry) would otherwise emit duplicate begin events
                # under one id — malformed per the Chrome trace format,
                # and Perfetto misdraws exactly the degraded dispatches
                edge = f"{linked_id}>{span['id']}"
                events.append({
                    "ph": "s", "name": "link", "cat": "link",
                    "id": edge, "pid": 1, "tid": linked["tid"],
                    "ts": round(linked["ts_us"], 3),
                })
                events.append({
                    "ph": "f", "bp": "e", "name": "link", "cat": "link",
                    "id": edge, "pid": 1, "tid": span["tid"],
                    "ts": round(span["ts_us"], 3),
                })
        return events

    def export_jsonl(self, path: str) -> int:
        """Write the Chrome-trace events as JSONL (one event object per
        line), crash-safely (the recovery journal's tmp + fsync +
        rename sequence). Returns the event count."""
        from karpenter_tpu.recovery.journal import atomic_write

        events = self.chrome_events()
        atomic_write(
            path,
            "".join(
                json.dumps(event, sort_keys=True) + "\n"
                for event in events
            ),
        )
        return len(events)


# -- process default ----------------------------------------------------------
# One tracer per process, like faults._active: instrumentation sites read
# it through default_tracer() so trace context crosses module boundaries
# (manager -> producers -> solver -> controller) with no parameter
# threading; the runtime binds its registry to it at boot.

_default = Tracer()


def default_tracer() -> Tracer:
    return _default


def set_default_tracer(tracer: Tracer) -> Tracer:
    global _default
    _default = tracer
    return tracer


def reset_default_tracer() -> Tracer:
    """Swap in a fresh default tracer (test isolation)."""
    return set_default_tracer(Tracer())
