"""Flight recorder: bounded structured event ring with crash-safe dumps.

Counters say HOW OFTEN the degradation ladder fired; they cannot say
WHICH requests a trip degraded or what preceded it. The flight recorder
keeps the last N structured events — fault injections, solver FSM trips,
actuation circuit opens, fence rejections, shard fallbacks, watchdog
restarts, journal compactions — each stamped with wall time, a sequence
number, and TRACE-ID BACKLINKS into the span ring
(observability.tracing), so a post-mortem reads "trip #3 degraded traces
t00000a1/t00000a4" instead of "fsm_trips_total went from 2 to 3".

Dump discipline: trip-class events (`DUMP_KINDS`) dump the whole ring
into `dump_dir` (the runtime wires `--journal-dir`) crash-safely — tmp
file + atomic rename, same idiom as the recovery checkpoint — keeping
the newest `keep_dumps` files, so the dump that explains a crash loop
survives the crash loop. `/debug/flightrecorder` serves the live ring.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time as _time
from typing import List, Optional, Sequence

SUBSYSTEM = "flightrecorder"

DUMP_PREFIX = "flightrecorder-"

# event kinds that snapshot the ring to disk when they land: each marks
# a degradation an operator will want the surrounding context for
# (selfslo_burn: the self-SLO monitor's fast-burn trip —
# observability/selfslo.py — whose whole point is arriving WITH the
# ring of events that burned the budget; compile_storm: the solver
# introspection plane's steady-state compile-miss burst —
# observability/devicetelemetry.py — the dump carries the ledger's
# trace backlinks to the ticks that paid the compiles)
DUMP_KINDS = frozenset((
    "fsm_trip", "circuit_open", "fence_rejection", "watchdog_restart",
    "selfslo_burn", "compile_storm",
))


class FlightRecorder:
    def __init__(
        self,
        capacity: int = 1024,
        clock=_time.time,
        dump_dir: Optional[str] = None,
        keep_dumps: int = 8,
        dump_cooldown_s: float = 30.0,
    ):
        self.capacity = capacity
        self._clock = clock
        self.dump_dir = dump_dir
        self.keep_dumps = keep_dumps
        # auto-dumps run synchronously on the recording (reconcile)
        # thread: without a per-kind cooldown, a fleet-wide incident
        # (N circuit opens in one tick) would pay N fsync pairs AND
        # prune away the incident-origin dumps in favor of the newest
        self.dump_cooldown_s = dump_cooldown_s
        self._last_auto_dump: dict = {}
        self._events: collections.deque = collections.deque(
            maxlen=capacity
        )
        self._lock = threading.Lock()
        self._seq = 0
        self.dumps_written = 0
        self._c_events = self._c_dumps = None

    def configure(
        self,
        dump_dir: Optional[str] = None,
        keep_dumps: Optional[int] = None,
        dump_cooldown_s: Optional[float] = None,
    ) -> None:
        """Late wiring (the runtime knows --journal-dir, the module
        global is built first)."""
        if dump_dir is not None:
            self.dump_dir = dump_dir
        if keep_dumps is not None:
            self.keep_dumps = keep_dumps
        if dump_cooldown_s is not None:
            self.dump_cooldown_s = dump_cooldown_s

    def bind_registry(self, registry) -> None:
        """karpenter_flightrecorder_{events,dumps}_total{name=<kind>}."""
        self._c_events = registry.register(
            SUBSYSTEM, "events_total", kind="counter"
        )
        self._c_dumps = registry.register(
            SUBSYSTEM, "dumps_total", kind="counter"
        )

    # -- recording ---------------------------------------------------------

    def record(
        self, kind: str, trace_ids: Sequence[str] = (),
        auto_dump: bool = True, **fields
    ) -> dict:
        """Append one structured event. `trace_ids` backlinks the event
        to the reconcile traces it concerns; when omitted, the
        recording thread's CURRENT trace (if any) is captured — an
        event fired inside a tick is automatically attributed to it."""
        if not trace_ids:
            from karpenter_tpu.observability.tracing import default_tracer

            current = default_tracer().current_trace_id()
            trace_ids = (current,) if current else ()
        with self._lock:
            self._seq += 1
            event = {
                "seq": self._seq,
                "kind": kind,
                "ts": self._clock(),
                "trace_ids": [t for t in trace_ids if t],
                **fields,
            }
            self._events.append(event)
        if self._c_events is not None:
            self._c_events.inc(kind, "-")
        if auto_dump:
            self.maybe_auto_dump(kind)
        return event

    def maybe_auto_dump(self, kind: str) -> Optional[str]:
        """Cooldown-respecting ring snapshot for a trip-class kind.
        Callers recording two causally-linked trip events for ONE
        incident (watchdog restart that also trips the FSM) pass
        `auto_dump=False` on the first record and invoke this only if
        the second never fires, so an incident writes one dump — not
        two near-identical fsync'd files eating two retention slots."""
        if kind not in DUMP_KINDS or not self.dump_dir:
            return None
        now = self._clock()
        last = self._last_auto_dump.get(kind)
        if last is not None and now - last < self.dump_cooldown_s:
            return None
        self._last_auto_dump[kind] = now
        return self.dump(reason=kind)

    def events(self, kind: Optional[str] = None) -> List[dict]:
        with self._lock:
            events = list(self._events)
        if kind is not None:
            events = [e for e in events if e["kind"] == kind]
        return events

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    # -- dumping -----------------------------------------------------------

    def dump(
        self, path: Optional[str] = None, reason: str = "manual"
    ) -> Optional[str]:
        """Write the ring as one JSON document, crash-safely (tmp +
        atomic rename). Default path: dump_dir/flightrecorder-<seq>-
        <reason>.json, pruning past keep_dumps. Returns the path, or
        None when there is nowhere to write (no dump_dir and no path) —
        recording must never raise into the degradation path it
        records."""
        if path is None:
            if not self.dump_dir:
                return None
            with self._lock:
                seq = self._seq
            path = os.path.join(
                self.dump_dir,
                f"{DUMP_PREFIX}{seq:06d}-{reason}.json",
            )
        doc = {
            "dumped_at": self._clock(),
            "reason": reason,
            "events": self.events(),
        }
        # the recovery journal's durability sequence (tmp + fsync +
        # rename + dir fsync): a rename-durable-but-data-torn dump
        # would defeat "the dump that explains a crash loop survives
        # the crash loop"
        from karpenter_tpu.recovery.journal import atomic_write

        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            atomic_write(path, json.dumps(doc, sort_keys=True))
        except OSError:
            return None
        self.dumps_written += 1
        if self._c_dumps is not None:
            self._c_dumps.inc(reason, "-")
        self._prune_dumps(os.path.dirname(path))
        return path

    def _prune_dumps(self, directory: str) -> None:
        try:
            dumps = sorted(
                name for name in os.listdir(directory or ".")
                if name.startswith(DUMP_PREFIX)
                and name.endswith(".json")
            )
            # keep_dumps <= 0 keeps NOTHING (dumps[:-0] would silently
            # invert the bound and keep everything)
            stales = (
                dumps if self.keep_dumps <= 0
                else dumps[:-self.keep_dumps]
            )
            for stale in stales:
                os.unlink(os.path.join(directory, stale))
        except OSError:
            pass  # pruning is best-effort


# -- process default ----------------------------------------------------------

_default = FlightRecorder()


def default_flight_recorder() -> FlightRecorder:
    return _default


def set_default_flight_recorder(recorder: FlightRecorder) -> FlightRecorder:
    global _default
    _default = recorder
    return recorder


def reset_default_flight_recorder() -> FlightRecorder:
    """Swap in a fresh default recorder (test isolation)."""
    return set_default_flight_recorder(FlightRecorder())
