"""Device-resident fleet state: the canonical solve operands live ON
device and churn arrives as batched scatter updates.

Before this module, every dispatch re-uploaded the full padded operand
stack — PR 8 isolated that as the "upload" stage (~1.4 ms p50 at the
bench fleet, plus a ~0.06 ms device_put floor per tick per subsystem) —
even when the encoder's delta layer (SnapshotDeltaCache) had proven
that only a handful of rows changed since the last tick. BLITZSCALE
makes the same observation for model state (PAPERS.md: "Fast and Live
Large Model Autoscaling with O(1) Host Caching"): keep the hot state
resident where the compute is and ship only deltas, so per-decision
transfer cost stops scaling with fleet size.

ResidentFleetState is the SolverService-owned cache that closes the
loop:

  * each entry holds ONE caller's padded, batch-stacked BinPackInputs
    as live device buffers (NamedSharding-placed on the mesh-sharded
    path), keyed by the IDENTITY of the host inputs object the encoder
    produced — the same identity contract the encode memo and the delta
    layer already uphold (an unchanged dedup set returns the SAME
    object);
  * an identical inputs object re-dispatches against the resident
    buffers with ZERO host encode and ZERO upload;
  * a delta-encoded successor (encoder.resident_plan carries the
    changed-row indices the splice computed) applies as a batched
    scatter — `.at[:, rows].set(updates)` under jit — shipping only the
    changed rows over the transfer link; group operands are reused
    outright (the delta layer only engages when profiles are
    identity-equal);
  * anything else — unknown inputs, a bucket/mode change (the
    shard-threshold crossing), a dropped plan — REBUILDS: one full
    device_put, after which the entry is resident again.

Per-tenant resident slices fall out of the identity keying: every
tenant stack owns its own feed -> delta-cache identity chain, so each
occupies its own entry under the shared service (the LRU holds
MAX_ENTRIES chains).

Correctness posture (pinned by tests/test_resident.py):

  * the scatter result is BIT-IDENTICAL to a cold full upload by
    construction — unchanged rows are byte-equal between consecutive
    delta encodes (that is the delta layer's contract) and changed rows
    are written with exactly the new host bytes;
  * residency is an OPTIMIZATION LAYER only: any inconsistency (shape
    drift, a failed scatter, a missing plan) falls back to the full
    upload, never an error — the never-block contract;
  * resident buffers are NEVER donated to the solve program (the
    dispatch compiles the donate=False family) and scatters build new
    arrays functionally, so an in-flight pipelined dispatch keeps
    reading a consistent buffer;
  * the degradation ladder discards residency cleanly: a device-path
    failure or a recovery boot (SolverService.reset_caches) drops every
    entry, so a numpy-served or post-crash tick can never splice into
    stale device state.
"""

from __future__ import annotations

import collections
import threading
from typing import Optional, Tuple

import numpy as np

from karpenter_tpu.ops.binpack import BinPackInputs
from karpenter_tpu.solver.bucketing import bucket_up, pad_to_bucket

# scatter row counts pad up the shared {1, 1.5} x 2^k ladder so churn
# jitter (3 rows changed, then 7, then 4) reuses one compiled scatter
# program instead of compiling per distinct count
_ROW_FLOOR = 8

# operand leaves the delta layer splices row-wise (everything else in a
# delta-encoded successor is either reused by identity — the group
# arrays — or absent on the delta path; pod_weight has its own row set)
_ROW_LEAVES = ("pod_requests", "pod_valid", "pod_required", "pod_intolerant")


class _Entry:
    """One resident operand stack: the host inputs identity it mirrors,
    the (shape, mode) it was padded/stacked/placed for, and the device
    pytree the dispatch consumes. `tenant`/`created_at` are telemetry
    only (the introspection plane's per-entry byte accounting,
    observability/devicetelemetry.py) — neither participates in
    lookup."""

    __slots__ = (
        "host", "shape", "mode", "stacked", "nbytes", "rows",
        "tenant", "created_at",
    )

    def __init__(self, host, shape, mode, stacked, tenant=None,
                 created_at: float = 0.0):
        self.host = host
        self.shape = shape
        self.mode = mode
        self.stacked = stacked
        self.nbytes = _stack_bytes(stacked)
        self.rows = int(shape[0])
        self.tenant = tenant
        self.created_at = created_at


def _stack_bytes(stacked: BinPackInputs) -> int:
    import dataclasses

    total = 0
    for f in dataclasses.fields(BinPackInputs):
        leaf = getattr(stacked, f.name)
        if leaf is not None:
            total += int(np.prod(leaf.shape)) * leaf.dtype.itemsize
    return total


def _scatter_rows(buf, rows, updates):
    """Batch-stacked row scatter: buf [B, P, ...] <- updates [n, ...]
    at row indices `rows`, replicated across the batch axis (resident
    entries are singleton stacks, B == 1). Padded index slots repeat a
    real row with its own values, so duplicate indices always write
    identical bytes and the result is deterministic."""
    return buf.at[:, rows].set(updates[None])


def _scatter_stack(stacked, rows, u_req, u_val, u_reqd, u_int, u_w):
    """ONE fused scatter over every spliced row leaf (including the
    weight column) — a single compiled dispatch instead of one per
    leaf, which matters on backends where per-dispatch overhead rivals
    the copies. Rows whose bytes didn't actually change (the union-set
    over-approximation) are rewritten with identical values."""
    import dataclasses

    return dataclasses.replace(
        stacked,
        pod_requests=_scatter_rows(stacked.pod_requests, rows, u_req),
        pod_valid=_scatter_rows(stacked.pod_valid, rows, u_val),
        pod_required=_scatter_rows(stacked.pod_required, rows, u_reqd),
        pod_intolerant=_scatter_rows(stacked.pod_intolerant, rows, u_int),
        pod_weight=_scatter_rows(stacked.pod_weight, rows, u_w),
    )


class ResidentFleetState:
    """Bounded identity-keyed cache of device-resident operand stacks
    (module docstring). All mutation happens on the service worker
    thread; `drop_all` (recovery boot / ladder discard) may race it,
    so the entry table swaps whole under a lock and a worker mid-lookup
    keeps a consistent view."""

    MAX_ENTRIES = 8  # distinct caller identity chains (tenants) kept live

    def __init__(self, scatter: str = "auto"):
        self._lock = threading.Lock()
        # insertion-ordered LRU keyed by id(host inputs); entries hold
        # the host object strongly, so a live entry's id is never reused
        self._entries: "collections.OrderedDict[int, _Entry]" = (
            collections.OrderedDict()
        )
        self._stack_scatter_jit = None
        # the scatter rung's gate: "auto" engages it only where device
        # memory is a REAL accelerator behind a transfer link (TPU/GPU
        # — the backends with donation support). On CPU the "device"
        # memory IS host memory, so a copy-on-write scatter costs about
        # what the memcpy upload it avoids costs (measured ~0.94x by
        # `make bench-resident`) and auto mode serves identity hits +
        # rebuilds instead. "always"/"never" force it (tests, bench).
        self.scatter = scatter
        self._scatter_auto: Optional[bool] = None
        # drop generation: drop_all bumps it, and a store whose serve
        # began under an older generation is DISCARDED — a recovery
        # boot racing the worker must not have its drop undone by an
        # entry built from pre-drop buffers
        self._generation = 0
        # plain-int observability, mirrored into the
        # karpenter_solver_resident_* gauges by the owning service
        self.hits = 0
        self.scatters = 0
        self.rebuilds = 0
        self.drops = 0
        self.last_scatter_rows = 0

    # -- bookkeeping -------------------------------------------------------

    def drop_all(self) -> None:
        """Discard every resident buffer (recovery boot, device-path
        failure, shard-route trip): the next dispatch rebuilds from a
        full upload."""
        with self._lock:
            if self._entries:
                self.drops += 1
            self._entries = collections.OrderedDict()
            self._generation += 1

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(e.nbytes for e in self._entries.values())

    def resident_rows(self) -> int:
        with self._lock:
            return sum(e.rows for e in self._entries.values())

    def entries(self, now: Optional[float] = None) -> list:
        """Per-entry telemetry view of the LRU, oldest-use first — the
        EXACT byte accounting the introspection plane publishes as
        karpenter_solver_resident_entry_bytes and /debug/solver renders
        (observability/devicetelemetry.py). `slot` is the LRU position
        at snapshot time; `age_s` needs `now` on the same clock that
        stamped created_at (the owning service's)."""
        with self._lock:
            snapshot = list(self._entries.values())
        return [
            {
                "slot": f"entry{i}",
                "bytes": entry.nbytes,
                "rows": entry.rows,
                "shape": tuple(entry.shape),
                "mode": entry.mode[0],
                "tenant": entry.tenant,
                "age_s": (
                    round(max(0.0, now - entry.created_at), 3)
                    if now is not None else None
                ),
            }
            for i, entry in enumerate(snapshot)
        ]

    def _find(self, host, shape, mode) -> Optional[_Entry]:
        with self._lock:
            for key, entry in self._entries.items():
                if (
                    entry.host is host
                    and entry.shape == shape
                    and entry.mode == mode
                ):
                    self._entries.move_to_end(key)
                    return entry
        return None

    def _store(self, entry: _Entry, generation: int, evict=None) -> None:
        """Admit one entry, unless drop_all ran since the serve began
        (`generation` mismatch: the entry was built from pre-drop
        buffers and must not resurrect them). `evict` removes the
        superseded predecessor of a scatter — its identity can never be
        looked up again (plans chain forward only), and leaving it
        would fill the LRU with dead stacks that evict other tenants'
        LIVE chains."""
        with self._lock:
            if generation != self._generation:
                return
            if evict is not None:
                self._entries.pop(id(evict), None)
            self._entries[id(entry.host)] = entry
            self._entries.move_to_end(id(entry.host))
            while len(self._entries) > self.MAX_ENTRIES:
                self._entries.popitem(last=False)

    # -- the serve path ----------------------------------------------------

    def obtain(
        self,
        inputs: BinPackInputs,
        shape: Tuple[int, int, int, int, int],
        mode: tuple,
        put,
        tenant=None,
        now: float = 0.0,
    ) -> Tuple[BinPackInputs, str]:
        """(device-resident stacked operands, kind) for one singleton
        dispatch. kind is "hit" (identity match — zero encode, zero
        upload), "scatter" (delta plan applied — only the changed rows
        crossed the link), or "rebuild" (full upload through `put`).

        `put` is the service's placement hook — (pytree) -> device
        pytree, device_put with NamedShardings on the sharded path —
        billed to the "upload" stage ring only by the rebuild's full
        stack (a scatter result passes through it to re-pin shardings,
        a device-side no-op). `mode` keys the placement: a mode change
        (the shard-threshold crossing, either direction) misses
        identity on purpose and rebuilds under the new placement.

        Never raises past the full-upload fallback: a scatter that
        fails for ANY reason rebuilds instead."""
        with self._lock:
            generation = self._generation
        entry = self._find(inputs, shape, mode)
        if entry is not None:
            self.hits += 1
            return entry.stacked, "hit"
        # the plan is consulted even when the scatter gate holds (CPU
        # auto mode): a successor ALWAYS supersedes its predecessor's
        # entry, whichever rung serves it
        plan = _plan_for(inputs)
        if plan is not None and self._scatter_allowed():
            prev_entry = self._find(plan.prev, shape, mode)
            if prev_entry is not None:
                try:
                    stacked = self._apply_plan(prev_entry, inputs, plan)
                    if len(mode) > 1:
                        # mesh placement: re-pin the NamedShardings on
                        # the scatter result (device-side, no host
                        # bytes); single-device output is already home
                        stacked = put(stacked)
                    self._store(
                        _Entry(inputs, shape, mode, stacked,
                               tenant=tenant, created_at=now),
                        generation, evict=plan.prev,
                    )
                    self.scatters += 1
                    return stacked, "scatter"
                except Exception:  # noqa: BLE001 — optimization layer:
                    # any scatter-path inconsistency rebuilds instead
                    pass
        stacked = put(_stack_one(pad_to_bucket(inputs, shape)))
        self._store(
            _Entry(inputs, shape, mode, stacked,
                   tenant=tenant, created_at=now),
            generation,
            evict=plan.prev if plan is not None else None,
        )
        self.rebuilds += 1
        return stacked, "rebuild"

    def _apply_plan(self, entry, inputs, plan) -> BinPackInputs:
        """Scatter the changed rows into a NEW stacked pytree: every
        spliced row leaf (and the weight column) updates at the UNION
        of plan.rows and plan.weight_rows in ONE fused dispatch; group
        leaves (identity-reused by the delta layer) carry over
        untouched. The padded update blocks are the only host bytes the
        jitted scatter ships to the device."""
        import jax

        stacked = entry.stacked
        P = entry.shape[0]
        union = (
            plan.rows
            if not len(plan.weight_rows)
            else np.union1d(plan.rows, plan.weight_rows).astype(np.int32)
        )
        if not len(union):
            return stacked
        if int(union.max()) >= P:
            raise ValueError("plan rows exceed resident extent")
        if stacked.pod_weight is None:
            raise ValueError("resident stack lacks the weight operand")
        rows = _pad_rows(union)
        out = self._stack_scatter_fn()(
            stacked, rows,
            *(
                _gather_update(
                    getattr(inputs, name), rows, getattr(stacked, name)
                )
                for name in (*_ROW_LEAVES, "pod_weight")
            ),
        )
        jax.block_until_ready(out)
        self.last_scatter_rows = int(len(union))
        return out

    def _scatter_allowed(self) -> bool:
        if self.scatter == "always":
            return True
        if self.scatter == "never":
            return False
        if self._scatter_auto is None:
            import jax

            self._scatter_auto = jax.default_backend() in (
                "tpu", "gpu", "cuda", "rocm"
            )
        return self._scatter_auto

    def _stack_scatter_fn(self):
        """The fused all-leaves row scatter (one dispatch), compiled
        once per (buffer shapes, padded row count) signature by jax's
        own cache — the row-count ladder (_pad_rows) keeps that
        signature set logarithmic. Donation is deliberately OFF: the
        previous resident buffer may still be read by an in-flight
        pipelined dispatch, and the device-local copy costs no
        transfer."""
        if self._stack_scatter_jit is None:
            import jax

            self._stack_scatter_jit = jax.jit(_scatter_stack)
        return self._stack_scatter_jit


def _pad_rows(rows: np.ndarray) -> np.ndarray:
    """Pad a changed-row index vector up the bucket ladder by repeating
    the FIRST index (its update row is duplicated alongside, so the
    duplicate writes carry identical bytes)."""
    n = len(rows)
    target = bucket_up(n, _ROW_FLOOR)
    out = np.full(target, rows[0], np.int32)
    out[:n] = rows
    return out


def _gather_update(leaf, padded_rows: np.ndarray, buf):
    """Gather the (padded) changed rows of one host operand into the
    update block [n_padded, *tail]. Rows at/past the host extent (the
    shrunk-fleet case: the new encode has fewer rows than the resident
    buffer) read as zeros — exactly what the padded resident rows must
    hold there."""
    host = np.asarray(leaf)
    tail = tuple(buf.shape[2:])
    out = np.zeros((len(padded_rows), *tail), buf.dtype)
    in_range = padded_rows < host.shape[0]
    if in_range.any():
        out[in_range] = host[padded_rows[in_range]]
    return out


def _stack_one(padded: BinPackInputs) -> BinPackInputs:
    """Host stack of ONE padded request (batch axis 1) — the resident
    mirror of the service's _stack_group singleton case."""
    import dataclasses

    def one(name):
        leaf = getattr(padded, name)
        if leaf is None:
            return None
        return np.asarray(leaf)[None]

    return BinPackInputs(
        **{f.name: one(f.name) for f in dataclasses.fields(BinPackInputs)}
    )


def _plan_for(inputs):
    """The delta layer's changed-row plan for `inputs`, or None (cold
    encode, full rebuild, or a non-delta caller). Imported lazily: the
    encoder module owns the registry, so plan production and
    consumption share one lifetime."""
    from karpenter_tpu.metrics.producers.pendingcapacity.encoder import (
        resident_plan,
    )

    return resident_plan(inputs)
