"""Shape bucketing for the shared solve service.

Every distinct operand shape is a distinct XLA program: a fleet whose
pending-pod count wanders 9,812 → 10,407 → 9,955 across ticks would
recompile the bin-pack on every tick if requests were dispatched at
their natural sizes. The encoder already pads to coarse multiples
(producers/pendingcapacity/constants.py), but other callers — the
sidecar's wire requests, simulate, bench — arrive at arbitrary shapes,
and even encoder-padded shapes step at every +256 pods.

The service therefore rounds every axis UP a power-of-two-ish ladder
(1, 1.5, 2, 3, 4, 6, 8, ... × floor): consecutive rungs are ≤ 1.5×
apart, so padding waste is bounded at 50% (33% amortized) while the
number of distinct compiled shapes for traffic in [floor, N] is
O(log N), not O(N). Steady-state traffic whose sizes jitter inside one
rung hits the same compiled program forever — zero recompiles after
warmup, which is what turns the 20–40 s TPU compile from a per-tick
hazard into a once-per-deployment cost.

Padding is SEMANTICS-PRESERVING by construction (the same argument the
encoder's own padding rests on):

  * extra pod rows: valid=False, weight=0 — excluded from assignment,
    every aggregate they touch adds exact zeros;
  * extra group columns: zero allocatable — `_feasibility` rejects them
    outright, so no pod is ever assigned to a padding group and their
    output rows are sliced off before results scatter back;
  * extra taint/label bits: zero on both sides of the bitset matmuls —
    they contribute nothing to either violation count.

Integer outputs (assigned, counts, node totals, unschedulable) are
therefore EQUAL to the unpadded solve, not merely close; the float
intermediate (the LP-bound einsum) only gains exactly-zero terms.
tests/test_solver_service.py pins service outputs against direct
ops/binpack calls element for element.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from karpenter_tpu.ops.binpack import BinPackInputs

# Axis floors: the smallest bucket on each ladder. Pods/groups mirror the
# encoder's pads (a fleet encoded at POD_PAD multiples lands exactly on a
# rung for small fleets); constraint universes mirror their pad constants.
POD_FLOOR = 256
GROUP_FLOOR = 8
TAINT_FLOOR = 32
LABEL_FLOOR = 64
RESOURCE_FLOOR = 4
# Coalesced batches are padded up this ladder too (1, 2, 3, 4, 6, 8, ...)
# so the number of distinct batched programs stays logarithmic in the
# coalesce cap.
BATCH_FLOOR = 1
# Constraint-plane axes (pack classes C, spread slots S, spread domains
# D) ride their own tiny ladders: real fleets have a handful of
# anti-affinity groups and zones, so a floor of 2 keeps the rung count
# in the low single digits.
CLASS_FLOOR = 2
SLOT_FLOOR = 2
DOMAIN_FLOOR = 2


def bucket_up(n: int, floor: int) -> int:
    """Round `n` up to the next rung of the {1, 1.5} × 2^k ladder above
    `floor` (floor, 1.5·floor, 2·floor, 3·floor, 4·floor, ...)."""
    if n <= floor:
        return floor
    rung = floor
    while True:
        if n <= rung:
            return rung
        if n <= rung + rung // 2:
            return rung + rung // 2
        rung *= 2


def bucket_shape(inputs: BinPackInputs) -> Tuple[int, int, int, int, int]:
    """(P, T, R, K, L) rounded up their ladders — the shape half of the
    compile-cache key."""
    p, r = inputs.pod_requests.shape
    t = inputs.group_allocatable.shape[0]
    k = inputs.pod_intolerant.shape[1]
    l = inputs.pod_required.shape[1]
    return (
        bucket_up(p, POD_FLOOR),
        bucket_up(t, GROUP_FLOOR),
        bucket_up(r, RESOURCE_FLOOR),
        bucket_up(k, TAINT_FLOOR),
        bucket_up(l, LABEL_FLOOR),
    )


def constraint_shape(inputs: BinPackInputs) -> Tuple[int, ...]:
    """(C, S, D) constraint-plane axes rounded up their ladders — joins
    the compile-cache key beside bucket_shape. Returns () when no
    shape-bearing constraint operand rides the request, so
    constraint-free traffic keeps a compact key. Padding these axes is
    inert by construction: all-false pack-class columns contribute empty
    histograms, appended zero-cap domains never change the first-fit
    target, and padded cap rows are never referenced (slot <= S_real)."""
    pc = inputs.pod_pack_class
    caps = inputs.spread_cap
    if pc is None and caps is None:
        return ()
    c = 0 if pc is None else bucket_up(pc.shape[1], CLASS_FLOOR)
    s = 0 if caps is None else bucket_up(caps.shape[0], SLOT_FLOOR)
    d = 0 if caps is None else bucket_up(caps.shape[1], DOMAIN_FLOOR)
    return (c, s, d)


def mesh_aligned_shape(
    shape: Tuple[int, int, int, int, int], extents: Tuple[int, int]
) -> Tuple[int, int, int, int, int]:
    """Grow a bucket shape's pod/group axes to the mesh-divisible
    multiples GSPMD requires (extents = parallel.mesh.mesh_extents).
    Constraint-universe axes are replicated on the mesh and stay on
    their own ladders. The result is a deterministic function of
    (bucket shape, extents), so the sharded compile-cache key only
    needs to carry the extents — same-rung traffic still never
    recompiles."""
    from karpenter_tpu.utils.functional import pad_to_multiple

    p, t, r, k, l = shape
    rows, cols = extents
    return (pad_to_multiple(p, rows) if rows > 1 else p,
            pad_to_multiple(t, cols) if cols > 1 else t,
            r, k, l)


def _rung_part(part) -> str:
    """One compile-cache key element, compactly: bool tuples
    (presence) as a 10-string, int tuples (shapes, extents) as
    PxTx..., everything else via str()."""
    if not isinstance(part, tuple) or not part:
        return str(part)
    if all(isinstance(x, bool) for x in part):
        return "".join("1" if x else "0" for x in part)
    if all(isinstance(x, int) for x in part):
        return "x".join(str(x) for x in part)
    return str(part)


def rung_label(key: tuple) -> str:
    """Human-readable rung of one compile-cache key, for telemetry —
    the compile ledger and /debug/solver render cache keys through
    this one formatter (observability/devicetelemetry.py). Unknown
    key vocabularies degrade to repr() rather than raise: a telemetry
    label must never break the dispatch it describes."""
    try:
        return "/".join(_rung_part(part) for part in key)
    except Exception:  # noqa: BLE001 — labels are best-effort
        return repr(key)


def presence(inputs: BinPackInputs) -> Tuple[bool, ...]:
    """Which optional operands ride this request — the other half of the
    compile-cache key (an absent operand removes whole program stages)."""
    return (
        inputs.pod_weight is not None,
        inputs.pod_group_forbidden is not None,
        inputs.pod_group_score is not None,
        inputs.pod_exclusive is not None,
        inputs.pod_priority is not None,
        inputs.group_tier is not None,
        inputs.pod_claim is not None,
        inputs.group_reservation is not None,
        inputs.pod_pack_class is not None,
        inputs.pod_spread_slot is not None,
        inputs.group_domain is not None,
        inputs.spread_cap is not None,
    )


def _pad2(a, rows: int, cols: Optional[int] = None):
    """Zero-pad a 1-D/2-D array up to (rows[, cols]); the same object is
    returned when no padding is needed so already-bucketed traffic (the
    encoder's steady state) keeps identity-based device caches warm."""
    a = np.asarray(a)
    if a.ndim == 1:
        if a.shape[0] == rows:
            return a
        out = np.zeros(rows, a.dtype)
        out[: a.shape[0]] = a
        return out
    if a.shape == (rows, cols):
        return a
    out = np.zeros((rows, cols), a.dtype)
    out[: a.shape[0], : a.shape[1]] = a
    return out


def pad_to_bucket(  # lint: allow-complexity — one presence guard per optional operand
    inputs: BinPackInputs, shape: Tuple[int, int, int, int, int]
) -> BinPackInputs:
    """Pad every operand to the bucket `shape` (see module docstring for
    why this is exact). Returns `inputs` unchanged when already there."""
    p, t, r, k, l = shape
    pc = inputs.pod_pack_class
    caps = inputs.spread_cap
    c_pad = None if pc is None else bucket_up(pc.shape[1], CLASS_FLOOR)
    s_pad = None if caps is None else bucket_up(caps.shape[0], SLOT_FLOOR)
    d_pad = None if caps is None else bucket_up(caps.shape[1], DOMAIN_FLOOR)
    if (
        inputs.pod_requests.shape == (p, r)
        and inputs.group_allocatable.shape == (t, r)
        and inputs.pod_intolerant.shape == (p, k)
        and inputs.pod_required.shape == (p, l)
        and (pc is None or pc.shape == (p, c_pad))
        and (caps is None or caps.shape == (s_pad, d_pad))
    ):
        return inputs
    # pod_weight: absent means "every row counts once", so padding an
    # absent weight must materialize ones for real rows + zeros for pads
    # (an all-ones pad would count invalid padding rows into nothing —
    # they are valid=False — but zero weight keeps the aggregates exact
    # even if a future stage forgets the validity mask)
    weight = inputs.pod_weight
    if weight is not None:
        weight = _pad2(weight, p)
    forbidden = inputs.pod_group_forbidden
    if forbidden is not None:
        forbidden = _pad2(forbidden, p, t)
    score = inputs.pod_group_score
    if score is not None:
        score = _pad2(score, p, t)
    exclusive = inputs.pod_exclusive
    if exclusive is not None:
        exclusive = _pad2(exclusive, p)
    # priority pads at 0 (no steering) and tier at 0 (on-demand) — both
    # only act on rows/columns that are valid/feasible anyway
    priority = inputs.pod_priority
    if priority is not None:
        priority = _pad2(priority, p)
    tier = inputs.group_tier
    if tier is not None:
        tier = _pad2(tier, t)
    # constraint-plane operands: claim/slot pad 0 (unclaimed /
    # unconstrained — their rows are invalid anyway), reservation/domain
    # pad 0 on zero-allocatable groups nothing fits, pack-class rows pad
    # all-false (invalid rows never reach a histogram) and class/slot/
    # domain axes pad up their own ladders (inert — see
    # constraint_shape)
    claim = inputs.pod_claim
    if claim is not None:
        claim = _pad2(claim, p)
    reservation = inputs.group_reservation
    if reservation is not None:
        reservation = _pad2(reservation, t)
    if pc is not None:
        pc = _pad2(pc, p, c_pad)
    slot = inputs.pod_spread_slot
    if slot is not None:
        slot = _pad2(slot, p)
    domain = inputs.group_domain
    if domain is not None:
        domain = _pad2(domain, t)
    if caps is not None:
        caps = _pad2(caps, s_pad, d_pad)
    return BinPackInputs(
        pod_requests=_pad2(inputs.pod_requests, p, r),
        pod_valid=_pad2(inputs.pod_valid, p),
        pod_intolerant=_pad2(inputs.pod_intolerant, p, k),
        pod_required=_pad2(inputs.pod_required, p, l),
        group_allocatable=_pad2(inputs.group_allocatable, t, r),
        group_taints=_pad2(inputs.group_taints, t, k),
        group_labels=_pad2(inputs.group_labels, t, l),
        pod_weight=weight,
        pod_group_forbidden=forbidden,
        pod_group_score=score,
        pod_exclusive=exclusive,
        pod_priority=priority,
        group_tier=tier,
        pod_claim=claim,
        group_reservation=reservation,
        pod_pack_class=pc,
        pod_spread_slot=slot,
        group_domain=domain,
        spread_cap=caps,
    )


# -- eviction-planning (ops/preempt.py) shape ladder --------------------------
# Candidate counts are preemption-scale (a handful of high-priority
# pending pods), victim counts are occupancy-scale; each gets its own
# floor so both single-candidate probes and fleet-wide storms land on
# stable rungs.
CANDIDATE_FLOOR = 8
VICTIM_FLOOR = 64


def preempt_bucket_shape(inputs) -> Tuple[int, int, int, int]:
    """(C, N, R, V) rounded up their ladders — the shape half of the
    preempt compile-cache key."""
    c, r = inputs.pod_requests.shape
    n = inputs.node_free.shape[0]
    v = inputs.victim_requests.shape[0]
    return (
        bucket_up(c, CANDIDATE_FLOOR),
        bucket_up(n, GROUP_FLOOR),
        bucket_up(r, RESOURCE_FLOOR),
        bucket_up(v, VICTIM_FLOOR),
    )


def pad_preempt_inputs(inputs, shape: Tuple[int, int, int, int]):
    """Zero-pad a PreemptInputs up to the bucket `shape`, semantics-
    preserving: padding candidates are invalid (excluded from every
    aggregate), padding node columns are zero-free AND forbidden for
    every candidate (never chosen), padding victims are invalid +
    zero-request with the LAST node column (the sorted-victim contract
    survives) and contribute nothing to prefix sums or maxima."""
    from karpenter_tpu.ops.preempt import PreemptInputs

    c, n, r, v = shape
    if (
        inputs.pod_requests.shape == (c, r)
        and inputs.node_free.shape == (n, r)
        and inputs.victim_requests.shape == (v, r)
    ):
        return inputs
    c0, n0, v0 = (
        inputs.pod_requests.shape[0],
        inputs.node_free.shape[0],
        inputs.victim_requests.shape[0],
    )
    forbidden = np.ones((c, n), bool)
    forbidden[:c0, :n0] = inputs.pod_node_forbidden
    victim_node = np.full(v, n - 1, np.int32)
    victim_node[:v0] = np.asarray(inputs.victim_node, np.int32)
    return PreemptInputs(
        pod_requests=_pad2(inputs.pod_requests, c, r),
        pod_priority=_pad2(inputs.pod_priority, c),
        pod_valid=_pad2(inputs.pod_valid, c),
        pod_node_forbidden=forbidden,
        node_free=_pad2(inputs.node_free, n, r),
        node_tier=_pad2(inputs.node_tier, n),
        victim_requests=_pad2(inputs.victim_requests, v, r),
        victim_priority=_pad2(inputs.victim_priority, v),
        victim_node=victim_node,
        victim_valid=_pad2(inputs.victim_valid, v),
        victim_evictable=_pad2(inputs.victim_evictable, v),
    )


def crop_preempt_outputs(out, n_candidates: int, n_victims: int):
    """Slice a padded preempt solve back to the true candidate/victim
    axes. Padding nodes are forbidden, so no real candidate's
    chosen_node points past the real columns; padding candidates are
    invalid, so `unplaceable` never counts them."""
    return dataclasses.replace(
        out,
        chosen_node=out.chosen_node[:n_candidates],
        evict_count=out.evict_count[:n_candidates],
        evict_mask=out.evict_mask[:n_candidates, :n_victims],
    )


def crop_outputs(out, n_pods: int, n_groups: int):
    """Slice a padded solve's outputs back to the request's true axes.

    Padding groups are all-infeasible, so no real pod's `assigned` index
    ever points past n_groups; padding pods are invalid, so the scalar
    `unschedulable` never counts them. Host numpy in, host numpy out."""
    return dataclasses.replace(
        out,
        assigned=out.assigned[:n_pods],
        assigned_count=out.assigned_count[:n_groups],
        nodes_needed=out.nodes_needed[:n_groups],
        lp_bound=out.lp_bound[:n_groups],
    )
