"""Shared solve service: request coalescing, shape-bucketed compile
cache, and backpressure for every bin-pack caller (docs/solver-service.md).

Public surface:

  * SolverService       — the long-lived in-process service
  * SolveFuture         — async handle from SolverService.submit
  * SolverSaturated     — bounded-queue backpressure signal
  * SolverTimeout       — per-request deadline expiry
  * default_service     — the process-shared instance (simulate, sidecar)
  * bucket_up / bucket_shape / pad_to_bucket — the shape ladder
"""

from karpenter_tpu.solver.bucketing import (
    bucket_shape,
    bucket_up,
    mesh_aligned_shape,
    pad_to_bucket,
)
from karpenter_tpu.solver.resident import ResidentFleetState
from karpenter_tpu.solver.service import (
    DEFAULT_SHARD_THRESHOLD,
    SUBSYSTEM,
    SolveFuture,
    SolverSaturated,
    SolverService,
    SolverStatistics,
    SolverTimeout,
    default_service,
    reset_default_service,
)

__all__ = [
    "DEFAULT_SHARD_THRESHOLD",
    "ResidentFleetState",
    "SUBSYSTEM",
    "SolveFuture",
    "SolverSaturated",
    "SolverService",
    "SolverStatistics",
    "SolverTimeout",
    "bucket_shape",
    "bucket_up",
    "default_service",
    "mesh_aligned_shape",
    "pad_to_bucket",
    "reset_default_service",
]
