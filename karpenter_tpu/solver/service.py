"""Shared solve service: one amortized device pipeline for every caller.

Before this subsystem, each bin-pack caller — the pendingCapacity batch
solve, simulate, the gRPC sidecar's concurrent Solve RPCs, bench — drove
ops/binpack on its own: concurrent ticks paid separate XLA dispatches,
and every novel operand shape paid a fresh compile. The service is the
single in-process front door that turns those independent calls into one
production pipeline:

  submit → coalesce → pad → dispatch → scatter

  * ADAPTIVE COALESCING QUEUE: requests arriving within a gather window
    are batched, grouped by compatibility key, and same-key requests
    ride ONE batched device call (`lax.map` over the stacked operands —
    the per-item program is the same HLO as a direct solve, so results
    match a direct ops/binpack call element for element). The window is
    LOAD-ADAPTIVE: an idle queue dispatches immediately (a lone
    reconcile tick pays no batching-timer tax), and the window widens
    to `window_s` only while recent traffic was actually concurrent
    (backlog present, or the batch-size EWMA above the idle threshold).
  * PIPELINED DISPATCH: the worker double-buffers device work — while
    batch k computes on device, batch k+1 is gathered, padded, stacked,
    and dispatched, and only then is batch k's host-side fetch/crop
    paid. Steady-state dispatches stop paying the host round-trip in
    line, and `donate_argnums` on the (device-put) stacked operands
    lets XLA reuse batch buffers instead of reallocating per dispatch.
  * SHAPE BUCKETING + COMPILE CACHE: operands are padded up the
    power-of-two-ish ladder (solver/bucketing.py) and the compiled
    program is cached per (shape bucket, batch bucket, buckets,
    operand presence, backend). Steady-state traffic whose sizes jitter
    inside one rung never recompiles; the hit/miss counters make that
    claim testable.
  * BACKPRESSURE + DEADLINES: the queue is bounded — a full queue
    degrades the overflow request to the numpy backend inline instead
    of growing an unbounded backlog; each request carries a deadline,
    and an expired wait degrades the same way (or raises, per
    `on_timeout`). A device-path failure falls back to numpy per
    request: the control plane keeps producing signals through an
    accelerator outage, the posture every entry point in this repo
    takes (utils/backend.py).
  * BACKEND HEALTH FSM + WATCHDOG (docs/resilience.md): per-request
    fallback is the first rung; the FSM is the wholesale one. After
    `health_failure_threshold` CONSECUTIVE device failures the service
    trips to DEGRADED: every request routes straight to numpy with no
    device attempt (a dead accelerator stops billing each request a
    failed dispatch), and one probe dispatch per
    `health_probe_interval_s` rides the device path — a probe success
    flips back to HEALTHY. Separately, a watchdog (enabled by
    `watchdog_timeout_s` > 0) detects a worker HUNG inside a device
    call — the failure mode fallback can't catch, because the except
    never runs — restarts the worker thread (generation-stamped; the
    stale thread's late results are discarded) and drains the stuck
    requests to numpy, so no caller waits out a dead device. Both
    export karpenter_resilience_* metrics.
  * METRICS: queue depth, coalesce factor, compile-cache hits/misses,
    rejections/expiries/fallbacks, and per-stage latency percentiles,
    registered through the same GaugeRegistry the runtime serves on
    /metrics (subsystem "solver").

Besides bin-packs the queue carries three more program families through
the same pipeline: `decide` (the HPA decision kernel — no coalescing,
the batch autoscaler already evaluates the whole fleet at once),
`forecast` (forecast/models.py — concurrent forecast requests
concatenate along the series axis and ride ONE dispatch), and `preempt`
(ops/preempt.py — fleet-wide placement-with-eviction planning, every
candidate in one dispatch). Both of the latter degrade to numpy mirrors
that are bit-identical to their device kernels. A fourth synchronous
family, `cost` (ops/cost.py — the fleet's multi-objective cost/SLO
refinement in one dispatch), rides the same FSM with a deliberately
different failure posture: cost-blind, not mirror-served (docs/cost.md).

The service holds no DOMAIN state — results are a pure function of each
request — but it does own one derived cache: the DEVICE-RESIDENT fleet
state (solver/resident.py, docs/solver-service.md "Device-resident
fleet state"). Singleton solve dispatches keep their padded operand
stack resident on device, keyed by the host inputs object's identity:
an unchanged fleet re-dispatches with zero host encode and zero upload,
and a delta-encoded successor (the encoder's SnapshotDeltaCache
publishes the changed-row plan) applies as a batched scatter instead of
a full re-upload. Residency is bit-identical to the cold path by
construction, falls back to a full upload on any inconsistency, and is
discarded wholesale by the degradation ladder and the recovery boot
(reset_caches).
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time as _time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from karpenter_tpu.faults import inject
from karpenter_tpu.metrics.registry import GaugeRegistry, default_registry
from karpenter_tpu.observability import (
    default_flight_recorder,
    default_tracer,
    solver_trace,
)
from karpenter_tpu.ops.binpack import (
    DEFAULT_BUCKETS,
    BinPackInputs,
    has_constraint_operands,
)
from karpenter_tpu.solver.bucketing import (
    bucket_up,
    bucket_shape,
    constraint_shape,
    crop_outputs,
    crop_preempt_outputs,
    pad_preempt_inputs,
    pad_to_bucket,
    preempt_bucket_shape,
    presence,
)
from karpenter_tpu.utils.log import logger

SUBSYSTEM = "solver"

QUEUE_DEPTH = "queue_depth"
COALESCE_FACTOR = "coalesce_factor"
REQUESTS_TOTAL = "requests_total"
DISPATCH_TOTAL = "dispatch_total"
COMPILE_CACHE_HITS = "compile_cache_hits_total"
COMPILE_CACHE_MISSES = "compile_cache_misses_total"
FALLBACK_TOTAL = "fallback_total"
REJECTED_TOTAL = "rejected_total"
DEADLINE_EXPIRED_TOTAL = "deadline_expired_total"
STAGE_P50_MS = "stage_p50_ms"
STAGE_P99_MS = "stage_p99_ms"
STAGE_SECONDS = "stage_seconds"
COALESCE_BATCH_SIZE = "coalesce_batch_size"
WINDOW_MS = "window_ms"
PIPELINE_DEPTH = "pipeline_depth"
UPLOAD_MS = "upload_ms"
SHARD_DEVICES = "shard_devices"
# device-resident fleet state (solver/resident.py)
RESIDENT_BYTES = "resident_bytes"
RESIDENT_ROWS = "resident_rows"
RESIDENT_SCATTER_MS = "resident_scatter_ms"
RESIDENT_REBUILDS = "resident_rebuilds_total"
# boot-time compile pre-warm (docs/solver-service.md "Compile pre-warm")
PREWARM_COMPILES = "prewarm_compiles_total"
PREWARM_MS = "prewarm_ms"
# device programs the last reconcile tick paid (docs/solver-service.md
# "Fused tick"): 3+ on the chained steady-state path, 1 once
# --fused-tick engages — the production observable behind the bench's
# dispatch-count claim
DISPATCHES_PER_TICK = "dispatches_per_tick"

# Fused-family compile keys the PROCESS has already paid for: the fused
# program rides the module-level fused_tick_jit (process-global jit
# cache, disk-global under --compile-cache-dir), so freshness — and the
# compile-ledger rows it drives — is a process property, not a
# per-service one (_count_fused_compile). reset_caches() re-arms.
_FUSED_COMPILE_SEEN: set = set()

# Sharded dispatch (docs/solver-service.md "Sharded dispatch"): a request
# whose pods x groups constraint matrix reaches this many cells routes
# through the multi-device mesh (parallel/mesh.py) instead of the
# single-device program — when a mesh with >= 2 devices exists. 2^24
# cells ≈ the north-star 100k x 300 fleet at 5% occupancy headroom:
# small-fleet traffic (10k x 50 = 5 x 10^5) never pays mesh padding or
# the sharded compile, fleet-scale decisions (1M x 1k = 10^9) always
# shard. 0 disables sharding outright.
DEFAULT_SHARD_THRESHOLD = 1 << 24

# A lone coalesced map-strategy batch splits into pipeline_depth+1
# chunked dispatches (so the double buffer has something to overlap)
# only at or above this size — smaller batches aren't worth a second
# dispatch's fixed cost, and 2-request batches must keep riding one
# dispatch (the coalescing contract tests pin).
_PIPELINE_SPLIT_MIN = 4

# Backend health FSM states (karpenter_resilience_solver_backend_state)
HEALTHY = "healthy"
DEGRADED = "degraded"

# Forecast shape ladders (forecast requests share the bin-pack compile
# cache but bucket on (series, history-length) instead)
FORECAST_T_FLOOR = 16
FORECAST_S_FLOOR = 8

# Extra watchdog headroom for a dispatch that MISSED the compile cache:
# first-call XLA/Mosaic compiles legitimately run tens of seconds (TPU
# solver programs: 20-40s), and a restart mid-compile would loop — the
# fresh worker would just compile again. Steady-state dispatches (cache
# hits) get the bare watchdog_timeout_s.
COMPILE_GRACE_S = 120.0

_STAGE_WINDOW = 256  # per-stage latency ring size (fleet-scale constant)
# native-histogram ladders (docs/observability.md): stage latencies run
# from sub-ms host work to tens-of-seconds first compiles; coalesce
# batch sizes follow the power-of-two batch ladder
_STAGE_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 30.0,
)
_COALESCE_BUCKETS = (1, 2, 4, 8, 16, 32, 64)
# Adaptive-window load tracking: EWMA of gathered batch sizes. Below the
# threshold the queue is treated as idle (dispatch immediately); at or
# above it the full window holds so concurrent bursts keep coalescing.
_LOAD_ALPHA = 0.5
_LOAD_IDLE = 1.5


class SolverSaturated(RuntimeError):
    """The bounded request queue is full (backpressure signal)."""


class CostUnavailable(RuntimeError):
    """The cost-refinement path is short-circuited (backend-health FSM
    degraded, no probe due): the caller proceeds cost-blind this tick
    (docs/cost.md degradation contract)."""


class SolverTimeout(TimeoutError):
    """A request's deadline expired before the device path answered."""


@dataclass
class SolverStatistics:
    """Plain-int mirror of the service counters (tests and callers read
    these directly; the registry carries the same values for /metrics)."""

    requests: int = 0
    dispatches: int = 0
    coalesced_batches: int = 0
    compile_cache_hits: int = 0
    compile_cache_misses: int = 0
    fallbacks: int = 0
    rejected: int = 0
    deadline_expired: int = 0
    last_coalesce_factor: int = 0
    immediate_dispatches: int = 0  # idle-queue batches that skipped the window
    pipeline_overlaps: int = 0  # dispatches issued while another was in flight
    decide_calls: int = 0
    decide_errors: int = 0
    # cost-refinement seam (karpenter_tpu/cost, docs/cost.md)
    cost_calls: int = 0  # cost() entries
    cost_errors: int = 0  # cost() failures (the caller goes cost-blind)
    cost_dispatches: int = 0  # cost device dispatches
    # joint pool-group allocation seam (poolgroups/, docs/poolgroups.md)
    poolgroup_calls: int = 0  # poolgroup() entries
    poolgroup_errors: int = 0  # poolgroup() failures (even the floor died)
    poolgroup_dispatches: int = 0  # joint device dispatches
    poolgroup_independent_serves: int = 0  # degraded independent-ladder serves
    consolidate_calls: int = 0
    consolidate_candidates: int = 0
    # forecast seam (forecast/, docs/forecasting.md)
    forecast_calls: int = 0  # forecast() entries
    forecast_series: int = 0  # total series submitted across calls
    forecast_dispatches: int = 0  # coalesced forecast device dispatches
    # eviction-planning seam (ops/preempt.py, docs/preemption.md)
    preempt_calls: int = 0  # preempt() entries
    preempt_candidates: int = 0  # total candidates submitted across calls
    preempt_dispatches: int = 0  # preempt device dispatches
    # constraint plane (docs/constraints.md): pallas-resolved requests
    # carrying constraint operands rerouted to the XLA family (Mosaic
    # has no constraint entry — counted, never silently dropped)
    constraint_reroutes: int = 0
    # simlab cluster-stepping seam (ops/simstep.py, docs/simulator.md)
    sim_calls: int = 0  # sim_step() + sim_rollout() entries
    sim_dispatches: int = 0  # sim device dispatches (1 per batched call)
    sim_mirror_serves: int = 0  # sim calls served by the numpy mirror
    # fused steady-state tick (ops/fusedtick.py, docs/solver-service.md
    # "Fused tick")
    fused_calls: int = 0  # fused_tick() entries
    fused_dispatches: int = 0  # ticks answered by the ONE fused program
    fused_chained_serves: int = 0  # ticks served by the per-stage rung
    fused_mirror_serves: int = 0  # ticks served by the numpy floor
    last_dispatches_per_tick: int = 0  # note_tick() delta (the gauge)
    # sharded dispatch (docs/solver-service.md "Sharded dispatch")
    shard_dispatches: int = 0  # batches answered by the mesh-sharded program
    shard_requests: int = 0  # requests routed onto the mesh at submit
    shard_fallbacks: int = 0  # shard-path failures retried single-device
    # device-resident fleet state (solver/resident.py)
    resident_hits: int = 0  # dispatches served from resident buffers as-is
    resident_scatters: int = 0  # dispatches served via a changed-row scatter
    resident_rebuilds: int = 0  # full uploads (re)establishing residency
    resident_drops: int = 0  # wholesale discards (ladder / recovery boot)
    pipeline_splits: int = 0  # lone batches chunked so the pipeline overlaps
    # backend health FSM + watchdog (docs/resilience.md)
    device_failures: int = 0  # total device-path failures (any rung)
    fsm_trips: int = 0  # healthy -> degraded transitions
    fsm_recoveries: int = 0  # degraded -> healthy transitions
    fsm_probes: int = 0  # device probes granted while degraded
    fsm_short_circuits: int = 0  # batches routed to numpy with no attempt
    watchdog_restarts: int = 0  # hung-worker restarts


@dataclass
class _Request:
    inputs: BinPackInputs
    buckets: int
    backend: str
    key: tuple
    n_pods: int
    n_groups: int
    deadline: Optional[float]
    enqueued_at: float
    event: threading.Event = field(default_factory=threading.Event)
    result: Optional[object] = None
    error: Optional[BaseException] = None
    abandoned: bool = False
    # consolidate() batch marker: requests sharing an id were enqueued
    # atomically and must ride ONE dispatch — _collect keeps draining the
    # queue past max_batch while the head continues the same batch
    coalesce_id: Optional[int] = None
    # reconcile-trace span opened at submit (observability.tracing):
    # covers queue wait through completion; the coalesced dispatch span
    # LINKS it, and the FSM-trip flight-recorder event backlinks its
    # trace id. None with tracing disabled.
    span: Optional[object] = None
    # tenant id the submitter stamped (multi-tenant scheduler), carried
    # into the span args so /debug/traces?tenant= finds the request
    tenant: Optional[str] = None
    _finish_lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False
    )

    def trace_id(self) -> Optional[str]:
        return self.span.trace_id if self.span is not None else None

    def finish(self, result=None, error=None, degraded: bool = False) -> bool:
        """First finisher wins (idempotent): the watchdog may drain a
        stuck request to numpy while the stale worker later unwedges and
        tries to answer it too — the caller must see exactly one result.
        `degraded` marks the span of a request the ladder answered from
        numpy AFTER a device failure/hang — a trace reader must be able
        to tell those from healthy device-served requests (the
        fsm_trip flight-recorder event backlinks their traces)."""
        with self._finish_lock:
            if self.event.is_set():
                return False
            self.result = result
            self.error = error
            self.event.set()
            if self.span is not None:
                self.span.close(
                    ok=error is None, degraded=degraded or None
                )
            return True


class SolveFuture:
    """Handle returned by submit(); result() blocks with a deadline."""

    def __init__(self, request: _Request, service: "SolverService"):
        self._request = request
        self._service = service

    def result(self, timeout: Optional[float] = None):
        req = self._request
        if not req.event.wait(timeout):
            req.abandoned = True  # the worker will skip it
            self._service._on_expired(req)
            raise SolverTimeout(
                f"solve deadline expired after {timeout}s "
                f"(queue depth {self._service.queue_depth()})"
            )
        if req.error is not None:
            raise req.error
        return req.result


class SolverService:
    """Long-lived in-process solve service (module docstring).

    `device_solver` overrides the in-process device path with any
    (inputs, buckets=..., backend=...) -> BinPackOutputs callable — the
    sidecar SolverClient.solve under the gRPC process split, or a fault
    injector in tests. With an override the worker dispatches requests
    individually (the wire codec carries one problem per message), but
    queueing, deadlines, backpressure, fallback, and metrics still
    apply. `decider` seams the HPA decision kernel the same way.
    """

    def __init__(
        self,
        registry: Optional[GaugeRegistry] = None,
        *,
        window_s: float = 0.002,
        adaptive_window: bool = True,
        pipeline_depth: int = 1,
        max_queue: int = 64,
        max_batch: int = 8,
        default_timeout_s: float = 30.0,
        backend: str = "auto",
        on_timeout: str = "fallback",  # or "raise"
        device_solver: Optional[Callable] = None,
        decider: Optional[Callable] = None,
        clock: Callable[[], float] = _time.monotonic,
        health_failure_threshold: int = 3,
        health_probe_interval_s: float = 5.0,
        watchdog_timeout_s: float = 0.0,  # 0 = watchdog disabled
        shard_threshold: int = DEFAULT_SHARD_THRESHOLD,
        shard_devices: Optional[int] = None,
        shard_mesh_shape: Optional[tuple] = None,
        resident: bool = True,
    ):
        if on_timeout not in ("fallback", "raise"):
            raise ValueError(f"on_timeout must be fallback|raise, got {on_timeout!r}")
        self.registry = registry if registry is not None else default_registry()
        # window_s is now the MAX gather window: with adaptive_window an
        # idle queue dispatches immediately and only concurrent traffic
        # waits the window out; adaptive_window=False pins the fixed
        # always-wait window (the pre-overhaul behavior)
        self.window_s = window_s
        self.adaptive_window = adaptive_window
        # how many dispatched-but-unfetched batches may be in flight (1 =
        # double buffering: host scatter of batch k overlaps device
        # compute of batch k+1); 0 disables pipelining
        self.pipeline_depth = pipeline_depth
        self.max_queue = max_queue
        self.max_batch = max_batch
        self.default_timeout_s = default_timeout_s
        self.backend = backend
        self.on_timeout = on_timeout
        self.device_solver = device_solver
        self._decider = decider
        self._clock = clock
        self.stats = SolverStatistics()
        self._queue: collections.deque = collections.deque()
        self._coalesce_seq = 0  # consolidate() batch-marker source
        self._cond = threading.Condition()
        self._worker: Optional[threading.Thread] = None
        self._closed = False
        # (backend, shape, batch, buckets, presence) -> compiled callable
        self._compiled: Dict[tuple, Callable] = {}
        self._compile_seen: set = set()
        # kernel families already pre-warmed this process lifetime
        # (prewarm; reset_caches re-arms)
        self._prewarmed: set = set()
        self._stages: Dict[str, collections.deque] = {}
        self._stage_lock = threading.Lock()
        # worker-only state: batch-size EWMA (adaptive window), in-flight
        # dispatches (pipeline), and the gauges mirroring both
        self._load = 0.0
        self._window_now_s = 0.0 if adaptive_window else window_s
        self._inflight: collections.deque = collections.deque()
        self._last_pipeline_depth = 0
        # sharded dispatch (docs/solver-service.md "Sharded dispatch"):
        # requests whose pods x groups cell count reaches the threshold
        # route through a multi-device mesh, built lazily on first use.
        # shard_devices caps the device count (None = all), and
        # shard_mesh_shape pins explicit (pods, groups) extents instead
        # of the pods-major factorization.
        self.shard_threshold = shard_threshold
        self.shard_devices = shard_devices
        self.shard_mesh_shape = (
            tuple(shard_mesh_shape) if shard_mesh_shape else None
        )
        self._mesh = None
        self._mesh_ready = False
        self._mesh_lock = threading.Lock()
        # one shard-path failure stops routing NEW traffic to the mesh
        # (the single-device program keeps serving); reset_caches — the
        # recovery-boot seam — re-arms it
        self._shard_broken = False
        # device-resident fleet state (solver/resident.py): singleton
        # solve dispatches keep their operand stack on device and churn
        # applies as batched scatters. `resident=False` pins the
        # upload-every-dispatch path (the bench-resident OFF arm).
        self.resident_enabled = resident
        from karpenter_tpu.solver.resident import ResidentFleetState

        self._resident = ResidentFleetState()
        # solver introspection plane (observability/devicetelemetry.py,
        # --introspect): compile ledger + XLA cost attribution + device
        # memory telemetry. None (the default) keeps every hot-path
        # hook a single attribute read — the off-path pin.
        self._introspect = None
        # whether the decide family was given an injected kernel (the
        # gRPC split / tests): an injected decider owns its own device
        # semantics, so the sharded decide route must stay out of it
        self._decider_injected = decider is not None
        # backend health FSM (module docstring): trips wholesale to numpy
        # after K consecutive device failures, probes recovery
        self.health_failure_threshold = health_failure_threshold
        self.health_probe_interval_s = health_probe_interval_s
        self.watchdog_timeout_s = watchdog_timeout_s
        self._health_lock = threading.Lock()
        self._health = HEALTHY
        self._consec_device_failures = 0
        self._next_probe = 0.0
        # watchdog: generation-stamped worker threads; a restart bumps
        # the generation and the superseded thread discards its results
        self._worker_gen = 0
        self._watchdog: Optional[threading.Thread] = None
        self._busy_since: Optional[float] = None
        self._busy_requests: List[_Request] = []
        # the FULL batch the worker is currently processing (already
        # popped from the queue): on a watchdog restart, groups not yet
        # dispatched live only here and must be drained too
        self._current_batch: List[_Request] = []
        self._tls = threading.local()
        # per-tick dispatch accounting (note_tick): the gauge shows the
        # delta of stats.dispatches between manager ticks
        self._tick_dispatch_mark = 0
        self._register_metrics()

    # -- metrics ----------------------------------------------------------

    def _register_metrics(self) -> None:
        reg = self.registry.register
        self._g_queue = reg(SUBSYSTEM, QUEUE_DEPTH)
        self._g_coalesce = reg(SUBSYSTEM, COALESCE_FACTOR)
        self._c_requests = reg(SUBSYSTEM, REQUESTS_TOTAL, kind="counter")
        self._c_dispatch = reg(SUBSYSTEM, DISPATCH_TOTAL, kind="counter")
        self._c_hits = reg(SUBSYSTEM, COMPILE_CACHE_HITS, kind="counter")
        self._c_misses = reg(SUBSYSTEM, COMPILE_CACHE_MISSES, kind="counter")
        self._c_fallback = reg(SUBSYSTEM, FALLBACK_TOTAL, kind="counter")
        self._c_rejected = reg(SUBSYSTEM, REJECTED_TOTAL, kind="counter")
        self._c_expired = reg(
            SUBSYSTEM, DEADLINE_EXPIRED_TOTAL, kind="counter"
        )
        self._g_stage_p50 = reg(SUBSYSTEM, STAGE_P50_MS)
        self._g_stage_p99 = reg(SUBSYSTEM, STAGE_P99_MS)
        # native histograms (docs/observability.md): the stage rings as
        # real bucketed distributions {name=<stage>}, and the coalesce
        # factor as a batch-size histogram — histogram_quantile() works
        # where the p50/p99 gauge snapshots only sampled
        self._h_stage = reg(
            SUBSYSTEM, STAGE_SECONDS, kind="histogram",
            buckets=_STAGE_BUCKETS,
        )
        self._h_coalesce = reg(
            SUBSYSTEM, COALESCE_BATCH_SIZE, kind="histogram",
            buckets=_COALESCE_BUCKETS,
        )
        self._g_window = reg(SUBSYSTEM, WINDOW_MS)
        self._g_pipeline = reg(SUBSYSTEM, PIPELINE_DEPTH)
        # host->device transfer p50 of recent dispatches — the measured
        # baseline the device-resident-state work (ROADMAP item 4)
        # attacks; also present per-dispatch under stage_p50_ms{upload}
        self._g_upload = reg(SUBSYSTEM, UPLOAD_MS)
        # devices behind the sharded dispatch strategy (0 = single-device:
        # no mesh, below threshold traffic only, or shard path tripped)
        self._g_shard = reg(SUBSYSTEM, SHARD_DEVICES)
        # device-resident fleet state (solver/resident.py): bytes/rows
        # currently resident, the last scatter's wall time, and how
        # often residency had to rebuild from a full upload
        self._g_resident_bytes = reg(SUBSYSTEM, RESIDENT_BYTES)
        self._g_resident_rows = reg(SUBSYSTEM, RESIDENT_ROWS)
        self._g_resident_scatter = reg(SUBSYSTEM, RESIDENT_SCATTER_MS)
        self._c_resident_rebuilds = reg(
            SUBSYSTEM, RESIDENT_REBUILDS, kind="counter"
        )
        # boot-time pre-warm: rungs compiled {name=<family>} and the
        # wall cost of each family's warm dispatch — near-zero when the
        # persistent compile cache (KARPENTER_COMPILE_CACHE) served it
        self._c_prewarm = reg(SUBSYSTEM, PREWARM_COMPILES, kind="counter")
        self._g_prewarm_ms = reg(SUBSYSTEM, PREWARM_MS)
        # device programs per reconcile tick (note_tick): the fused-tick
        # 3+ → 1 program-count claim as a production observable
        self._g_dispatches_tick = reg(SUBSYSTEM, DISPATCHES_PER_TICK)
        # degradation-ladder surface (docs/resilience.md): FSM state
        # (0 healthy / 1 degraded) + transition and watchdog counters
        self._g_backend_state = reg("resilience", "solver_backend_state")
        self._g_backend_state.set("-", "-", 0.0)
        self._c_trips = reg(
            "resilience", "solver_trips_total", kind="counter"
        )
        self._c_probes = reg(
            "resilience", "solver_probes_total", kind="counter"
        )
        self._c_recoveries = reg(
            "resilience", "solver_recoveries_total", kind="counter"
        )
        self._c_watchdog = reg(
            "resilience", "solver_watchdog_restarts_total", kind="counter"
        )

    def _record_stage(self, stage: str, seconds: float) -> None:
        ms = seconds * 1e3
        with self._stage_lock:
            ring = self._stages.get(stage)
            if ring is None:
                ring = self._stages[stage] = collections.deque(
                    maxlen=_STAGE_WINDOW
                )
            ring.append(ms)
        self._h_stage.observe(stage, "-", seconds)

    def publish_gauges(self) -> None:
        """Refresh the point-in-time gauges (queue depth, coalesce
        factor, per-stage latency percentiles). Counters are incremented
        at event time and need no refresh; the Manager calls this each
        tick so /metrics stays current even across idle windows."""
        self._g_queue.set("-", "-", float(self.queue_depth()))
        self._g_coalesce.set(
            "-", "-", float(self.stats.last_coalesce_factor)
        )
        # the EFFECTIVE window of the last gather (0 on an idle queue,
        # window_s under concurrency) and the in-flight depth of the
        # last dispatch — the two tuning signals docs/solver-service.md's
        # latency section reads
        self._g_window.set("-", "-", self._window_now_s * 1e3)
        self._g_pipeline.set("-", "-", float(self._last_pipeline_depth))
        n_shard = 0
        if self._mesh is not None and not self._shard_broken:
            n_shard = int(self._mesh.devices.size)
        self._g_shard.set("-", "-", float(n_shard))
        self._g_resident_bytes.set(
            "-", "-", float(self._resident.resident_bytes())
        )
        self._g_resident_rows.set(
            "-", "-", float(self._resident.resident_rows())
        )
        with self._stage_lock:
            snapshot = {k: list(v) for k, v in self._stages.items()}
        uploads = snapshot.get("upload")
        if uploads:
            self._g_upload.set(
                "-", "-", float(np.percentile(uploads, 50))
            )
        for stage, samples in snapshot.items():
            if samples:
                self._g_stage_p50.set(
                    stage, "-", float(np.percentile(samples, 50))
                )
                self._g_stage_p99.set(
                    stage, "-", float(np.percentile(samples, 99))
                )

    def note_tick(self) -> None:
        """Per-tick dispatch accounting behind the
        karpenter_solver_dispatches_per_tick gauge: the Manager calls
        this once at the end of every reconcile tick; the gauge then
        shows how many device programs that tick paid — 3+ on the
        chained steady-state path (forecast + decide + cost), exactly 1
        once --fused-tick engages (docs/solver-service.md "Fused
        tick")."""
        delta = self.stats.dispatches - self._tick_dispatch_mark
        self._tick_dispatch_mark = self.stats.dispatches
        self.stats.last_dispatches_per_tick = delta
        self._g_dispatches_tick.set("-", "-", float(delta))

    @contextlib.contextmanager
    def track(self, stage: str):
        """Record an arbitrary caller stage (e.g. the HA controller's
        fleet decide) into the service's latency surface."""
        t0 = _time.perf_counter()
        try:
            yield
        finally:
            self._record_stage(stage, _time.perf_counter() - t0)

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def reset_caches(self) -> None:
        """Recovery-boot seam (docs/resilience.md "Crash recovery"):
        drop the compiled-program cache and the compile-seen keys so
        post-restart dispatches rebuild from scratch. Identity-keyed
        device caches (the encoder's delta layer hands back the SAME
        inputs object to skip re-upload) are only sound within one
        process lifetime of consistent state — a recovery boot must not
        silently reuse pre-crash arrays. Fresh dict/set objects are
        swapped in whole, so a worker mid-lookup keeps a consistent
        (old) view and the next lookup sees the reset."""
        with self._cond:
            self._compiled = {}
            self._compile_seen = set()
        # the fused family tracks freshness process-globally (its
        # program cache IS process-global — _count_fused_compile);
        # a recovery boot re-arms it alongside the instance caches
        _FUSED_COMPILE_SEEN.clear()
        # a recovery boot also re-arms the sharded dispatch strategy: a
        # pre-crash shard failure shouldn't pin the successor single-
        # device forever (the ladder re-trips on the next failure)
        self._shard_broken = False
        # and drops every device-resident operand stack: post-recovery
        # encodes must not scatter into pre-crash buffers (the encoder
        # clears its scatter plans through the same boot seam)
        self._resident.drop_all()
        # a reset plane may legitimately want a fresh warm-up
        self._prewarmed = set()

    # -- introspection plane (observability/devicetelemetry.py) ------------

    def attach_introspection(self, plane) -> None:
        """Wire the solver introspection plane (--introspect): dispatch
        sites note compile-cache misses into its ledger and dispatch
        spans gain the XLA cost attribution captured at compile time.
        Detached (the default), every hook below is one attribute
        read."""
        self._introspect = plane

    def _note_compile(
        self, family: str, key: tuple, seconds: float,
        live: List[_Request] = (), extents=None, cost_fn=None,
    ) -> None:
        """One compile-cache miss into the introspection ledger: the
        wall time the first dispatch paid (compile + dispatch for this
        rung), the trace ids that paid for it, and — lazily, only with
        the plane enabled — the lowered program's XLA cost analysis.
        Never raises into the dispatch path."""
        plane = self._introspect
        if plane is None:
            return
        try:
            plane.note_compile(
                family, key, seconds,
                trace_ids=self._trace_ids(list(live)),
                extents=extents, cost_fn=cost_fn,
            )
        except Exception:  # noqa: BLE001 — telemetry must never break a solve
            pass

    def _fresh_cost_thunk(self, fresh: bool, fn, stacked, buckets: int):
        """The lazy XLA cost-analysis thunk for a FRESH batched solve
        dispatch, or None when nothing will consume it (cache hit, or
        the introspection plane detached/disabled)."""
        plane = self._introspect
        if not fresh or plane is None or not plane.enabled:
            return None
        return self._cost_thunk(fn, (stacked,), {"buckets": buckets})

    def _note_fresh_compile(
        self, fresh: bool, family: str, key: tuple, t0: float,
        live: List[_Request], cost_fn=None, extents=None,
    ) -> None:
        """Ledger the compile a FRESH dispatch just paid — the jit call
        returns once tracing + compile are done (execution is what
        stays async), so perf_counter() - t0 IS the compile wall time
        this rung's first dispatch paid. No-op for cache hits and on a
        watchdog-superseded worker."""
        if not fresh or self._stale():
            return
        self._note_compile(
            family, key, _time.perf_counter() - t0, live,
            extents=extents, cost_fn=cost_fn,
        )

    @staticmethod
    def _cost_thunk(fn, args: tuple, static: dict):
        """Zero-arg thunk returning the XLA cost analysis of `fn`
        lowered at `args`' shapes. Shapes are captured EAGERLY as
        ShapeDtypeStructs (donated operands may be deleted by the time
        the thunk runs) and the analysis runs on the LOWERED module —
        jax.stages.Lowered.cost_analysis, the analytical model with no
        second backend compile."""
        import jax

        shapes = jax.tree_util.tree_map(
            lambda leaf: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype),
            args,
        )

        def thunk():
            return fn.lower(*shapes, **static).cost_analysis()

        return thunk

    def _span_cost_args(self, key: tuple) -> dict:
        """{flops, bytes} args for this dispatch's span, {} when the
        introspection plane is off or never attributed the key — the
        off path adds nothing to any span."""
        plane = self._introspect
        if plane is None:
            return {}
        return plane.dispatch_cost_args(key)

    # -- boot-time compile pre-warm ----------------------------------------

    def prewarm(self, families=("solve", "decide")) -> Dict[str, dict]:
        """Compile the SMALLEST bucket rungs of the named kernel
        families before the first real request arrives
        (docs/solver-service.md "Compile pre-warm").

        Why: the hotpath BASELINE shows service_idle_p99_ms 533 ms vs
        p50 30 ms — the tail is first-touch jit compiles, which would
        otherwise eat the entire sub-second budget on a cold plane's
        first EVENT PASS (the latency the event-driven reconcile loop
        exists to remove). The warm-up drives one tiny REAL dispatch per
        family through the normal queue — same bucketing, same compile
        cache, same FSM accounting — so the compiled program is exactly
        the one a small fleet's first touch hits:

          solve  — 1 pod x 1 group, padded up to the floor rung
                   (256 pods x 8 groups), weight operand present (the
                   encoder always carries pod_weight);
          decide — 1 autoscaler x 1 metric, padded to the decision
                   kernel's row bucket (ops/decision.pad_to);
          fused  — the --fused-tick megakernel with every stage
                   present (forecast + decide + cost) at the smallest
                   bucket rung; the runtime adds it to the warm list
                   when the fused tick is enabled.

        A family already warmed this process lifetime is SKIPPED (the
        compile cache hits; reset_caches re-arms). With the persistent
        compile cache (KARPENTER_COMPILE_CACHE) the compile itself is a
        disk read and the per-family prewarm_ms gauge shows it.
        Failures degrade, never block boot: a family whose warm dispatch
        errors is reported and skipped — the ladder serves real traffic
        from numpy exactly as it would have without the warm-up."""
        report: Dict[str, dict] = {}
        for family in families:
            if family in self._prewarmed:
                report[family] = {"skipped": True}
                continue
            misses_before = self.stats.compile_cache_misses
            t0 = _time.perf_counter()
            try:
                self._prewarm_dispatch(family)
            except Exception as error:  # noqa: BLE001 — never block boot
                logger().warning(
                    "compile pre-warm for family %r failed (%s: %s); "
                    "first-touch traffic will compile (or degrade) "
                    "instead", family, type(error).__name__, error,
                )
                report[family] = {
                    "skipped": False, "error": type(error).__name__,
                }
                continue
            elapsed_ms = (_time.perf_counter() - t0) * 1e3
            self._prewarmed.add(family)
            self._c_prewarm.inc(family, "-")
            self._g_prewarm_ms.set(family, "-", elapsed_ms)
            report[family] = {
                "skipped": False,
                "ms": round(elapsed_ms, 3),
            }
            if family in ("solve", "fused"):
                # only families that count compiles in the service's
                # cache counters report the number; decide rides
                # jax.jit's own cache, so claiming fresh_compiles=0
                # there would read as "cache-served" when the ms column
                # IS a first-touch compile — report the counter only
                # where it's real
                report[family]["fresh_compiles"] = (
                    self.stats.compile_cache_misses - misses_before
                )
        return report

    def _prewarm_dispatch(self, family: str) -> None:
        """One tiny real dispatch for `family` (see prewarm)."""
        if family == "solve":
            self.solve(_prewarm_solve_inputs())
            return
        if family == "decide":
            self.decide(_prewarm_decide_inputs())
            return
        if family == "fused":
            # the full-presence fused program (forecast + decide + cost
            # all engaged) at the smallest bucket rung — the program a
            # small fleet's first --fused-tick reconcile hits
            self.fused_tick(_prewarm_fused_inputs())
            return
        raise ValueError(f"unknown pre-warm family {family!r}")

    def stage_percentiles(self) -> Dict[str, Dict[str, float]]:
        """{stage: {"p50_ms", "p99_ms", "n"}} over the retained latency
        rings — the per-stage breakdown bench.py --hotpath publishes."""
        with self._stage_lock:
            snapshot = {k: list(v) for k, v in self._stages.items()}
        return {
            stage: {
                "p50_ms": round(float(np.percentile(samples, 50)), 4),
                "p99_ms": round(float(np.percentile(samples, 99)), 4),
                "n": len(samples),
            }
            for stage, samples in snapshot.items()
            if samples
        }

    # -- submission -------------------------------------------------------

    def _resolve_backend(self, backend: Optional[str]) -> str:
        backend = backend or self.backend
        if self.device_solver is not None:
            return backend  # the override owns backend semantics
        if backend == "auto":
            import jax

            if jax.default_backend() == "tpu":
                return "pallas"
            if jax.default_backend() == "cpu":
                return "numpy"
            return "xla"
        return backend

    def _shard_mesh(self):
        """The lazily-built dispatch mesh (parallel/mesh.py), or None
        when sharding is unavailable: disabled (shard_threshold <= 0),
        fewer than 2 devices and no explicit shape, or mesh construction
        failed (logged once; the single-device path serves)."""
        if self._mesh_ready:
            return self._mesh
        with self._mesh_lock:
            if self._mesh_ready:
                return self._mesh
            mesh = None
            try:
                if self.shard_threshold > 0:
                    import jax

                    from karpenter_tpu.parallel.mesh import build_mesh

                    devices = jax.devices()
                    n = len(devices)
                    if self.shard_devices is not None:
                        n = min(n, self.shard_devices)
                    shape = self.shard_mesh_shape
                    if shape is not None and shape[0] * shape[1] >= 2:
                        mesh = build_mesh(
                            devices=devices[:n], shape=shape
                        )
                    elif shape is None and n >= 2:
                        # a 1-device "mesh" (explicit 1x1 included)
                        # would route traffic through the inline
                        # sharded path with zero parallelism gain while
                        # reporting sharding active — below 2 devices
                        # the single-device program IS the right path
                        mesh = build_mesh(n_devices=n, devices=devices)
            except Exception as error:  # noqa: BLE001 — optional fast path
                logger().warning(
                    "sharded dispatch unavailable (%s: %s); staying "
                    "single-device",
                    type(error).__name__, error,
                )
            self._mesh = mesh
            self._mesh_ready = True
            return mesh

    def _shard_extents(self, resolved: str, n_pods: int, n_groups: int):
        """Route one request: (effective backend, mesh extents | None).

        A request whose pods x groups cell count reaches shard_threshold
        rides the mesh — including pallas-resolved traffic: the fused
        Mosaic kernel has no multi-chip entry, and above the threshold
        using every chip through the GSPMD-partitioned XLA program beats
        one chip's fused kernel. Below threshold (or with sharding
        unavailable/tripped, or under a device_solver override where
        device math lives out of process) nothing changes."""
        if (
            self.shard_threshold <= 0
            or self._shard_broken
            or self.device_solver is not None
            or resolved not in ("xla", "pallas")
            or n_pods * n_groups < self.shard_threshold
        ):
            return resolved, None
        mesh = self._shard_mesh()
        if mesh is None:
            return resolved, None
        from karpenter_tpu.parallel.mesh import mesh_extents

        return "xla", mesh_extents(mesh)

    def submit(
        self,
        inputs: BinPackInputs,
        buckets: int = DEFAULT_BUCKETS,
        backend: Optional[str] = None,
        timeout: Optional[float] = None,
        tenant: Optional[str] = None,
    ) -> SolveFuture:
        """Enqueue one solve; raises SolverSaturated when the bounded
        queue is full (solve() turns that into the numpy fallback).
        `tenant` stamps the request's trace span (the multi-tenant
        scheduler passes it, so /debug/traces?tenant= finds the
        request — docs/multitenancy.md)."""
        if self._closed:
            raise RuntimeError("solver service is closed")
        n_pods = inputs.pod_requests.shape[0]
        n_groups = inputs.group_allocatable.shape[0]
        resolved = self._resolve_backend(backend)
        if resolved == "pallas" and has_constraint_operands(inputs):
            # the Mosaic kernel has no constraint entry; route to the
            # XLA family (exact, still on-device) and COUNT it — the
            # PR 8 silent-operand-drop bug class, closed at this third
            # dispatch site
            resolved = "xla"
            self.stats.constraint_reroutes += 1
        resolved, extents = self._shard_extents(resolved, n_pods, n_groups)
        key = (
            bucket_shape(inputs), buckets, resolved, presence(inputs),
            constraint_shape(inputs),
        )
        if extents is not None:
            key += ("shard", extents)
            self.stats.shard_requests += 1
        timeout = self.default_timeout_s if timeout is None else timeout
        now = self._clock()
        request = _Request(
            inputs=inputs,
            buckets=buckets,
            backend=resolved,
            key=key,
            n_pods=n_pods,
            n_groups=n_groups,
            deadline=(now + timeout) if timeout else None,
            enqueued_at=now,
            tenant=tenant,
        )
        self._enqueue_one(request)
        return SolveFuture(request, self)

    def _begin_request_span(self, request: _Request) -> None:
        """Open the request's reconcile-trace span (parented to the
        submitter's current span, so a tick-minted trace id follows the
        request across the worker-thread boundary). No-op — request.span
        stays None — when tracing is disabled."""
        family = (
            request.key[0] if isinstance(request.key[0], str) else "binpack"
        )
        request.span = default_tracer().begin(
            "solver.request", family=family, backend=request.backend,
            tenant=request.tenant,
        )

    def _record_rejected_span(self, key, backend: str) -> None:
        """Open-and-close a rejected request span for an overflow slot
        that never became a _Request (the coalesced batch path) — a
        trace export taken during saturation must show the rejected
        fleet-batch candidates, not just rejected singletons."""
        family = key[0] if isinstance(key[0], str) else "binpack"
        span = default_tracer().begin(
            "solver.request", family=family, backend=backend,
        )
        if span is not None:
            span.close(ok=False, rejected=True)

    def _enqueue_one(self, request: _Request) -> None:
        """Admit one request to the bounded queue (raises
        SolverSaturated when full) and wake the worker."""
        self._begin_request_span(request)
        with self._cond:
            if len(self._queue) >= self.max_queue:
                self.stats.rejected += 1
                self._c_rejected.inc("-", "-")
                if request.span is not None:
                    request.span.close(ok=False, rejected=True)
                raise SolverSaturated(
                    f"solver queue full ({self.max_queue})"
                )
            self._ensure_worker()
            self._queue.append(request)
            self.stats.requests += 1
            self._c_requests.inc("-", "-")
            self._g_queue.set("-", "-", float(len(self._queue)))
            self._cond.notify_all()

    def solve(
        self,
        inputs: BinPackInputs,
        buckets: int = DEFAULT_BUCKETS,
        backend: Optional[str] = None,
        timeout: Optional[float] = None,
    ):
        """Synchronous solve through the service — the drop-in `solver`
        seam every caller already takes (any (inputs, buckets=...) ->
        BinPackOutputs callable). Saturation and (by default) deadline
        expiry degrade to the numpy backend inline, so a caller always
        gets an answer while the device path is sick."""
        timeout = self.default_timeout_s if timeout is None else timeout
        try:
            future = self.submit(
                inputs, buckets=buckets, backend=backend, timeout=timeout
            )
        except SolverSaturated:
            logger().warning(
                "solver queue saturated; degrading one request to numpy"
            )
            return self._numpy_fallback(inputs, buckets)
        try:
            return future.result(timeout if timeout else None)
        except SolverTimeout:
            if self.on_timeout == "raise":
                raise
            logger().warning(
                "solve deadline expired; degrading one request to numpy"
            )
            return self._numpy_fallback(inputs, buckets)

    def consolidate(
        self,
        inputs_list,
        buckets: int = DEFAULT_BUCKETS,
        backend: Optional[str] = None,
        timeout: Optional[float] = None,
    ) -> list:
        """Batched candidate evaluation for the consolidation planner:
        N masked bin-packs (one per drain candidate) submitted ATOMICALLY
        and answered as a list in input order.

        The requests ride the normal pipeline — same coalescing queue,
        shape ladder, compile cache, numpy fallback — but carry a shared
        batch marker, so the worker drains the whole set past `max_batch`
        and same-bucket candidates ride ONE device dispatch (lax.map over
        the stacked candidates). Candidate-count jitter only moves along
        the batch ladder, so steady-state consolidation never recompiles.

        Degradations match solve(): a full queue answers the overflow
        candidates from numpy inline; a deadline expiry degrades (or
        raises, per `on_timeout`) per candidate."""
        if not inputs_list:
            return []
        if self._closed:
            raise RuntimeError("solver service is closed")
        self.stats.consolidate_calls += 1
        self.stats.consolidate_candidates += len(inputs_list)
        resolved = self._resolve_backend(backend)
        timeout = self.default_timeout_s if timeout is None else timeout
        requests = self._enqueue_batch(
            inputs_list, buckets, resolved, timeout
        )
        return self._gather_batch(requests, inputs_list, buckets, timeout)

    def _gather_batch(
        self, requests, inputs_list, buckets: int, timeout
    ) -> list:
        """Wait out a consolidate() batch in input order; overflow slots
        (None) and expired candidates degrade to numpy per solve()'s
        semantics."""
        results = []
        for i, request in enumerate(requests):
            if request is None:
                logger().warning(
                    "solver queue saturated; degrading one consolidation "
                    "candidate to numpy"
                )
                results.append(
                    self._numpy_fallback(inputs_list[i], buckets)
                )
                continue
            try:
                results.append(
                    SolveFuture(request, self).result(
                        timeout if timeout else None
                    )
                )
            except SolverTimeout:
                if self.on_timeout == "raise":
                    # nobody will wait on the rest of the batch: flag
                    # them abandoned so the worker skips them instead of
                    # burning a full dispatch for dropped results
                    for rest in requests[i + 1:]:
                        if rest is not None:
                            rest.abandoned = True
                    raise
                logger().warning(
                    "consolidation candidate deadline expired; degrading "
                    "to numpy"
                )
                results.append(
                    self._numpy_fallback(inputs_list[i], buckets)
                )
        return results

    def _consolidate_key(self, inputs, buckets: int, resolved: str):
        """(key, effective backend) for one consolidate() candidate.

        5th key element: consolidation batches vectorize across
        candidates (jax.vmap) instead of scanning (lax.map) —
        cluster-scale operands make the C× memory amplification
        trivial, and vectorization is where the batched >> sequential
        throughput comes from. The distinct key keeps these groups from
        mixing with plain solve() traffic compiled for the
        memory-bounded scan. Fleet-scale candidate evaluations
        additionally ride the mesh ("vmap_shard" + extents — the
        sharded dispatch strategy, same ladder as solve())."""
        if resolved == "pallas" and has_constraint_operands(inputs):
            # same reroute as submit(): Mosaic has no constraint entry
            resolved = "xla"
            self.stats.constraint_reroutes += 1
        backend_eff, extents = self._shard_extents(
            resolved,
            inputs.pod_requests.shape[0],
            inputs.group_allocatable.shape[0],
        )
        if extents is None:
            return (
                bucket_shape(inputs), buckets, backend_eff,
                presence(inputs), constraint_shape(inputs), "vmap",
            ), backend_eff
        self.stats.shard_requests += 1
        return (
            bucket_shape(inputs), buckets, backend_eff,
            presence(inputs), constraint_shape(inputs), "vmap_shard",
            extents,
        ), backend_eff

    def _enqueue_batch(
        self, inputs_list, buckets: int, resolved: str, timeout
    ) -> List[Optional[_Request]]:
        """Enqueue a consolidate() batch atomically under one lock hold
        (contiguous in the deque, shared coalesce_id). Overflow slots
        come back as None, in order, for inline numpy degradation.

        Routing (keys + shard extents) resolves BEFORE the lock: the
        first fleet-scale batch lazily initializes the backend and
        builds the mesh, and doing that under self._cond would stall
        every submitter, the worker, and the watchdog."""
        now = self._clock()
        keyed = [
            self._consolidate_key(inputs, buckets, resolved)
            for inputs in inputs_list
        ]
        requests: List[Optional[_Request]] = []
        with self._cond:
            self._coalesce_seq += 1
            cid = self._coalesce_seq
            for inputs, (key, backend_eff) in zip(inputs_list, keyed):
                if len(self._queue) >= self.max_queue:
                    self.stats.rejected += 1
                    self._c_rejected.inc("-", "-")
                    self._record_rejected_span(key, backend_eff)
                    requests.append(None)
                    continue
                request = _Request(
                    inputs=inputs,
                    buckets=buckets,
                    backend=backend_eff,
                    key=key,
                    n_pods=inputs.pod_requests.shape[0],
                    n_groups=inputs.group_allocatable.shape[0],
                    deadline=(now + timeout) if timeout else None,
                    enqueued_at=now,
                    coalesce_id=cid,
                )
                self._begin_request_span(request)
                self._queue.append(request)
                self.stats.requests += 1
                self._c_requests.inc("-", "-")
                requests.append(request)
            self._ensure_worker()
            self._g_queue.set("-", "-", float(len(self._queue)))
            self._cond.notify_all()
        return requests

    def forecast(self, inputs, backend: Optional[str] = None,
                 timeout: Optional[float] = None):
        """Batched metric forecasting through the service
        (forecast/models.py, docs/forecasting.md): one ForecastInputs
        matrix of S series in, one ForecastOutputs out.

        Requests ride the SAME coalescing queue as bin-packs: concurrent
        forecast() callers whose histories share a time-axis bucket are
        concatenated along the series axis and answered by ONE device
        dispatch through the shared compile cache (shape-bucketed on
        (series, history) — steady fleets never recompile). Degradations
        match solve(): a full queue or expired deadline answers from the
        bit-identical numpy mirror inline, a device failure falls back
        per batch, and the backend-health FSM short-circuits a sick
        device wholesale. `forecast.predict` is the fault-injection
        point on the device path (docs/resilience.md)."""
        n_series = int(np.asarray(inputs.values).shape[0])
        self.stats.forecast_calls += 1
        self.stats.forecast_series += n_series
        if n_series == 0:
            from karpenter_tpu.forecast.models import ForecastOutputs

            empty = np.zeros(0, np.float32)
            return ForecastOutputs(
                point=empty, sigma2=empty.copy(),
                n_valid=np.zeros(0, np.int32),
            )
        if self._closed:
            raise RuntimeError("solver service is closed")
        timeout = self.default_timeout_s if timeout is None else timeout
        request = self._forecast_request(
            inputs, n_series, backend, timeout
        )
        try:
            self._enqueue_one(request)
        except SolverSaturated:
            logger().warning(
                "solver queue saturated; degrading one forecast to numpy"
            )
            return self._numpy_fallback(request.inputs, 0)
        try:
            return SolveFuture(request, self).result(
                timeout if timeout else None
            )
        except SolverTimeout:
            if self.on_timeout == "raise":
                raise
            logger().warning(
                "forecast deadline expired; degrading to numpy"
            )
            return self._numpy_fallback(request.inputs, 0)

    def _forecast_request(
        self, inputs, n_series: int, backend: Optional[str], timeout
    ) -> _Request:
        """Resolve the backend and build one queue-ready forecast
        request, padded up the history-length ladder."""
        from karpenter_tpu.forecast.models import pad_forecast_inputs

        resolved = self._resolve_backend(backend)
        if self.device_solver is not None:
            # the sidecar wire carries bin-packs only: under the gRPC
            # process split the control plane must not run device math,
            # so forecasts serve from the numpy mirror
            resolved = "numpy"
        elif resolved == "pallas":
            resolved = "xla"  # no Mosaic forecast kernel; XLA runs on TPU
        now = self._clock()
        t_bucket = bucket_up(
            int(np.asarray(inputs.values).shape[1]), FORECAST_T_FLOOR
        )
        # fleet-scale forecasts shard their SERIES axis over the mesh
        # rows (cells = series x history slots, same threshold as
        # bin-packs); below threshold the key is unchanged
        resolved, extents = self._shard_extents(
            resolved, n_series, t_bucket
        )
        key = ("forecast", t_bucket, resolved)
        if extents is not None:
            key += ("shard", extents)
            self.stats.shard_requests += 1
        return _Request(
            inputs=pad_forecast_inputs(inputs, t_bucket),
            buckets=0,
            backend=resolved,
            key=key,
            n_pods=n_series,
            n_groups=0,
            deadline=(now + timeout) if timeout else None,
            enqueued_at=now,
        )

    def preempt(self, inputs, backend: Optional[str] = None,
                timeout: Optional[float] = None):
        """Fleet-wide placement-with-eviction through the service
        (ops/preempt.py, docs/preemption.md): one PreemptInputs problem
        — C candidate pods x N node columns x V victims — in, one
        PreemptOutputs out, ONE device dispatch planning every
        candidate. Requests ride the same coalescing queue, shape-
        bucketed compile cache (preempt_bucket_shape ladder), numpy-
        fallback ladder, and backend-health FSM as bin-packs; the numpy
        mirror is BIT-IDENTICAL to the device kernel (integer-capacity
        arithmetic — ops/preempt.py docstring), so a degraded answer is
        the same answer. `preempt.plan` is the fault-injection point on
        the device path (docs/resilience.md)."""
        from karpenter_tpu.ops.preempt import MAX_VICTIMS, PreemptOutputs

        n_candidates = int(np.asarray(inputs.pod_requests).shape[0])
        n_victims = int(np.asarray(inputs.victim_requests).shape[0])
        self.stats.preempt_calls += 1
        self.stats.preempt_candidates += n_candidates
        if n_candidates == 0:
            return PreemptOutputs(
                chosen_node=np.zeros(0, np.int32),
                evict_count=np.zeros(0, np.int32),
                evict_mask=np.zeros((0, n_victims), bool),
                unplaceable=np.int32(0),
            )
        if n_victims > MAX_VICTIMS:
            raise ValueError(
                f"preempt solve supports at most {MAX_VICTIMS} victims, "
                f"got {n_victims}"
            )
        if self._closed:
            raise RuntimeError("solver service is closed")
        timeout = self.default_timeout_s if timeout is None else timeout
        request = self._preempt_request(
            inputs, n_candidates, n_victims, backend, timeout
        )
        try:
            self._enqueue_one(request)
        except SolverSaturated:
            logger().warning(
                "solver queue saturated; degrading one eviction plan "
                "to numpy"
            )
            return self._numpy_fallback(inputs, 0)
        try:
            return SolveFuture(request, self).result(
                timeout if timeout else None
            )
        except SolverTimeout:
            if self.on_timeout == "raise":
                raise
            logger().warning(
                "eviction-plan deadline expired; degrading to numpy"
            )
            return self._numpy_fallback(inputs, 0)

    def _preempt_request(
        self, inputs, n_candidates: int, n_victims: int,
        backend: Optional[str], timeout,
    ) -> _Request:
        """Resolve the backend and build one queue-ready eviction-plan
        request (keyed on the preempt shape ladder)."""
        resolved = self._resolve_backend(backend)
        if self.device_solver is not None:
            # the sidecar wire carries bin-packs only: under the gRPC
            # process split eviction plans serve from the numpy mirror
            resolved = "numpy"
        elif resolved == "pallas":
            resolved = "xla"  # no Mosaic preempt kernel; XLA runs on TPU
        now = self._clock()
        # fleet-scale eviction storms shard their CANDIDATE axis over
        # the mesh rows (cells = candidates x victims — the dominant
        # [C, V] evictability/prefix matrices — same threshold as
        # bin-packs); below threshold the key is unchanged
        resolved, extents = self._shard_extents(
            resolved, n_candidates, max(n_victims, 1)
        )
        key = ("preempt", preempt_bucket_shape(inputs), resolved)
        if extents is not None:
            key += ("shard", extents)
            self.stats.shard_requests += 1
        return _Request(
            inputs=inputs,
            buckets=0,
            backend=resolved,
            key=key,
            n_pods=n_candidates,
            n_groups=n_victims,
            deadline=(now + timeout) if timeout else None,
            enqueued_at=now,
        )

    def cost(self, inputs, backend: Optional[str] = None):
        """The multi-objective cost/SLO refinement through the service
        (ops/cost.py, docs/cost.md): one CostInputs matrix for the whole
        fleet in, one CostOutputs out, ONE device dispatch — synchronous
        like decide() (the BatchAutoscaler already batches the fleet).
        Shapes ride the decision kernel's pad_to bucket, so steady
        fleets never recompile (the module-level jit IS the cache).

        Degradation posture (deliberately different from forecast):
        the refinement is ADVISORY — on any failure the right answer is
        the UNREFINED base decision (the caller's never-block contract,
        CostEngine.adjust), not a host re-score every tick through an
        outage. So: the numpy mirror serves as the REQUESTED backend
        (CPU auto-resolution, the gRPC process split — bit-identical,
        tests/test_cost.py), device failures count toward the shared
        backend-health FSM and PROPAGATE (the tick goes cost-blind),
        and a DEGRADED FSM short-circuits with CostUnavailable instead
        of attempting the sick device — probes ride the normal recovery
        path. `cost.score` is the fault-injection point
        (faults/registry.py, docs/resilience.md)."""
        from karpenter_tpu.ops import cost as CK

        self.stats.cost_calls += 1
        resolved = self._resolve_backend(backend)
        if self.device_solver is not None:
            # the sidecar wire carries bin-packs only: under the gRPC
            # process split cost refinement serves from the numpy mirror
            resolved = "numpy"
        elif resolved == "pallas":
            resolved = "xla"  # no Mosaic cost kernel; XLA runs on TPU
        t0 = _time.perf_counter()
        try:
            if resolved == "numpy":
                # the REQUESTED backend, not a degradation: the
                # bit-identical mirror, no fallback counting
                with default_tracer().span("solver.cost", backend="numpy"):
                    out = CK.cost_numpy(inputs)
                self._annotate_provenance("numpy", "numpy")
                return out
            if not self._device_allowed():
                raise CostUnavailable(
                    "solver backend degraded; scaling cost-blind until "
                    "a probe recovers the device path"
                )
            import jax

            try:
                with default_tracer().span("solver.cost", backend=resolved):
                    with solver_trace("solver.cost"):
                        # the cost-path fault-injection point: an error
                        # plan exercises the cost-blind degradation +
                        # FSM trip (docs/resilience.md)
                        inject("cost.score")
                        out = CK.cost_jit(inputs)
                        jax.block_until_ready(out)
            except Exception:
                self._record_device_failure()
                raise
            self._record_device_success()
            self.stats.cost_dispatches += 1
            self._count_dispatch()
            self._annotate_provenance(resolved, "device")
            return CK.CostOutputs(
                desired=np.asarray(out.desired),
                expected_hourly=np.asarray(out.expected_hourly),
                violation_risk=np.asarray(out.violation_risk),
                headroom=np.asarray(out.headroom),
                cost_limited=np.asarray(out.cost_limited),
                slo_raised=np.asarray(out.slo_raised),
            )
        except Exception:
            self.stats.cost_errors += 1
            raise
        finally:
            self._record_stage("cost", _time.perf_counter() - t0)

    def poolgroup(self, inputs, backend: Optional[str] = None):
        """The joint pool-group allocation through the service
        (ops/poolgroup.py, docs/poolgroups.md): every PoolGroup's joint
        candidate ladder scored in ONE batched dispatch — the grouped
        HAs' replacement for N independent cost dispatches.

        Degradation is the never-block ladder and it is SEMANTIC, not
        just a backend swap: device joint kernel → INDEPENDENT per-pool
        ladders (the numpy mirror with joint selection disabled — each
        pool still refines exactly as the cost family would, but ratios
        and the shared budget go advisory for the tick) → the caller's
        own never-block contract. A numpy-resolved backend serves the
        full JOINT mirror (bit-identical, the REQUESTED backend, like
        cost()). Device failures feed the shared backend-health FSM; a
        DEGRADED FSM short-circuits straight to the independent rung so
        probes ride the normal recovery path. `poolgroup.solve` is the
        fault-injection point (faults/registry.py)."""
        from karpenter_tpu.ops import poolgroup as PGK

        self.stats.poolgroup_calls += 1
        resolved = self._resolve_backend(backend)
        if self.device_solver is not None:
            resolved = "numpy"  # the gRPC wire carries bin-packs only
        elif resolved == "pallas":
            resolved = "xla"  # no Mosaic poolgroup kernel; XLA runs on TPU
        t0 = _time.perf_counter()
        try:
            if resolved == "numpy":
                # the REQUESTED backend, not a degradation: the
                # bit-identical joint mirror, constraints fully enforced
                with default_tracer().span(
                    "solver.poolgroup", backend="numpy"
                ):
                    out = PGK.poolgroup_numpy(inputs)
                self._annotate_provenance("numpy", "numpy")
                return out
            if self._device_allowed():
                try:
                    import jax

                    with default_tracer().span(
                        "solver.poolgroup", backend=resolved
                    ):
                        with solver_trace("solver.poolgroup"):
                            # the joint-path fault-injection point: an
                            # error plan exercises the independent-
                            # ladder degradation + FSM trip
                            inject("poolgroup.solve")
                            out = PGK.poolgroup_jit(inputs)
                            jax.block_until_ready(out)
                    self._record_device_success()
                    self.stats.poolgroup_dispatches += 1
                    self._count_dispatch()
                    self._annotate_provenance(resolved, "device")
                    return jax.tree_util.tree_map(np.asarray, out)
                except Exception as error:  # noqa: BLE001 — never-block
                    self._record_device_failure()
                    logger().warning(
                        "joint poolgroup dispatch failed (%s: %s); "
                        "serving INDEPENDENT per-pool ladders this tick "
                        "(ratios advisory)",
                        type(error).__name__, error,
                    )
            with default_tracer().span(
                "solver.poolgroup", backend="independent"
            ):
                self.stats.poolgroup_independent_serves += 1
                out = PGK.poolgroup_numpy(inputs, enforce=False)
            self._annotate_provenance("numpy", "numpy")
            return out
        except Exception:
            self.stats.poolgroup_errors += 1
            raise
        finally:
            self._record_stage("poolgroup", _time.perf_counter() - t0)

    def sim_step(self, inputs, backend: Optional[str] = None):
        """One simulated-cluster tick through the service (ops/simstep.py,
        docs/simulator.md): elementwise over any leading batch shape, so
        a BatchedSimEnv's N clusters advance as ONE dispatch."""
        from karpenter_tpu.ops import simstep as SK

        return self._sim_dispatch(
            "solver.sim_step", SK.sim_step_jit, SK.sim_step_numpy, inputs,
            backend,
        )

    def sim_rollout(self, inputs, backend: Optional[str] = None):
        """A whole simulated episode (in-kernel tuned policy) through
        the service: batched trails ride the vmapped program — N
        clusters x T ticks in one device dispatch (docs/simulator.md)."""
        from karpenter_tpu.ops import simstep as SK

        batched = np.asarray(inputs.replicas0).ndim > 1
        return self._sim_dispatch(
            "solver.sim_rollout",
            SK.sim_rollout_vmapped if batched else SK.sim_rollout_jit,
            SK.sim_rollout_numpy, inputs, backend,
        )

    def _sim_dispatch(self, span, device_fn, numpy_fn, inputs, backend):
        """The simlab family's one door: tracing + stats + backend
        resolution like cost(), but a NEVER-BLOCK degradation posture —
        the numpy mirror is bit-identical (tests/test_simlab.py), so a
        device failure serves the mirror instead of raising; failures
        still feed the shared backend-health FSM. `simlab.step` is the
        fault-injection point (faults/registry.py)."""
        self.stats.sim_calls += 1
        resolved = self._resolve_backend(backend)
        if self.device_solver is not None:
            resolved = "numpy"  # the gRPC wire carries bin-packs only
        elif resolved == "pallas":
            resolved = "xla"  # no Mosaic sim kernel; XLA runs on TPU
        t0 = _time.perf_counter()
        try:
            if resolved != "numpy" and self._device_allowed():
                try:
                    import jax

                    with default_tracer().span(span, backend=resolved):
                        with solver_trace(span):
                            inject("simlab.step")
                            out = device_fn(inputs)
                            jax.block_until_ready(out)
                    self._record_device_success()
                    self.stats.sim_dispatches += 1
                    self._count_dispatch()
                    return jax.tree_util.tree_map(np.asarray, out)
                except Exception as error:  # noqa: BLE001 — never-block
                    self._record_device_failure()
                    logger().warning(
                        "sim device dispatch failed (%s: %s); serving "
                        "the bit-identical numpy mirror",
                        type(error).__name__, error,
                    )
            with default_tracer().span(span, backend="numpy"):
                self.stats.sim_mirror_serves += 1
                return numpy_fn(inputs)
        finally:
            self._record_stage("sim", _time.perf_counter() - t0)

    def fused_tick(self, inputs, backend: Optional[str] = None):
        """The fused steady-state tick through the service
        (ops/fusedtick.py, docs/solver-service.md "Fused tick"):
        forecast → decide → cost as ONE compiled program, zero host
        round-trips between stages — the whole fleet's reconcile math
        in a single dispatch.

        Degradation posture is the never-block ladder: a fused-program
        failure falls back to the CHAINED per-stage path (the exact
        pre-fusion wire, bit-identical outputs), a chained failure
        serves the numpy floor — the tick always completes. Fused
        failures feed the shared backend-health FSM; the chained rung
        is a degraded serve and leaves the FSM counting, so a
        persistently faulting fused program still trips wholesale to
        numpy and probes recovery like every other family. Fleets whose
        N x M cells reach shard_threshold take the chained rung by
        design: its decide stage rides the mesh-sharded program (the
        megakernel has no multi-chip entry). `fused.tick` is the
        fault-injection point (faults/registry.py)."""
        from karpenter_tpu.ops import fusedtick as FT

        self.stats.fused_calls += 1
        resolved = self._resolve_backend(backend)
        if self.device_solver is not None:
            resolved = "numpy"  # the gRPC wire carries bin-packs only
        elif resolved == "pallas":
            resolved = "xla"  # no Mosaic fused kernel; XLA runs on TPU
        # pad the forecast group up the forecast family's shape ladders
        # ONCE at the door — every rung (fused, chained, numpy) consumes
        # the SAME padded operands, so the ladder can switch rungs
        # mid-tick bit for bit and compile keys bucket like the
        # standalone forecast family's
        t_bucket = s_bucket = n_series = 0
        if inputs.forecast is not None:
            import dataclasses

            from karpenter_tpu.forecast.models import pad_forecast_inputs

            shape = np.asarray(inputs.forecast.values).shape
            n_series = int(shape[0])
            t_bucket = bucket_up(int(shape[1]), FORECAST_T_FLOOR)
            s_bucket = bucket_up(n_series, FORECAST_S_FLOOR)
            inputs = dataclasses.replace(
                inputs,
                forecast=pad_forecast_inputs(inputs.forecast, t_bucket),
            )
            inputs = FT.pad_series(inputs, s_bucket)
        n = int(np.asarray(inputs.decision.spec_replicas).shape[0])
        m = int(np.asarray(inputs.decision.metric_value).shape[1])
        t0 = _time.perf_counter()
        try:
            if resolved != "numpy" and self._device_allowed():
                out = self._fused_device(
                    inputs, resolved, n, m, t_bucket, s_bucket, t0
                )
                if out is not None:
                    return self._fused_slice(out, n_series)
            with default_tracer().span(
                "solver.fused_tick", backend="numpy"
            ):
                out = FT.fused_tick_numpy(inputs)
            if resolved != "numpy":
                self.stats.fused_mirror_serves += 1
            self._annotate_provenance("numpy", "numpy")
            return self._fused_slice(out, n_series)
        finally:
            self._record_stage("fused", _time.perf_counter() - t0)

    def _fused_device(
        self, inputs, resolved: str, n: int, m: int,
        t_bucket: int, s_bucket: int, t0: float,
    ):
        """The fused + chained device rungs of fused_tick's ladder;
        None = both failed (the caller serves the numpy floor)."""
        import jax

        from karpenter_tpu.ops import fusedtick as FT

        _, extents = self._shard_extents("xla", n, max(m, 1))
        if extents is None:
            key = (
                "fused", n, m, t_bucket, s_bucket,
                inputs.forecast is not None,
                inputs.slo_valid is not None,
                inputs.poolgroup is not None,
                resolved,
            )
            try:
                fresh = self._count_fused_compile(key)
                cost_fn = None
                plane = self._introspect
                if fresh and plane is not None and plane.enabled:
                    cost_fn = self._cost_thunk(
                        FT.fused_tick_jit, (inputs,), {}
                    )
                with default_tracer().span(
                    "solver.fused_tick", backend=resolved,
                    **self._span_cost_args(key),
                ):
                    with solver_trace("solver.fused_tick"):
                        # the fused-path fault-injection point: an
                        # error plan exercises the fused → chained →
                        # numpy ladder + FSM trip (docs/resilience.md)
                        inject("fused.tick")
                        out = FT.fused_tick_jit(inputs)
                        jax.block_until_ready(out)
                self._note_fresh_compile(
                    fresh, "fused", key, t0, [], cost_fn=cost_fn,
                )
                self._record_device_success()
                self.stats.fused_dispatches += 1
                self._count_dispatch()
                self._annotate_provenance(resolved, "device")
                return jax.tree_util.tree_map(np.asarray, out)
            except Exception as error:  # noqa: BLE001 — never-block
                self._record_device_failure()
                logger().warning(
                    "fused tick dispatch failed (%s: %s); falling back "
                    "to the chained per-stage path",
                    type(error).__name__, error,
                )
        try:
            with default_tracer().span(
                "solver.fused_tick", backend="chained"
            ):
                with solver_trace("solver.fused_tick.chained"):
                    out = FT.fused_tick_chained(inputs)
            # a degraded serve: stage dispatches are counted (the
            # dispatches-per-tick gauge must show the real program
            # count) but the FSM keeps counting fused failures — a
            # persistently faulting megakernel must still trip
            self.stats.fused_chained_serves += 1
            for _ in range(FT.programs(inputs)):
                self._count_dispatch()
            self._annotate_provenance("xla", "device")
            return out
        except Exception as error:  # noqa: BLE001 — never-block
            self._record_device_failure()
            logger().warning(
                "chained fused-tick fallback failed (%s: %s); serving "
                "the bit-identical numpy floor",
                type(error).__name__, error,
            )
            return None

    @staticmethod
    def _fused_slice(out, n_series: int):
        """Slice the forecast outputs back to the caller's S (padding
        series are service-internal, exactly like the queue family)."""
        if out.forecast is None:
            return out
        import dataclasses

        from karpenter_tpu.forecast.models import slice_forecast_outputs

        return dataclasses.replace(
            out,
            forecast=slice_forecast_outputs(out.forecast, 0, n_series),
        )

    def _annotate_provenance(self, backend: str, rung: str) -> None:
        """Provenance slice (observability/provenance.py): stamp the
        backend + degradation rung that actually served onto the
        CURRENT ledger batch — only for batches whose owner opted into
        service-side stamping (autosolver: the BatchAutoscaler flow;
        the MultiTenantScheduler stamps rungs per tenant slice itself).
        One attribute read when the ledger is off."""
        from karpenter_tpu.observability import default_ledger

        ledger = default_ledger()
        if not ledger.enabled:
            return
        batch = ledger.current()
        if batch is not None and batch.autosolver:
            batch.annotate(solver_backend=backend, solver_rung=rung)

    def decide(self, inputs):
        """The HPA decision kernel through the service: same metrics
        surface and error accounting, no coalescing (the batch
        autoscaler already evaluates the whole fleet in one call). A
        fleet whose N x M cell count reaches shard_threshold rides the
        mesh — the decision fleet axis shards over the mesh rows
        (parallel/mesh.decision_shardings), with a single-device retry
        on any mesh failure (the same ladder posture as bin-packs)."""
        self.stats.decide_calls += 1
        t0 = _time.perf_counter()
        try:
            with default_tracer().span("solver.decide"):
                with solver_trace("solver.decide"):
                    out = self._decide_dispatch(inputs)
            # the decide kernel has no numpy mirror: it is served by
            # the in-process jitted program ("device": XLA on whatever
            # backend jax resolved) or across the gRPC split
            self._annotate_provenance(
                "grpc" if self.device_solver is not None else "xla",
                "sidecar" if self.device_solver is not None
                else "device",
            )
            return out
        except Exception:
            self.stats.decide_errors += 1
            raise
        finally:
            self._record_stage("decide", _time.perf_counter() - t0)

    def _decide_fn(self):
        if self._decider is None:
            from karpenter_tpu.ops.decision import decide_jit

            self._decider = decide_jit
        return self._decider

    def _decide_dispatch(self, inputs):
        """Route one fleet decide: the sharded program above threshold
        (in-process default kernel only — an injected decider or the
        gRPC split owns its own device semantics), the single-device
        jit otherwise. A mesh failure retries single-device inline and
        trips the shard route, exactly like the bin-pack ladder —
        decide stays the never-block kernel either way."""
        fn = self._decide_fn()
        if self._decider_injected:
            return fn(inputs)
        # the SAME routing guards every queue family takes
        # (_shard_extents: threshold, shard-broken trip, device_solver,
        # mesh availability) — decide's cells are fleet x metric columns
        n = int(inputs.spec_replicas.shape[0])
        m = int(inputs.metric_value.shape[1])
        _, extents = self._shard_extents("xla", n, max(m, 1))
        if extents is None:
            return fn(inputs)
        mesh = self._shard_mesh()
        from karpenter_tpu.parallel.mesh import sharded_decide

        self.stats.shard_requests += 1
        try:
            out = sharded_decide(mesh, inputs)
            self.stats.shard_dispatches += 1
            return out
        except Exception as error:  # noqa: BLE001 — shard-rung failure
            self.stats.shard_fallbacks += 1
            self._shard_broken = True
            logger().warning(
                "sharded decide failed (%s: %s); retrying single-device "
                "and disabling the shard route",
                type(error).__name__, error,
            )
            default_flight_recorder().record(
                "shard_fallback",
                subsystem="solver",
                error=type(error).__name__,
                family="decide",
            )
            return fn(inputs)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        worker = self._worker
        if worker is not None:
            worker.join(timeout=5.0)
            self._worker = None
        watchdog = self._watchdog
        if watchdog is not None:
            watchdog.join(timeout=2.0)
            self._watchdog = None

    # -- backend health FSM + watchdog ------------------------------------

    def backend_health(self) -> str:
        with self._health_lock:
            return self._health

    def _device_allowed(self) -> bool:
        """Gate one batch's device attempt through the FSM: always in
        HEALTHY; in DEGRADED only the periodic probe — everything else
        short-circuits to numpy without billing a failed dispatch."""
        with self._health_lock:
            if self._health == HEALTHY:
                return True
            now = self._clock()
            if now >= self._next_probe:
                # this dispatch IS the recovery probe; schedule the next
                # one now so concurrent groups don't all probe at once
                self._next_probe = now + self.health_probe_interval_s
                self.stats.fsm_probes += 1
                self._c_probes.inc("-", "-")
                return True
            self.stats.fsm_short_circuits += 1
            return False

    def _record_device_failure(self, requests: List[_Request] = ()) -> bool:
        # the degradation ladder discards residency cleanly: after ANY
        # device-path failure the resident buffers are suspect (a hung
        # or faulted device may hold poisoned state), so the next
        # healthy dispatch re-establishes them from a full upload
        self._resident.drop_all()
        with self._health_lock:
            self.stats.device_failures += 1
            self._consec_device_failures += 1
            tripped = (
                self._health == HEALTHY
                and self._consec_device_failures
                >= self.health_failure_threshold
            )
            if tripped:
                self._health = DEGRADED
                self._next_probe = (
                    self._clock() + self.health_probe_interval_s
                )
                self.stats.fsm_trips += 1
                self._c_trips.inc("-", "-")
                self._g_backend_state.set("-", "-", 1.0)
        if tripped:
            logger().warning(
                "solver backend DEGRADED after %d consecutive device "
                "failures; serving from numpy, probing recovery every "
                "%.1fs",
                self._consec_device_failures,
                self.health_probe_interval_s,
            )
            # post-mortem surface (observability.flightrecorder): WHICH
            # reconcile traces the trip degraded, not just that it
            # happened — dumps crash-safely when a dump dir is wired
            default_flight_recorder().record(
                "fsm_trip",
                trace_ids=self._trace_ids(requests),
                subsystem="solver",
                consecutive_failures=self._consec_device_failures,
                requests=len(requests),
            )
        return tripped

    @staticmethod
    def _trace_ids(requests: List[_Request]) -> List[str]:
        """Distinct trace ids of the requests a degradation touched
        (insertion-ordered, deduped)."""
        return list(dict.fromkeys(
            tid for r in requests
            if (tid := r.trace_id()) is not None
        ))

    def _record_device_success(self) -> None:
        with self._health_lock:
            self._consec_device_failures = 0
            recovered = self._health == DEGRADED
            if recovered:
                self._health = HEALTHY
                self.stats.fsm_recoveries += 1
                self._c_recoveries.inc("-", "-")
                self._g_backend_state.set("-", "-", 0.0)
        if recovered:
            logger().info(
                "solver backend recovered; device path re-enabled"
            )

    def _stale(self) -> bool:
        """True on a worker thread superseded by a watchdog restart: its
        late results are discarded (the watchdog already answered its
        requests from numpy)."""
        gen = getattr(self._tls, "gen", None)
        return gen is not None and gen != self._worker_gen

    @contextlib.contextmanager
    def _device_section(self, requests: List[_Request], grace: float = 0.0):
        """Mark the worker busy inside a device call — the window the
        watchdog supervises. A hang here never raises, so supervision
        must come from outside the thread. `grace` shifts the busy mark
        forward (compile-miss dispatches get COMPILE_GRACE_S headroom)."""
        with self._cond:
            self._busy_since = self._clock() + grace
            self._busy_requests = list(requests)
        try:
            yield
        finally:
            with self._cond:
                if not self._stale():  # a restart already reset these
                    self._busy_since = None
                    self._busy_requests = []

    def _watchdog_loop(self) -> None:
        poll = max(0.05, self.watchdog_timeout_s / 4.0)
        while not self._closed:
            _time.sleep(poll)
            self._watchdog_check()

    def _watchdog_check(self) -> None:
        """One supervision pass: if the worker has been inside a device
        call longer than watchdog_timeout_s, supersede it (generation
        bump + fresh thread) and drain every request it held — the stuck
        batch AND the pipelined in-flight ones — to numpy."""
        stuck: List[_Request] = []
        with self._cond:
            busy = self._busy_since
            if busy is None or (
                self._clock() - busy <= self.watchdog_timeout_s
            ):
                return
            # everything the superseded worker holds: the stuck device
            # batch, pipelined in-flight batches, AND the not-yet-
            # dispatched groups of its current batch (already popped
            # from the queue — they live nowhere else). Dedup by
            # identity: a request can appear in more than one list.
            stuck.extend(self._busy_requests)
            for _out, live, _t in self._inflight:
                stuck.extend(live)
            stuck.extend(self._current_batch)
            self._inflight.clear()
            self._busy_since = None
            self._busy_requests = []
            self._current_batch = []
            self.stats.watchdog_restarts += 1
            self._c_watchdog.inc("-", "-")
            if not self._closed:
                self._spawn_worker()
        stuck = list({id(r): r for r in stuck}.values())
        logger().warning(
            "solver worker hung in a device call > %.1fs; restarted the "
            "worker and draining %d request(s) to numpy",
            self.watchdog_timeout_s, len(stuck),
        )
        recorder = default_flight_recorder()
        # one incident, one dump: when the hang also trips the FSM, the
        # fsm_trip auto-dump lands milliseconds later with THIS event
        # already in the ring, so dumping here too would write two
        # near-identical fsync'd files and burn two retention slots
        recorder.record(
            "watchdog_restart",
            trace_ids=self._trace_ids(stuck),
            subsystem="solver",
            requests=len(stuck),
            auto_dump=False,
        )
        tripped = self._record_device_failure(stuck)  # hang counts toward trip
        if not tripped:
            recorder.maybe_auto_dump("watchdog_restart")
        self._finish_from_numpy(stuck)

    # -- worker -----------------------------------------------------------

    def _spawn_worker(self) -> None:
        # called under self._cond
        self._worker_gen += 1
        self._worker = threading.Thread(
            target=self._run, args=(self._worker_gen,),
            name="solver-service", daemon=True,
        )
        self._worker.start()

    def _ensure_worker(self) -> None:
        # called under self._cond
        if self._worker is None or not self._worker.is_alive():
            self._spawn_worker()
        if self.watchdog_timeout_s > 0 and (
            self._watchdog is None or not self._watchdog.is_alive()
        ):
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, name="solver-watchdog",
                daemon=True,
            )
            self._watchdog.start()

    def _run(self, gen: Optional[int] = None) -> None:
        self._tls.gen = gen
        while not self._stale():
            if self._inflight:
                # a dispatch is computing on device: gather the NEXT
                # batch without blocking — if nothing is queued, the
                # useful work left is fetching the in-flight results
                batch = self._collect(block=False)
                if batch is None:
                    self._drain_inflight()
                    return
                if not batch:
                    self._drain_one()
                    continue
            else:
                batch = self._collect()
                if batch is None:
                    self._drain_inflight()
                    return
            with self._cond:
                self._current_batch = list(batch)
            groups: Dict[tuple, List[_Request]] = {}
            for request in batch:
                groups.setdefault(request.key, []).append(request)
            # lone = this batch is one compatibility group with nothing
            # else in flight to overlap — the shape the pipeline
            # chunk-split exists for (multi-group batches overlap
            # naturally: group k+1 dispatches while group k computes)
            for key, requests in groups.items():
                self._dispatch_group(
                    key, requests, lone=len(groups) == 1
                )
            with self._cond:
                if not self._stale():  # a restart already drained it
                    self._current_batch = []
            if not self._queue:
                # nothing else waiting: complete in-flight work now
                # rather than holding a lone batch's results hostage to
                # traffic that may never come
                self._drain_inflight()
            self.publish_gauges()

    def _collect(self, block: bool = True) -> Optional[List[_Request]]:
        """Gather one batch: block for the first request (block=True),
        then hold the ADAPTIVE coalescing window open, gathering up to
        max_batch requests. The window is 0 — dispatch immediately —
        when the queue was empty behind the first request and recent
        batches were singletons; it widens to window_s while traffic is
        concurrent. None = closed+drained; [] = block=False and idle."""
        with self._cond:
            while not self._queue:
                if self._closed:
                    return None
                if not block:
                    return []
                self._cond.wait()
            batch = [self._queue.popleft()]
            backlog = len(self._queue)
        window = self._effective_window(backlog)
        self._window_now_s = window
        if window > 0:
            self._gather_window(batch, window)
        else:
            self.stats.immediate_dispatches += 1
            with self._cond:
                while self._queue and len(batch) < self.max_batch:
                    batch.append(self._queue.popleft())
        with self._cond:
            self._drain_batch_tail(batch)
            self._g_queue.set("-", "-", float(len(self._queue)))
        # worker-only EWMA of observed concurrency: decays back to 1 a
        # few idle batches after a burst, so steady singleton traffic
        # keeps dispatching immediately
        self._load = (
            (1 - _LOAD_ALPHA) * self._load + _LOAD_ALPHA * len(batch)
        )
        return batch

    def _gather_window(self, batch: List[_Request], window: float) -> None:
        """Hold the coalescing window open, folding arrivals into the
        batch until it fills or the window closes."""
        window_end = self._clock() + window
        while len(batch) < self.max_batch:
            remaining = window_end - self._clock()
            if remaining <= 0:
                return
            with self._cond:
                if not self._queue:
                    self._cond.wait(timeout=remaining)
                while self._queue and len(batch) < self.max_batch:
                    batch.append(self._queue.popleft())

    def _effective_window(self, backlog: int) -> float:
        """The gather window for this batch: 0 (dispatch now) on an idle
        queue, window_s while concurrency is observed — either directly
        (requests already queued behind the head) or recently (the
        batch-size EWMA is still above the idle threshold)."""
        if not self.adaptive_window:
            return self.window_s
        if backlog > 0 or self._load >= _LOAD_IDLE:
            return self.window_s
        return 0.0

    def _drain_batch_tail(self, batch: List[_Request]) -> None:
        """consolidate() batches are enqueued atomically (contiguous in
        the deque) and must ride one dispatch: keep draining past
        max_batch while the queue head continues a batch already
        partially collected. Called under self._cond."""
        taken = {
            r.coalesce_id for r in batch if r.coalesce_id is not None
        }
        while (
            self._queue
            and self._queue[0].coalesce_id is not None
            and self._queue[0].coalesce_id in taken
        ):
            batch.append(self._queue.popleft())

    def _filter_live(self, requests: List[_Request]) -> List[_Request]:
        """Drop abandoned and queue-expired requests; the survivors are
        the batch that actually dispatches."""
        now = self._clock()
        live: List[_Request] = []
        for request in requests:
            if request.abandoned:
                # caller already gave up (counted there) — but the
                # caller-side timeout never calls finish(), so close
                # the trace span HERE or the timed-out request (the
                # most diagnosis-worthy kind) vanishes from the export
                if request.span is not None:
                    request.span.close(ok=False, abandoned=True)
                continue
            if request.deadline is not None and now > request.deadline:
                self._on_expired(request)
                request.finish(
                    error=SolverTimeout("deadline expired in queue")
                )
                continue
            self._record_stage("queue_wait", now - request.enqueued_at)
            live.append(request)
        return live

    @staticmethod
    def _shard_strategy(key: tuple) -> Optional[str]:
        """The shard strategy marker of a request key, or None for a
        single-device key. Sharded bin-pack keys: (shape, buckets,
        backend, presence, cshape, "shard"|"vmap_shard", extents).
        Sharded forecast/preempt keys: ("forecast"|"preempt", shape-ish,
        backend, "shard", extents)."""
        if key[0] in ("forecast", "preempt"):
            return (
                "shard" if len(key) > 3 and key[3] == "shard" else None
            )
        if len(key) > 6 and key[5] in ("shard", "vmap_shard"):
            return key[5]
        return None

    @staticmethod
    def _single_device_key(key: tuple) -> tuple:
        """The single-device key a sharded group degrades to — same
        bucket shape/buckets/backend/presence, mesh routing stripped
        ("vmap_shard" keeps the vectorized consolidate program;
        forecast/preempt keys drop their trailing shard marker)."""
        if key[0] in ("forecast", "preempt"):
            return key[:3]
        if key[5] == "vmap_shard":
            return key[:5] + ("vmap",)
        return key[:5]

    def _dispatch_group(
        self, key: tuple, requests: List[_Request], lone: bool = False
    ) -> None:
        live = self._filter_live(requests)
        if not live:
            return
        self.stats.last_coalesce_factor = len(live)
        self.stats.coalesced_batches += len(live) > 1
        self._g_coalesce.set("-", "-", float(len(live)))
        self._h_coalesce.observe("-", "-", float(len(live)))
        device_path = key[2] != "numpy"
        if device_path and not self._device_allowed():
            # FSM degraded, not this window's probe: serve the whole
            # batch from numpy without attempting the sick device
            self._finish_from_numpy(live)
            return
        try:
            self._solve_group(key, live, lone=lone)
            return
        except Exception as exc:  # noqa: BLE001 — device failure path
            error: BaseException = exc
            if device_path and not self._stale():
                self._record_device_failure(live)
        if self._shard_strategy(key) is not None and not self._stale():
            error = self._retry_unsharded(key, live, error)
            if error is None:
                return
        logger().warning(
            "solver device path failed (%s: %s); degrading %d "
            "request(s) to numpy",
            type(error).__name__, error, len(live),
        )
        self._finish_from_numpy(live)

    def _retry_unsharded(
        self, key: tuple, live: List[_Request], error: BaseException
    ) -> Optional[BaseException]:
        """The sharded rung of the degradation ladder
        (docs/solver-service.md): shard -> single-device BEFORE numpy —
        the mesh failing is not the device failing, so the same program
        re-runs unpartitioned. One shard failure also stops routing NEW
        traffic to the mesh (reset_caches, the recovery-boot seam,
        re-arms it). Returns None on success, else the error the numpy
        rung should report."""
        self.stats.shard_fallbacks += 1
        self._shard_broken = True
        logger().warning(
            "sharded dispatch failed (%s: %s); retrying %d request(s) "
            "on the single-device path and disabling the shard route",
            type(error).__name__, error, len(live),
        )
        default_flight_recorder().record(
            "shard_fallback",
            trace_ids=self._trace_ids(live),
            error=type(error).__name__,
            requests=len(live),
        )
        try:
            self._solve_group(self._single_device_key(key), live)
            return None
        except Exception as single_error:  # noqa: BLE001
            if not self._stale():
                self._record_device_failure(live)
            return single_error

    def _dispatch_span(self, name: str, live: List[_Request], **args):
        """The coalesced dispatch span (observability.tracing): opened
        on the worker thread, parented into the FIRST rider's trace for
        correlation, and LINKING every request span that rode the
        dispatch — the one-to-many join the coalescing queue otherwise
        erases (trace-export renders the links as Perfetto flow
        arrows)."""
        tracer = default_tracer()
        if not tracer.enabled:
            return tracer.span(name)  # the shared no-op span
        spans = [r.span for r in live if r.span is not None]
        return tracer.span(
            name,
            parent=spans[0] if spans else None,
            links=spans,
            n_requests=len(live),
            **args,
        )

    def _finish_from_numpy(self, live: List[_Request]) -> None:
        for request in live:
            if request.event.is_set():
                # already answered (watchdog drain vs. stale-worker
                # unwind): don't burn a redundant host solve on a
                # result finish() would discard anyway
                continue
            try:
                request.finish(
                    result=self._numpy_fallback(
                        request.inputs, request.buckets
                    ),
                    degraded=True,
                )
            except Exception as numpy_error:  # noqa: BLE001
                request.finish(error=numpy_error, degraded=True)

    def _solve_group(
        self, key: tuple, live: List[_Request], lone: bool = False
    ) -> None:
        # forecast and preempt ride the mesh too (PR 13 closed the
        # PR 8 "no sharded parity pin" caveat): a request whose cell
        # count reached shard_threshold carries a ("shard", extents)
        # marker and its group dispatches mesh-partitioned — with the
        # same shard -> single-device -> numpy ladder as bin-packs
        # (parity pinned in tests/test_parallel.py). Below threshold —
        # the common S series x T history / C candidates x V victims
        # fleet — nothing changes.
        if key[0] == "forecast":
            self._forecast_group(key, live)
            return
        if key[0] == "preempt":
            self._preempt_group(key, live)
            return
        shape, buckets, backend = key[0], key[1], key[2]
        if backend == "numpy":
            # host program: no device dispatch, no padding (the sparse
            # numpy stages don't compile, so shape stability buys
            # nothing), and no fallback counting — this is the REQUESTED
            # backend, not a degradation. Completes inline, so in-flight
            # device work drains first to keep completion ordered. It is
            # still ONE coalesced group answer, so the dispatch span
            # links its riders like the device paths do.
            self._drain_inflight()
            with self._dispatch_span(
                "solver.dispatch", live, strategy="host"
            ):
                for request in live:
                    t0 = _time.perf_counter()
                    request.finish(
                        result=self._numpy_solve(request.inputs, buckets)
                    )
                    self._record_stage(
                        "dispatch", _time.perf_counter() - t0
                    )
            return
        # the device-dispatch injection point (faults/registry.py): an
        # error plan here exercises the per-request numpy fallback and
        # the FSM trip; a hang plan blocks inside a supervised device
        # section, exercising the watchdog restart + drain
        with self._device_section(live):
            inject("solver.dispatch")
        if self.device_solver is not None:
            self._drain_inflight()
            with self._device_section(live):
                for request in live:
                    t0 = _time.perf_counter()
                    out = self.device_solver(
                        request.inputs, buckets=buckets, backend=backend
                    )
                    self._record_stage(
                        "dispatch", _time.perf_counter() - t0
                    )
                    self._count_dispatch()
                    request.finish(result=out)
            self._record_device_success()
            return
        if backend == "pallas":
            # the fused Mosaic kernel has no batched entry; requests
            # still share the bucketed shapes (compile stability) and
            # the single worker (bounded device pressure). Supervision
            # happens per request inside (_solve_pallas), where the
            # compile-miss grace is known.
            self._drain_inflight()
            self._solve_pallas(shape, buckets, live)
            self._record_device_success()
            return
        if self._shard_strategy(key) is not None:
            # mesh-partitioned dispatch: completes INLINE — fleet-scale
            # operands must not double-buffer (two in-flight 10^9-cell
            # batches would double peak memory), and a synchronous
            # failure is what lets _dispatch_group walk the
            # shard -> single-device -> numpy ladder. Success is
            # recorded INSIDE (_sharded_xla), after its stale check —
            # a watchdog-superseded dispatch completing late must not
            # erase the failure the watchdog just counted.
            self._drain_inflight()
            self._sharded_xla(shape, buckets, live, key)
            return
        self._begin_pipelined_xla(
            shape, buckets, live,
            strategy=key[5] if len(key) > 5 else "map", lone=lone,
        )

    def _begin_pipelined_xla(
        self, shape, buckets: int, live: List[_Request],
        strategy: str, lone: bool,
    ) -> None:
        """Dispatch a map/vmap group, SPLITTING a lone map batch into
        pipeline chunks — the dead-pipeline fix: a lone coalesced batch
        with nothing in flight would dispatch once and drain immediately
        (closed-loop callers can't enqueue the next round until this one
        answers), so the double buffer never engaged. Chunking gives it
        the overlap: chunk k+1's pad/stack/dispatch runs while chunk k
        computes, and chunk k's fetch/scatter overlaps chunk k+1's
        compute. lax.map scans the batch SERIALLY on device, so halving
        a batch costs no device efficiency — only the vmap (consolidate)
        family, whose vectorization is the point, never splits."""
        split = (
            strategy == "map"
            and lone
            and self.pipeline_depth > 0
            and not self._inflight
            and len(live) >= _PIPELINE_SPLIT_MIN
        )
        if not split:
            self._begin_batched_xla(
                shape, buckets, live, strategy=strategy
            )
            return
        n_chunks = min(self.pipeline_depth + 1, len(live) // 2)
        size = -(-len(live) // n_chunks)
        self.stats.pipeline_splits += 1
        for start in range(0, len(live), size):
            self._begin_batched_xla(
                shape, buckets, live[start:start + size],
                strategy=strategy,
            )

    def _forecast_group(  # lint: allow-complexity — one guard per shard rung (route/pad/place/count), numpy short-circuit
        self, key: tuple, live: List[_Request]
    ) -> None:
        """One coalesced forecast dispatch: same-T-bucket requests are
        concatenated along the series axis, padded up the series ladder,
        and answered by ONE compiled program; results slice back per
        request. backend == "numpy" serves the mirror inline (the
        REQUESTED backend, not a degradation). Device failures raise to
        _dispatch_group, which degrades the batch to numpy and feeds the
        backend-health FSM like any other device path."""
        from karpenter_tpu.forecast import models as FM

        t_bucket, backend = key[1], key[2]
        # completes inline (no pipelining: forecast batches are small
        # and latency-bound), so drain in-flight bin-pack work first to
        # keep completion ordered
        self._drain_inflight()
        if backend == "numpy":
            for request in live:
                t0 = _time.perf_counter()
                request.finish(result=FM.forecast_numpy(request.inputs))
                self._record_stage("dispatch", _time.perf_counter() - t0)
            return
        shard = self._shard_strategy(key) is not None
        mesh = self._shard_mesh() if shard else None
        if shard and mesh is None:
            raise RuntimeError(
                "shard mesh unavailable for a shard-routed forecast"
            )
        t0 = _time.perf_counter()
        sizes = [request.n_pods for request in live]
        s_bucket = bucket_up(sum(sizes), FORECAST_S_FLOOR)
        if shard:
            # grow the series bucket to the mesh-row extent GSPMD
            # requires; padding series are all-invalid and sliced off
            from karpenter_tpu.utils.functional import pad_to_multiple

            s_bucket = pad_to_multiple(s_bucket, key[4][0])
        stacked = FM.concat_forecast_inputs(
            [request.inputs for request in live], s_bucket
        )
        self._record_stage("pad", _time.perf_counter() - t0)
        cache_key = ("forecast", s_bucket, t_bucket, backend)
        if shard:
            cache_key += ("shard", key[4])
        fn, fresh = self._forecast_compiled(cache_key)
        import jax

        t0 = _time.perf_counter()
        with self._dispatch_span(
            "solver.dispatch.forecast" + (".shard" if shard else ""),
            live, **self._span_cost_args(cache_key),
        ):
            with self._device_section(
                live, grace=COMPILE_GRACE_S if fresh else 0.0
            ):
                with solver_trace("solver.forecast"):
                    # the forecast-path fault-injection point
                    # (faults/registry.py, docs/resilience.md): an error
                    # plan exercises the numpy degradation + FSM, a hang
                    # plan the watchdog drain
                    inject("forecast.predict")
                    if shard:
                        from karpenter_tpu.parallel.mesh import (
                            forecast_shardings,
                        )

                        stacked = self._upload(
                            stacked, forecast_shardings(mesh)
                        )
                    out = fn(stacked)
                    jax.block_until_ready(out)
        if self._stale():
            return  # watchdog already answered these from numpy
        if fresh:
            self._note_compile(
                "forecast", cache_key, _time.perf_counter() - t0, live,
                extents=key[4] if shard else None,
                cost_fn=(
                    self._cost_thunk(fn, (stacked,), {})
                    if self._introspect is not None
                    and self._introspect.enabled else None
                ),
            )
        self._record_stage("dispatch", _time.perf_counter() - t0)
        self._count_dispatch()
        self.stats.forecast_dispatches += 1
        if shard:
            self.stats.shard_dispatches += 1
        t0 = _time.perf_counter()
        offset = 0
        for request, size in zip(live, sizes):
            request.finish(
                result=FM.slice_forecast_outputs(
                    out, offset, offset + size
                )
            )
            offset += size
        self._record_stage("scatter", _time.perf_counter() - t0)
        self._record_device_success()

    def _preempt_group(  # lint: allow-complexity — one guard per shard rung (route/pad/place/count), numpy short-circuit
        self, key: tuple, live: List[_Request]
    ) -> None:
        """Eviction-planning dispatches: each request is already a
        whole-fleet batched problem (the candidate axis IS the batch —
        ops/preempt.py plans candidates data-parallel), so same-key
        requests dispatch one after another through one compiled
        program. Completes inline (latency-bound, like forecasts), so
        in-flight bin-pack work drains first to keep completion
        ordered. Device failures raise to _dispatch_group, which
        degrades the batch to the bit-identical numpy mirror and feeds
        the backend-health FSM like any other device path."""
        from karpenter_tpu.ops import preempt as PK

        shape, backend = key[1], key[2]
        self._drain_inflight()
        if backend == "numpy":
            # the REQUESTED backend, not a degradation: no padding (the
            # host program doesn't compile), no fallback counting
            for request in live:
                t0 = _time.perf_counter()
                request.finish(result=PK.preempt_numpy(request.inputs))
                self._record_stage("dispatch", _time.perf_counter() - t0)
            return
        import jax

        shard = self._shard_strategy(key) is not None
        mesh = self._shard_mesh() if shard else None
        if shard and mesh is None:
            raise RuntimeError(
                "shard mesh unavailable for a shard-routed eviction plan"
            )
        shardings = None
        if shard:
            # grow the CANDIDATE axis (the data-parallel one the mesh
            # rows shard) to the mesh extent; padding candidates are
            # invalid + all-forbidden, cropped off below
            from karpenter_tpu.parallel.mesh import preempt_shardings
            from karpenter_tpu.utils.functional import pad_to_multiple

            c, n, r, v = shape
            shape = (pad_to_multiple(c, key[4][0]), n, r, v)
            shardings = preempt_shardings(mesh)
        cache_key = ("preempt", shape, backend)
        if shard:
            cache_key += ("shard", key[4])
        fresh = self._count_compile(cache_key)
        grace = COMPILE_GRACE_S if fresh else 0.0
        for request in live:
            t0 = _time.perf_counter()
            padded = pad_preempt_inputs(request.inputs, shape)
            self._record_stage("pad", _time.perf_counter() - t0)
            t0 = _time.perf_counter()
            with self._dispatch_span(
                "solver.dispatch.preempt" + (".shard" if shard else ""),
                [request], **self._span_cost_args(cache_key),
            ):
                with self._device_section([request], grace=grace):
                    with solver_trace("solver.preempt"):
                        # the preempt-path fault-injection point
                        # (faults/registry.py, docs/resilience.md): an
                        # error plan exercises the numpy degradation +
                        # FSM, a hang plan the watchdog drain
                        inject("preempt.plan")
                        placed = (
                            self._upload(padded, shardings)
                            if shard
                            else jax.device_put(padded)
                        )
                        out = PK.preempt_plan(placed)
                        jax.block_until_ready(out)
            grace = 0.0  # only the first dispatch of the batch compiles
            if self._stale():
                return  # watchdog already answered these from numpy
            if fresh:
                fresh = False  # only the first dispatch paid the compile
                self._note_compile(
                    "preempt", cache_key, _time.perf_counter() - t0,
                    [request], extents=key[4] if shard else None,
                    cost_fn=(
                        self._cost_thunk(PK.preempt_plan, (padded,), {})
                        if self._introspect is not None
                        and self._introspect.enabled else None
                    ),
                )
            self._record_stage("dispatch", _time.perf_counter() - t0)
            self._count_dispatch()
            self.stats.preempt_dispatches += 1
            if shard:
                self.stats.shard_dispatches += 1
            t0 = _time.perf_counter()
            host = PK.PreemptOutputs(
                chosen_node=np.asarray(out.chosen_node),
                evict_count=np.asarray(out.evict_count),
                evict_mask=np.asarray(out.evict_mask),
                unplaceable=np.asarray(out.unplaceable),
            )
            request.finish(
                result=crop_preempt_outputs(
                    host, request.n_pods, request.n_groups
                )
            )
            self._record_stage("scatter", _time.perf_counter() - t0)
        self._record_device_success()

    def _forecast_compiled(self, cache_key: tuple):
        """(compiled batched forecast program, fresh) — the forecast
        face of the shared compile cache (same hit/miss counters)."""
        fresh = self._count_compile(cache_key)
        fn = self._compiled.get(cache_key)
        if fn is None:
            import jax

            from karpenter_tpu.forecast import models as FM

            fn = self._compiled[cache_key] = jax.jit(FM.forecast)
        return fn, fresh

    def _solve_pallas(self, shape, buckets: int, live: List[_Request]) -> None:
        import jax

        from karpenter_tpu.ops import binpack as B

        cache_key = ("pallas", shape, buckets, live[0].key[3])
        fresh = self._count_compile(cache_key)
        grace = COMPILE_GRACE_S if fresh else 0.0
        for request in live:
            padded = pad_to_bucket(request.inputs, shape)
            t0 = _time.perf_counter()
            with self._device_section([request], grace=grace):
                out = B.solve(padded, buckets=buckets, backend="pallas")
                jax.block_until_ready(out)
            grace = 0.0  # only the first call of the batch compiles
            if fresh:
                # no jit handle to lower here (B.solve resolves the
                # fused Mosaic kernel internally), so the ledger row
                # carries the wall time without cost attribution; the
                # helper's stale check keeps a watchdog-superseded
                # worker's discarded dispatch out of the ledger
                self._note_fresh_compile(
                    fresh, "solve", cache_key, t0, [request]
                )
                fresh = False
            self._record_stage("dispatch", _time.perf_counter() - t0)
            self._count_dispatch()
            request.finish(result=self._crop_host(out, request))

    def _begin_batched_xla(
        self, shape, buckets: int, live: List[_Request],
        strategy: str = "map",
    ) -> None:
        """The coalesced path: pad each request to the shape bucket,
        stack along a new leading axis, pad the batch axis up its own
        ladder, dispatch ONE compiled program — and DON'T wait for it.
        The dispatch joins the in-flight pipeline; its host-side fetch +
        crop + scatter are paid by _drain_one, which the worker calls
        after dispatching the NEXT batch (overlap) or when the queue
        goes idle (no result is ever held hostage to future traffic).

        strategy="map" (plain solve() traffic) scans the batch with
        lax.map: the per-item program inside the scan is the same HLO as
        a direct binpack call on the same (padded) shapes, so outputs
        match direct calls element for element, and peak memory stays at
        one item's working set (coalesced 100k-pod ticks must not pay a
        batch× amplification). strategy="vmap" (consolidate() batches)
        vectorizes across the batch instead — candidates are cluster-
        scale operands, so the amplification is trivial and the batched
        throughput gain is the whole point.

        The stacked operands are device_put FIRST and the compiled
        program donates them (donate_argnums): on backends with real
        donation support the batch buffers are reused instead of
        reallocated every dispatch; where donation is unimplemented it
        is a no-op with identical outputs (pinned by the donation-parity
        test).

        Singleton map groups first consult the DEVICE-RESIDENT fleet
        state (solver/resident.py): an identity hit or changed-row
        scatter skips the pad/stack/upload entirely, and the dispatch
        compiles the donate=False family so the resident buffers
        survive the solve."""
        resident = self._resident_stack(shape, live, strategy)
        if resident is not None:
            stacked, n_batch, donate = resident, 1, False
        else:
            stacked, n_batch = self._stack_group(shape, live)
            donate = self._donation_supported()
        cache_key = (
            "xla", shape, n_batch, buckets, live[0].key[3],
            live[0].key[4], strategy,
        )
        fn, fresh = self._compiled_for(cache_key, donate=donate)
        # shape capture must precede the dispatch: donated operand
        # buffers are deleted by the time the thunk could run
        cost_fn = self._fresh_cost_thunk(fresh, fn, stacked, buckets)
        t0 = _time.perf_counter()
        with self._dispatch_span(
            "solver.dispatch", live, strategy=strategy, batch=n_batch,
            **self._span_cost_args(cache_key),
        ):
            with self._device_section(
                live, grace=COMPILE_GRACE_S if fresh else 0.0
            ):
                with solver_trace("solver.dispatch"):
                    if resident is None:
                        stacked = self._upload(stacked)
                    out = fn(stacked, buckets)
        self._note_fresh_compile(
            fresh, "solve", cache_key, t0, live, cost_fn=cost_fn
        )
        if self._stale():
            # superseded by a watchdog restart while dispatching: the
            # watchdog already answered these requests from numpy —
            # discard the late device results
            return
        if self._inflight:
            self.stats.pipeline_overlaps += 1
        self._inflight.append((out, live, t0))
        self._last_pipeline_depth = len(self._inflight)
        self._count_dispatch()
        # cap in-flight work at pipeline_depth, draining OLDEST first:
        # with depth 1 this is classic double buffering (batch k's fetch
        # is paid here, after batch k+1's dispatch); depth 0 restores
        # the serial dispatch→wait→scatter loop
        while len(self._inflight) > max(0, self.pipeline_depth):
            self._drain_one()

    def _stack_group(self, shape, live: List[_Request]):
        """(stacked operands, batch bucket): pad each request to the
        shape bucket, stack along a new leading axis, pad the batch
        axis up its own ladder — batch padding replicates the first
        request, the cheapest valid filler (its outputs are computed
        and discarded). Shared by the single-device and sharded
        dispatch paths; records the "pad" stage."""
        t0 = _time.perf_counter()
        padded = [pad_to_bucket(r.inputs, shape) for r in live]
        n_batch = bucket_up(len(padded), 1)
        padded.extend(padded[:1] * (n_batch - len(padded)))
        stacked = _stack_inputs(padded)
        self._record_stage("pad", _time.perf_counter() - t0)
        return stacked, n_batch

    def _resident_stack(
        self, shape, live: List[_Request], strategy: str,
        shardings=None, extents: Optional[tuple] = None,
    ):
        """The device-resident serve path for a SINGLETON map-strategy
        group: returns the resident stacked operands (batch axis 1), or
        None when residency does not apply — disabled, a coalesced
        multi-request batch (those stacks are ephemeral by nature), the
        vmap consolidate family, or an out-of-process device solver.

        kind accounting: a "hit" records a 0.0 upload sample (nothing
        crossed the link — the claim `make bench-hotpath`'s upload p50
        verifies), a "scatter" records the scatter wall time under the
        resident_scatter stage + gauge (its host->device traffic is the
        changed-row blocks inside the jitted scatter), and a "rebuild"
        billed its full upload through the normal _upload hook."""
        if (
            not self.resident_enabled
            or strategy != "map"
            or len(live) != 1
            or self.device_solver is not None
        ):
            return None
        request = live[0]
        mode = ("single",) if extents is None else ("shard", extents)
        t0 = _time.perf_counter()
        try:
            stacked, kind = self._resident.obtain(
                request.inputs, shape, mode,
                lambda tree: self._upload(tree, shardings),
                tenant=request.tenant,
                now=self._clock(),
            )
        except Exception as error:  # noqa: BLE001 — optimization layer
            logger().warning(
                "resident fleet state unavailable (%s: %s); "
                "re-uploading the full operand stack",
                type(error).__name__, error,
            )
            return None
        if kind == "hit":
            self.stats.resident_hits += 1
            self._record_stage("upload", 0.0)
        elif kind == "scatter":
            self.stats.resident_scatters += 1
            elapsed = _time.perf_counter() - t0
            self._record_stage("resident_scatter", elapsed)
            self._g_resident_scatter.set("-", "-", elapsed * 1e3)
        else:
            self.stats.resident_rebuilds += 1
            self._c_resident_rebuilds.inc("-", "-")
        self.stats.resident_drops = self._resident.drops
        return stacked

    def _upload(self, stacked, shardings=None):
        """device_put the stack (with NamedShardings on the sharded
        path) and record the ISOLATED host->device transfer cost (the
        device-resident-state target, ROADMAP item 4): compute waits on
        the transfer either way, so the sync point only moves the wait
        to where it can be measured."""
        import jax

        t_up = _time.perf_counter()
        stacked = (
            jax.device_put(stacked)
            if shardings is None
            else jax.device_put(stacked, shardings)
        )
        jax.block_until_ready(stacked)
        self._record_stage("upload", _time.perf_counter() - t_up)
        return stacked

    def _sharded_xla(
        self, shape, buckets: int, live: List[_Request], key: tuple
    ) -> None:
        """The sharded dispatch strategy (docs/solver-service.md
        "Sharded dispatch"): the same batched program the single-device
        path compiles, partitioned over the pods x groups mesh by GSPMD.

        Each request pads up the normal bucket ladder GROWN to
        mesh-divisible pod/group extents (mesh_aligned_shape — padding
        stays semantics-preserving: extra rows invalid, extra columns
        infeasible), the stack is device_put with NamedShardings (pod
        axis over mesh rows, group axis over mesh columns, batch axis
        replicated), and the jitted lax.map/vmap program runs with its
        feasibility matmuls as local blocks and one cross-shard
        reduction per aggregate. Results merge host-side: one fetch per
        batch, then the standard per-request crop — the caller-visible
        slices carry no mesh padding. Outputs are BIT-IDENTICAL to the
        single-device program on integer fields (the padding argument of
        solver/bucketing.py; property-pinned in tests/test_parallel.py
        and tests/test_solver_service.py); the f32 lp_bound may differ
        by the reduction-order ulp the numpy-parity contract already
        carves out."""
        import jax

        from karpenter_tpu.parallel.mesh import stacked_binpack_shardings
        from karpenter_tpu.solver.bucketing import mesh_aligned_shape

        mesh = self._shard_mesh()
        if mesh is None:
            raise RuntimeError(
                "shard mesh unavailable for a shard-routed batch"
            )
        extents = key[6]
        strategy = "vmap" if key[5] == "vmap_shard" else "map"
        aligned = mesh_aligned_shape(shape, extents)
        shardings = stacked_binpack_shardings(mesh, key[3])
        # sharded residency: the resident entry holds the NamedSharding-
        # placed stack, so an unchanged/delta tick skips the full
        # sharded upload too; a threshold crossing (either direction)
        # misses on mode and rebuilds under the new placement
        resident = self._resident_stack(
            aligned, live, strategy, shardings=shardings, extents=extents
        )
        if resident is not None:
            stacked, n_batch, donate = resident, 1, False
        else:
            stacked, n_batch = self._stack_group(aligned, live)
            donate = self._donation_supported()
        cache_key = (
            "xla", aligned, n_batch, buckets, key[3], key[4], strategy,
            "shard", extents,
        )
        fn, fresh = self._compiled_for(cache_key, donate=donate)
        cost_fn = self._fresh_cost_thunk(fresh, fn, stacked, buckets)
        t0 = _time.perf_counter()
        with self._dispatch_span(
            "solver.dispatch.shard", live,
            strategy=strategy, devices=int(mesh.devices.size),
            **self._span_cost_args(cache_key),
        ):
            with self._device_section(
                live, grace=COMPILE_GRACE_S if fresh else 0.0
            ):
                with solver_trace("solver.shard"):
                    if resident is None:
                        stacked = self._upload(stacked, shardings)
                    out = fn(stacked, buckets)
                    jax.block_until_ready(out)
        if self._stale():
            return  # watchdog already answered these from numpy
        self._note_fresh_compile(
            fresh, "solve", cache_key, t0, live,
            extents=extents, cost_fn=cost_fn,
        )
        self._record_stage("dispatch", _time.perf_counter() - t0)
        self._count_dispatch()
        self.stats.shard_dispatches += 1
        t0 = _time.perf_counter()
        host = _fetch_outputs(out)
        for i, request in enumerate(live):
            request.finish(
                result=crop_outputs(
                    _index_outputs(host, i),
                    request.n_pods, request.n_groups,
                )
            )
        self._record_stage("scatter", _time.perf_counter() - t0)
        self._record_device_success()

    def _drain_one(self) -> None:
        """Complete the OLDEST in-flight dispatch: wait out the device,
        fetch once, crop + scatter per request. Device-path failures
        surface here (async dispatch defers them to the wait) and
        degrade each request to numpy exactly like a sync failure.

        Stage-metric caveat: under pipelining the "dispatch" sample is
        dispatch-to-drain WALL time — it includes whatever gather/pad
        work for the next batch overlapped the device compute, not pure
        device time. On an idle queue (drain immediately follows
        dispatch) it degenerates to the device latency; under load read
        it as "time a batch spent in flight" (docs/solver-service.md
        "Latency tuning")."""
        with self._cond:
            # pop under the lock: the watchdog clears _inflight when it
            # supersedes a hung worker, and a stale worker must not
            # steal the NEW worker's in-flight batches
            if self._stale() or not self._inflight:
                return
            out, live, t_dispatch = self._inflight.popleft()
        try:
            import jax

            with self._device_section(live):
                jax.block_until_ready(out)
            self._record_stage(
                "dispatch", _time.perf_counter() - t_dispatch
            )
            t0 = _time.perf_counter()
            host = _fetch_outputs(out)
            for i, request in enumerate(live):
                request.finish(
                    result=self._crop_host(_index_outputs(host, i), request)
                )
            self._record_stage("scatter", _time.perf_counter() - t0)
            self._record_device_success()
        except Exception as error:  # noqa: BLE001 — device failure path
            if not self._stale():
                self._record_device_failure(live)
            logger().warning(
                "solver device path failed in flight (%s: %s); degrading "
                "%d request(s) to numpy",
                type(error).__name__, error, len(live),
            )
            self._finish_from_numpy(live)

    def _drain_inflight(self) -> None:
        while self._inflight:
            self._drain_one()

    def _crop_host(self, out, request: _Request):
        return crop_outputs(
            _fetch_outputs(out), request.n_pods, request.n_groups
        )

    _donation_ok: Optional[bool] = None

    def _donation_supported(self) -> bool:
        """Donate only where the backend can actually alias donated
        buffers (TPU/GPU); on CPU donation is a warning-per-executable
        no-op, so the worker compiles the non-donating family there.
        Outputs are identical either way — the donation-parity test
        compiles BOTH families explicitly regardless of backend."""
        if SolverService._donation_ok is None:
            import jax

            SolverService._donation_ok = jax.default_backend() in (
                "tpu", "gpu", "cuda", "rocm"
            )
        return SolverService._donation_ok

    def _compiled_for(self, cache_key: tuple, donate: bool = False):
        """(compiled batched program, fresh) for the cache key — fresh
        means the key was a compile-cache MISS, so the first dispatch
        pays the compile (and gets the watchdog grace). donate=True
        marks the stacked operand pytree donated (donate_argnums=0): the
        worker device_puts the stack first, so backends with donation
        support recycle the batch buffers instead of allocating fresh
        ones every dispatch; outputs are identical either way (the
        donation-parity test pins it). The flag is part of the cache key
        so the two program families never alias."""
        cache_key = (*cache_key, "donate" if donate else "keep")
        fresh = self._count_compile(cache_key)
        fn = self._compiled.get(cache_key)
        if fn is not None:
            return fn, fresh

        from functools import partial

        import jax
        from jax import lax

        from karpenter_tpu.ops import binpack as B

        jit = partial(
            jax.jit,
            static_argnames=("buckets",),
            **({"donate_argnums": (0,)} if donate else {}),
        )
        if "vmap" in cache_key:

            @jit
            def batched(stacked, buckets):
                return jax.vmap(
                    lambda one: B.binpack(one, buckets=buckets)
                )(stacked)

        else:

            @jit
            def batched(stacked, buckets):
                return lax.map(
                    lambda one: B.binpack(one, buckets=buckets), stacked
                )

        self._compiled[cache_key] = batched
        return batched, fresh

    def _count_compile(self, cache_key: tuple) -> bool:
        """Count a compile-cache lookup; True = MISS (first sight of the
        key — the following dispatch pays a fresh compile and earns the
        watchdog's COMPILE_GRACE_S headroom)."""
        if cache_key in self._compile_seen:
            self.stats.compile_cache_hits += 1
            self._c_hits.inc("-", "-")
            return False
        self._compile_seen.add(cache_key)
        self.stats.compile_cache_misses += 1
        self._c_misses.inc("-", "-")
        return True

    def _count_fused_compile(self, cache_key: tuple) -> bool:
        """Fused-family compile-cache lookup. Unlike the solve family
        (whose compiled closures live on THIS service instance), the
        fused program rides the module-level fused_tick_jit whose
        compile cache is process-global — and disk-global under the
        persistent compile cache — so freshness is tracked in the
        module-level set: a rebooted service in a warm process pays no
        compile and must not ledger one (the restart contract
        --compile-cache-dir exists for). reset_caches() re-arms."""
        if cache_key in _FUSED_COMPILE_SEEN:
            self.stats.compile_cache_hits += 1
            self._c_hits.inc("-", "-")
            return False
        _FUSED_COMPILE_SEEN.add(cache_key)
        self.stats.compile_cache_misses += 1
        self._c_misses.inc("-", "-")
        return True

    def _count_dispatch(self) -> None:
        self.stats.dispatches += 1
        self._c_dispatch.inc("-", "-")

    def _on_expired(self, request: _Request) -> None:
        self.stats.deadline_expired += 1
        self._c_expired.inc("-", "-")

    def _numpy_fallback(self, inputs: BinPackInputs, buckets: int):
        self.stats.fallbacks += 1
        self._c_fallback.inc("-", "-")
        return self._numpy_solve(inputs, buckets)

    def _numpy_solve(self, inputs, buckets: int):
        from karpenter_tpu.forecast.models import (
            ForecastInputs,
            forecast_numpy,
        )

        if isinstance(inputs, ForecastInputs):
            # bit-identical mirror of the device kernel
            # (forecast/models.py parity contract)
            return forecast_numpy(inputs)
        from karpenter_tpu.ops.preempt import PreemptInputs, preempt_numpy

        if isinstance(inputs, PreemptInputs):
            # bit-identical mirror (ops/preempt.py parity contract)
            return preempt_numpy(inputs)
        from karpenter_tpu.ops.numpy_binpack import binpack_numpy

        return binpack_numpy(inputs, buckets=buckets)


def _stack_inputs(padded: List[BinPackInputs]) -> BinPackInputs:
    """Stack same-shaped requests along a new leading batch axis (host
    numpy; one device transfer happens inside the jitted dispatch).
    Optional operands are presence-consistent across the batch (the
    compatibility key includes the presence tuple)."""
    import dataclasses

    def stack(name: str):
        leaves = [getattr(p, name) for p in padded]
        if leaves[0] is None:
            return None
        return np.stack([np.asarray(leaf) for leaf in leaves], axis=0)

    return BinPackInputs(
        **{
            f.name: stack(f.name)
            for f in dataclasses.fields(BinPackInputs)
        }
    )


def _fetch_outputs(out):
    """Device outputs -> host numpy (one transfer per leaf, amortized
    over the whole coalesced batch). Host outputs pass through."""
    import dataclasses

    import jax

    if not isinstance(out.assigned, jax.Array):
        return out
    return dataclasses.replace(
        out,
        assigned=np.asarray(out.assigned),
        assigned_count=np.asarray(out.assigned_count),
        nodes_needed=np.asarray(out.nodes_needed),
        lp_bound=np.asarray(out.lp_bound),
        unschedulable=np.asarray(out.unschedulable),
    )


def _index_outputs(host, i: int):
    import dataclasses

    return dataclasses.replace(
        host,
        assigned=host.assigned[i],
        assigned_count=host.assigned_count[i],
        nodes_needed=host.nodes_needed[i],
        lp_bound=host.lp_bound[i],
        unschedulable=host.unschedulable[i],
    )


# -- pre-warm problem builders (prewarm docstring) ---------------------------


def _prewarm_solve_inputs() -> BinPackInputs:
    """1 pod x 1 group, weight present: pads up to the floor rung
    (256 x 8 x 4 x 32 x 64) inside the queue — the exact program a
    small fleet's first pendingCapacity solve compiles."""
    return BinPackInputs(
        pod_requests=np.ones((1, 1), np.float32),
        pod_valid=np.ones(1, bool),
        pod_intolerant=np.zeros((1, 1), bool),
        pod_required=np.zeros((1, 1), bool),
        group_allocatable=np.full((1, 1), 8.0, np.float32),
        group_taints=np.zeros((1, 1), bool),
        group_labels=np.zeros((1, 1), bool),
        pod_weight=np.ones(1, np.int32),
    )


def _prewarm_decide_inputs():
    """1 autoscaler x 1 metric at the decision kernel's smallest row
    bucket (ops/decision.pad_to) — the first fleet decide's program."""
    from karpenter_tpu.ops import decision as D

    n = D.pad_to(1)
    zeros_i = np.zeros(n, np.int32)
    zeros_f = np.zeros(n, np.float32)
    col_i = np.zeros((n, 1), np.int32)
    col_b = np.zeros((n, 1), bool)
    return D.DecisionInputs(
        metric_value=np.zeros((n, 1), np.float32),
        target_value=np.ones((n, 1), np.float32),
        target_type=np.full((n, 1), D.TYPE_AVERAGE_VALUE, np.int32),
        metric_valid=col_b.copy(),
        spec_replicas=zeros_i.copy(),
        status_replicas=zeros_i.copy(),
        min_replicas=zeros_i.copy(),
        max_replicas=np.ones(n, np.int32),
        up_window=zeros_i.copy(),
        down_window=zeros_i.copy(),
        up_policy=np.full(n, D.POLICY_MAX, np.int32),
        down_policy=np.full(n, D.POLICY_MAX, np.int32),
        last_scale_time=zeros_f.copy(),
        has_last_scale=np.zeros(n, bool),
        now=np.float32(0.0),
        up_ptype=col_i.copy(),
        up_pvalue=col_i.copy(),
        up_pperiod=np.ones((n, 1), np.int32),
        up_pvalid=col_b.copy(),
        down_ptype=col_i.copy(),
        down_pvalue=col_i.copy(),
        down_pperiod=np.ones((n, 1), np.int32),
        down_pvalid=col_b.copy(),
    )


def _prewarm_fused_inputs():
    """The full-presence fused tick (forecast + decide + cost engaged)
    at the smallest rung of every shape ladder: 1 series x 1 sample
    (padded to 8 x 16 inside fused_tick), the decide kernel's smallest
    row bucket, 1 metric column — the program a small fleet's first
    --fused-tick reconcile compiles."""
    from karpenter_tpu.forecast.models import ForecastInputs
    from karpenter_tpu.ops import fusedtick as FT

    dec = _prewarm_decide_inputs()
    n = int(dec.spec_replicas.shape[0])
    return FT.FusedTickInputs(
        decision=dec,
        forecast=ForecastInputs(
            values=np.zeros((1, 1), np.float32),
            valid=np.zeros((1, 1), bool),
            times=np.zeros((1, 1), np.float32),
            weights=np.ones((1, 1), np.float32),
            horizon=np.ones(1, np.float32),
            step_s=np.ones(1, np.float32),
            model=np.zeros(1, np.int32),
            season=np.zeros(1, np.int32),
            alpha=np.full(1, 0.5, np.float32),
            beta=np.full(1, 0.1, np.float32),
            gamma=np.full(1, 0.1, np.float32),
        ),
        series_row=np.zeros(1, np.int32),
        series_col=np.zeros(1, np.int32),
        series_need=np.full(1, 2, np.int32),
        series_blend=np.zeros(1, bool),
        ha_min=np.zeros(n, np.int32),
        ha_max=np.ones(n, np.int32),
        unit_cost=np.zeros(n, np.float32),
        slo_weight=np.zeros(n, np.float32),
        max_hourly_cost=np.zeros(n, np.float32),
        slo_valid=np.zeros(n, bool),
        slo_target=np.ones((n, 1), np.float32),
        observed=np.zeros((n, 1), np.float32),
        demand_base_valid=np.zeros((n, 1), bool),
        prior_point=np.zeros((n, 1), np.float32),
        prior_sigma2=np.zeros((n, 1), np.float32),
        prior_valid=np.zeros((n, 1), bool),
    )


# -- process-default service -------------------------------------------------
# simulate and the sidecar server share one service per process (the whole
# point: concurrent callers coalesce); the runtime builds its OWN instance
# so its gauges land in the runtime registry.

_default_lock = threading.Lock()
_default_service: Optional[SolverService] = None


def default_service() -> SolverService:
    global _default_service
    with _default_lock:
        if _default_service is None:
            _default_service = SolverService()
        return _default_service


def reset_default_service() -> None:
    """Close and drop the process-default service (test isolation)."""
    global _default_service
    with _default_lock:
        if _default_service is not None:
            _default_service.close()
            _default_service = None


def reset_default_service_caches() -> None:
    """Invalidate the process-default service's compile caches WITHOUT
    closing it — the recovery-boot seam for the one solver instance
    that can genuinely outlive an in-process controller restart
    (simulate/sidecar embedders share it across runtime incarnations)."""
    with _default_lock:
        if _default_service is not None:
            _default_service.reset_caches()
