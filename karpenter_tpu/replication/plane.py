"""ReplicatedControlPlane: one replica's view of the partitioned fleet.

Runs once per manager tick (`on_tick`, wired into the runtime's
composed tick hook): a lease round (lease.py), then the ownership diff
becomes fenced tenant handoffs (handoff.py) — adopt every tenant whose
partition we now hold, release every tenant whose partition moved away
— then the per-tenant warm-ups advance and the gauges publish.

Observability surface (docs/OPERATIONS.md):

  karpenter_replica_partitions_owned   partitions this replica holds
  karpenter_replica_replicas_live      live heartbeats it can see
  karpenter_replica_lease_rounds_total election rounds completed
  karpenter_replica_lease_failures_total held-lease renew failures
  karpenter_handoff_tenants_adopted_total fenced adoptions completed
  karpenter_handoff_tenants_released_total releases (moves + shutdown)
  karpenter_handoff_tenants_serving    tenants fully serving here
  karpenter_handoff_tenants_warming    tenants still in warm-up
  karpenter_handoff_replay_seconds     last adoption's journal replay

plus the /debug/replicas scoreboard (`scoreboard()`) and the self-SLO
source (`slo_source`): a tick with held-lease renew failures or tenants
still warming is a BAD control-health event — a handoff in flight burns
error budget exactly like a degraded solver FSM.
"""

from __future__ import annotations

import time as _time
from typing import Callable, Dict, List, Optional

from karpenter_tpu.controllers.errors import RetryableError
from karpenter_tpu.faults import inject
from karpenter_tpu.leaderelection import (
    DEFAULT_LEASE_DURATION,
    DEFAULT_LEASE_NAMESPACE,
    DEFAULT_SKEW_TOLERANCE,
)
from karpenter_tpu.replication.handoff import TenantHandoff
from karpenter_tpu.replication.lease import LeaseRound, PartitionLeaseManager
from karpenter_tpu.replication.partitions import partition_of
from karpenter_tpu.utils.log import logger

SUBSYSTEM_REPLICA = "replica"
SUBSYSTEM_HANDOFF = "handoff"

# flight-recorder kind for one completed fenced adoption
HANDOFF_EVENT = "tenant_handoff"


class ReplicatedControlPlane:
    """Seams (all callables, so tests and the simulator compose pieces
    freely, the SelfSLOMonitor posture):

      tenants_source   () -> [tenant ids] — the tenant universe this
                       replica partitions (the TenantRegistry's list)
      journal_dir_for  (tenant) -> Optional[dir] — the per-tenant
                       journal/fence dir (TenantRegistry.journal_dir_for)
      validator        the provider-side FenceValidator adoptions seed
                       (cloudprovider factory `.fence_validator`)
      validator_for    (tenant) -> validator — the per-tenant form for
                       worlds where every tenant has its own provider
                       (the failover simulator); wins over `validator`
    """

    def __init__(
        self,
        store,
        replica_id: Optional[str],
        partitions: int,
        lease_duration: float = DEFAULT_LEASE_DURATION,
        tenants_source: Optional[Callable[[], List[str]]] = None,
        journal_dir_for: Optional[Callable[[str], Optional[str]]] = None,
        validator=None,
        validator_for: Optional[Callable[[str], object]] = None,
        warmup_ticks: int = 1,
        registry=None,
        clock: Callable[[], float] = _time.time,
        monotonic=None,
        skew_tolerance: float = DEFAULT_SKEW_TOLERANCE,
        namespace: str = DEFAULT_LEASE_NAMESPACE,
        recorder=None,
    ):
        if not replica_id:
            import uuid

            replica_id = f"karpenter-{uuid.uuid4().hex[:8]}"
        self.replica_id = replica_id
        self.partitions = partitions
        self.clock = clock
        self.warmup_ticks = warmup_ticks
        self.validator = validator
        self.validator_for = validator_for
        self.tenants_source = tenants_source or (lambda: [])
        self.journal_dir_for = journal_dir_for or (lambda tenant: None)
        self._recorder = recorder
        self.leases = PartitionLeaseManager(
            store,
            replica_id=replica_id,
            partitions=partitions,
            lease_duration=lease_duration,
            clock=clock,
            monotonic=monotonic,
            skew_tolerance=skew_tolerance,
            namespace=namespace,
        )
        self.handoffs: Dict[str, TenantHandoff] = {}
        self.rounds = 0
        self.adopted_total = 0
        self.released_total = 0
        self.last_round: Optional[LeaseRound] = None
        self._g_owned = self._g_live = None
        self._c_rounds = self._c_failures = None
        self._c_adopted = self._c_released = None
        self._g_serving = self._g_warming = self._g_replay = None
        if registry is not None:
            reg = registry.register
            self._g_owned = reg(SUBSYSTEM_REPLICA, "partitions_owned")
            self._g_live = reg(SUBSYSTEM_REPLICA, "replicas_live")
            self._c_rounds = reg(
                SUBSYSTEM_REPLICA, "lease_rounds_total", kind="counter"
            )
            self._c_failures = reg(
                SUBSYSTEM_REPLICA, "lease_failures_total", kind="counter"
            )
            self._c_adopted = reg(
                SUBSYSTEM_HANDOFF, "tenants_adopted_total", kind="counter"
            )
            self._c_released = reg(
                SUBSYSTEM_HANDOFF, "tenants_released_total", kind="counter"
            )
            self._g_serving = reg(SUBSYSTEM_HANDOFF, "tenants_serving")
            self._g_warming = reg(SUBSYSTEM_HANDOFF, "tenants_warming")
            self._g_replay = reg(SUBSYSTEM_HANDOFF, "replay_seconds")

    # -- ownership ---------------------------------------------------------

    def partition_for(self, tenant: str) -> int:
        return partition_of(tenant, self.partitions)

    def owns(self, tenant: str) -> bool:
        """Whether this replica holds the tenant's partition lease."""
        return self.leases.owns(self.partition_for(tenant))

    def serving(self, tenant: str) -> bool:
        """Owned AND past the handoff warm-up: safe to decide + actuate
        disruptively for this tenant."""
        handoff = self.handoffs.get(tenant)
        return handoff is not None and handoff.ready()

    def handoff_for(self, tenant: str) -> Optional[TenantHandoff]:
        return self.handoffs.get(tenant)

    def token_for(self, tenant: str):
        """The fence stamp this replica's actuations for `tenant` carry
        (None when not owned or unfenced)."""
        handoff = self.handoffs.get(tenant)
        return handoff.token() if handoff is not None else None

    def allow_disruption(self, tenant: str) -> bool:
        handoff = self.handoffs.get(tenant)
        return handoff is not None and handoff.allow_disruption()

    # -- the per-tick protocol ---------------------------------------------

    def on_tick(self) -> LeaseRound:
        """One replica tick: crash seam, lease round, ownership diff ->
        adoptions/releases, warm-up advance, gauges."""
        try:
            # the kill point of the failover chaos family: a crash plan
            # here is this replica dying between lease rounds
            inject(f"replica.crash.{self.replica_id}")
        except RetryableError:
            pass  # error plans at a kill point degrade to a no-op tick
        self.rounds += 1
        round_ = self.leases.round()
        self.last_round = round_
        desired = {
            tenant
            for tenant in self.tenants_source()
            if self.partition_for(tenant) in round_.owned
        }
        adopted_now = desired - set(self.handoffs)
        for tenant in sorted(adopted_now):
            self._adopt(tenant)
        for tenant in sorted(set(self.handoffs) - desired):
            self._release(tenant)
        for tenant, handoff in self.handoffs.items():
            # an adoption mid-round has observed ZERO full ticks of its
            # fleet: the warm-up starts counting NEXT round
            if tenant not in adopted_now:
                handoff.on_tick()
        self._publish(round_)
        return round_

    def _adopt(self, tenant: str) -> None:
        validator = (
            self.validator_for(tenant)
            if self.validator_for is not None else self.validator
        )
        handoff = TenantHandoff(
            tenant,
            journal_dir=self.journal_dir_for(tenant),
            validator=validator,
            warmup_ticks=self.warmup_ticks,
            clock=self.clock,
        )
        self.handoffs[tenant] = handoff
        self.adopted_total += 1
        if self._c_adopted is not None:
            self._c_adopted.inc("-", "-")
        if self._g_replay is not None:
            self._g_replay.set("-", "-", handoff.replay_seconds)
        self._recorder_or_default().record(
            HANDOFF_EVENT,
            tenant=tenant,
            replica=self.replica_id,
            partition=self.partition_for(tenant),
            generation=handoff.generation,
        )
        logger().info(
            "replication: %s adopted tenant %s (partition %d, fence "
            "generation %d, replay %.3fs)",
            self.replica_id, tenant, self.partition_for(tenant),
            handoff.generation, handoff.replay_seconds,
        )

    def _release(self, tenant: str) -> None:
        handoff = self.handoffs.pop(tenant, None)
        if handoff is None:
            return
        handoff.release()
        self.released_total += 1
        if self._c_released is not None:
            self._c_released.inc("-", "-")

    def _publish(self, round_: LeaseRound) -> None:
        if self._g_owned is None:
            return
        serving = sum(1 for h in self.handoffs.values() if h.ready())
        self._g_owned.set("-", "-", float(len(round_.owned)))
        self._g_live.set("-", "-", float(len(round_.live)))
        self._c_rounds.inc("-", "-")
        for _ in range(round_.failures):
            self._c_failures.inc("-", "-")
        self._g_serving.set("-", "-", float(serving))
        self._g_warming.set(
            "-", "-", float(len(self.handoffs) - serving)
        )

    # -- surfaces ----------------------------------------------------------

    def slo_source(self) -> Optional[bool]:
        """Self-SLO control-health source: True = BAD (a held lease
        failed to renew this round, or a handoff is still warming —
        the plane is mid-failover), False = healthy, None = no round
        yet (contributes no event)."""
        if self.last_round is None:
            return None
        warming = any(not h.ready() for h in self.handoffs.values())
        return bool(self.last_round.failures) or warming

    def scoreboard(self) -> dict:
        """The /debug/replicas document: this replica's identity, the
        live set, per-partition holders, and per-tenant handoff state."""
        round_ = self.last_round
        return {
            "replica": self.replica_id,
            "partitions": self.partitions,
            "rounds": self.rounds,
            "live": list(round_.live) if round_ else [],
            "owned": sorted(round_.owned) if round_ else [],
            "lease_failures": round_.failures if round_ else 0,
            "holders": {
                str(p): self.leases.holder_of(p)
                for p in range(self.partitions)
            },
            "tenants": {
                tenant: {
                    "partition": self.partition_for(tenant),
                    "state": handoff.state,
                    "generation": handoff.generation,
                    "warmup_remaining": handoff.warmup_remaining,
                    "replay_seconds": round(handoff.replay_seconds, 6),
                }
                for tenant, handoff in sorted(self.handoffs.items())
            },
            "adopted_total": self.adopted_total,
            "released_total": self.released_total,
        }

    def close(self) -> None:
        """Graceful shutdown: release every tenant (checkpointing their
        journals) and surrender the leases so successors take over
        without waiting out the lease duration."""
        for tenant in sorted(self.handoffs):
            self._release(tenant)
        self.leases.release_all()

    def _recorder_or_default(self):
        if self._recorder is not None:
            return self._recorder
        from karpenter_tpu.observability import default_flight_recorder

        return default_flight_recorder()
