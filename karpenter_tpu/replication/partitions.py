"""Tenant -> partition -> replica assignment.

Two pure functions, both keyed on a seeded stable digest (blake2b —
`hash()` is salted per process, useless for cross-replica agreement):

  * partition_of(tenant, partitions) — which partition a tenant lives
    in. Stable across restarts and replica-set changes: a tenant only
    moves when the partition COUNT changes (an operator action).
  * rendezvous_rank(partition, replicas) — highest-random-weight
    ranking of candidate replicas for one partition. Every replica
    computes the same ranking from the same inputs with no
    coordination, and removing one replica only reassigns the
    partitions it owned (the classic rendezvous property) — the
    surviving assignments do not churn.

Stickiness lives a layer up (lease.py): ranking decides who CONTENDS
for a vacant or expired partition lease; it never evicts a live holder.
"""

from __future__ import annotations

import hashlib
from typing import List, Sequence


def _digest(key: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big"
    )


def partition_of(tenant: str, partitions: int) -> int:
    """The partition `tenant` hashes into (0 <= p < partitions)."""
    if partitions <= 0:
        raise ValueError(f"partitions must be positive: {partitions}")
    return _digest(f"tenant:{tenant}") % partitions


def rendezvous_rank(partition: int, replicas: Sequence[str]) -> List[str]:
    """Replicas ranked highest-random-weight for one partition: index 0
    is the preferred owner; each later entry is the failover successor
    if everything before it is dead. Deterministic and agreed-upon by
    every replica that sees the same candidate set."""
    return sorted(
        replicas,
        key=lambda replica: (_digest(f"p{partition}@{replica}"), replica),
        reverse=True,
    )
