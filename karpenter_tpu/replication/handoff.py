"""TenantHandoff: the fenced adoption of ONE tenant by a new owner.

The exactly-once contract across a handoff is three moves, all riding
the PR 7 recovery machinery against the tenant's own journal dir
(tenancy/registry.py `journal_dir_for`, `<root>/tenants/<id>`):

  1. CLAIM — construct the tenant's RecoveryManager: it bumps the
     journaled fence generation durably (flock'd read-modify-write of
     `<dir>/FENCE`) BEFORE anything can actuate, and arms the zombie
     self-fence on the journal — the deposed owner's journal handle
     goes read-only the moment the claim lands.
  2. REPLAY — the same construction replays checkpoint + journal into
     the per-subsystem tables, so the new owner resumes the deposed
     owner's in-flight intent instead of re-deriving it. The provider's
     FenceValidator is seeded with the fresh generation: the deposed
     owner's in-flight `set_replicas`, stamped with the old generation,
     is rejected with `FenceRejected` — not applied.
  3. WARM-UP — the conservative hold: `allow_disruption()` stays False
     (and `ready()` reports warming) until `warmup_ticks` full ticks
     confirm fleet state, exactly the restarted-controller posture.

Without a journal dir (fencing not configured) adoption degrades to
the bookkeeping-only form: no generation, no replay, warm-up still held
— the unfenced deployment keeps its pre-replication semantics.
"""

from __future__ import annotations

import time as _time
from typing import Callable, Optional

from karpenter_tpu.recovery.fence import FenceToken


class TenantHandoff:
    """One tenant's ownership record on ONE replica: claim -> replay ->
    warm-up -> serving, then `release()` when the partition moves."""

    def __init__(
        self,
        tenant: str,
        journal_dir: Optional[str] = None,
        validator=None,
        warmup_ticks: int = 1,
        clock: Callable[[], float] = _time.time,
    ):
        self.tenant = tenant
        self.journal_dir = journal_dir
        self.released = False
        self.recovery = None
        self.replay_seconds = 0.0
        t0 = _time.perf_counter()
        if journal_dir:
            from karpenter_tpu.recovery import RecoveryManager

            # the claim: fence bump + journal replay + warm-up arming,
            # all in construction (recovery/manager.py boot sequence)
            self.recovery = RecoveryManager(
                journal_dir, clock=clock, warmup_ticks=warmup_ticks
            )
            self.replay_seconds = _time.perf_counter() - t0
            if validator is not None:
                validator.observe(self.recovery.fence.generation)
            self._warmup_remaining = self.recovery.warmup_remaining
        else:
            # unfenced: hold the conservative warm-up anyway — the new
            # owner has observed zero ticks of this tenant's fleet
            self._warmup_remaining = max(0, int(warmup_ticks))

    @property
    def generation(self) -> int:
        return self.recovery.fence.generation if self.recovery else 0

    def token(self) -> Optional[FenceToken]:
        """The stamp this owner's actuations carry (None when
        unfenced)."""
        return self.recovery.fence.token() if self.recovery else None

    def on_tick(self) -> None:
        """One full serving tick completed: advance the warm-up."""
        if self.recovery is not None:
            self.recovery.on_tick()
            self._warmup_remaining = self.recovery.warmup_remaining
        elif self._warmup_remaining > 0:
            self._warmup_remaining -= 1

    @property
    def warmup_remaining(self) -> int:
        return self._warmup_remaining

    def ready(self) -> bool:
        """Fully serving: warm-up drained, not released."""
        return not self.released and self._warmup_remaining <= 0

    def allow_disruption(self) -> bool:
        """The per-tenant disruption gate (consolidation/preemption must
        not plan against a fleet this owner has not yet confirmed)."""
        return self.ready()

    @property
    def state(self) -> str:
        if self.released:
            return "released"
        return "serving" if self._warmup_remaining <= 0 else "warmup"

    def release(self) -> None:
        """The partition moved away (or the replica is shutting down):
        checkpoint + close the journal so the successor replays one
        compact file. Idempotent."""
        if self.released:
            return
        self.released = True
        if self.recovery is not None:
            self.recovery.close()
