"""PartitionLeaseManager: one CAS lease per tenant partition.

Built entirely on LeaderElector (leaderelection.py) — same Lease kind,
same store transport, same CAS-on-resourceVersion invariant — so the
partition plane inherits the monotonic/skew clock discipline and the
`lease.acquire.*` / `lease.renew.*` chaos seams for free. Two lease
families per replica:

  * `karpenter-replica-<id>`   — the replica's HEARTBEAT. Only its own
    replica renews it; every replica reads all of them to agree on the
    live-replica set the rendezvous ranking runs over. A replica whose
    heartbeat lapses is dead to the fleet, whatever its process thinks.
  * `karpenter-partition-<p>`  — ownership of partition p. STICKY: the
    holder renews every round and is never evicted by a ranking change
    (a new replica joining does not churn assignments); a NON-holder
    contends only when (a) it is the top-ranked LIVE replica for p and
    (b) the current lease is vacant or expired. One deterministic
    contender per vacant partition keeps CAS conflicts to the genuine
    races.

`round()` is the whole protocol: heartbeat, read liveness, contend,
renew — returning the ownership delta the ReplicatedControlPlane turns
into fenced tenant handoffs.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from karpenter_tpu.leaderelection import (
    DEFAULT_LEASE_DURATION,
    DEFAULT_LEASE_NAMESPACE,
    DEFAULT_SKEW_TOLERANCE,
    LeaderElector,
)
from karpenter_tpu.replication.partitions import rendezvous_rank

HEARTBEAT_PREFIX = "karpenter-replica-"
PARTITION_PREFIX = "karpenter-partition-"


@dataclass
class LeaseRound:
    """The outcome of one `round()`: the ownership delta drives
    handoffs, the counters drive the karpenter_replica_* gauges."""

    owned: Set[int] = field(default_factory=set)
    gained: Set[int] = field(default_factory=set)
    lost: Set[int] = field(default_factory=set)
    live: List[str] = field(default_factory=list)
    failures: int = 0  # rounds a lease write/contend failed (partition)


class PartitionLeaseManager:
    def __init__(
        self,
        store,
        replica_id: str,
        partitions: int,
        lease_duration: float = DEFAULT_LEASE_DURATION,
        clock=_time.time,
        monotonic=None,
        skew_tolerance: float = DEFAULT_SKEW_TOLERANCE,
        namespace: str = DEFAULT_LEASE_NAMESPACE,
    ):
        if partitions <= 0:
            raise ValueError(f"partitions must be positive: {partitions}")
        self.store = store
        self.replica_id = replica_id
        self.partitions = partitions
        self.lease_duration = lease_duration
        self.clock = clock
        self.skew_tolerance = skew_tolerance
        self.namespace = namespace

        def elector(name: str) -> LeaderElector:
            return LeaderElector(
                store,
                identity=replica_id,
                name=name,
                namespace=namespace,
                lease_duration=lease_duration,
                clock=clock,
                monotonic=monotonic,
                skew_tolerance=skew_tolerance,
            )

        self.heartbeat = elector(f"{HEARTBEAT_PREFIX}{replica_id}")
        self.electors: Dict[int, LeaderElector] = {
            p: elector(f"{PARTITION_PREFIX}{p}") for p in range(partitions)
        }
        self.owned: Set[int] = set()
        self._rounds = 0

    # -- liveness ----------------------------------------------------------

    def live_replicas(self) -> List[str]:
        """Replica ids with an unexpired heartbeat lease (wall clock +
        skew margin), always including ourselves — a replica that can
        run this code is alive even if its first heartbeat write has
        not landed yet."""
        now = self.clock()
        live = {self.replica_id}
        for lease in self.store.list("Lease", namespace=self.namespace):
            if not lease.metadata.name.startswith(HEARTBEAT_PREFIX):
                continue
            fresh = now <= (
                lease.renew_time
                + lease.lease_duration
                + self.skew_tolerance
            )
            if lease.holder and fresh:
                live.add(lease.holder)
        return sorted(live)

    # -- the per-tick protocol ---------------------------------------------

    def round(self) -> LeaseRound:
        """One lease round: heartbeat, read the live set, renew what we
        hold (sticky), contend for vacant/expired partitions we are the
        top-ranked live replica for. Returns the ownership delta."""
        self.heartbeat.try_acquire()
        live = self.live_replicas()
        self._rounds += 1
        owned: Set[int] = set()
        failures = 0
        for partition, elector in self.electors.items():
            holding = partition in self.owned
            # the first round only heartbeats + renews: co-booting
            # replicas see each other's heartbeats before anyone
            # contends, so a simultaneous start spreads partitions by
            # rendezvous instead of first-ticker-takes-all
            contend = holding or (
                self._rounds > 1
                and rendezvous_rank(partition, live)[0] == self.replica_id
            )
            if not contend:
                continue
            if elector.try_acquire():
                owned.add(partition)
            elif holding:
                failures += 1
        result = LeaseRound(
            owned=owned,
            gained=owned - self.owned,
            lost=self.owned - owned,
            live=live,
            failures=failures,
        )
        self.owned = owned
        return result

    def release_all(self) -> None:
        """Graceful shutdown: surrender heartbeat + every held
        partition so successors take over without waiting out the
        leases."""
        for partition in sorted(self.owned):
            self.electors[partition].release()
        self.owned = set()
        self.heartbeat.release()

    def owns(self, partition: int) -> bool:
        return partition in self.owned

    def holder_of(self, partition: int) -> Optional[str]:
        """Who the store says owns `partition` right now (diagnostics +
        the /debug/replicas scoreboard)."""
        lease = self.store.try_get(
            "Lease", self.namespace, f"{PARTITION_PREFIX}{partition}"
        )
        return lease.holder or None if lease is not None else None
