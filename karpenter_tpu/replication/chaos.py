"""The failover chaos family: plan builders over the replication seams.

The fault registry (faults/registry.py) already gives every plan a
seeded RNG stream; these helpers just spell the failover scenarios'
recurring shapes against the replication points:

  lease.acquire.<replica>   a candidate's takeover/first-acquire round
  lease.renew.<replica>     a holder's renew round
  replica.crash.<replica>   the top of the replica tick (plane.on_tick)

`partition_plans` = the replica can reach nothing (both lease verbs
fail — the network-partition analog: its heartbeat lapses, its
partitions expire, survivors adopt). `crash_plan` = the replica dies
between ticks (ProcessCrash out of on_tick; the harness abandons it).
`SkewedClock` = a stepped wall clock for the clock-skew scenarios —
deliberately NOT a registry mode: skew is not an exception, it is a
lying clock, so it wraps the clock seam directly.
"""

from __future__ import annotations

from typing import Callable, List


def partition_plans(
    registry,
    replica_id: str = "*",
    times=None,
    probability: float = 1.0,
) -> List:
    """Install error plans cutting `replica_id` (or every replica, the
    default glob) off from the lease store: acquire AND renew rounds
    fail while the plans last. Returns the plans (their `fired` counts
    are the scenario's partition-duration evidence)."""
    return [
        registry.plan(
            f"lease.{verb}.{replica_id}",
            mode="error",
            times=times,
            probability=probability,
            code="LeasePartitioned",
            message=f"injected store partition: lease {verb} unreachable",
        )
        for verb in ("acquire", "renew")
    ]


def crash_plan(registry, replica_id: str, times: int = 1):
    """Install the replica-death plan: ProcessCrash out of the NEXT
    `times` replica ticks (plane.on_tick's kill point). The harness
    catches it and abandons the incarnation — the SIGKILL analog."""
    return registry.plan(
        f"replica.crash.{replica_id}", mode="crash", times=times
    )


class SkewedClock:
    """A wall clock stepped by `offset_s`, for the clock-skew plans: a
    replica reading this clock stamps skewed renew_times while its
    monotonic source stays honest — exactly the failure the
    LeaderElector's monotonic expiry + skew margin must absorb.
    `step()` changes the offset mid-scenario (the NTP-jump analog)."""

    def __init__(self, base: Callable[[], float], offset_s: float = 0.0):
        self.base = base
        self.offset_s = offset_s

    def step(self, delta_s: float) -> None:
        self.offset_s += delta_s

    def __call__(self) -> float:
        return self.base() + self.offset_s
