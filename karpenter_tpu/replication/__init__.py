"""Replicated control plane: leader-elected solver replicas with fenced
tenant handoff (ROADMAP item 2, docs/resilience.md "Replicated control
plane").

The MultiTenantScheduler batches 1k tenants through one SolverService —
but one process is one blast radius. This package partitions tenants
across N replicas and makes replica death a non-event:

  * partitions.py — stable tenant -> partition hashing plus rendezvous
    (highest-random-weight) ranking of replicas per partition;
  * lease.py      — PartitionLeaseManager: one CAS lease per partition
    on the existing LeaderElector, plus a per-replica heartbeat lease
    that defines the live-replica set the rendezvous ranks over;
  * handoff.py    — TenantHandoff: the fenced adoption of one tenant
    (claim the journaled fence generation, replay the journal, hold the
    conservative warm-up) and the exactly-once audit trail;
  * plane.py      — ReplicatedControlPlane: the per-replica tick (lease
    round -> ownership diff -> adoptions/releases), the
    karpenter_replica_* / karpenter_handoff_* gauges, the
    /debug/replicas scoreboard, and the self-SLO source;
  * chaos.py      — the failover chaos family: store-partition plans
    over the lease.acquire/lease.renew points, replica.crash kill
    plans, and the SkewedClock used by clock-skew scenarios.
"""

from karpenter_tpu.replication.chaos import (
    SkewedClock,
    crash_plan,
    partition_plans,
)
from karpenter_tpu.replication.handoff import TenantHandoff
from karpenter_tpu.replication.lease import PartitionLeaseManager
from karpenter_tpu.replication.partitions import (
    partition_of,
    rendezvous_rank,
)
from karpenter_tpu.replication.plane import ReplicatedControlPlane

__all__ = [
    "PartitionLeaseManager",
    "ReplicatedControlPlane",
    "SkewedClock",
    "TenantHandoff",
    "crash_plan",
    "partition_of",
    "partition_plans",
    "rendezvous_rank",
]
