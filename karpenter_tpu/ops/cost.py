"""Batched multi-objective cost x SLO-risk refinement of fleet decisions.

The decision kernel (ops/decision.py) answers "how many replicas does
the observed load need" — cost is invisible and SLO risk is implicit in
the metric targets. This kernel is the second half of a multi-objective
solve (docs/cost.md, PAPERS.md "An SLO Driven and Cost-Aware Autoscaling
Framework for Kubernetes"): given the whole fleet's base decisions, it
evaluates K candidate replica counts per autoscaler IN ONE array program
and picks, per row, the count minimizing

    score(n) = violationCostWeight * risk(n)  +  n * unitHourlyCost

where risk(n) is the normalized one-sigma demand shortfall — the
fraction of pessimistic demand (forecast mean + one forecast sigma, the
PR 5 forecast distribution as the risk input; observed value with sigma
0 when no forecast) that n replicas' SLO capacity (n * sloTarget) cannot
absorb, maxed over the autoscaler's metrics. A hard budget
(spec.behavior.slo.maxHourlyCost) caps candidates at the affordable
replica ceiling (never below minReplicas — the budget trims headroom,
it must not take a workload below its declared floor).

Wire-compat contract (property-pinned in tests/test_cost.py): a row
whose slo_valid is False — no spec.behavior.slo — comes out EXACTLY as
it went in, and a valid row with violationCostWeight 0 and no budget cap
scores minimal at candidate 0 (ties break to the first index), so
absent/zero cost operands reproduce today's decisions bit-identically.

Parity contract (pinned bit-for-bit by tests/test_cost.py): the jitted
kernel and `cost_numpy` produce IDENTICAL f32 bits, the same discipline
as forecast/models.py — the one multiply-accumulate (the score line) is
written in single-mul `a * b + c` form, which XLA:CPU contracts into one
FMA, reproduced on host by a float64 round-trip; every other operation
(mul, div, ceil, floor, clip, max, argmin-first-index) is IEEE-exact
elementwise on both sides, and the only reduction (max over the metric
axis) is order-free.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_tpu.ops.decision import _I32_SAFE_MAX, _I32_SAFE_MIN

# Candidate ladder width: each row scores replica counts
# base..base+CANDIDATES-1 (clipped to bounds and the budget cap). Static
# so the whole fleet stays one compiled program; 8 covers a one-sigma
# demand excursion of 8 replicas per tick — larger jumps converge over
# consecutive ticks exactly like the reactive path does.
CANDIDATES = 8

_EPS = np.float32(1e-6)
_ZERO = np.float32(0.0)
_ONE = np.float32(1.0)


@jax.tree_util.register_dataclass
@dataclass
class CostInputs:
    """Structure-of-arrays cost/SLO view of the fleet, padded to the
    decision kernel's row bucket (rows beyond the live fleet carry
    slo_valid=False and pass through untouched)."""

    base_desired: jax.Array  # i32[N] the decide() output being refined
    min_replicas: jax.Array  # i32[N]
    max_replicas: jax.Array  # i32[N]
    unit_cost: jax.Array  # f32[N] hourly cost per replica (0 = unknown)
    slo_weight: jax.Array  # f32[N] violationCostWeight ($/h at risk 1.0)
    max_hourly_cost: jax.Array  # f32[N] hard budget (0 = uncapped)
    slo_valid: jax.Array  # bool[N] row carries spec.behavior.slo
    slo_target: jax.Array  # f32[N, M] per-replica SLO capacity per metric
    demand_mu: jax.Array  # f32[N, M] demand point (forecast or observed)
    demand_sigma: jax.Array  # f32[N, M] forecast spread (0 = none)
    demand_valid: jax.Array  # bool[N, M]


@jax.tree_util.register_dataclass
@dataclass
class CostOutputs:
    desired: jax.Array  # i32[N] multi-objective choice (== base when !valid)
    expected_hourly: jax.Array  # f32[N] desired * unit_cost
    violation_risk: jax.Array  # f32[N] risk at the chosen count
    headroom: jax.Array  # i32[N] one-sigma demand replicas beyond desired
    cost_limited: jax.Array  # bool[N] budget capped below the base desire
    slo_raised: jax.Array  # bool[N] risk term bought replicas above base


def _to_i32(x: jax.Array) -> jax.Array:
    return jnp.clip(
        x, jnp.float32(_I32_SAFE_MIN), jnp.float32(_I32_SAFE_MAX)
    ).astype(jnp.int32)


def cost_decide(inputs: CostInputs) -> CostOutputs:
    """The batched refinement program (module docstring)."""
    base = inputs.base_desired.astype(jnp.float32)  # [N]
    min_f = inputs.min_replicas.astype(jnp.float32)
    max_f = inputs.max_replicas.astype(jnp.float32)

    # candidate replica counts: base + 0..K-1, bounded by [min, max] and
    # the affordable ceiling floor(maxHourlyCost / unitCost) — the
    # budget never forces a row below its minReplicas floor
    offsets = jnp.arange(CANDIDATES, dtype=jnp.float32)  # [K]
    cap_on = (
        inputs.slo_valid
        & (inputs.unit_cost > 0)
        & (inputs.max_hourly_cost > 0)
    )
    safe_unit = jnp.where(inputs.unit_cost > 0, inputs.unit_cost, _ONE)
    cap = jnp.floor(inputs.max_hourly_cost / safe_unit)
    hi = jnp.where(cap_on, jnp.minimum(max_f, jnp.maximum(cap, min_f)), max_f)
    cand = jnp.clip(
        base[:, None] + offsets[None, :], min_f[:, None], hi[:, None]
    )  # [N, K]

    # one-sigma pessimistic demand vs candidate SLO capacity, as a
    # normalized shortfall fraction in [0, 1], maxed over valid metrics
    demand_hi = inputs.demand_mu + inputs.demand_sigma  # [N, M]
    capacity = cand[:, :, None] * inputs.slo_target[:, None, :]  # [N, K, M]
    denom = jnp.maximum(demand_hi, _EPS)[:, None, :]
    short = jnp.clip((demand_hi[:, None, :] - capacity) / denom, _ZERO, _ONE)
    short = jnp.where(inputs.demand_valid[:, None, :], short, _ZERO)
    risk = jnp.max(short, axis=2)  # [N, K]

    # the multi-objective score (single-mul FMA form — module docstring)
    hourly = cand * inputs.unit_cost[:, None]  # [N, K]
    score = inputs.slo_weight[:, None] * risk + hourly

    # argmin ties break to the FIRST (cheapest) candidate on both jnp
    # and np — the wire-compat anchor: weight 0 scores flat-or-rising,
    # so candidate 0 (the base decision) wins exactly
    k_star = jnp.argmin(score, axis=1)  # [N]
    take = lambda a: jnp.take_along_axis(a, k_star[:, None], axis=1)[:, 0]
    chosen = take(cand)
    chosen_risk = take(risk)

    # warm-pool sizing signal (docs/cost.md "Warm pools"): how many
    # replicas the one-sigma demand needs BEYOND the chosen count —
    # pre-provisioned headroom sized by forecast risk
    needed = jnp.ceil(demand_hi / jnp.maximum(inputs.slo_target, _EPS))
    needed = jnp.where(inputs.demand_valid, needed, _ZERO)
    headroom = jnp.maximum(jnp.max(needed, axis=1) - chosen, _ZERO)

    valid = inputs.slo_valid
    desired = jnp.where(valid, chosen, base)
    return CostOutputs(
        desired=_to_i32(desired),
        expected_hourly=desired * inputs.unit_cost,
        violation_risk=jnp.where(valid, chosen_risk, _ZERO),
        headroom=_to_i32(jnp.where(valid, headroom, _ZERO)),
        cost_limited=cap_on & (base > hi),
        slo_raised=valid & (chosen > base),
    )


cost_jit = jax.jit(cost_decide)


# -- numpy mirror -------------------------------------------------------------
# The parity oracle AND the requested-numpy backend (CPU auto-resolution,
# the gRPC process split) — every line mirrors the kernel's op order;
# _fma reproduces XLA:CPU's mul-add contraction exactly
# (forecast/models.py discipline).


def _fma(a, b, c):
    return (
        np.asarray(a, np.float64) * np.asarray(b, np.float64)
        + np.asarray(c, np.float64)
    ).astype(np.float32)


def cost_numpy(inputs: CostInputs) -> CostOutputs:
    """Host mirror of cost_decide() — bit-identical f32 outputs (module
    docstring parity contract)."""
    base = np.asarray(inputs.base_desired, np.int32).astype(np.float32)
    min_f = np.asarray(inputs.min_replicas, np.int32).astype(np.float32)
    max_f = np.asarray(inputs.max_replicas, np.int32).astype(np.float32)
    unit = np.asarray(inputs.unit_cost, np.float32)
    weight = np.asarray(inputs.slo_weight, np.float32)
    budget = np.asarray(inputs.max_hourly_cost, np.float32)
    valid = np.asarray(inputs.slo_valid, bool)
    slo_target = np.asarray(inputs.slo_target, np.float32)
    mu = np.asarray(inputs.demand_mu, np.float32)
    sigma = np.asarray(inputs.demand_sigma, np.float32)
    dvalid = np.asarray(inputs.demand_valid, bool)

    offsets = np.arange(CANDIDATES, dtype=np.float32)
    cap_on = valid & (unit > 0) & (budget > 0)
    safe_unit = np.where(unit > 0, unit, _ONE).astype(np.float32)
    cap = np.floor(budget / safe_unit).astype(np.float32)
    hi = np.where(
        cap_on, np.minimum(max_f, np.maximum(cap, min_f)), max_f
    ).astype(np.float32)
    cand = np.clip(
        base[:, None] + offsets[None, :], min_f[:, None], hi[:, None]
    ).astype(np.float32)

    demand_hi = (mu + sigma).astype(np.float32)
    denom = np.maximum(demand_hi, _EPS)[:, None, :].astype(np.float32)
    # demand_hi - cand*slo_target: XLA:CPU contracts the subtract-of-a-
    # product into one negated FMA, mirrored by the f64 round-trip
    # (_fma broadcasts like the kernel's [N,K,1] x [N,1,M] operands)
    shortfall = _fma(
        -cand[:, :, None], slo_target[:, None, :], demand_hi[:, None, :]
    )
    short = np.clip((shortfall / denom).astype(np.float32), _ZERO, _ONE)
    short = np.where(dvalid[:, None, :], short, _ZERO).astype(np.float32)
    risk = np.max(short, axis=2)

    hourly = (cand * unit[:, None]).astype(np.float32)
    score = _fma(weight[:, None], risk, hourly)

    k_star = np.argmin(score, axis=1)
    rows = np.arange(len(base))
    chosen = cand[rows, k_star]
    chosen_risk = risk[rows, k_star]

    needed = np.ceil(
        (demand_hi / np.maximum(slo_target, _EPS)).astype(np.float32)
    ).astype(np.float32)
    needed = np.where(dvalid, needed, _ZERO).astype(np.float32)
    headroom = np.maximum(np.max(needed, axis=1) - chosen, _ZERO)

    desired = np.where(valid, chosen, base).astype(np.float32)

    def to_i32(x):
        return np.clip(
            x, np.float32(_I32_SAFE_MIN), np.float32(_I32_SAFE_MAX)
        ).astype(np.int32)

    return CostOutputs(
        desired=to_i32(desired),
        expected_hourly=(desired * unit).astype(np.float32),
        violation_risk=np.where(valid, chosen_risk, _ZERO).astype(np.float32),
        headroom=to_i32(np.where(valid, headroom, _ZERO)),
        cost_limited=cap_on & (base > hi),
        slo_raised=valid & (chosen > base),
    )
