"""Pallas TPU kernel: the fused hot stage of the pending-pods bin-pack.

The XLA path (ops/binpack.py) materializes several [P, T] intermediates in
HBM (feasibility, dominant share, membership, bucket index — ~120 MB each at
the 100k x 300 bench scale). This kernel fuses the whole per-pod stage into
one VMEM-resident pass over pod tiles:

  feasibility (resource compare + taint/label bitset matmuls on the MXU)
  -> first-feasible assignment (min-index reduction)
  -> dominant-share bucket quantization
  -> histogram [T, B] + demand [T, R] accumulation (transpose matmuls on
     the MXU, accumulated across sequential grid steps in VMEM)

so the only HBM traffic is the structure-of-arrays inputs once and the tiny
[T, *] outputs. The shelf-BFD node-count scan stays in XLA (ops/binpack.py
_shelf_bfd): it is O(B^2) on [T, B] state — not worth a kernel.

reference: this signal is the one the reference STUBS
(pkg/metrics/producers/pendingcapacity/producer.go:29-31, design intent in
docs/designs/DESIGN.md "Pending Pods"); there is no reference kernel to
mirror — the algorithm contract is pinned by ops/binpack.py and its scalar
oracle, and this kernel must match it bit-for-bit (tests/test_pallas_binpack).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from karpenter_tpu.ops.binpack import BinPackInputs

DEFAULT_TILE_P = 512
_LANE = 128


def _round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


def _kernel(
    req_ref,  # f32[TILE_P, R]
    valid_ref,  # f32[TILE_P, 1]  (bool as f32: VMEM-friendly layout)
    intol_ref,  # f32[TILE_P, K]
    required_ref,  # f32[TILE_P, L]
    weight_ref,  # f32[TILE_P, 1] row multiplicity (1.0 when undeduplicated)
    alloc_t_ref,  # f32[R_pad, T] — transposed so resource rows are slices
    taints_ref,  # f32[T, K]
    labels_ref,  # f32[T, L]
    *rest,  # [forbidden_ref f32[TILE_P, T] when has_forbidden,]
    #         [score_ref f32[TILE_P, T] when has_score,]
    #         [exclusive_ref f32[TILE_P, 1] when has_exclusive,]
    #         assigned_ref i32[TILE_P, 1], hist_ref f32[T, B],
    #         demand_ref f32[T, R]
    buckets: int,
    n_resources: int,
    has_forbidden: bool = False,
    has_score: bool = False,
    has_exclusive: bool = False,
):
    rest = list(rest)
    forbidden_ref = rest.pop(0) if has_forbidden else None
    score_ref = rest.pop(0) if has_score else None
    exclusive_ref = rest.pop(0) if has_exclusive else None
    assigned_ref, hist_ref, demand_ref = rest
    # Everything stays 2D: Mosaic lowers static row/column slices and 2D
    # broadcasts, but not the gathers that 1D intermediates / fancy
    # indexing produce.
    step = pl.program_id(0)

    req = req_ref[:]  # [TILE_P, R]
    alloc_t = alloc_t_ref[:]  # [R_pad, T]
    tile_p = req.shape[0]
    n_groups = alloc_t.shape[1]

    # --- feasibility [TILE_P, T] ---------------------------------------
    fits = jnp.ones((tile_p, n_groups), jnp.float32)
    for r in range(n_resources):  # R tiny+static: unrolled by design
        fits = fits * (
            req[:, r : r + 1] <= alloc_t[r : r + 1, :]
        ).astype(jnp.float32)
    # zero-alloc group: nothing fits (padded resource rows are zero)
    nonempty = jnp.max(alloc_t, axis=0, keepdims=True) > 0  # [1, T]
    fits = fits * nonempty.astype(jnp.float32)

    # taints / required labels as bitset matmuls -> MXU
    taint_violations = jax.lax.dot_general(
        intol_ref[:],
        taints_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [TILE_P, T]
    label_violations = jax.lax.dot_general(
        required_ref[:],
        1.0 - labels_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [TILE_P, T]
    fits = fits * (taint_violations < 0.5) * (label_violations < 0.5)
    if forbidden_ref is not None:  # required node affinity (host-evaluated)
        fits = fits * (1.0 - forbidden_ref[:])
    fits = fits * valid_ref[:]  # [TILE_P, 1] broadcast

    feasible = fits > 0.5  # bool[TILE_P, T]

    # --- assignment: min feasible column index, or (with preference
    # scores) the min index among max-score feasible groups — f32 score
    # equality is exact because scores are integer weight sums ----------
    col = jax.lax.broadcasted_iota(jnp.int32, (tile_p, n_groups), 1)
    if score_ref is not None:
        big = jnp.float32(3.4e38)
        masked = jnp.where(feasible, score_ref[:], -big)
        best = jnp.max(masked, axis=1, keepdims=True)  # [TILE_P, 1]
        candidate = feasible & (masked == best)
    else:
        candidate = feasible
    first = jnp.min(
        jnp.where(candidate, col, n_groups), axis=1, keepdims=True
    )  # [TILE_P, 1], == n_groups when none
    has = first < n_groups  # [TILE_P, 1]
    assigned_ref[:] = jnp.where(has, first, -1)

    member = (col == first) & has  # one-hot [TILE_P, T]
    member_f = member.astype(jnp.float32)
    # weighted membership: the hist/demand accumulators count each row
    # `weight` times (rows are deduplicated pod shapes)
    member_w = member_f * weight_ref[:]  # [TILE_P, 1] broadcast

    # --- dominant share of the assigned group -> bucket one-hot --------
    share = jnp.zeros((tile_p, n_groups), jnp.float32)
    for r in range(n_resources):
        a = alloc_t[r : r + 1, :]  # [1, T]
        big = jnp.float32(3.4e38)  # stand-in for inf: req>0 on 0-alloc
        s = jnp.where(a > 0, req[:, r : r + 1] / jnp.maximum(a, 1e-30), big)
        s = jnp.where((a <= 0) & (req[:, r : r + 1] <= 0), 0.0, s)
        share = jnp.maximum(share, s)
    share_assigned = jnp.sum(
        member_f * share, axis=1, keepdims=True
    )  # [TILE_P, 1]
    bucket = jnp.clip(
        jnp.ceil(share_assigned * buckets).astype(jnp.int32), 1, buckets
    )  # [TILE_P, 1]
    if exclusive_ref is not None:
        # hostname self-anti-affinity: the pod takes a whole node
        bucket = jnp.where(exclusive_ref[:] > 0.5, buckets, bucket)
    bcol = jax.lax.broadcasted_iota(jnp.int32, (tile_p, buckets), 1)
    bucket_onehot = ((bcol == (bucket - 1)) & has).astype(
        jnp.float32
    )  # [TILE_P, B]

    # --- accumulate [T, B] histogram + [T, R] demand (MXU transposes) ---
    # Both accumulators pin precision=HIGHEST: Mosaic's default MXU path
    # rounds f32 operands to bf16, and member_w carries pod multiplicities
    # (dedup weights reach ~1e4 at bench scale — past bf16's 8-bit
    # mantissa), so the default would miscount the histogram and drift the
    # demand sum. ops/binpack.py's einsum is pinned the same way.
    hist_update = jax.lax.dot_general(
        member_w,
        bucket_onehot,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )  # [T, B]
    demand_update = jax.lax.dot_general(
        member_w,
        req,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )  # [T, R]

    @pl.when(step == 0)
    def _():
        hist_ref[:] = jnp.zeros_like(hist_ref)
        demand_ref[:] = jnp.zeros_like(demand_ref)

    hist_ref[:] += hist_update
    demand_ref[:] += demand_update


@partial(
    jax.jit, static_argnames=("buckets", "tile_p", "interpret")
)
def fused_assign(
    inputs: BinPackInputs,
    buckets: int,
    tile_p: int = DEFAULT_TILE_P,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused assignment stage on TPU via Pallas.

    Returns (assigned i32[P], histogram i32[T, B], demand f32[T, R]) with
    identical semantics to the corresponding ops/binpack.py stage. P is
    padded to tile_p and T/K/L to the 128-lane width internally; padding is
    invisible in the outputs (padded pods are invalid, padded groups have
    zero allocatable so nothing fits them).
    """
    if tile_p % 8 != 0:
        raise ValueError(f"tile_p must be a multiple of 8, got {tile_p}")
    n_pods, n_resources = inputs.pod_requests.shape
    n_groups = inputs.group_allocatable.shape[0]
    n_taints = inputs.pod_intolerant.shape[1]
    n_labels = inputs.pod_required.shape[1]

    pad_p = _round_up(max(n_pods, 1), tile_p)
    pad_t = _round_up(max(n_groups, 1), _LANE)
    pad_k = _round_up(max(n_taints, 1), _LANE)
    pad_l = _round_up(max(n_labels, 1), _LANE)

    def pad(x, rows, cols=None):
        pads = [(0, rows - x.shape[0])]
        if cols is not None:
            pads.append((0, cols - x.shape[1]))
        return jnp.pad(x.astype(jnp.float32), pads)

    pad_r = 8  # alloc_t sublane dim: R resource rows zero-padded to 8

    req = pad(inputs.pod_requests, pad_p, n_resources)
    valid = pad(inputs.pod_valid[:, None], pad_p, 1)
    intol = pad(inputs.pod_intolerant, pad_p, pad_k)
    required = pad(inputs.pod_required, pad_p, pad_l)
    weight = (
        jnp.ones((pad_p, 1), jnp.float32)
        if inputs.pod_weight is None
        else pad(inputs.pod_weight[:, None], pad_p, 1)
    )
    alloc_t = pad(inputs.group_allocatable.T, pad_r, pad_t)
    taints = pad(inputs.group_taints, pad_t, pad_k)
    labels = pad(inputs.group_labels, pad_t, pad_l)

    has_forbidden = inputs.pod_group_forbidden is not None
    has_score = inputs.pod_group_score is not None
    has_exclusive = inputs.pod_exclusive is not None
    operands = [req, valid, intol, required, weight, alloc_t, taints, labels]
    in_specs = [
        pl.BlockSpec(
            (tile_p, n_resources), lambda i: (i, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (tile_p, 1), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        pl.BlockSpec(
            (tile_p, pad_k), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        pl.BlockSpec(
            (tile_p, pad_l), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        pl.BlockSpec(
            (tile_p, 1), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        pl.BlockSpec(
            (pad_r, pad_t), lambda i: (0, 0), memory_space=pltpu.VMEM
        ),
        pl.BlockSpec(
            (pad_t, pad_k), lambda i: (0, 0), memory_space=pltpu.VMEM
        ),
        pl.BlockSpec(
            (pad_t, pad_l), lambda i: (0, 0), memory_space=pltpu.VMEM
        ),
    ]
    if has_forbidden:
        operands.append(pad(inputs.pod_group_forbidden, pad_p, pad_t))
        in_specs.append(
            pl.BlockSpec(
                (tile_p, pad_t), lambda i: (i, 0), memory_space=pltpu.VMEM
            )
        )
    if has_score:
        # score padding is 0 on padded group columns; they are infeasible
        # (zero allocatable), so the -big mask keeps them out regardless
        operands.append(pad(inputs.pod_group_score, pad_p, pad_t))
        in_specs.append(
            pl.BlockSpec(
                (tile_p, pad_t), lambda i: (i, 0), memory_space=pltpu.VMEM
            )
        )
    if has_exclusive:
        # padded pod rows are 0.0 (non-exclusive) and invalid anyway
        operands.append(pad(inputs.pod_exclusive[:, None], pad_p, 1))
        in_specs.append(
            pl.BlockSpec(
                (tile_p, 1), lambda i: (i, 0), memory_space=pltpu.VMEM
            )
        )

    n_tiles = pad_p // tile_p
    grid = (n_tiles,)

    assigned2d, hist, demand = pl.pallas_call(
        partial(
            _kernel,
            buckets=buckets,
            n_resources=n_resources,
            has_forbidden=has_forbidden,
            has_score=has_score,
            has_exclusive=has_exclusive,
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec(
                (tile_p, 1), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (pad_t, buckets), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (pad_t, n_resources), lambda i: (0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((pad_p, 1), jnp.int32),
            jax.ShapeDtypeStruct((pad_t, buckets), jnp.float32),
            jax.ShapeDtypeStruct((pad_t, n_resources), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * pad_p * pad_t * (pad_k + pad_l + buckets + n_resources),
            bytes_accessed=4
            * (
                pad_p * (n_resources + pad_k + pad_l + 2)
                + pad_t * (n_resources + pad_k + pad_l + buckets)
            ),
            transcendentals=0,
        ),
        interpret=interpret,
    )(*operands)

    assigned = assigned2d.reshape(-1)[:n_pods]
    # padded groups are index >= n_groups and never win the min-index
    # reduction, so clipping the accumulators is a pure slice
    hist = lax.round(hist[:n_groups]).astype(jnp.int32)
    demand = demand[:n_groups]
    return assigned, hist, demand


@partial(jax.jit, static_argnames=("buckets", "tile_p", "interpret"))
def binpack_pallas(
    inputs: BinPackInputs,
    buckets: int = 32,
    tile_p: int = DEFAULT_TILE_P,
    interpret: bool = False,
):
    """Full bin-pack via the fused Pallas stage + the shared XLA tail.

    Same contract as ops/binpack.binpack (BinPackOutputs); tests pin the two
    backends equal element-for-element.
    """
    from karpenter_tpu.ops.binpack import BinPackOutputs, _shelf_bfd

    assigned, hist, demand = fused_assign(
        inputs, buckets=buckets, tile_p=tile_p, interpret=interpret
    )
    assigned_count = jnp.sum(hist, axis=1)
    nodes_needed = _shelf_bfd(hist, buckets)
    alloc = inputs.group_allocatable
    per_resource = jnp.where(
        alloc > 0,
        jnp.ceil(demand / jnp.maximum(alloc, 1e-30) - 1e-5),
        0.0,
    )
    lp_bound = jnp.max(per_resource, axis=1).astype(jnp.int32)
    unsched_mask = ((assigned < 0) & inputs.pod_valid).astype(jnp.int32)
    unschedulable = jnp.sum(
        unsched_mask
        if inputs.pod_weight is None
        else unsched_mask * inputs.pod_weight,
        dtype=jnp.int32,
    )
    return BinPackOutputs(
        assigned=assigned,
        assigned_count=assigned_count,
        nodes_needed=nodes_needed,
        lp_bound=lp_bound,
        unschedulable=unschedulable,
    )


def default_interpret() -> bool:
    """Compiled Mosaic path on TPU; interpreter elsewhere (CPU tests)."""
    return jax.default_backend() != "tpu"


def pallas_available() -> bool:
    try:
        import jax.experimental.pallas  # noqa: F401

        return True
    except Exception:  # noqa: BLE001 — availability probe only
        return False


__all__ = [
    "fused_assign",
    "binpack_pallas",
    "default_interpret",
    "pallas_available",
    "DEFAULT_TILE_P",
]
