"""Degraded-mode bin-pack: the XLA program's semantics in plain numpy,
shaped for CPUs.

Why this exists: the XLA program (ops/binpack.py) is laid out for the
TPU — its bucket histogram is a B-deep stack of [P, T] masked reductions,
O(P*T*B) elementwise work that the MXU-fed vector units eat for free but
that DOMINATES a CPU fallback solve (seconds at the 100k x 300 bench
scale). A CPU doesn't want that layout; it wants the sparse one: each pod
has exactly ONE assigned group, so every post-assignment aggregate is an
O(P) scatter (np.bincount), not an O(P*T*B) dense reduction. Feasibility
stays dense ([P, K] @ [K, T] bitset matmuls ride BLAS sgemm), assignment
is one argmax, and the shelf-BFD histogram walk is O(B^2) over [T, B+1] —
trivial.

This backend is selected by ops/binpack.solve(backend="auto") whenever
the default jax backend is CPU — i.e. exactly the accelerator-outage
degraded mode (utils/backend.py) and CPU-only test environments. Outputs
are pinned equal to the XLA program by tests/test_numpy_binpack.py
property tests (same argmax tie-breaks, same f32 quantization
arithmetic) — exactly for assigned/assigned_count/nodes_needed/
unschedulable; lp_bound within +-1 at f32-resolution boundaries, where
this path's f64 demand accumulation is strictly MORE accurate than the
accelerator's f32 einsum and the shared -1e-5 ceil guard is smaller
than one f32 ulp of the ratio (above ~84 nodes demanded per group).

reference: the reference stubs this producer entirely
(pkg/metrics/producers/pendingcapacity/producer.go:29-31); its design doc
warns the naive host form "scales linearly with node groups and
unschedulable pods" (docs/designs/DESIGN.md) — this is the non-naive
host form for when the accelerator is away.
"""

from __future__ import annotations

import numpy as np

from karpenter_tpu.ops.binpack import (
    BinPackInputs,
    BinPackOutputs,
    constraint_mask,
    has_constraint_operands,
)


def _as_np(x, dtype=None):
    arr = np.asarray(x)
    return arr if dtype is None else arr.astype(dtype, copy=False)


def _pack_bits(matrix: np.ndarray, lib=None) -> np.ndarray:
    """bool[N, K] -> uint64[N, W] little-endian bit words (the native
    kernel's taint/label operand layout). With the native lib, one
    scalar C pass (memory-bound, shape-indifferent) replaces
    np.packbits, which pays per-row overhead on narrow matrices and a
    full 64-column bool pad on wide ones — the pack was most of the
    degraded-mode solve before this (profiled r4)."""
    n, k = matrix.shape
    words = max(1, -(-k // 64))
    if lib is not None and n and k:
        import ctypes

        src = np.asarray(matrix)
        if src.dtype != np.bool_:
            # the C octet-gather needs strictly 0/1 bytes
            src = src != 0
        src = (
            # bool and uint8 share layout: view, don't cast-copy
            src.view(np.uint8)
            if src.flags.c_contiguous
            else np.ascontiguousarray(src).view(np.uint8)
        )
        out = np.empty((n, words), np.uint64)
        lib.karpenter_pack_bits(
            ctypes.c_longlong(n),
            ctypes.c_longlong(k),
            ctypes.c_longlong(words),
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        )
        return out
    padded = np.zeros((n, words * 64), bool)
    padded[:, :k] = matrix
    return np.ascontiguousarray(
        np.packbits(padded, axis=1, bitorder="little")
    ).view(np.uint64)


# one-slot identity-keyed pack cache, the numpy-path analog of
# ops/binpack._put_memo: callers that pass the SAME BinPackInputs object
# again (the encode memo, the bench's steady-state loop) skip re-packing
# the [P, K] bool operands into bit words — ~2 ms of pure memory traffic
# per solve at the 100k x 64-taint scale. Same contract as the device
# cache: inputs are immutable once passed to solve().
_pack_memo = None


def _packed_operands(inputs, intolerant, taints, labels, required, lib):
    global _pack_memo
    memo = _pack_memo
    if inputs is not None and memo is not None and memo[0] is inputs:
        return memo[1]
    packed = (
        _pack_bits(intolerant, lib),
        _pack_bits(taints, lib),
        _pack_bits(required, lib),
        _pack_bits(~labels, lib),
    )
    if inputs is not None:
        _pack_memo = (inputs, packed)
    return packed


def _assign_native(
    lib, requests, valid, intolerant, required, alloc, taints, labels,
    forbidden, score, weight, exclusive, buckets, inputs=None,
):
    """One fused native pass: (assigned, assigned_count, histogram,
    demand, unschedulable). Same contract as the numpy stages it
    replaces; parity pinned by tests/test_numpy_binpack.py."""
    import ctypes

    n_pods, n_resources = requests.shape
    n_groups = alloc.shape[0]
    (
        intolerant_words,
        taint_words,
        required_words,
        missing_words,
    ) = _packed_operands(inputs, intolerant, taints, labels, required, lib)

    assigned = np.empty(n_pods, np.int32)
    assigned_count = np.zeros(n_groups, np.int64)
    histogram = np.zeros((n_groups, buckets), np.int64)
    demand = np.zeros((n_groups, n_resources), np.float64)
    unschedulable = np.zeros(1, np.int64)

    def ptr(arr, ctype):
        return arr.ctypes.data_as(ctypes.POINTER(ctype))

    requests = np.ascontiguousarray(requests, np.float32)
    alloc_c = np.ascontiguousarray(alloc, np.float32)
    valid_c = np.ascontiguousarray(valid, np.uint8)
    forbidden_c = (
        None
        if forbidden is None
        else np.ascontiguousarray(forbidden, np.uint8)
    )
    score_c = (
        None if score is None else np.ascontiguousarray(score, np.float32)
    )
    weight_c = (
        None if weight is None else np.ascontiguousarray(weight, np.int64)
    )
    exclusive_c = (
        None
        if exclusive is None
        else np.ascontiguousarray(exclusive, np.uint8)
    )
    null = ctypes.POINTER(ctypes.c_float)()
    entry, extra = _assign_entry(lib, ctypes, n_pods)
    entry(
        ctypes.c_longlong(n_pods),
        ctypes.c_longlong(n_groups),
        ctypes.c_longlong(n_resources),
        ctypes.c_longlong(intolerant_words.shape[1]),
        ctypes.c_longlong(required_words.shape[1]),
        ctypes.c_longlong(buckets),
        ptr(requests, ctypes.c_float),
        ptr(valid_c, ctypes.c_ubyte),
        ptr(intolerant_words, ctypes.c_uint64),
        ptr(required_words, ctypes.c_uint64),
        ptr(alloc_c, ctypes.c_float),
        ptr(taint_words, ctypes.c_uint64),
        ptr(missing_words, ctypes.c_uint64),
        (
            ptr(forbidden_c, ctypes.c_ubyte)
            if forbidden_c is not None
            else ctypes.POINTER(ctypes.c_ubyte)()
        ),
        ptr(score_c, ctypes.c_float) if score_c is not None else null,
        (
            ptr(weight_c, ctypes.c_longlong)
            if weight_c is not None
            else ctypes.POINTER(ctypes.c_longlong)()
        ),
        (
            ptr(exclusive_c, ctypes.c_ubyte)
            if exclusive_c is not None
            else ctypes.POINTER(ctypes.c_ubyte)()
        ),
        ptr(assigned, ctypes.c_int32),
        ptr(assigned_count, ctypes.c_longlong),
        ptr(histogram, ctypes.c_longlong),
        ptr(demand, ctypes.c_double),
        ptr(unschedulable, ctypes.c_longlong),
        *extra,
    )
    return assigned, assigned_count, histogram, demand, int(unschedulable[0])


# minimum pods per thread before fan-out pays: below this, the per-call
# pthread create/join (~tens of us each) rivals the whole fused solve
# (a 1000-pod tick measures ~0.2 ms), so small solves stay single-pass
_MIN_PODS_PER_THREAD = 8192


def _assign_entry(lib, ctypes, n_pods: int):
    """The native entry point + trailing args: the threaded choice phase
    when the host has cores for it AND the problem is big enough to
    amortize spawn/join. KARPENTER_SOLVER_THREADS overrides both (an
    explicit operator/test choice bypasses the size gate); 0/1, a small
    auto-sized solve, or a prebuilt .so without the symbol = the fused
    single pass. Outputs are bitwise identical either way: the C side
    accumulates every aggregate sequentially in pod order."""
    n_threads = _solver_threads(n_pods)
    if n_threads > 1 and hasattr(lib, "karpenter_assign_mt"):
        return lib.karpenter_assign_mt, (ctypes.c_longlong(n_threads),)
    return lib.karpenter_assign, ()


def _solver_threads(n_pods: int) -> int:
    """Choice-phase thread count. Explicit KARPENTER_SOLVER_THREADS is
    honored as-is; otherwise the CPUs actually AVAILABLE to this
    process — sched_getaffinity sees cgroup cpusets/affinity where
    os.cpu_count() reports the node's cores and would oversubscribe a
    cpu-limited pod — capped by the size gate. 1 = the fused pass."""
    import os

    raw = os.environ.get("KARPENTER_SOLVER_THREADS", "")
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            return 1
    try:
        cores = len(os.sched_getaffinity(0)) or 1
    except (AttributeError, OSError):  # non-Linux
        cores = os.cpu_count() or 1
    return min(cores, max(1, n_pods // _MIN_PODS_PER_THREAD))


def _feasibility_np(
    requests, valid, intolerant, required, alloc, taints, labels, forbidden
):
    """bool[P, T], boolean-identical to ops/binpack._feasibility. The
    taint/label violations stay f32 matmuls here too: measured on this
    class of CPU, single-threaded BLAS sgemm beats a packed-uint64
    broadcast formulation (which is memory-traffic-bound on the [P, T]
    word temps) — and the small-integer counts are exact in f32."""
    fits = np.ones((requests.shape[0], alloc.shape[0]), bool)
    for r in range(requests.shape[1]):
        fits &= requests[:, r : r + 1] <= alloc[None, :, r]
    fits &= np.any(alloc > 0, axis=1)[None, :]
    taint_violations = intolerant.astype(np.float32) @ taints.astype(
        np.float32
    ).T
    label_violations = required.astype(np.float32) @ (~labels).astype(
        np.float32
    ).T
    fits &= taint_violations < 0.5
    fits &= label_violations < 0.5
    if forbidden is not None:
        fits &= ~forbidden
    fits &= valid[:, None]
    return fits


# the C shelf pass keeps its per-group bin state in a stack VLA; cap the
# bucket count it accepts so a pathological caller degrades to the numpy
# path instead of overflowing the thread stack (production uses <= 64)
_NATIVE_SHELF_MAX_BUCKETS = 4096


def _shelf_bfd(histogram: np.ndarray, buckets: int, lib) -> np.ndarray:
    """i32[T, B] -> i32[T]: the C pass when the kernel is loaded (the
    [T, B+1] state is tiny — the numpy form pays ~1000 array-op
    dispatches of interpreter overhead per solve), else numpy."""
    if lib is not None and buckets <= _NATIVE_SHELF_MAX_BUCKETS:
        import ctypes

        hist = np.ascontiguousarray(histogram, np.int64)
        total = np.zeros(histogram.shape[0], np.int64)
        lib.karpenter_shelf_bfd(
            ctypes.c_longlong(histogram.shape[0]),
            ctypes.c_longlong(buckets),
            hist.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
            total.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
        )
        return total.astype(np.int32)
    return _shelf_bfd_np(histogram, buckets)


def _shelf_bfd_np(histogram: np.ndarray, buckets: int) -> np.ndarray:
    """i32[T, B] -> i32[T]; the vectorized shelf best-fit-decreasing of
    ops/binpack._shelf_bfd, same pass structure, numpy state."""
    n_groups = histogram.shape[0]
    rem_index = np.arange(buckets + 1, dtype=np.int64)
    bins = np.zeros((n_groups, buckets + 1), np.int64)
    total = np.zeros(n_groups, np.int64)
    for k in range(buckets, 0, -1):
        c = histogram[:, k - 1].astype(np.int64)
        for _ in range(buckets):
            if not c.any():
                break  # pure speedup: remaining passes are no-ops
            avail = np.where(
                (rem_index[None, :] >= k) & (rem_index[None, :] > 0),
                bins,
                0,
            )
            cum_before = np.cumsum(avail, axis=1) - avail
            place = np.clip(c[:, None] - cum_before, 0, avail)
            bins = bins - place + np.roll(place, -k, axis=1)
            c = c - place.sum(axis=1)
        per_bin = buckets // k
        full_bins = c // per_bin
        leftover = c - full_bins * per_bin
        has_partial = (leftover > 0).astype(np.int64)
        total += full_bins + has_partial
        full_rem = buckets - per_bin * k
        bins[:, full_rem] += full_bins
        partial_rem = buckets - leftover * k
        bins[np.arange(n_groups), partial_rem] += has_partial
    return total.astype(np.int32)


def _assign_numpy(
    requests, valid, intolerant, required, alloc, taints, labels,
    forbidden, score, weight, exclusive, buckets, steer=None,
    claim=None, reservation=None, slot=None, domain=None, caps=None,
    pack_class=None,
):
    """The pure-numpy assignment pass (the fallback while the C kernel's
    background build finishes, and the only pass expressing the
    two-stage lexicographic steer+score choice and the constraint
    plane). Sparse layout: everything after the argmax scatters over
    the ONE assigned group per pod — O(P), where the dense XLA layout
    is O(P*T*(B|R))."""
    _, n_resources = requests.shape
    n_groups = alloc.shape[0]
    feasible = _feasibility_np(
        requests, valid, intolerant, required, alloc, taints, labels,
        forbidden,
    )
    # reservation + spread mask: the shared integer-exact definition
    # (ops/binpack.constraint_mask with xp=np) — bitwise identical to
    # the XLA feasibility stage by construction
    cmask = constraint_mask(
        claim, reservation, slot, domain, caps, weight, valid, xp=np
    )
    if cmask is not None:
        feasible = feasible & cmask
    any_feasible = feasible.any(axis=1)
    if score is None and steer is None:
        choice = np.argmax(feasible, axis=1)
    else:
        from karpenter_tpu.ops.binpack import steered_choice

        choice = steered_choice(feasible, score, steer, xp=np)
    assigned = np.where(any_feasible, choice, -1).astype(np.int32)

    rows = np.nonzero(any_feasible & valid)[0]
    groups_of = choice[rows]
    w_of = (
        np.ones(len(rows), np.int64)
        if weight is None
        else weight[rows]
    )

    assigned_count = np.bincount(
        groups_of, weights=w_of, minlength=n_groups
    ).astype(np.int32)

    # dominant share of each assigned pod ON ITS GROUP ONLY, f32 ops
    # in the same order as _dominant_share so the quantized bucket
    # matches the XLA program bit for bit
    share = np.zeros(len(rows), np.float32)
    row_alloc = alloc[groups_of]  # [n, R]
    row_req = requests[rows]
    for r in range(n_resources):
        a = row_alloc[:, r]
        s = np.where(
            a > 0,
            row_req[:, r] / np.maximum(a, np.float32(1e-30)),
            np.float32(np.inf),
        ).astype(np.float32)
        s = np.where(
            (a <= 0) & (row_req[:, r] <= 0), np.float32(0.0), s
        )
        share = np.maximum(share, s)
    bucket_of = np.clip(
        np.ceil(share * np.float32(buckets)).astype(np.int64),
        1,
        buckets,
    )
    if exclusive is not None:
        # hostname self-anti-affinity: the pod takes a whole node
        bucket_of = np.where(exclusive[rows], buckets, bucket_of)
    if pack_class is None:
        histogram = np.bincount(
            groups_of.astype(np.int64) * buckets + (bucket_of - 1),
            weights=w_of,
            minlength=n_groups * buckets,
        ).reshape(n_groups, buckets)
    else:
        # per-class histograms [C*T, B], mirroring the XLA program's
        # class-partitioned shelf exactly: rows with no class bit fold
        # to the shared class 0, and a row counts in EVERY set class
        # (one-hot by compiler contract, but the mirror pins the kernel
        # semantics, not the contract)
        n_classes = pack_class.shape[1]
        pc = pack_class.copy()
        pc[:, 0] |= ~pc.any(axis=1)
        histogram = np.zeros((n_classes * n_groups, buckets), np.float64)
        flat = groups_of.astype(np.int64) * buckets + (bucket_of - 1)
        for c in range(n_classes):
            m = pc[rows, c]
            histogram[c * n_groups : (c + 1) * n_groups] = np.bincount(
                flat[m], weights=w_of[m], minlength=n_groups * buckets
            ).reshape(n_groups, buckets)

    # f64 demand accumulation in pod order — bitwise-identical to
    # the native kernel's accumulation
    demand64 = np.zeros((n_groups, n_resources), np.float64)
    np.add.at(
        demand64, groups_of, row_req.astype(np.float64) * w_of[:, None]
    )
    unsched_mask = (~any_feasible) & valid
    if weight is None:
        unschedulable = int(unsched_mask.sum())
    else:
        unschedulable = int(weight[unsched_mask].sum())
    return assigned, assigned_count, histogram, demand64, unschedulable


def _steered(inputs: BinPackInputs, score):
    """(score, steer) under priority x tier steering, mirroring the
    XLA kernel exactly (ops/binpack.steer_matrix/steered_choice are the
    single definitions). Score-free steering folds the steer matrix
    INTO the score slot — the native C pass consumes it unchanged, and
    argmax-over-steer equals the lexicographic choice when no base
    score exists. A fleet carrying BOTH keeps them separate for the
    two-stage choice (and routes around the native pass, which takes a
    single score operand)."""
    if inputs.pod_priority is None or inputs.group_tier is None:
        return score, None
    from karpenter_tpu.ops.binpack import steer_matrix

    steer = steer_matrix(
        _as_np(inputs.pod_priority, np.int32),
        _as_np(inputs.group_tier, np.int32),
        xp=np,
    )
    if score is None:
        return steer, None
    return score, steer


def binpack_numpy(  # lint: allow-complexity — the bitwise numpy mirror: mirrors every optional-operand arm of the XLA kernel
    inputs: BinPackInputs, buckets: int = 32, use_native: bool = True
) -> BinPackOutputs:
    """use_native=True (default) routes the assignment pass through the
    C kernel (native/binpack_kernel.c) when a toolchain has built it —
    the scalar scan early-exits at the first feasible group, making the
    pass nearly O(P) on realistic inputs where the dense numpy stages
    are O(P*T). Falls back to the pure-numpy stages silently; both are
    pinned equal to the XLA program by tests/test_numpy_binpack.py."""
    requests = _as_np(inputs.pod_requests, np.float32)
    valid = _as_np(inputs.pod_valid, bool)
    intolerant = _as_np(inputs.pod_intolerant, bool)
    required = _as_np(inputs.pod_required, bool)
    alloc = _as_np(inputs.group_allocatable, np.float32)
    taints = _as_np(inputs.group_taints, bool)
    labels = _as_np(inputs.group_labels, bool)
    forbidden = (
        None
        if inputs.pod_group_forbidden is None
        else _as_np(inputs.pod_group_forbidden, bool)
    )
    score = (
        None
        if inputs.pod_group_score is None
        else _as_np(inputs.pod_group_score, np.float32)
    )
    weight = (
        None
        if inputs.pod_weight is None
        else _as_np(inputs.pod_weight, np.int64)
    )
    exclusive = (
        None
        if inputs.pod_exclusive is None
        else _as_np(inputs.pod_exclusive, bool)
    )
    score, steer = _steered(inputs, score)
    constrained = has_constraint_operands(inputs)
    claim = (
        None
        if inputs.pod_claim is None
        else _as_np(inputs.pod_claim, np.int32)
    )
    reservation = (
        None
        if inputs.group_reservation is None
        else _as_np(inputs.group_reservation, np.int32)
    )
    slot = (
        None
        if inputs.pod_spread_slot is None
        else _as_np(inputs.pod_spread_slot, np.int32)
    )
    domain = (
        None
        if inputs.group_domain is None
        else _as_np(inputs.group_domain, np.int32)
    )
    caps = (
        None
        if inputs.spread_cap is None
        else _as_np(inputs.spread_cap, np.int32)
    )
    pack_class = (
        None
        if inputs.pod_pack_class is None
        else _as_np(inputs.pod_pack_class, bool)
    )
    n_pods, n_resources = requests.shape
    n_groups = alloc.shape[0]

    lib = None
    # steer != None means BOTH a preference score and tier steering are
    # live: the choice is two-stage (lexicographic) and the native
    # kernel's single-score argmax can't express it — numpy stages only.
    # Constraint-plane operands route around the native pass the same
    # way: its fixed C argument list predates them, and silently
    # dropping an operand is the PR 8 bug class.
    if use_native and n_pods and steer is None and not constrained:
        # never block a degraded-mode tick inside a cc subprocess: use
        # the kernel only once its background build has finished, and
        # run the numpy stages meanwhile (peek/ensure-async pattern,
        # native/__init__.py)
        from karpenter_tpu.native import ensure_kbinpack_async, peek_kbinpack

        lib = peek_kbinpack()
        if lib is None:
            ensure_kbinpack_async()
    if lib is not None:
        (
            assigned,
            assigned_count64,
            histogram,
            demand64,
            unschedulable,
        ) = _assign_native(
            lib, requests, valid, intolerant, required, alloc, taints,
            labels, forbidden, score, weight, exclusive, buckets,
            inputs=inputs,
        )
        assigned_count = assigned_count64.astype(np.int32)
    else:
        (
            assigned,
            assigned_count,
            histogram,
            demand64,
            unschedulable,
        ) = _assign_numpy(
            requests, valid, intolerant, required, alloc, taints, labels,
            forbidden, score, weight, exclusive, buckets, steer=steer,
            claim=claim, reservation=reservation, slot=slot,
            domain=domain, caps=caps, pack_class=pack_class,
        )

    nodes_needed = _shelf_bfd(histogram, buckets, lib)
    if pack_class is not None:
        # class-partitioned shelf: [C*T] node counts sum across classes
        nodes_needed = (
            nodes_needed.reshape(-1, n_groups).sum(axis=0).astype(np.int32)
        )

    # LP bound: f64-accumulated demand — strictly more accurate than the
    # XLA program's f32 einsum; at demand/allocatable ratios above ~84
    # one f32 ulp exceeds the shared -1e-5 ceil guard, so the two
    # backends may legitimately differ by +-1 there (the documented
    # lp_bound exception)
    demand = demand64.astype(np.float32)
    per_resource = np.where(
        alloc > 0,
        np.ceil(
            demand / np.maximum(alloc, np.float32(1e-30))
            - np.float32(1e-5)
        ),
        np.float32(0.0),
    )
    lp_bound = per_resource.max(axis=1).astype(np.int32)

    return BinPackOutputs(
        assigned=assigned,
        assigned_count=assigned_count,
        nodes_needed=nodes_needed,
        lp_bound=lp_bound,
        unschedulable=np.int32(unschedulable),
    )
