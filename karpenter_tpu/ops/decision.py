"""Batched HorizontalAutoscaler decision kernel.

The reference computes one scalar decision per autoscaler per 10s tick
(reference: pkg/autoscaler/autoscaler.go:144-194 calling
pkg/autoscaler/algorithms/proportional.go:30-47 and the behavior logic in
pkg/apis/autoscaling/v1alpha1/horizontalautoscaler.go:226-275). Here the
same semantics run as ONE jitted array program over all N autoscalers ×
M metrics at once:

    recommendation -> select policy (Max/Min/Disabled by direction)
                   -> stabilization window mask
                   -> [min, max] clamp + condition flags

Design notes (TPU):
- everything is fixed-shape f32/i32 tensors; ragged metric lists are padded
  and masked with metric_valid, so one compiled program serves any fleet
  size up to the padded bucket (no per-object recompiles, no host loop).
- time stays on the host: last_scale_time/now enter as f32 seconds relative
  to a host-chosen epoch (SURVEY.md §7 hard part (e)).
- ceil() is computed with a 1e-5 guard band so f32 rounding cannot round an
  exactly-representable f64 quotient across an integer boundary (the Go
  implementation computes in f64).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

# target types (order matters: encoded into int arrays)
TYPE_VALUE = 0
TYPE_AVERAGE_VALUE = 1
TYPE_UTILIZATION = 2
TYPE_UNKNOWN = 3

# select policies
POLICY_MAX = 0
POLICY_MIN = 1
POLICY_DISABLED = 2

# scaling-policy types (reference: horizontalautoscaler.go:131-138)
POLICY_TYPE_COUNT = 0
POLICY_TYPE_PERCENT = 1

_CEIL_GUARD = 1e-5

# f32 saturation bounds for the final int32 cast. 2**31-1 is NOT exactly
# representable in f32 (rounds up to 2**31, which fptosi wraps to INT32_MIN),
# so saturate at 2**31-128 = 2^7*(2^24-1), the largest f32-exact value below
# the int32 ceiling.
_I32_SAFE_MAX = float(2**31 - 128)
_I32_SAFE_MIN = float(-(2**31))  # exact power of two, representable


@jax.tree_util.register_dataclass
@dataclass
class DecisionInputs:
    """Structure-of-arrays snapshot of every HorizontalAutoscaler."""

    metric_value: jax.Array  # f32[N, M]
    target_value: jax.Array  # f32[N, M]
    target_type: jax.Array  # i32[N, M]
    metric_valid: jax.Array  # bool[N, M]
    spec_replicas: jax.Array  # i32[N]  scale target .spec.replicas
    status_replicas: jax.Array  # i32[N]  scale target .status.replicas
    min_replicas: jax.Array  # i32[N]
    max_replicas: jax.Array  # i32[N]
    up_window: jax.Array  # i32[N] stabilization seconds (default 0)
    down_window: jax.Array  # i32[N] stabilization seconds (default 300)
    up_policy: jax.Array  # i32[N]
    down_policy: jax.Array  # i32[N]
    last_scale_time: jax.Array  # f32[N] seconds since epoch0
    has_last_scale: jax.Array  # bool[N]
    now: jax.Array  # f32 scalar, seconds since epoch0
    # Count/Percent scaling policies, K fixed slots per direction
    # (reference MODELS these, horizontalautoscaler.go:111-146, but leaves
    # application a TODO at autoscaler.go:186-189 — applied here)
    up_ptype: jax.Array  # i32[N, K] POLICY_TYPE_*
    up_pvalue: jax.Array  # i32[N, K] permitted change (count or percent)
    up_pperiod: jax.Array  # i32[N, K] periodSeconds
    up_pvalid: jax.Array  # bool[N, K]
    down_ptype: jax.Array  # i32[N, K]
    down_pvalue: jax.Array  # i32[N, K]
    down_pperiod: jax.Array  # i32[N, K]
    down_pvalid: jax.Array  # bool[N, K]
    # proactive blend (docs/forecasting.md): the metric values the
    # forecaster predicts `horizon` seconds ahead. Optional — None (the
    # reactive-only fleet) keeps the pre-forecast program; when present,
    # each valid forecast's recommendation is max()-blended into the
    # reactive one, so a predicted breach scales up EARLY while
    # scale-down stays governed by the observed values alone (the blend
    # can only raise a recommendation — monotonicity is pinned by
    # tests/test_forecast.py).
    forecast_value: Optional[jax.Array] = None  # f32[N, M]
    forecast_valid: Optional[jax.Array] = None  # bool[N, M]


@jax.tree_util.register_dataclass
@dataclass
class DecisionOutputs:
    desired: jax.Array  # i32[N] final bounded decision
    recommendation: jax.Array  # i32[N] post-select, pre-limit
    limited: jax.Array  # i32[N] post-window/policy, pre-[min,max] value
    able_to_scale: jax.Array  # bool[N] False iff held by window or policy
    scaling_unbounded: jax.Array  # bool[N] False iff clamped by [min, max]
    able_at: jax.Array  # f32[N] hold end time (valid when !able_to_scale)
    rate_limited: jax.Array  # bool[N] True iff a scaling policy clamped
    # furthest a hypothetical up/down move could go this tick under the
    # declared stabilization windows + rate policies (the bound the cost
    # refinement's candidate ladder must respect — cost/engine.py)
    up_ceiling: jax.Array  # i32[N]
    down_floor: jax.Array  # i32[N]


def _ceil_guarded(x: jax.Array) -> jax.Array:
    return jnp.ceil(x - _CEIL_GUARD)


def _recommendations(
    inputs: DecisionInputs, values: Optional[jax.Array] = None
) -> jax.Array:
    """Per-metric desired replicas, f32[N, M] (reference: proportional.go:30-47).
    `values` overrides the observed metric values (the forecast blend
    runs the identical HPA math on the predicted values)."""
    if values is None:
        values = inputs.metric_value
    # zero target: ratio collapses to 0, matching the scalar oracle
    # (algorithms/proportional.py) — float division by zero never reaches XLA
    safe_target = jnp.where(inputs.target_value != 0, inputs.target_value, 1.0)
    ratio = jnp.where(
        inputs.target_value != 0, values / safe_target, 0.0
    )
    status = inputs.status_replicas[:, None].astype(jnp.float32)
    proportional = status * ratio

    by_value = jnp.maximum(1.0, _ceil_guarded(proportional))
    by_average = _ceil_guarded(ratio)
    by_utilization = jnp.maximum(1.0, _ceil_guarded(proportional * 100.0))
    fallback = status  # unknown target type keeps current replicas

    rec = jnp.select(
        [
            inputs.target_type == TYPE_VALUE,
            inputs.target_type == TYPE_AVERAGE_VALUE,
            inputs.target_type == TYPE_UTILIZATION,
        ],
        [by_value, by_average, by_utilization],
        fallback,
    )
    return rec


def decide(inputs: DecisionInputs) -> DecisionOutputs:
    """The full decision pipeline (reference: autoscaler.go:144-194)."""
    rec = _recommendations(inputs)  # f32[N, M]
    if inputs.forecast_value is not None:
        # proactive blend: run the SAME per-metric math on the predicted
        # values and take the max — a forecasted breach raises the
        # recommendation early, a forecasted lull changes nothing (the
        # blend is monotone up; everything downstream — select policy,
        # stabilization, rate limits, bounds — applies unchanged)
        rec_forecast = _recommendations(inputs, inputs.forecast_value)
        blend = inputs.forecast_valid & inputs.metric_valid
        rec = jnp.where(blend, jnp.maximum(rec, rec_forecast), rec)
    valid = inputs.metric_valid
    spec = inputs.spec_replicas.astype(jnp.float32)  # [N]

    # --- select policy (reference: horizontalautoscaler.go:226-247) -------
    any_valid = jnp.any(valid, axis=1)
    any_up = jnp.any(valid & (rec > spec[:, None]), axis=1)
    any_down = jnp.any(valid & (rec < spec[:, None]), axis=1)
    # direction picks which rules apply; no movement (or no metrics) disables
    policy = jnp.where(
        any_up,
        inputs.up_policy,
        jnp.where(any_down, inputs.down_policy, POLICY_DISABLED),
    )
    neg_inf = jnp.float32(np.finfo(np.float32).min)
    pos_inf = jnp.float32(np.finfo(np.float32).max)
    rec_max = jnp.max(jnp.where(valid, rec, neg_inf), axis=1)
    rec_min = jnp.min(jnp.where(valid, rec, pos_inf), axis=1)
    selected = jnp.select(
        [policy == POLICY_MAX, policy == POLICY_MIN],
        [rec_max, rec_min],
        spec,
    )
    selected = jnp.where(any_valid, selected, spec)

    # --- transient limits: stabilization window (autoscaler.go:172-194) ---
    going_up = selected > spec
    going_down = selected < spec
    window = jnp.where(
        going_up,
        inputs.up_window,
        jnp.where(going_down, inputs.down_window, 0),
    ).astype(jnp.float32)
    elapsed = inputs.now - inputs.last_scale_time
    moving = going_up | going_down
    within = (
        moving & inputs.has_last_scale & (elapsed < window)
    )
    window_end = inputs.last_scale_time + window
    limited = jnp.where(within, spec, selected)

    # --- scaling policies: per-direction allowed-delta clamp --------------
    # The reference models Count/Percent policies with periodSeconds
    # (horizontalautoscaler.go:111-146) and leaves application a TODO
    # (autoscaler.go:186-189). Semantics here, with the state the CRD
    # actually carries (LastScaleTime only — no replica-change history):
    # a policy's budget is `value` (Count) or ceil(max(spec,1)*value/100)
    # (Percent — floored at one replica's worth so a Percent-only policy
    # can still escape zero replicas; percent-of-zero would deadlock the
    # autoscaler at 0 forever) per periodSeconds; a scale event inside the
    # trailing period is conservatively assumed to have spent the budget,
    # so the policy contributes 0 until the period elapses. The
    # direction's select policy combines multiple policies (Max = most
    # permissive, Min = most restrictive); no policies, or no scale
    # history, means unlimited (matching the reference's policy-free
    # default rules, horizontalautoscaler.go:249-265).
    def _allowed(ptype, pvalue, pperiod, pvalid, select):
        base = jnp.maximum(spec[:, None], 1.0)
        budget = jnp.where(
            ptype == POLICY_TYPE_PERCENT,
            _ceil_guarded(base * pvalue.astype(jnp.float32) / 100.0),
            pvalue.astype(jnp.float32),
        )
        spent = inputs.has_last_scale[:, None] & (
            elapsed[:, None] < pperiod.astype(jnp.float32)
        )
        per_policy = jnp.where(spent, 0.0, budget)
        a_max = jnp.max(jnp.where(pvalid, per_policy, neg_inf), axis=1)
        a_min = jnp.min(jnp.where(pvalid, per_policy, pos_inf), axis=1)
        allowed = jnp.where(select == POLICY_MIN, a_min, a_max)
        unlimited = ~jnp.any(pvalid, axis=1) | ~inputs.has_last_scale
        # soonest the binding budget frees: Max select frees when ANY
        # period elapses (min), Min select when ALL do (max)
        p_f32 = pperiod.astype(jnp.float32)
        p_min = jnp.min(jnp.where(pvalid, p_f32, pos_inf), axis=1)
        p_max = jnp.max(jnp.where(pvalid, p_f32, neg_inf), axis=1)
        frees = jnp.where(select == POLICY_MIN, p_max, p_min)
        return jnp.where(unlimited, pos_inf, allowed), frees

    allowed_up, up_frees = _allowed(
        inputs.up_ptype,
        inputs.up_pvalue,
        inputs.up_pperiod,
        inputs.up_pvalid,
        inputs.up_policy,
    )
    allowed_down, down_frees = _allowed(
        inputs.down_ptype,
        inputs.down_pvalue,
        inputs.down_pperiod,
        inputs.down_pvalid,
        inputs.down_policy,
    )
    rate_clamped = jnp.clip(limited, spec - allowed_down, spec + allowed_up)
    rate_limited = rate_clamped != limited
    # budget exhausted entirely (no movement possible despite a desired
    # move): a transient hold exactly like the stabilization window
    fully_held = rate_limited & (rate_clamped == spec)
    rate_end = inputs.last_scale_time + jnp.where(
        limited > spec, up_frees, down_frees
    )
    limited = rate_clamped

    # within => limited==spec => the rate clamp is a no-op, so the two
    # holds are mutually exclusive and able_at needs no combining
    able_to_scale = ~within & ~fully_held
    able_at = jnp.where(fully_held, rate_end, window_end)

    # --- bounded limits: [min, max] clamp (autoscaler.go:155-170) ---------
    bounded = jnp.clip(
        limited,
        inputs.min_replicas.astype(jnp.float32),
        inputs.max_replicas.astype(jnp.float32),
    )
    scaling_unbounded = bounded == limited

    # --- per-direction movement bounds (the cost-refinement contract) -----
    # The furthest a HYPOTHETICAL move could go this tick under the
    # declared behavior, independent of where the reactive recommendation
    # actually landed: a direction still inside its stabilization window
    # holds at spec, otherwise the rate budget bounds the step. The cost
    # subsystem (cost/engine.py) clamps its candidate ladder to
    # [down_floor, up_ceiling] so an SLO raise or budget trim can never
    # outrun the scaleUp/scaleDown rules the operator declared.
    up_hold = inputs.has_last_scale & (
        elapsed < inputs.up_window.astype(jnp.float32)
    )
    down_hold = inputs.has_last_scale & (
        elapsed < inputs.down_window.astype(jnp.float32)
    )
    up_ceiling = jnp.where(up_hold, spec, spec + allowed_up)
    down_floor = jnp.where(down_hold, spec, spec - allowed_down)

    to_i32 = lambda x: jnp.clip(
        x, jnp.float32(_I32_SAFE_MIN), jnp.float32(_I32_SAFE_MAX)
    ).astype(jnp.int32)
    return DecisionOutputs(
        desired=to_i32(bounded),
        recommendation=to_i32(selected),
        limited=to_i32(limited),
        able_to_scale=able_to_scale,
        scaling_unbounded=scaling_unbounded,
        able_at=able_at,
        rate_limited=rate_limited,
        up_ceiling=to_i32(up_ceiling),
        down_floor=to_i32(down_floor),
    )


decide_jit = jax.jit(decide)


# -- numpy mirror -------------------------------------------------------------
# The parity oracle for the fused steady-state tick (ops/fusedtick.py)
# and the decide stage of its numpy floor. Every line mirrors the
# kernel's op order; decide() carries no reductions that depend on
# order (any/max/min over masked lanes are order-free) and no
# multiply-accumulate in single-mul form except the Percent-budget
# line, whose divide sits between the multiply and the subtract, so no
# XLA:CPU FMA contraction applies and plain f32 ops reproduce the
# kernel bit for bit (pinned by tests/test_fusedtick.py).

_F32_ONE = np.float32(1.0)
_F32_ZERO = np.float32(0.0)
_F32_GUARD = np.float32(_CEIL_GUARD)
_F32_NEG = np.float32(np.finfo(np.float32).min)
_F32_POS = np.float32(np.finfo(np.float32).max)


def _ceil_guarded_np(x: np.ndarray) -> np.ndarray:
    return np.ceil((x - _F32_GUARD).astype(np.float32)).astype(np.float32)


def _recommendations_numpy(
    inputs: DecisionInputs, values: Optional[np.ndarray] = None
) -> np.ndarray:
    """Host mirror of _recommendations() — bit-identical f32."""
    if values is None:
        values = inputs.metric_value
    values = np.asarray(values, np.float32)
    target = np.asarray(inputs.target_value, np.float32)
    target_type = np.asarray(inputs.target_type, np.int32)
    safe_target = np.where(target != 0, target, _F32_ONE).astype(np.float32)
    ratio = np.where(
        target != 0, (values / safe_target).astype(np.float32), _F32_ZERO
    ).astype(np.float32)
    status = (
        np.asarray(inputs.status_replicas, np.int32)[:, None]
        .astype(np.float32)
    )
    proportional = (status * ratio).astype(np.float32)

    by_value = np.maximum(_F32_ONE, _ceil_guarded_np(proportional))
    by_average = _ceil_guarded_np(ratio)
    by_utilization = np.maximum(
        _F32_ONE,
        _ceil_guarded_np((proportional * np.float32(100.0)).astype(np.float32)),
    )
    fallback = np.broadcast_to(status, ratio.shape)

    return np.select(
        [
            target_type == TYPE_VALUE,
            target_type == TYPE_AVERAGE_VALUE,
            target_type == TYPE_UTILIZATION,
        ],
        [by_value, by_average, by_utilization],
        fallback,
    ).astype(np.float32)


def decide_numpy(inputs: DecisionInputs) -> DecisionOutputs:  # lint: allow-complexity — line-for-line kernel mirror, linear
    """Host mirror of decide() — bit-identical f32/i32 outputs (the
    fused-tick parity contract; see the mirror banner above)."""
    rec = _recommendations_numpy(inputs)
    if inputs.forecast_value is not None:
        rec_forecast = _recommendations_numpy(inputs, inputs.forecast_value)
        blend = (
            np.asarray(inputs.forecast_valid, bool)
            & np.asarray(inputs.metric_valid, bool)
        )
        rec = np.where(
            blend, np.maximum(rec, rec_forecast), rec
        ).astype(np.float32)
    valid = np.asarray(inputs.metric_valid, bool)
    spec = np.asarray(inputs.spec_replicas, np.int32).astype(np.float32)

    any_valid = np.any(valid, axis=1)
    any_up = np.any(valid & (rec > spec[:, None]), axis=1)
    any_down = np.any(valid & (rec < spec[:, None]), axis=1)
    policy = np.where(
        any_up,
        np.asarray(inputs.up_policy, np.int32),
        np.where(
            any_down, np.asarray(inputs.down_policy, np.int32),
            POLICY_DISABLED,
        ),
    ).astype(np.int32)
    rec_max = np.max(np.where(valid, rec, _F32_NEG), axis=1).astype(np.float32)
    rec_min = np.min(np.where(valid, rec, _F32_POS), axis=1).astype(np.float32)
    selected = np.select(
        [policy == POLICY_MAX, policy == POLICY_MIN],
        [rec_max, rec_min],
        spec,
    ).astype(np.float32)
    selected = np.where(any_valid, selected, spec).astype(np.float32)

    going_up = selected > spec
    going_down = selected < spec
    window = np.where(
        going_up,
        np.asarray(inputs.up_window, np.int32),
        np.where(going_down, np.asarray(inputs.down_window, np.int32), 0),
    ).astype(np.float32)
    last = np.asarray(inputs.last_scale_time, np.float32)
    has_last = np.asarray(inputs.has_last_scale, bool)
    elapsed = (np.float32(inputs.now) - last).astype(np.float32)
    moving = going_up | going_down
    within = moving & has_last & (elapsed < window)
    window_end = (last + window).astype(np.float32)
    limited = np.where(within, spec, selected).astype(np.float32)

    def _allowed(ptype, pvalue, pperiod, pvalid, select):
        ptype = np.asarray(ptype, np.int32)
        pvalue_f = np.asarray(pvalue, np.int32).astype(np.float32)
        pperiod_f = np.asarray(pperiod, np.int32).astype(np.float32)
        pvalid = np.asarray(pvalid, bool)
        select = np.asarray(select, np.int32)
        base = np.maximum(spec[:, None], _F32_ONE).astype(np.float32)
        budget = np.where(
            ptype == POLICY_TYPE_PERCENT,
            _ceil_guarded_np(
                (
                    (base * pvalue_f).astype(np.float32)
                    / np.float32(100.0)
                ).astype(np.float32)
            ),
            pvalue_f,
        ).astype(np.float32)
        spent = has_last[:, None] & (elapsed[:, None] < pperiod_f)
        per_policy = np.where(spent, _F32_ZERO, budget).astype(np.float32)
        a_max = np.max(np.where(pvalid, per_policy, _F32_NEG), axis=1)
        a_min = np.min(np.where(pvalid, per_policy, _F32_POS), axis=1)
        allowed = np.where(
            select == POLICY_MIN, a_min, a_max
        ).astype(np.float32)
        unlimited = ~np.any(pvalid, axis=1) | ~has_last
        p_min = np.min(np.where(pvalid, pperiod_f, _F32_POS), axis=1)
        p_max = np.max(np.where(pvalid, pperiod_f, _F32_NEG), axis=1)
        frees = np.where(
            select == POLICY_MIN, p_max, p_min
        ).astype(np.float32)
        return (
            np.where(unlimited, _F32_POS, allowed).astype(np.float32),
            frees,
        )

    allowed_up, up_frees = _allowed(
        inputs.up_ptype,
        inputs.up_pvalue,
        inputs.up_pperiod,
        inputs.up_pvalid,
        inputs.up_policy,
    )
    allowed_down, down_frees = _allowed(
        inputs.down_ptype,
        inputs.down_pvalue,
        inputs.down_pperiod,
        inputs.down_pvalid,
        inputs.down_policy,
    )
    rate_clamped = np.clip(
        limited,
        (spec - allowed_down).astype(np.float32),
        (spec + allowed_up).astype(np.float32),
    ).astype(np.float32)
    rate_limited = rate_clamped != limited
    fully_held = rate_limited & (rate_clamped == spec)
    rate_end = (
        last + np.where(limited > spec, up_frees, down_frees)
    ).astype(np.float32)
    limited = rate_clamped

    able_to_scale = ~within & ~fully_held
    able_at = np.where(fully_held, rate_end, window_end).astype(np.float32)

    bounded = np.clip(
        limited,
        np.asarray(inputs.min_replicas, np.int32).astype(np.float32),
        np.asarray(inputs.max_replicas, np.int32).astype(np.float32),
    ).astype(np.float32)
    scaling_unbounded = bounded == limited

    up_hold = has_last & (
        elapsed < np.asarray(inputs.up_window, np.int32).astype(np.float32)
    )
    down_hold = has_last & (
        elapsed < np.asarray(inputs.down_window, np.int32).astype(np.float32)
    )
    up_ceiling = np.where(
        up_hold, spec, (spec + allowed_up).astype(np.float32)
    ).astype(np.float32)
    down_floor = np.where(
        down_hold, spec, (spec - allowed_down).astype(np.float32)
    ).astype(np.float32)

    def to_i32(x):
        return np.clip(
            x, np.float32(_I32_SAFE_MIN), np.float32(_I32_SAFE_MAX)
        ).astype(np.int32)

    return DecisionOutputs(
        desired=to_i32(bounded),
        recommendation=to_i32(selected),
        limited=to_i32(limited),
        able_to_scale=able_to_scale,
        scaling_unbounded=scaling_unbounded,
        able_at=able_at,
        rate_limited=rate_limited,
        up_ceiling=to_i32(up_ceiling),
        down_floor=to_i32(down_floor),
    )


def pad_to(n: int, bucket: int = 64) -> int:
    """Round a fleet size up to a compile bucket so recompiles only happen on
    bucket crossings, not every added autoscaler."""
    if n <= 0:
        return bucket
    return ((n + bucket - 1) // bucket) * bucket
