"""Batched HorizontalAutoscaler decision kernel.

The reference computes one scalar decision per autoscaler per 10s tick
(reference: pkg/autoscaler/autoscaler.go:144-194 calling
pkg/autoscaler/algorithms/proportional.go:30-47 and the behavior logic in
pkg/apis/autoscaling/v1alpha1/horizontalautoscaler.go:226-275). Here the
same semantics run as ONE jitted array program over all N autoscalers ×
M metrics at once:

    recommendation -> select policy (Max/Min/Disabled by direction)
                   -> stabilization window mask
                   -> [min, max] clamp + condition flags

Design notes (TPU):
- everything is fixed-shape f32/i32 tensors; ragged metric lists are padded
  and masked with metric_valid, so one compiled program serves any fleet
  size up to the padded bucket (no per-object recompiles, no host loop).
- time stays on the host: last_scale_time/now enter as f32 seconds relative
  to a host-chosen epoch (SURVEY.md §7 hard part (e)).
- ceil() is computed with a 1e-5 guard band so f32 rounding cannot round an
  exactly-representable f64 quotient across an integer boundary (the Go
  implementation computes in f64).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

# target types (order matters: encoded into int arrays)
TYPE_VALUE = 0
TYPE_AVERAGE_VALUE = 1
TYPE_UTILIZATION = 2
TYPE_UNKNOWN = 3

# select policies
POLICY_MAX = 0
POLICY_MIN = 1
POLICY_DISABLED = 2

_CEIL_GUARD = 1e-5

# f32 saturation bounds for the final int32 cast. 2**31-1 is NOT exactly
# representable in f32 (rounds up to 2**31, which fptosi wraps to INT32_MIN),
# so saturate at 2**31-128 = 2^7*(2^24-1), the largest f32-exact value below
# the int32 ceiling.
_I32_SAFE_MAX = float(2**31 - 128)
_I32_SAFE_MIN = float(-(2**31))  # exact power of two, representable


@jax.tree_util.register_dataclass
@dataclass
class DecisionInputs:
    """Structure-of-arrays snapshot of every HorizontalAutoscaler."""

    metric_value: jax.Array  # f32[N, M]
    target_value: jax.Array  # f32[N, M]
    target_type: jax.Array  # i32[N, M]
    metric_valid: jax.Array  # bool[N, M]
    spec_replicas: jax.Array  # i32[N]  scale target .spec.replicas
    status_replicas: jax.Array  # i32[N]  scale target .status.replicas
    min_replicas: jax.Array  # i32[N]
    max_replicas: jax.Array  # i32[N]
    up_window: jax.Array  # i32[N] stabilization seconds (default 0)
    down_window: jax.Array  # i32[N] stabilization seconds (default 300)
    up_policy: jax.Array  # i32[N]
    down_policy: jax.Array  # i32[N]
    last_scale_time: jax.Array  # f32[N] seconds since epoch0
    has_last_scale: jax.Array  # bool[N]
    now: jax.Array  # f32 scalar, seconds since epoch0


@jax.tree_util.register_dataclass
@dataclass
class DecisionOutputs:
    desired: jax.Array  # i32[N] final bounded decision
    recommendation: jax.Array  # i32[N] post-select, pre-limit
    able_to_scale: jax.Array  # bool[N] False iff within stabilization window
    scaling_unbounded: jax.Array  # bool[N] False iff clamped by [min, max]
    able_at: jax.Array  # f32[N] window end time (valid when !able_to_scale)


def _ceil_guarded(x: jax.Array) -> jax.Array:
    return jnp.ceil(x - _CEIL_GUARD)


def _recommendations(inputs: DecisionInputs) -> jax.Array:
    """Per-metric desired replicas, f32[N, M] (reference: proportional.go:30-47)."""
    # zero target: ratio collapses to 0, matching the scalar oracle
    # (algorithms/proportional.py) — float division by zero never reaches XLA
    safe_target = jnp.where(inputs.target_value != 0, inputs.target_value, 1.0)
    ratio = jnp.where(
        inputs.target_value != 0, inputs.metric_value / safe_target, 0.0
    )
    status = inputs.status_replicas[:, None].astype(jnp.float32)
    proportional = status * ratio

    by_value = jnp.maximum(1.0, _ceil_guarded(proportional))
    by_average = _ceil_guarded(ratio)
    by_utilization = jnp.maximum(1.0, _ceil_guarded(proportional * 100.0))
    fallback = status  # unknown target type keeps current replicas

    rec = jnp.select(
        [
            inputs.target_type == TYPE_VALUE,
            inputs.target_type == TYPE_AVERAGE_VALUE,
            inputs.target_type == TYPE_UTILIZATION,
        ],
        [by_value, by_average, by_utilization],
        fallback,
    )
    return rec


def decide(inputs: DecisionInputs) -> DecisionOutputs:
    """The full decision pipeline (reference: autoscaler.go:144-194)."""
    rec = _recommendations(inputs)  # f32[N, M]
    valid = inputs.metric_valid
    spec = inputs.spec_replicas.astype(jnp.float32)  # [N]

    # --- select policy (reference: horizontalautoscaler.go:226-247) -------
    any_valid = jnp.any(valid, axis=1)
    any_up = jnp.any(valid & (rec > spec[:, None]), axis=1)
    any_down = jnp.any(valid & (rec < spec[:, None]), axis=1)
    # direction picks which rules apply; no movement (or no metrics) disables
    policy = jnp.where(
        any_up,
        inputs.up_policy,
        jnp.where(any_down, inputs.down_policy, POLICY_DISABLED),
    )
    neg_inf = jnp.float32(np.finfo(np.float32).min)
    pos_inf = jnp.float32(np.finfo(np.float32).max)
    rec_max = jnp.max(jnp.where(valid, rec, neg_inf), axis=1)
    rec_min = jnp.min(jnp.where(valid, rec, pos_inf), axis=1)
    selected = jnp.select(
        [policy == POLICY_MAX, policy == POLICY_MIN],
        [rec_max, rec_min],
        spec,
    )
    selected = jnp.where(any_valid, selected, spec)

    # --- transient limits: stabilization window (autoscaler.go:172-194) ---
    going_up = selected > spec
    going_down = selected < spec
    window = jnp.where(
        going_up,
        inputs.up_window,
        jnp.where(going_down, inputs.down_window, 0),
    ).astype(jnp.float32)
    elapsed = inputs.now - inputs.last_scale_time
    moving = going_up | going_down
    within = (
        moving & inputs.has_last_scale & (elapsed < window)
    )
    able_to_scale = ~within
    able_at = inputs.last_scale_time + window
    limited = jnp.where(within, spec, selected)

    # --- bounded limits: [min, max] clamp (autoscaler.go:155-170) ---------
    bounded = jnp.clip(
        limited,
        inputs.min_replicas.astype(jnp.float32),
        inputs.max_replicas.astype(jnp.float32),
    )
    scaling_unbounded = bounded == limited

    to_i32 = lambda x: jnp.clip(
        x, jnp.float32(_I32_SAFE_MIN), jnp.float32(_I32_SAFE_MAX)
    ).astype(jnp.int32)
    return DecisionOutputs(
        desired=to_i32(bounded),
        recommendation=to_i32(selected),
        able_to_scale=able_to_scale,
        scaling_unbounded=scaling_unbounded,
        able_at=able_at,
    )


decide_jit = jax.jit(decide)


def pad_to(n: int, bucket: int = 64) -> int:
    """Round a fleet size up to a compile bucket so recompiles only happen on
    bucket crossings, not every added autoscaler."""
    if n <= 0:
        return bucket
    return ((n + bucket - 1) // bucket) * bucket
