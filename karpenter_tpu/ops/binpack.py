"""Pending-pods bin-packing: the north-star device solver.

The reference STUBS this signal (pkg/metrics/producers/pendingcapacity/
producer.go:29-31); its design doc defines the intent: "when a pod becomes
unschedulable, find a node group which, if scaled up, would cause the pod to
be scheduled; emit a signal per node group" (docs/designs/DESIGN.md "Pending
Pods"), and warns the naive form "scales linearly with node groups and
unschedulable pods" (DESIGN.md Queue Length discussion). Here the whole
problem — P pending pods × T node groups/instance types — is one fixed-shape
XLA program:

1. FEASIBILITY [P, T]: resource fit (req <= allocatable, accumulated per
   resource to avoid a [P,T,R] intermediate), taints/tolerations and
   nodeSelector/affinity as BITSET MATMULS: violations = intolerant[P,K] @
   taints[K,T] — the K/L axes ride the MXU instead of per-pair host loops.
2. ASSIGNMENT [P]: each pod goes to its first feasible group (argmax of the
   boolean row), so only one group scales up per pod — the DESIGN.md
   single-scale-up rule.
3. PACKING: per group, pod sizes collapse to dominant-share fractions
   s = max_r(req/alloc) in (0,1], quantized UP into B buckets. The bucket
   histogram [T, B] then feeds a vectorized shelf best-fit-decreasing: a
   remaining-capacity histogram [T, B+1] is updated size-by-size (descending)
   with cumsum-based placement — O(B) lax steps regardless of P, every group
   in parallel. Quantizing up makes the result a VALID (sufficient) node
   count; the LP relaxation bound is returned alongside as the lower sandwich.

Everything is static-shape; P and T are padded to compile buckets.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

DEFAULT_BUCKETS = 32


@jax.tree_util.register_dataclass
@dataclass
class BinPackInputs:
    """Structure-of-arrays snapshot of the pending-pods problem.

    K = taint-universe size (distinct taints across groups), L = label-
    constraint universe (distinct pod-required labels). Both are padded.

    Rows are pod SHAPES, not necessarily pods: two pods with identical
    (requests, required labels, tolerations) are interchangeable to every
    stage of the solve — same feasibility row, same first-feasible group,
    same bucket — so the encoder collapses them into one row with
    `pod_weight` = multiplicity (producers/pendingcapacity
    encode_snapshot). That is what turns the 100k-pod snapshot into a
    few-hundred-row upload. pod_weight=None means every row counts once.
    """

    pod_requests: jax.Array  # f32[P, R] resource requests
    pod_valid: jax.Array  # bool[P]
    pod_intolerant: jax.Array  # bool[P, K] pod does NOT tolerate taint k
    pod_required: jax.Array  # bool[P, L] pod requires label l
    group_allocatable: jax.Array  # f32[T, R] per-node allocatable
    group_taints: jax.Array  # bool[T, K] group nodes carry taint k
    group_labels: jax.Array  # bool[T, L] group nodes carry label l
    pod_weight: Optional[jax.Array] = None  # i32[P] row multiplicity
    # bool[P, T]: pod p's REQUIRED node affinity (matchExpressions with
    # In/NotIn/Exists/DoesNotExist/Gt/Lt, OR'd terms) rules out group t.
    # Arbitrary boolean structure doesn't factor into the conjunctive
    # required-label bitset, so the host evaluates each DISTINCT affinity
    # shape against each group profile (S_a x T, both tiny) and gathers to
    # rows (producers/pendingcapacity.encode_snapshot); rows are
    # deduplicated shapes, so this stays KB-scale. None = no pod
    # constrains affinity (the common case costs nothing).
    pod_group_forbidden: Optional[jax.Array] = None
    # f32[P, T]: pod p's PREFERRED node affinity score for group t
    # (weight-sum of matching preference terms, host-evaluated per
    # distinct shape like the mask above). Never affects feasibility;
    # among feasible groups the pod assigns to its max-score group with
    # lowest-index tie-break — score None or all-equal degenerates to
    # exactly the first-feasible rule. Integer-valued (weight sums
    # <= 100 x terms), so f32 comparison is exact.
    pod_group_score: Optional[jax.Array] = None
    # i32[P]: pod priority (the PriorityClass value; 0 when unset).
    # Paired with group_tier it STEERS assignment: among feasible
    # groups, a pod with positive priority prefers the lowest-tier
    # (on-demand) group — encoded as an integer-valued score (binpack
    # docstring "priority steering"), so priority-0 fleets and
    # absent-priority fleets assign identically. The eviction-planning
    # kernel (ops/preempt.py) consumes the same vector for
    # evictability. None = all-equal priority, today's behavior
    # bit-identically.
    pod_priority: Optional[jax.Array] = None
    # i32[T]: capacity tier per group — 0 on-demand, >0 preemptible/
    # spot (derived from the well-known capacity-type node labels,
    # api/core.capacity_tier_of). Only acts when pod_priority is also
    # present; alone it rides through for the preemption encoder.
    group_tier: Optional[jax.Array] = None
    # bool[P]: the row's pods demand a node to themselves — required
    # inter-pod SELF-anti-affinity on kubernetes.io/hostname ("one
    # replica per node", the StatefulSet/daemon pattern). Encoded by
    # forcing the row's quantized size to a FULL node (bucket = B), so
    # shelf-BFD opens one node per weighted pod and shares it with
    # nothing — conservative for a scale-up signal: the real scheduler
    # could co-locate non-matching pods on those nodes, but the signal
    # never under-counts. Feasibility/assignment are untouched. None =
    # no exclusive rows (the common case costs nothing).
    pod_exclusive: Optional[jax.Array] = None
    # --- constraint plane (karpenter_tpu/constraints) ------------------
    # All six operands are Optional; absent operands reproduce the
    # pre-constraint outputs bit-identically (the PR 6 pattern). The
    # constraint compiler (constraints/compiler.py) is the only producer.
    #
    # i32[P]: reservation claim id per row (0 = unclaimed). A row with
    # claim c fits ONLY groups whose group_reservation == c; unclaimed
    # rows fit only open (reservation-0) groups. One integer equality
    # covers both reserved-capacity claims and the open-capacity fence.
    pod_claim: Optional[jax.Array] = None
    # i32[T]: reservation id carried by each group's nodes (0 = open
    # capacity). Meaningful alone (fences reserved groups away from
    # unclaimed pods) or with pod_claim.
    group_reservation: Optional[jax.Array] = None
    # bool[P, C]: one-hot pack class per row. Column 0 is the shared
    # default class; columns 1+ are isolation classes (anti-affinity
    # groups / compact placement) whose rows must not share a node with
    # any other class. C rides the operand SHAPE (no static kwarg); the
    # kernel folds rows with no bit set into class 0 in BOTH backends.
    # Affects ONLY the packing stage: per-class shelf-BFD histograms
    # [C*T, B] sum into nodes_needed — conservative (never under-counts)
    # because the real scheduler could co-locate across classes only
    # when no anti-affinity matches.
    pod_pack_class: Optional[jax.Array] = None
    # i32[P]: topology-spread slot per row (0 = unconstrained; s >= 1
    # indexes spread_cap row s-1). Rows in slot s water-fill domains in
    # index order under that slot's per-domain caps via an exclusive
    # prefix-sum rank; EXACTNESS CONTRACT: the compiler pre-splits
    # constrained rows at cap boundaries so no weighted row straddles a
    # domain boundary. Rank >= total cap -> infeasible everywhere
    # (conservative unschedulable).
    pod_spread_slot: Optional[jax.Array] = None
    # i32[T]: topology domain index per group (zone); domain D-1 is the
    # no-zone sink with zero cap in every slot.
    group_domain: Optional[jax.Array] = None
    # i32[S, D]: per-slot per-domain pod-count caps (balanced allocation
    # computed by the compiler so skew <= 1 <= any max_skew >= 1).
    spread_cap: Optional[jax.Array] = None


@jax.tree_util.register_dataclass
@dataclass
class BinPackOutputs:
    assigned: jax.Array  # i32[P] group index per input ROW, -1 if unschedulable
    assigned_count: jax.Array  # i32[T] pods (weighted rows) routed to each group
    nodes_needed: jax.Array  # i32[T] shelf-BFD node count (valid upper bound)
    lp_bound: jax.Array  # i32[T] LP-relaxation lower bound
    unschedulable: jax.Array  # i32 scalar: pods with no feasible group


# Priority steering (pod_priority x group_tier). The steer never
# COMPOSES arithmetically with the preference score — score magnitudes
# are unbounded (soft-spread scores scale with live domain counts), so
# any clamp-and-add scheme silently reorders large scores. Steering is
# instead LEXICOGRAPHIC: best steer first, preference score as the
# tie-break within the winning steer level (steered_choice).


def steer_matrix(priority, tier, xp=np):
    """f32[P, T] steer — 0 everywhere except -1 where a
    positive-priority pod meets a tier>0 group — or None when priority
    or tier is absent. Boolean by design: within one pod's row the only
    question argmax/max can ask is on-demand vs preemptible, so the
    priority MAGNITUDE can never reorder anything (the eviction kernel
    is where magnitudes compare). Priority-0 rows steer nowhere, so
    fleets without PriorityClasses order exactly as before; the -1/0
    values are trivially exact in f32 on both backends."""
    if priority is None or tier is None:
        return None
    return (
        -(
            (tier > 0)[None, :] & (priority > 0)[:, None]
        ).astype(np.int32)
    ).astype(np.float32)


def steered_choice(feasible, score, steer, xp=np):
    """i32[P]: the assignment argmax under lexicographic
    (steer, score) preference — among feasible groups, take the
    best-steer level (positive-priority pods prefer on-demand tiers),
    then the best score within it, argmax's first-max rule breaking
    the final tie to the lowest index. With steer absent this is
    exactly the historical score path; with both absent callers use
    the plain first-feasible argmax. All comparisons are on
    integer-valued f32 (steer) or caller-provided scores compared
    verbatim — no composition arithmetic, so no magnitude limits."""
    neg_inf = np.float32(-np.inf)
    if steer is None:
        return xp.argmax(xp.where(feasible, score, neg_inf), axis=1)
    masked_steer = xp.where(feasible, steer, neg_inf)
    if score is None:
        return xp.argmax(masked_steer, axis=1)
    best_steer = xp.max(masked_steer, axis=1, keepdims=True)
    tie = feasible & (masked_steer == best_steer)
    return xp.argmax(xp.where(tie, score, neg_inf), axis=1)


_CONSTRAINT_FIELDS = (
    "pod_claim",
    "group_reservation",
    "pod_pack_class",
    "pod_spread_slot",
    "group_domain",
    "spread_cap",
)


def has_constraint_operands(inputs: BinPackInputs) -> bool:
    """True when any constraint-plane operand is present. The solver
    service and the pallas fold both route constraint-carrying traffic
    to the XLA family on this predicate (Mosaic has no constraint
    entry — silently dropping an operand is the PR 8 bug class)."""
    return any(getattr(inputs, f) is not None for f in _CONSTRAINT_FIELDS)


def constraint_mask(
    claim, reservation, slot, domain, caps, weight, valid, xp=np
):
    """Feasibility mask (broadcastable against [P, T]) for the
    reservation-claim and topology-spread constraint operands, or None
    when neither constraint is present.

    Reservation is one integer equality: claim[p] == reservation[t]
    (0 == 0 keeps unclaimed pods on open capacity; c == c keeps claimed
    pods on their reservation). Either side absent substitutes zeros —
    expressed through broadcasting so no zeros array is materialized.

    Spread is an in-kernel rank-interval water-fill: rows in slot s
    (s >= 1) take an exclusive weighted prefix-sum rank over their slot,
    and each row targets the FIRST domain whose cumulative cap still has
    room for its rank. The compiler pre-splits rows at cap boundaries
    (see pod_spread_slot docstring) so the greedy fill is exact; a rank
    past the total cap is infeasible everywhere (conservative
    unschedulable). Integer-only arithmetic end to end, so the numpy
    mirror (xp=np) is bitwise identical to the XLA program (xp=jnp)."""
    mask = None
    if claim is not None or reservation is not None:
        if claim is None:
            res_m = reservation[None, :] == 0  # [1, T]
        elif reservation is None:
            res_m = (claim == 0)[:, None]  # [P, 1]
        else:
            res_m = claim[:, None] == reservation[None, :]  # [P, T]
        mask = res_m
    if slot is not None and domain is not None and caps is not None:
        n_slots = caps.shape[0]
        valid_i = valid.astype(xp.int32)
        w_eff = valid_i if weight is None else weight * valid_i  # i32[P]
        onehot = (
            slot[:, None]
            == xp.arange(1, n_slots + 1, dtype=xp.int32)[None, :]
        )  # bool[P, S]
        contrib = w_eff[:, None] * onehot.astype(xp.int32)  # i32[P, S]
        rank = xp.cumsum(contrib, axis=0) - contrib  # exclusive, per slot
        rank_p = xp.sum(xp.where(onehot, rank, 0), axis=1)  # i32[P]
        cumcap = xp.cumsum(caps, axis=1)  # i32[S, D]
        row_caps = cumcap[xp.clip(slot - 1, 0, n_slots - 1)]  # i32[P, D]
        fits_dom = rank_p[:, None] < row_caps  # bool[P, D]
        target = xp.argmax(fits_dom, axis=1).astype(xp.int32)  # first fit
        has_dom = xp.any(fits_dom, axis=1)
        sp_m = (slot[:, None] <= 0) | (
            (domain[None, :] == target[:, None]) & has_dom[:, None]
        )  # [P, T]
        mask = sp_m if mask is None else mask & sp_m
    return mask


def _feasibility(inputs: BinPackInputs) -> jax.Array:
    """bool[P, T]: pod p can run on a node of group t."""
    req = inputs.pod_requests  # [P, R]
    alloc = inputs.group_allocatable  # [T, R]
    n_resources = req.shape[1]

    # resource fit, accumulated one resource at a time: [P, T] live, never
    # [P, T, R]
    fits = jnp.ones((req.shape[0], alloc.shape[0]), bool)
    for r in range(n_resources):  # R is tiny and static: unrolled by design
        fits &= req[:, r : r + 1] <= alloc[None, :, r]
    # a group with zero allocatable in every resource is an empty/unknown
    # group: nothing fits it
    fits &= jnp.any(alloc > 0, axis=1)[None, :]

    # taints: violation iff the group has a taint the pod does not tolerate.
    # bitset matmul [P, K] @ [K, T] -> MXU.
    taint_violations = jnp.dot(
        inputs.pod_intolerant.astype(jnp.float32),
        inputs.group_taints.astype(jnp.float32).T,
        precision=lax.Precision.DEFAULT,
    )
    # node selector / required affinity: violation iff the pod requires a
    # label the group lacks.
    label_violations = jnp.dot(
        inputs.pod_required.astype(jnp.float32),
        (~inputs.group_labels).astype(jnp.float32).T,
        precision=lax.Precision.DEFAULT,
    )
    fits &= taint_violations < 0.5
    fits &= label_violations < 0.5
    if inputs.pod_group_forbidden is not None:
        fits &= ~inputs.pod_group_forbidden
    fits &= inputs.pod_valid[:, None]
    cmask = constraint_mask(
        inputs.pod_claim,
        inputs.group_reservation,
        inputs.pod_spread_slot,
        inputs.group_domain,
        inputs.spread_cap,
        inputs.pod_weight,
        inputs.pod_valid,
        xp=jnp,
    )
    if cmask is not None:
        fits = fits & cmask
    return fits


def _dominant_share(inputs: BinPackInputs) -> jax.Array:
    """f32[P, T]: max over resources of req/alloc (the pod's size as a
    fraction of one node of each group)."""
    req = inputs.pod_requests
    alloc = inputs.group_allocatable
    share = jnp.zeros((req.shape[0], alloc.shape[0]), jnp.float32)
    for r in range(req.shape[1]):
        a = alloc[None, :, r]
        s = jnp.where(a > 0, req[:, r : r + 1] / jnp.maximum(a, 1e-30), jnp.inf)
        # a zero-allocatable resource with zero request contributes 0
        s = jnp.where((a <= 0) & (req[:, r : r + 1] <= 0), 0.0, s)
        share = jnp.maximum(share, s)
    return share


def _shelf_bfd(histogram: jax.Array, buckets: int) -> jax.Array:
    """Vectorized shelf best-fit-decreasing over bucket histograms.

    histogram: i32[T, B] — count of items of quantized size (b+1)/B per group.
    Returns i32[T]: bins (nodes) needed. State is a remaining-capacity
    histogram bins[T, B+1] (bins[t, rem] = open bins with integer remaining
    capacity rem); items of size k first fill existing bins best-fit
    (smallest sufficient rem first, via masked cumsum), then open new bins
    holding floor(B/k) items each. Processing sizes descending preserves the
    FFD property that large remnants get reused by smaller items.
    """
    n_groups = histogram.shape[0]
    rem_index = jnp.arange(buckets + 1, dtype=jnp.int32)  # [B+1]

    def step(carry, k):
        bins, total = carry  # bins i32[T, B+1], total i32[T]
        c = histogram[:, k - 1]  # items of integer size k

        # repeatedly fill existing bins; each pass places one item per
        # available bin (smallest sufficient rem first), remnants re-enter at
        # rem-k and may take another item next pass — capped at B passes,
        # exiting EARLY once nothing is left or a pass makes no progress
        # (both make every further pass a no-op: place=0 leaves bins and c
        # untouched, so the early exit is bit-exact vs. running out the
        # cap; the usual 1-2 productive passes are what actually run,
        # which is the difference between O(B^2) and ~O(B) lax steps per
        # solve)
        def fill_cond(state):
            i, _, c_i, placed = state
            return (
                (i < buckets)
                & jnp.any(c_i > 0)
                & ((i == 0) | (placed > 0))
            )

        def fill_body(state):
            i, bins_i, c_i, _ = state
            avail = jnp.where(
                (rem_index[None, :] >= k) & (rem_index[None, :] > 0), bins_i, 0
            )
            cum_before = jnp.cumsum(avail, axis=1) - avail  # exclusive cumsum
            place = jnp.clip(c_i[:, None] - cum_before, 0, avail)
            bins_i = bins_i - place + jnp.roll(place, -k, axis=1)
            c_i = c_i - jnp.sum(place, axis=1)
            return i + 1, bins_i, c_i, jnp.sum(place)

        _, bins, c, _ = lax.while_loop(
            fill_cond, fill_body,
            (jnp.int32(0), bins, c, jnp.int32(0)),
        )

        # leftovers open fresh bins, floor(B/k) items per bin
        per_bin = buckets // k
        full_bins = c // per_bin
        leftover = c - full_bins * per_bin
        has_partial = (leftover > 0).astype(jnp.int32)
        new_bins = full_bins + has_partial
        total = total + new_bins
        # register remnants so smaller sizes can reuse them
        full_rem = buckets - per_bin * k
        partial_rem = buckets - leftover * k
        bins = bins.at[:, full_rem].add(full_bins)
        bins = bins + (
            (rem_index[None, :] == partial_rem[:, None]).astype(jnp.int32)
            * has_partial[:, None]
        )
        return (bins, total), None

    bins0 = jnp.zeros((n_groups, buckets + 1), jnp.int32)
    total0 = jnp.zeros((n_groups,), jnp.int32)
    sizes_desc = jnp.arange(buckets, 0, -1, dtype=jnp.int32)
    (_, total), _ = lax.scan(step, (bins0, total0), sizes_desc)
    return total


@partial(jax.jit, static_argnames=("buckets",))
def binpack(inputs: BinPackInputs, buckets: int = DEFAULT_BUCKETS) -> BinPackOutputs:  # lint: allow-complexity — kernel entry: one guard per optional operand
    feasible = _feasibility(inputs)  # [P, T]
    share = _dominant_share(inputs)  # [P, T]

    # first feasible group wins (argmax returns the first True); with
    # preference scores, highest score among feasible wins and argmax's
    # first-max rule provides the lowest-index tie-break — identical to
    # first-feasible when scores are absent or uniform. Priority x tier
    # steering is LEXICOGRAPHICALLY senior to the score
    # (steered_choice): positive-priority pods prefer on-demand over
    # preemptible tiers, preference scores break ties within a tier.
    any_feasible = jnp.any(feasible, axis=1)
    steer = steer_matrix(
        inputs.pod_priority, inputs.group_tier, xp=jnp
    )
    if steer is None and inputs.pod_group_score is None:
        choice = jnp.argmax(feasible, axis=1)
    else:
        choice = steered_choice(
            feasible, inputs.pod_group_score, steer, xp=jnp
        )
    assigned = jnp.where(any_feasible, choice.astype(jnp.int32), -1)
    n_groups = inputs.group_allocatable.shape[0]
    member = (
        (assigned[:, None] == jnp.arange(n_groups, dtype=jnp.int32)[None, :])
        & any_feasible[:, None]
    )  # [P, T]

    # weighted membership: every aggregate below counts each row
    # `pod_weight` times (rows are deduplicated pod shapes)
    w = inputs.pod_weight
    member_w = (
        member.astype(jnp.int32)
        if w is None
        else member.astype(jnp.int32) * w[:, None]
    )  # i32[P, T]

    assigned_count = jnp.sum(member_w, axis=0)  # [T]

    # quantize UP into B integer sizes; clip to [1, B]
    bucket_of = jnp.clip(
        jnp.ceil(share * buckets).astype(jnp.int32), 1, buckets
    )  # [P, T]
    if inputs.pod_exclusive is not None:
        # hostname self-anti-affinity: the pod takes a whole node
        bucket_of = jnp.where(
            inputs.pod_exclusive[:, None], buckets, bucket_of
        )
    # per-bucket reduction keeps peak memory at [P, T] (a [P, T, B] one-hot
    # would be ~1 GB at the 100k x 300 bench scale)
    pc = inputs.pod_pack_class
    if pc is None:
        histogram = jnp.stack(
            [
                jnp.sum(
                    jnp.where(bucket_of == b, member_w, 0),
                    axis=0,
                    dtype=jnp.int32,
                )
                for b in range(1, buckets + 1)
            ],
            axis=1,
        )  # [T, B]

        nodes_needed = _shelf_bfd(histogram, buckets)
    else:
        # isolation pack classes: rows of different classes must not
        # share a node, so shelf-BFD runs on a per-class [T, B] histogram
        # and nodes sum across classes (shelf rows are independent, so
        # per-class-then-sum == the [C*T, B] stacked solve). Rows with no
        # class bit fold to the shared class 0 (the safety rule both
        # backends pin). Kept as C separate [T, B] solves rather than one
        # concatenated [C*T, B]: GSPMD miscompiles a concat of
        # separately-reduced pods-axis partial sums (the pending psum is
        # applied per concat operand AND per shard, inflating counts by
        # the pods-shard factor), while the [T, B] shape partitions
        # correctly — pinned by the sharded-parity tests.
        n_classes = pc.shape[1]
        fold0 = pc[:, 0] | ~jnp.any(pc, axis=1)
        nodes_needed = jnp.zeros((n_groups,), jnp.int32)
        for c in range(n_classes):
            cls = fold0 if c == 0 else pc[:, c]
            member_c = member_w * cls[:, None].astype(jnp.int32)
            hist_c = jnp.stack(
                [
                    jnp.sum(
                        jnp.where(bucket_of == b, member_c, 0),
                        axis=0,
                        dtype=jnp.int32,
                    )
                    for b in range(1, buckets + 1)
                ],
                axis=1,
            )  # [T, B]
            nodes_needed = nodes_needed + _shelf_bfd(hist_c, buckets)

    # LP lower bound: per resource, total assigned demand / per-node
    # allocatable, ceil; max across resources
    # HIGHEST precision: the TPU MXU rounds f32 operands to bf16 by default,
    # which drifts the demand sum ~1e-4 relative and can flip the ceil at a
    # fit boundary; the matmul is tiny ([T, R] output) so exactness is free
    demand = jnp.einsum(
        "pt,pr->tr",
        member_w.astype(jnp.float32),
        inputs.pod_requests,
        precision=lax.Precision.HIGHEST,
    )  # [T, R]
    alloc = inputs.group_allocatable
    per_resource = jnp.where(
        alloc > 0,
        jnp.ceil(demand / jnp.maximum(alloc, 1e-30) - 1e-5),
        0.0,
    )
    lp_bound = jnp.max(per_resource, axis=1).astype(jnp.int32)

    unsched_mask = ((~any_feasible) & inputs.pod_valid).astype(jnp.int32)
    unschedulable = jnp.sum(
        unsched_mask if w is None else unsched_mask * w, dtype=jnp.int32
    )
    return BinPackOutputs(
        assigned=assigned,
        assigned_count=assigned_count,
        nodes_needed=nodes_needed,
        lp_bound=lp_bound,
        unschedulable=unschedulable,
    )


def _fold_for_pallas(inputs: BinPackInputs):
    """(inputs, backend) for the Mosaic path, which predates the
    priority operands. Score-free priority fleets fold the steer
    matrix into the score operand the kernel does understand (with no
    base score, steer IS the score — assignment identical by
    construction) and strip the priority fields. A fleet carrying BOTH
    a preference score and steering needs the lexicographic
    (steer, score) choice, which a single score operand cannot
    express without magnitude limits — that rare combination routes to
    the XLA program instead (exact, still on-device). Everyone else
    passes through untouched; only priority fleets pay the host fold
    (and forgo the identity device memo). Constraint-plane operands
    (has_constraint_operands) always route to XLA: Mosaic has no
    constraint entry, and dropping an operand silently is the PR 8 bug
    class."""
    if has_constraint_operands(inputs):
        return inputs, "xla"
    if inputs.pod_priority is None or inputs.group_tier is None:
        return inputs, "pallas"
    if inputs.pod_group_score is not None:
        return inputs, "xla"
    import dataclasses

    return (
        dataclasses.replace(
            inputs,
            pod_group_score=steer_matrix(
                np.asarray(inputs.pod_priority),
                np.asarray(inputs.group_tier),
                xp=np,
            ),
            pod_priority=None,
            group_tier=None,
        ),
        "pallas",
    )


# one-slot identity-keyed device residency cache: callers that pass the SAME
# BinPackInputs object again (the encode memo in producers/pendingcapacity.py
# does exactly that when no pod/node/producer changed) skip the host->device
# transfer of the full ~10 MB input set — the dominant tick cost when the
# chip sits behind a network tunnel. Contract: inputs must be treated as
# immutable once passed to solve(); every encode path builds fresh arrays.
_put_memo = None


def _device_resident(inputs: BinPackInputs) -> BinPackInputs:
    global _put_memo
    memo = _put_memo
    if memo is not None and memo[0] is inputs:
        return memo[1]
    resident = jax.device_put(inputs)
    _put_memo = (inputs, resident)
    return resident


def solve(
    inputs: BinPackInputs,
    buckets: int = DEFAULT_BUCKETS,
    backend: str = "auto",
) -> BinPackOutputs:
    """Backend dispatcher: 'xla' (this module), 'pallas' (the fused Mosaic
    kernel, ops/pallas_binpack.py), 'numpy' (the CPU-shaped degraded-mode
    program, ops/numpy_binpack.py), or 'auto' — pallas on TPU, numpy on a
    CPU default backend (the accelerator-outage fallback: the XLA
    program's dense O(P*T*B) histogram layout is built for the MXU and
    dominates a CPU solve, while the numpy program's sparse scatters are
    O(P)). All backends are pinned element-for-element equal by
    tests/test_pallas_binpack.py and tests/test_numpy_binpack.py. Inputs
    are device-cached by object identity (see _device_resident): treat
    them as immutable.

    This is the kernel-level entry; production callers submit through the
    shared solve service (karpenter_tpu/solver — coalescing, shape
    bucketing, backpressure) rather than calling here directly."""
    if backend == "auto":
        if jax.default_backend() == "tpu":
            backend = "pallas"
        elif jax.default_backend() == "cpu":
            backend = "numpy"
        else:
            backend = "xla"
    if backend == "numpy":
        from karpenter_tpu.ops.numpy_binpack import binpack_numpy

        return binpack_numpy(inputs, buckets=buckets)
    if backend == "pallas":
        inputs, backend = _fold_for_pallas(inputs)
    inputs = _device_resident(inputs)
    if backend == "xla":
        return binpack(inputs, buckets=buckets)
    if backend == "pallas":
        from karpenter_tpu.ops.pallas_binpack import (
            binpack_pallas,
            default_interpret,
        )

        return binpack_pallas(
            inputs, buckets=buckets, interpret=default_interpret()
        )
    raise ValueError(f"unknown binpack backend {backend!r}")


# ---------------------------------------------------------------------------
# Scalar oracle (NumPy): the same shelf-BFD algorithm, item by item, used by
# property tests to pin the kernel exactly, plus a classic full-precision FFD
# for quality sandwich checks.
# ---------------------------------------------------------------------------


def _bfd_fill_existing(bins: np.ndarray, k: int, c: int, buckets: int) -> int:
    """Place as many of `c` items of size k into existing bins, best-fit
    (smallest sufficient remnant first), re-scanning as remnants shrink.
    Returns the unplaced count."""
    while c > 0:
        placed = False
        for rem in range(k, buckets + 1):
            m = min(c, int(bins[rem]))
            if m > 0:
                bins[rem] -= m
                bins[rem - k] += m
                c -= m
                placed = True
            if c == 0:
                break
        if not placed:
            break
    return c


def oracle_shelf_bfd(histogram: np.ndarray, buckets: int) -> np.ndarray:
    """histogram: i32[T, B] -> i32[T], mirroring _shelf_bfd semantics."""
    n_groups = histogram.shape[0]
    totals = np.zeros(n_groups, np.int64)
    for t in range(n_groups):
        bins = np.zeros(buckets + 1, np.int64)  # count by remaining capacity
        for k in range(buckets, 0, -1):
            c = _bfd_fill_existing(bins, k, int(histogram[t, k - 1]), buckets)
            if c > 0:
                per_bin = buckets // k
                full = c // per_bin
                leftover = c - full * per_bin
                totals[t] += full + (1 if leftover > 0 else 0)
                bins[buckets - per_bin * k] += full
                if leftover > 0:
                    bins[buckets - leftover * k] += 1
    return totals.astype(np.int64)


def oracle_ffd(sizes: np.ndarray) -> int:
    """Classic full-precision first-fit-decreasing on fractional sizes."""
    bins: list = []
    for s in sorted(sizes, reverse=True):
        for i, rem in enumerate(bins):
            if s <= rem + 1e-9:
                bins[i] = rem - s
                break
        else:
            bins.append(1.0 - s)
    return len(bins)
