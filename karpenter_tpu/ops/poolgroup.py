"""Joint candidate-ladder allocation for PoolGroups — coordinated
heterogeneous / disaggregated scaling as ONE batched device program.

The cost kernel (ops/cost.py) refines each autoscaler in isolation: a
K-candidate ladder per row, argmin of risk-vs-cost per row. Serving
workloads split into interdependent pools (prefill vs decode, router vs
worker) need the refinement to be JOINT — "Taming the Chaos" (PAPERS.md)
shows per-pool loops oscillate and strand capacity because each pool's
optimum ignores the ratio the workload actually needs. This kernel
generalizes the candidate ladder to the PRODUCT of the member pools'
ladders: for every group of P pools it enumerates all K^P joint
candidates (mixed-radix digits over the per-pool K=8 ladders), scores
each pool's digit with EXACTLY the cost kernel's op sequence, and adds
exact-integer penalty operands for the group's declared constraints:

- cross-pool ratio bands (decode:prefill in [2:1, 4:1]) — integer
  cross-multiplication, no division, bit-exact on both backends
- a shared group budget cap (sum of pool spends vs maxHourlyCost)

Selection is two-level, which makes the wire-compat pin exact BY
CONSTRUCTION instead of probabilistically: first each pool's INDEPENDENT
argmin is computed exactly as cost_decide computes it; if that joint
point violates nothing, it IS the answer (so slack constraints reproduce
the uncoordinated fixed point bit for bit — a float argmin over summed
scores could not promise that: a strictly larger addend can round to an
equal sum at a smaller index and steal the tie-break). Only when the
independent point violates a constraint does the repair argmin engage:
fewest violations, then cheapest joint score, then first index.

Capacity-tier preference folds into the objective as a per-pool
`tierPenalty` added to the hourly rate (score only — the budget cap
stays in real dollars); a penalty of 0.0 adds f32 zero to a
non-negative rate, bit-identical to the cost kernel's term, so the
joint == independent parity pin holds whenever penalties are absent.

Parity contract (pinned bitwise in tests/test_poolgroup.py, the
ops/cost.py discipline): the jitted kernel and `poolgroup_numpy`
produce IDENTICAL bits on every output leaf. The two multiply-
accumulates (per-pool score, group spend accumulation) are written in
single-mul `a * b + c` form — XLA:CPU contracts each into one FMA,
reproduced on host by a float64 round-trip; the joint score total and
the spend are accumulated pool-by-pool in UNROLLED static order
(identical add order on both sides); every violation operand is exact
int32; both argmins break ties to the first index on both backends.

Pool and ratio axes are padded to static buckets (pad pools carry
base=min=max=unit=weight=0, scoring 0 at every candidate — inert in
every sum and argmin) so steady fleets never recompile.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_tpu.ops.cost import CANDIDATES, _EPS, _ONE, _ZERO, _fma
from karpenter_tpu.ops.decision import _I32_SAFE_MAX, _I32_SAFE_MIN

# Pool-count ceiling per group and the static pool-axis buckets: the
# joint candidate space is K^P, so P is hard-bounded (4 pools x K=8 is
# 4096 joint candidates — one gather-heavy but small program) and padded
# to 2 or 4 to keep compiled shapes stable as groups gain a pool.
MAX_POOLS = 4
POOL_BUCKETS = (2, 4)

# Ratio-constraint slots per group (static axis; unused slots are
# ratio_valid=False and integer-self-disabling — see _violations).
RATIO_SLOTS = 4

# Ratio numerators/denominators are bounded so the int32 cross products
# n * den can never overflow: counts up to ~2M replicas stay exact.
RATIO_BOUND = 1024

_INF = np.float32(np.inf)


def pad_pool_count(pools: int) -> int:
    """The static pool-axis bucket for a fleet whose widest group has
    `pools` members (compile-key stability: 2 covers the common
    prefill/decode pair, 4 everything the validator admits)."""
    for bucket in POOL_BUCKETS:
        if pools <= bucket:
            return bucket
    raise ValueError(f"pool groups support at most {MAX_POOLS} pools")


def joint_digits(pools: int) -> np.ndarray:
    """i32[P, K^P] mixed-radix digit matrix: digits[p, c] is pool p's
    ladder index within joint candidate c. A host constant folded into
    the compiled program (pure function of the static pool bucket)."""
    c = CANDIDATES ** pools
    return (
        (np.arange(c)[None, :] // (CANDIDATES ** np.arange(pools)[:, None]))
        % CANDIDATES
    ).astype(np.int32)


@jax.tree_util.register_dataclass
@dataclass
class PoolGroupInputs:
    """Structure-of-arrays joint view of every PoolGroup: G groups x P
    pools (both padded to static buckets) x M metrics. Per-pool fields
    carry exactly what the cost kernel sees for that pool's row;
    movement bounds (min/max_replicas) arrive PRE-CLAMPED to each HA's
    rate-limited movement interval (the engine's job, the CostEngine
    discipline), so the joint choice can never outrun a pool's scaling
    policies."""

    base_desired: jax.Array  # i32[G, P] the decide() output per pool
    min_replicas: jax.Array  # i32[G, P] movement-clamped floor
    max_replicas: jax.Array  # i32[G, P] movement-clamped ceiling
    unit_cost: jax.Array  # f32[G, P] hourly cost per replica (0 = unknown)
    slo_weight: jax.Array  # f32[G, P] violationCostWeight per pool
    max_hourly_cost: jax.Array  # f32[G, P] per-pool budget (0 = uncapped)
    tier_penalty: jax.Array  # f32[G, P] capacity-tier score penalty ($/h)
    pool_valid: jax.Array  # bool[G, P] slot holds a live member pool
    slo_target: jax.Array  # f32[G, P, M] per-replica SLO capacity
    demand_mu: jax.Array  # f32[G, P, M] demand point (forecast/observed)
    demand_sigma: jax.Array  # f32[G, P, M] forecast spread (0 = none)
    demand_valid: jax.Array  # bool[G, P, M]
    ratio_a: jax.Array  # i32[G, R] numerator pool index per ratio slot
    ratio_b: jax.Array  # i32[G, R] denominator pool index
    ratio_min_num: jax.Array  # i32[G, R] lower band: a/b >= min_num/min_den
    ratio_min_den: jax.Array  # i32[G, R]
    ratio_max_num: jax.Array  # i32[G, R] upper band: a/b <= max_num/max_den
    ratio_max_den: jax.Array  # i32[G, R] (0/0 = no upper bound)
    ratio_valid: jax.Array  # bool[G, R]
    group_budget: jax.Array  # f32[G] shared maxHourlyCost (0 = uncapped)
    group_valid: jax.Array  # bool[G]


@jax.tree_util.register_dataclass
@dataclass
class PoolGroupOutputs:
    desired: jax.Array  # i32[G, P] joint choice (== base when pool invalid)
    expected_hourly: jax.Array  # f32[G, P] desired * unit_cost
    violation_risk: jax.Array  # f32[G, P] SLO risk at the chosen count
    headroom: jax.Array  # i32[G, P] one-sigma demand beyond desired
    cost_limited: jax.Array  # bool[G, P] per-pool budget capped below base
    slo_raised: jax.Array  # bool[G, P] risk bought replicas above base
    ratio_ok: jax.Array  # bool[G] selected point satisfies every constraint
    group_hourly: jax.Array  # f32[G] summed pool spend at the selection
    joint_repair: jax.Array  # bool[G] coordination moved a pool off its
    #                          independent optimum this tick


def _to_i32(x: jax.Array) -> jax.Array:
    return jnp.clip(
        x, jnp.float32(_I32_SAFE_MIN), jnp.float32(_I32_SAFE_MAX)
    ).astype(jnp.int32)


def poolgroup_decide(
    inputs: PoolGroupInputs, enforce: bool = True
) -> PoolGroupOutputs:
    """The batched joint program (module docstring). `enforce=False` is
    the DEGRADED independent rung the solver-service ladder serves when
    the joint device path is down: the per-pool cost ladders still
    refine every pool (same math, same bits), but the selection is
    pinned to the independent point — ratios and the group budget go
    advisory for the tick (ratio_ok still reports them honestly)."""
    base = inputs.base_desired.astype(jnp.float32)  # [G, P]
    min_f = inputs.min_replicas.astype(jnp.float32)
    max_f = inputs.max_replicas.astype(jnp.float32)
    g, p = base.shape
    c = CANDIDATES ** p
    digits = jnp.asarray(joint_digits(p))  # i32[P, C] host constant

    # -- per-pool half: EXACTLY cost_decide's op sequence, one rank up --
    offsets = jnp.arange(CANDIDATES, dtype=jnp.float32)  # [K]
    cap_on = (
        inputs.pool_valid
        & (inputs.unit_cost > 0)
        & (inputs.max_hourly_cost > 0)
    )
    safe_unit = jnp.where(inputs.unit_cost > 0, inputs.unit_cost, _ONE)
    cap = jnp.floor(inputs.max_hourly_cost / safe_unit)
    hi = jnp.where(
        cap_on, jnp.minimum(max_f, jnp.maximum(cap, min_f)), max_f
    )
    cand = jnp.clip(
        base[:, :, None] + offsets[None, None, :],
        min_f[:, :, None],
        hi[:, :, None],
    )  # [G, P, K]

    demand_hi = inputs.demand_mu + inputs.demand_sigma  # [G, P, M]
    capacity = cand[:, :, :, None] * inputs.slo_target[:, :, None, :]
    denom = jnp.maximum(demand_hi, _EPS)[:, :, None, :]
    short = jnp.clip(
        (demand_hi[:, :, None, :] - capacity) / denom, _ZERO, _ONE
    )
    short = jnp.where(inputs.demand_valid[:, :, None, :], short, _ZERO)
    risk = jnp.max(short, axis=3)  # [G, P, K]

    # tier preference rides the score's hourly rate only (the budget cap
    # above stays real dollars); penalty 0 adds f32 zero to unit >= 0 —
    # bit-identical to the cost kernel's term
    rate = inputs.unit_cost + inputs.tier_penalty
    hourly = cand * rate[:, :, None]  # [G, P, K]
    score = inputs.slo_weight[:, :, None] * risk + hourly

    # each pool's INDEPENDENT first-index argmin — the cost kernel's
    # k_star, the anchor of the two-level selection
    k_star = jnp.argmin(score, axis=2).astype(jnp.int32)  # [G, P]

    # -- joint half: gather ladders into the K^P candidate space --------
    idx = jnp.broadcast_to(digits[None, :, :], (g, p, c))
    cand_j = jnp.take_along_axis(cand, idx, axis=2)  # [G, P, C]
    score_j = jnp.take_along_axis(score, idx, axis=2)
    risk_j = jnp.take_along_axis(risk, idx, axis=2)
    n_j = cand_j.astype(jnp.int32)  # integer-valued f32 by construction

    # joint score and group spend, accumulated in UNROLLED static pool
    # order (the parity contract forbids a reduction whose association
    # the backend may reorder); spend accumulation is single-mul FMA form
    total = score_j[:, 0, :]
    spend = cand_j[:, 0, :] * inputs.unit_cost[:, 0, None]
    for pool in range(1, p):
        total = score_j[:, pool, :] + total
        spend = (
            cand_j[:, pool, :] * inputs.unit_cost[:, pool, None] + spend
        )

    viol = _violations(inputs, n_j, spend, jnp)  # i32[G, C]

    # -- two-level selection --------------------------------------------
    indep_c = k_star[:, 0]
    for pool in range(1, p):
        indep_c = indep_c + k_star[:, pool] * jnp.int32(CANDIDATES ** pool)
    indep_viol = jnp.take_along_axis(viol, indep_c[:, None], axis=1)[:, 0]
    min_viol = jnp.min(viol, axis=1)
    masked_total = jnp.where(viol == min_viol[:, None], total, _INF)
    repair_c = jnp.argmin(masked_total, axis=1).astype(jnp.int32)
    if enforce:
        selected = jnp.where(indep_viol == 0, indep_c, repair_c)
    else:
        selected = indep_c

    sel = jnp.broadcast_to(selected[:, None, None], (g, p, 1))
    chosen = jnp.take_along_axis(cand_j, sel, axis=2)[:, :, 0]  # [G, P]
    chosen_risk = jnp.take_along_axis(risk_j, sel, axis=2)[:, :, 0]
    sel_viol = jnp.take_along_axis(viol, selected[:, None], axis=1)[:, 0]
    sel_spend = jnp.take_along_axis(spend, selected[:, None], axis=1)[:, 0]

    needed = jnp.ceil(demand_hi / jnp.maximum(inputs.slo_target, _EPS))
    needed = jnp.where(inputs.demand_valid, needed, _ZERO)
    headroom = jnp.maximum(jnp.max(needed, axis=2) - chosen, _ZERO)

    valid = inputs.pool_valid
    desired = jnp.where(valid, chosen, base)
    return PoolGroupOutputs(
        desired=_to_i32(desired),
        expected_hourly=desired * inputs.unit_cost,
        violation_risk=jnp.where(valid, chosen_risk, _ZERO),
        headroom=_to_i32(jnp.where(valid, headroom, _ZERO)),
        cost_limited=cap_on & (base > hi),
        slo_raised=valid & (chosen > base),
        ratio_ok=inputs.group_valid & (sel_viol == 0),
        group_hourly=jnp.where(inputs.group_valid, sel_spend, _ZERO),
        joint_repair=inputs.group_valid & (selected != indep_c),
    )


def _violations(inputs, n_j, spend, xp):
    """Exact-i32 constraint-violation count per joint candidate,
    identical op-for-op under `xp` in {jnp, np} (int math only, plus
    one f32 compare for the budget whose operand `spend` the caller
    already computed under the parity discipline).

    Ratio bands compare by integer cross-multiplication — a/b >= lo is
    a*lo_den >= b*lo_num — so a slot with min_num=0 self-disables the
    lower bound (n*den < 0 is false for n >= 0) and max_num=max_den=0
    self-disables the upper (0 > 0 is false): absent bounds need no
    masks, only genuinely invalid slots do."""
    g, p, c = n_j.shape
    viol = xp.zeros((g, c), np.int32)
    for r in range(RATIO_SLOTS):
        a_idx = xp.clip(inputs.ratio_a[:, r], 0, p - 1).astype(np.int32)
        b_idx = xp.clip(inputs.ratio_b[:, r], 0, p - 1).astype(np.int32)
        if xp is jnp:
            n_a = xp.take_along_axis(
                n_j, xp.broadcast_to(a_idx[:, None, None], (g, 1, c)),
                axis=1,
            )[:, 0, :]
            n_b = xp.take_along_axis(
                n_j, xp.broadcast_to(b_idx[:, None, None], (g, 1, c)),
                axis=1,
            )[:, 0, :]
        else:
            rows = np.arange(g)
            n_a = n_j[rows, a_idx]
            n_b = n_j[rows, b_idx]
        low = (
            n_a * inputs.ratio_min_den[:, r, None]
            < n_b * inputs.ratio_min_num[:, r, None]
        )
        high = (
            n_a * inputs.ratio_max_den[:, r, None]
            > n_b * inputs.ratio_max_num[:, r, None]
        )
        live = inputs.ratio_valid[:, r, None]
        viol = viol + xp.where(live & low, np.int32(1), np.int32(0))
        viol = viol + xp.where(live & high, np.int32(1), np.int32(0))
    over = (inputs.group_budget[:, None] > 0) & (
        spend > inputs.group_budget[:, None]
    )
    return viol + xp.where(over, np.int32(1), np.int32(0))


poolgroup_jit = jax.jit(partial(poolgroup_decide, enforce=True))
poolgroup_independent_jit = jax.jit(partial(poolgroup_decide, enforce=False))


# -- numpy mirror -------------------------------------------------------------
# The parity oracle AND the requested-numpy backend — every line mirrors
# the kernel's op order; _fma reproduces XLA:CPU's mul-add contraction
# (ops/cost.py discipline).


def poolgroup_numpy(
    inputs: PoolGroupInputs, enforce: bool = True
) -> PoolGroupOutputs:
    """Host mirror of poolgroup_decide() — bit-identical output leaves
    (module docstring parity contract)."""
    base = np.asarray(inputs.base_desired, np.int32).astype(np.float32)
    min_f = np.asarray(inputs.min_replicas, np.int32).astype(np.float32)
    max_f = np.asarray(inputs.max_replicas, np.int32).astype(np.float32)
    unit = np.asarray(inputs.unit_cost, np.float32)
    weight = np.asarray(inputs.slo_weight, np.float32)
    budget = np.asarray(inputs.max_hourly_cost, np.float32)
    tier = np.asarray(inputs.tier_penalty, np.float32)
    valid = np.asarray(inputs.pool_valid, bool)
    slo_target = np.asarray(inputs.slo_target, np.float32)
    mu = np.asarray(inputs.demand_mu, np.float32)
    sigma = np.asarray(inputs.demand_sigma, np.float32)
    dvalid = np.asarray(inputs.demand_valid, bool)
    group_valid = np.asarray(inputs.group_valid, bool)
    g, p = base.shape
    c = CANDIDATES ** p
    digits = joint_digits(p)  # [P, C]

    offsets = np.arange(CANDIDATES, dtype=np.float32)
    cap_on = valid & (unit > 0) & (budget > 0)
    safe_unit = np.where(unit > 0, unit, _ONE).astype(np.float32)
    cap = np.floor(budget / safe_unit).astype(np.float32)
    hi = np.where(
        cap_on, np.minimum(max_f, np.maximum(cap, min_f)), max_f
    ).astype(np.float32)
    cand = np.clip(
        base[:, :, None] + offsets[None, None, :],
        min_f[:, :, None],
        hi[:, :, None],
    ).astype(np.float32)

    demand_hi = (mu + sigma).astype(np.float32)
    denom = np.maximum(demand_hi, _EPS)[:, :, None, :].astype(np.float32)
    shortfall = _fma(
        -cand[:, :, :, None],
        slo_target[:, :, None, :],
        demand_hi[:, :, None, :],
    )
    short = np.clip((shortfall / denom).astype(np.float32), _ZERO, _ONE)
    short = np.where(dvalid[:, :, None, :], short, _ZERO).astype(np.float32)
    risk = np.max(short, axis=3)

    rate = (unit + tier).astype(np.float32)
    hourly = (cand * rate[:, :, None]).astype(np.float32)
    score = _fma(weight[:, :, None], risk, hourly)

    k_star = np.argmin(score, axis=2).astype(np.int32)

    idx = np.broadcast_to(digits[None, :, :], (g, p, c))
    cand_j = np.take_along_axis(cand, idx, axis=2)
    score_j = np.take_along_axis(score, idx, axis=2)
    risk_j = np.take_along_axis(risk, idx, axis=2)
    n_j = cand_j.astype(np.int32)

    total = score_j[:, 0, :]
    spend = (cand_j[:, 0, :] * unit[:, 0, None]).astype(np.float32)
    for pool in range(1, p):
        total = (score_j[:, pool, :] + total).astype(np.float32)
        spend = _fma(cand_j[:, pool, :], unit[:, pool, None], spend)

    viol = _violations(inputs, n_j, spend, np)

    indep_c = k_star[:, 0].copy()
    for pool in range(1, p):
        indep_c = (
            indep_c + k_star[:, pool] * np.int32(CANDIDATES ** pool)
        ).astype(np.int32)
    rows = np.arange(g)
    indep_viol = viol[rows, indep_c]
    min_viol = np.min(viol, axis=1)
    masked_total = np.where(
        viol == min_viol[:, None], total, _INF
    ).astype(np.float32)
    repair_c = np.argmin(masked_total, axis=1).astype(np.int32)
    if enforce:
        selected = np.where(indep_viol == 0, indep_c, repair_c).astype(
            np.int32
        )
    else:
        selected = indep_c

    chosen = cand_j[rows[:, None], np.arange(p)[None, :], selected[:, None]]
    chosen_risk = risk_j[
        rows[:, None], np.arange(p)[None, :], selected[:, None]
    ]
    sel_viol = viol[rows, selected]
    sel_spend = spend[rows, selected]

    needed = np.ceil(
        (demand_hi / np.maximum(slo_target, _EPS)).astype(np.float32)
    ).astype(np.float32)
    needed = np.where(dvalid, needed, _ZERO).astype(np.float32)
    headroom = np.maximum(np.max(needed, axis=2) - chosen, _ZERO)

    desired = np.where(valid, chosen, base).astype(np.float32)

    def to_i32(x):
        return np.clip(
            x, np.float32(_I32_SAFE_MIN), np.float32(_I32_SAFE_MAX)
        ).astype(np.int32)

    return PoolGroupOutputs(
        desired=to_i32(desired),
        expected_hourly=(desired * unit).astype(np.float32),
        violation_risk=np.where(valid, chosen_risk, _ZERO).astype(
            np.float32
        ),
        headroom=to_i32(np.where(valid, headroom, _ZERO)),
        cost_limited=cap_on & (base > hi),
        slo_raised=valid & (chosen > base),
        ratio_ok=group_valid & (sel_viol == 0),
        group_hourly=np.where(group_valid, sel_spend, _ZERO).astype(
            np.float32
        ),
        joint_repair=group_valid & (selected != indep_c),
    )
