"""Fused steady-state tick: forecast → decide → cost in ONE device program.

The steady-state reconcile chain used to issue a separate compiled
program per stage — forecast the eligible series, round-trip the points
to the host, scatter them into the decide operands, dispatch decide,
round-trip again, assemble the cost/SLO operands, dispatch the
8-candidate ladder. Three host↔device transfers and 3+ dispatch spans
per tick, and PR 15's XLA cost attribution shows the hot path is
dominated by exactly that overhead, not flops.

fused_tick() runs the whole chain as one program:

    forecast (Holt-Winters / robust-linear, masked history)
        │  point/sigma2/n_valid per series — stays on device
        ▼  trash-row scatter into the fleet's [N, M] metric grid
    decide (max(reactive, predicted) blend, stabilization, rate limits)
        │  desired + movement bounds (up_ceiling / down_floor)
        ▼
    cost ladder (8 candidates around desired, budget cap, SLO risk)

Stage seams reproduce the unfused wire bit for bit:

- The forecast→decide seam scatters `point` into `forecast_value` /
  `forecast_valid` exactly where the host loop would have filled the
  dict: series with `n_valid >= need` AND an active (skill-gated)
  blend. Pad series are routed to a trash row N of an (N+1, M) grid
  that is sliced off, so padding can never clobber a live cell.
- The decide→cost seam applies the engine's movement-bound clamp
  (`max(ha_min, min(down_floor, ha_max))` / the mirror for max) and
  overlays the FRESH in-device distribution (gate: `n_valid >= need`,
  shadow series included — risk gates on its own spec, not the blend
  verdict) over the host-read PRIOR distribution, which is what the
  chained path's post-refresh `distribution()` read would return.
- Absent stages are absent operands: `forecast=None` and
  `slo_valid=None` drop the stage from the traced program, and the
  masked rows of present stages (blend-gate all-False, slo_valid
  False) pass through byte-identical to the unfused wire.

Three entry points, one contract (property-pinned bitwise equal):

    fused_tick / fused_tick_jit   one program, zero host round-trips
    fused_tick_chained            stage-per-program with host glue —
                                  the fallback rung and the bench's
                                  comparison arm
    fused_tick_numpy              pure-host floor (never-block ladder)
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..forecast import models as M
from . import cost as C
from . import decision as D
from . import poolgroup as PG

_I32_MAX = np.int32(np.iinfo(np.int32).max)


@jax.tree_util.register_dataclass
@dataclass
class PoolGroupOperands:
    """The fused tick's optional joint-allocation stage (ops/poolgroup.py):
    G pool groups x P pools, each pool a fleet row. Everything the
    standalone PoolGroupEngine assembles EXCEPT what only exists
    post-decide — the base desired counts and the movement bounds are
    gathered/derived IN-DEVICE from the decide stage's fresh outputs
    (pgMin/pgMax here are the SPEC bounds: HA [min, max] intersected
    with the member's own tightening), and the demand overlay mirrors
    the cost stage's seam: fresh in-device distribution over the
    host-read prior."""

    member_row: jax.Array  # i32[G, P] fleet row per pool (pad slots: 0)
    pg_min: jax.Array  # i32[G, P] spec-bound floor (pre movement clamp)
    pg_max: jax.Array  # i32[G, P] spec-bound ceiling
    unit_cost: jax.Array  # f32[G, P]
    slo_weight: jax.Array  # f32[G, P]
    max_hourly_cost: jax.Array  # f32[G, P] per-pool budget
    tier_penalty: jax.Array  # f32[G, P]
    pool_valid: jax.Array  # bool[G, P]
    slo_target: jax.Array  # f32[G, P, M]
    observed: jax.Array  # f32[G, P, M]
    demand_base_valid: jax.Array  # bool[G, P, M]
    prior_point: jax.Array  # f32[G, P, M]
    prior_sigma2: jax.Array  # f32[G, P, M]
    prior_valid: jax.Array  # bool[G, P, M]
    ratio_a: jax.Array  # i32[G, R]
    ratio_b: jax.Array  # i32[G, R]
    ratio_min_num: jax.Array  # i32[G, R]
    ratio_min_den: jax.Array  # i32[G, R]
    ratio_max_num: jax.Array  # i32[G, R]
    ratio_max_den: jax.Array  # i32[G, R]
    ratio_valid: jax.Array  # bool[G, R]
    group_budget: jax.Array  # f32[G]
    group_valid: jax.Array  # bool[G]


@jax.tree_util.register_dataclass
@dataclass
class FusedTickInputs:
    """Operands for the whole steady-state chain, host-assembled once.

    `decision` is the standard decide() view of the fleet (N rows,
    M metric columns, forecast operands None — the kernel fills them).
    The forecast group carries S series plus the scatter map into the
    [N, M] grid; the cost group carries the engine's _build_inputs
    surface SPLIT at the demand seam: `observed` + the PRIOR
    distribution as read on the host pre-dispatch, with the fresh
    distribution overlaid in-device. Either group may be None — the
    stage is then absent from the program.
    """

    decision: D.DecisionInputs
    # -- forecast stage (None = absent) --
    forecast: Optional[M.ForecastInputs] = None
    series_row: Optional[jax.Array] = None  # i32[S] fleet row (N = trash)
    series_col: Optional[jax.Array] = None  # i32[S] metric column
    series_need: Optional[jax.Array] = None  # i32[S] min samples for the fit
    series_blend: Optional[jax.Array] = None  # bool[S] skill gate verdict
    # -- cost stage (None = absent; slo_valid is the presence sentinel) --
    ha_min: Optional[jax.Array] = None  # i32[N] spec minReplicas
    ha_max: Optional[jax.Array] = None  # i32[N] spec maxReplicas
    unit_cost: Optional[jax.Array] = None  # f32[N]
    slo_weight: Optional[jax.Array] = None  # f32[N]
    max_hourly_cost: Optional[jax.Array] = None  # f32[N]
    slo_valid: Optional[jax.Array] = None  # bool[N]
    slo_target: Optional[jax.Array] = None  # f32[N, M] per-replica capacity
    observed: Optional[jax.Array] = None  # f32[N, M] reactive demand
    demand_base_valid: Optional[jax.Array] = None  # bool[N, M]
    prior_point: Optional[jax.Array] = None  # f32[N, M] host dist read
    prior_sigma2: Optional[jax.Array] = None  # f32[N, M]
    prior_valid: Optional[jax.Array] = None  # bool[N, M]
    # -- joint pool-group stage (None = absent; docs/poolgroups.md) --
    poolgroup: Optional[PoolGroupOperands] = None


@jax.tree_util.register_dataclass
@dataclass
class FusedTickOutputs:
    decision: D.DecisionOutputs
    forecast: Optional[M.ForecastOutputs] = None
    cost: Optional[C.CostOutputs] = None
    poolgroup: Optional[PG.PoolGroupOutputs] = None


def programs(inputs: FusedTickInputs) -> int:
    """Device programs the CHAINED path needs for these operands (the
    fused path always needs exactly one)."""
    return (
        1
        + int(inputs.forecast is not None)
        + int(inputs.slo_valid is not None)
        + int(inputs.poolgroup is not None)
    )


# -- device kernel ------------------------------------------------------------


def _scatter(n, m, rows, cols, vals):
    """Scatter S series values into an (N+1, M) grid and slice the
    trash row off: pad series carry row == N and land there, so no
    bounds juggling is needed inside the traced program."""
    grid = jnp.zeros((n + 1, m), vals.dtype)
    return grid.at[rows, cols].set(vals)[:n]


def _demand_overlay(inputs, dout, dist):
    """The decide→cost seam: movement-bound clamps plus the engine's
    _demand() selection, with the fresh in-device distribution
    overlaid on the host-read prior."""
    prior_point = inputs.prior_point
    prior_sigma2 = inputs.prior_sigma2
    have = inputs.prior_valid
    if dist is not None:
        dist_point, dist_sigma2, dist_ok = dist
        prior_point = jnp.where(dist_ok, dist_point, prior_point)
        prior_sigma2 = jnp.where(dist_ok, dist_sigma2, prior_sigma2)
        have = dist_ok | have
    observed = inputs.observed
    mu = jnp.where(
        have & jnp.isfinite(prior_point),
        jnp.maximum(observed, prior_point),
        observed,
    )
    sigma = jnp.where(
        have & jnp.isfinite(prior_sigma2) & (prior_sigma2 > 0),
        jnp.sqrt(prior_sigma2),
        jnp.float32(0.0),
    )
    valid = inputs.demand_base_valid
    mu = jnp.where(valid, mu, jnp.float32(0.0)).astype(jnp.float32)
    sigma = jnp.where(valid, sigma, jnp.float32(0.0)).astype(jnp.float32)
    slo = inputs.slo_valid
    min_eff = jnp.where(
        slo,
        jnp.maximum(inputs.ha_min, jnp.minimum(dout.down_floor, inputs.ha_max)),
        0,
    ).astype(jnp.int32)
    max_eff = jnp.where(
        slo,
        jnp.minimum(inputs.ha_max, jnp.maximum(dout.up_ceiling, inputs.ha_min)),
        0,
    ).astype(jnp.int32)
    return C.CostInputs(
        base_desired=dout.desired,
        min_replicas=min_eff,
        max_replicas=max_eff,
        unit_cost=inputs.unit_cost,
        slo_weight=inputs.slo_weight,
        max_hourly_cost=inputs.max_hourly_cost,
        slo_valid=slo,
        slo_target=inputs.slo_target,
        demand_mu=mu,
        demand_sigma=sigma,
        demand_valid=valid,
    )


def _pg_overlay(pg: PoolGroupOperands, final_desired, dout, dist):
    """The cost→poolgroup seam: gather each pool's base from the tick's
    post-cost desired, derive movement-clamped bounds from the decide
    stage's fresh up_ceiling/down_floor (the engine clamp order: spec
    bounds outrank the rate bound), and run the cost stage's demand
    overlay per pool — fresh in-device distribution over the host-read
    prior, gathered at each pool's fleet row."""
    n = final_desired.shape[0]
    rows = jnp.clip(pg.member_row, 0, n - 1)
    valid = pg.pool_valid
    base = jnp.where(valid, jnp.take(final_desired, rows), 0).astype(
        jnp.int32
    )
    down = jnp.take(dout.down_floor, rows)
    up = jnp.take(dout.up_ceiling, rows)
    min_eff = jnp.where(
        valid,
        jnp.maximum(pg.pg_min, jnp.minimum(down, pg.pg_max)),
        0,
    ).astype(jnp.int32)
    max_eff = jnp.where(
        valid,
        jnp.minimum(pg.pg_max, jnp.maximum(up, pg.pg_min)),
        0,
    ).astype(jnp.int32)
    prior_point = pg.prior_point
    prior_sigma2 = pg.prior_sigma2
    have = pg.prior_valid
    if dist is not None:
        dist_point, dist_sigma2, dist_ok = dist  # [N, M] grids
        g_ok = jnp.take(dist_ok, rows, axis=0)  # [G, P, M]
        prior_point = jnp.where(
            g_ok, jnp.take(dist_point, rows, axis=0), prior_point
        )
        prior_sigma2 = jnp.where(
            g_ok, jnp.take(dist_sigma2, rows, axis=0), prior_sigma2
        )
        have = g_ok | have
    observed = pg.observed
    mu = jnp.where(
        have & jnp.isfinite(prior_point),
        jnp.maximum(observed, prior_point),
        observed,
    )
    sigma = jnp.where(
        have & jnp.isfinite(prior_sigma2) & (prior_sigma2 > 0),
        jnp.sqrt(prior_sigma2),
        jnp.float32(0.0),
    )
    dvalid = pg.demand_base_valid
    mu = jnp.where(dvalid, mu, jnp.float32(0.0)).astype(jnp.float32)
    sigma = jnp.where(dvalid, sigma, jnp.float32(0.0)).astype(jnp.float32)
    return PG.PoolGroupInputs(
        base_desired=base,
        min_replicas=min_eff,
        max_replicas=max_eff,
        unit_cost=pg.unit_cost,
        slo_weight=pg.slo_weight,
        max_hourly_cost=pg.max_hourly_cost,
        tier_penalty=pg.tier_penalty,
        pool_valid=valid,
        slo_target=pg.slo_target,
        demand_mu=mu,
        demand_sigma=sigma,
        demand_valid=dvalid,
        ratio_a=pg.ratio_a,
        ratio_b=pg.ratio_b,
        ratio_min_num=pg.ratio_min_num,
        ratio_min_den=pg.ratio_min_den,
        ratio_max_num=pg.ratio_max_num,
        ratio_max_den=pg.ratio_max_den,
        ratio_valid=pg.ratio_valid,
        group_budget=pg.group_budget,
        group_valid=pg.group_valid,
    )


def fused_tick(inputs: FusedTickInputs) -> FusedTickOutputs:
    """The megakernel: forecast → decide → cost → poolgroup with every
    seam on device. Traceable under jit; stage presence is pytree
    structure, so each operand shape class compiles once."""
    dec = inputs.decision
    n = dec.spec_replicas.shape[0]
    m = dec.metric_value.shape[1]
    fout = None
    dist = None
    if inputs.forecast is not None:
        fout = M.forecast(inputs.forecast)
        rows = inputs.series_row
        cols = inputs.series_col
        dist_gate = fout.n_valid >= inputs.series_need
        blend_gate = inputs.series_blend & dist_gate
        zero = jnp.float32(0.0)
        fv = _scatter(
            n, m, rows, cols, jnp.where(blend_gate, fout.point, zero)
        )
        fvalid = _scatter(n, m, rows, cols, blend_gate)
        dist = (
            _scatter(
                n, m, rows, cols, jnp.where(dist_gate, fout.point, zero)
            ),
            _scatter(
                n, m, rows, cols, jnp.where(dist_gate, fout.sigma2, zero)
            ),
            _scatter(n, m, rows, cols, dist_gate),
        )
        dec = replace(dec, forecast_value=fv, forecast_valid=fvalid)
    dout = D.decide(dec)
    cout = None
    if inputs.slo_valid is not None:
        cout = C.cost_decide(_demand_overlay(inputs, dout, dist))
    pout = None
    if inputs.poolgroup is not None:
        final = cout.desired if cout is not None else dout.desired
        pout = PG.poolgroup_decide(
            _pg_overlay(inputs.poolgroup, final, dout, dist)
        )
    return FusedTickOutputs(
        decision=dout, forecast=fout, cost=cout, poolgroup=pout
    )


fused_tick_jit = jax.jit(fused_tick)


# -- chained path (fallback rung + bench comparison arm) ----------------------
# Same operands, one program PER STAGE with numpy host glue between —
# the pre-fusion wire. The glue mirrors the kernel seams exactly
# (boolean-index writes land on zero-initialised cells, identical to
# the kernel's gate-masked scatter), so chained == fused bitwise.


def _np_scatter(inputs, fout, n: int, m: int):
    rows = np.asarray(inputs.series_row, np.int64)
    cols = np.asarray(inputs.series_col, np.int64)
    point = np.asarray(fout.point, np.float32)
    sigma2 = np.asarray(fout.sigma2, np.float32)
    live = rows < n
    dist_gate = (
        np.asarray(fout.n_valid, np.int32)
        >= np.asarray(inputs.series_need, np.int32)
    ) & live
    blend_gate = np.asarray(inputs.series_blend, bool) & dist_gate
    fv = np.zeros((n, m), np.float32)
    fvalid = np.zeros((n, m), bool)
    fv[rows[blend_gate], cols[blend_gate]] = point[blend_gate]
    fvalid[rows[blend_gate], cols[blend_gate]] = True
    dist_point = np.zeros((n, m), np.float32)
    dist_sigma2 = np.zeros((n, m), np.float32)
    dist_ok = np.zeros((n, m), bool)
    dist_point[rows[dist_gate], cols[dist_gate]] = point[dist_gate]
    dist_sigma2[rows[dist_gate], cols[dist_gate]] = sigma2[dist_gate]
    dist_ok[rows[dist_gate], cols[dist_gate]] = True
    return fv, fvalid, (dist_point, dist_sigma2, dist_ok)


def _np_demand_overlay(inputs, dout, dist) -> C.CostInputs:
    prior_point = np.asarray(inputs.prior_point, np.float32)
    prior_sigma2 = np.asarray(inputs.prior_sigma2, np.float32)
    have = np.asarray(inputs.prior_valid, bool)
    if dist is not None:
        dist_point, dist_sigma2, dist_ok = dist
        prior_point = np.where(dist_ok, dist_point, prior_point)
        prior_sigma2 = np.where(dist_ok, dist_sigma2, prior_sigma2)
        have = dist_ok | have
    observed = np.asarray(inputs.observed, np.float32)
    with np.errstate(invalid="ignore"):
        mu = np.where(
            have & np.isfinite(prior_point),
            np.maximum(observed, prior_point),
            observed,
        )
        sigma = np.where(
            have & np.isfinite(prior_sigma2) & (prior_sigma2 > 0),
            np.sqrt(prior_sigma2),
            np.float32(0.0),
        )
    valid = np.asarray(inputs.demand_base_valid, bool)
    mu = np.where(valid, mu, np.float32(0.0)).astype(np.float32)
    sigma = np.where(valid, sigma, np.float32(0.0)).astype(np.float32)
    slo = np.asarray(inputs.slo_valid, bool)
    ha_min = np.asarray(inputs.ha_min, np.int32)
    ha_max = np.asarray(inputs.ha_max, np.int32)
    down_floor = np.asarray(dout.down_floor, np.int32)
    up_ceiling = np.asarray(dout.up_ceiling, np.int32)
    min_eff = np.where(
        slo, np.maximum(ha_min, np.minimum(down_floor, ha_max)), 0
    ).astype(np.int32)
    max_eff = np.where(
        slo, np.minimum(ha_max, np.maximum(up_ceiling, ha_min)), 0
    ).astype(np.int32)
    return C.CostInputs(
        base_desired=np.asarray(dout.desired, np.int32),
        min_replicas=min_eff,
        max_replicas=max_eff,
        unit_cost=np.asarray(inputs.unit_cost, np.float32),
        slo_weight=np.asarray(inputs.slo_weight, np.float32),
        max_hourly_cost=np.asarray(inputs.max_hourly_cost, np.float32),
        slo_valid=slo,
        slo_target=np.asarray(inputs.slo_target, np.float32),
        demand_mu=mu,
        demand_sigma=sigma,
        demand_valid=valid,
    )


def _np_pg_overlay(
    pg: PoolGroupOperands, final_desired, dout, dist
) -> PG.PoolGroupInputs:
    """Host mirror of _pg_overlay (same gather + overlay, np ops)."""
    final_desired = np.asarray(final_desired, np.int32)
    n = final_desired.shape[0]
    rows = np.clip(np.asarray(pg.member_row, np.int32), 0, n - 1)
    valid = np.asarray(pg.pool_valid, bool)
    base = np.where(valid, final_desired[rows], 0).astype(np.int32)
    down = np.asarray(dout.down_floor, np.int32)[rows]
    up = np.asarray(dout.up_ceiling, np.int32)[rows]
    pg_min = np.asarray(pg.pg_min, np.int32)
    pg_max = np.asarray(pg.pg_max, np.int32)
    min_eff = np.where(
        valid, np.maximum(pg_min, np.minimum(down, pg_max)), 0
    ).astype(np.int32)
    max_eff = np.where(
        valid, np.minimum(pg_max, np.maximum(up, pg_min)), 0
    ).astype(np.int32)
    prior_point = np.asarray(pg.prior_point, np.float32)
    prior_sigma2 = np.asarray(pg.prior_sigma2, np.float32)
    have = np.asarray(pg.prior_valid, bool)
    if dist is not None:
        dist_point, dist_sigma2, dist_ok = dist  # [N, M] grids
        g_ok = dist_ok[rows]
        prior_point = np.where(g_ok, dist_point[rows], prior_point)
        prior_sigma2 = np.where(g_ok, dist_sigma2[rows], prior_sigma2)
        have = g_ok | have
    observed = np.asarray(pg.observed, np.float32)
    with np.errstate(invalid="ignore"):
        mu = np.where(
            have & np.isfinite(prior_point),
            np.maximum(observed, prior_point),
            observed,
        )
        sigma = np.where(
            have & np.isfinite(prior_sigma2) & (prior_sigma2 > 0),
            np.sqrt(prior_sigma2),
            np.float32(0.0),
        )
    dvalid = np.asarray(pg.demand_base_valid, bool)
    mu = np.where(dvalid, mu, np.float32(0.0)).astype(np.float32)
    sigma = np.where(dvalid, sigma, np.float32(0.0)).astype(np.float32)
    return PG.PoolGroupInputs(
        base_desired=base,
        min_replicas=min_eff,
        max_replicas=max_eff,
        unit_cost=np.asarray(pg.unit_cost, np.float32),
        slo_weight=np.asarray(pg.slo_weight, np.float32),
        max_hourly_cost=np.asarray(pg.max_hourly_cost, np.float32),
        tier_penalty=np.asarray(pg.tier_penalty, np.float32),
        pool_valid=valid,
        slo_target=np.asarray(pg.slo_target, np.float32),
        demand_mu=mu,
        demand_sigma=sigma,
        demand_valid=dvalid,
        ratio_a=np.asarray(pg.ratio_a, np.int32),
        ratio_b=np.asarray(pg.ratio_b, np.int32),
        ratio_min_num=np.asarray(pg.ratio_min_num, np.int32),
        ratio_min_den=np.asarray(pg.ratio_min_den, np.int32),
        ratio_max_num=np.asarray(pg.ratio_max_num, np.int32),
        ratio_max_den=np.asarray(pg.ratio_max_den, np.int32),
        ratio_valid=np.asarray(pg.ratio_valid, bool),
        group_budget=np.asarray(pg.group_budget, np.float32),
        group_valid=np.asarray(pg.group_valid, bool),
    )


def _to_host(out):
    return jax.tree_util.tree_map(lambda x: np.asarray(x), out)


def fused_tick_chained(
    inputs: FusedTickInputs,
    forecast_fn: Optional[Callable] = None,
    decide_fn: Optional[Callable] = None,
    cost_fn: Optional[Callable] = None,
    poolgroup_fn: Optional[Callable] = None,
) -> FusedTickOutputs:
    """One program per stage, host round-trip between each — the
    pre-fusion wire and the never-block fallback rung. np.asarray on
    every stage output forces the transfer (and the device sync)."""
    forecast_fn = forecast_fn or M.forecast_jit
    decide_fn = decide_fn or D.decide_jit
    cost_fn = cost_fn or C.cost_jit
    poolgroup_fn = poolgroup_fn or PG.poolgroup_jit
    dec = inputs.decision
    n = int(np.asarray(dec.spec_replicas).shape[0])
    m = int(np.asarray(dec.metric_value).shape[1])
    fout = None
    dist = None
    if inputs.forecast is not None:
        fout = _to_host(forecast_fn(inputs.forecast))
        fv, fvalid, dist = _np_scatter(inputs, fout, n, m)
        dec = replace(dec, forecast_value=fv, forecast_valid=fvalid)
    dout = _to_host(decide_fn(dec))
    cout = None
    if inputs.slo_valid is not None:
        cout = _to_host(cost_fn(_np_demand_overlay(inputs, dout, dist)))
    pout = None
    if inputs.poolgroup is not None:
        final = cout.desired if cout is not None else dout.desired
        pout = _to_host(
            poolgroup_fn(
                _np_pg_overlay(inputs.poolgroup, final, dout, dist)
            )
        )
    return FusedTickOutputs(
        decision=dout, forecast=fout, cost=cout, poolgroup=pout
    )


def fused_tick_numpy(inputs: FusedTickInputs) -> FusedTickOutputs:
    """Pure-host floor of the never-block ladder: the stage mirrors
    joined by the same glue. Bitwise equal to fused_tick."""
    return fused_tick_chained(
        inputs,
        M.forecast_numpy,
        D.decide_numpy,
        C.cost_numpy,
        PG.poolgroup_numpy,
    )


# -- padding ------------------------------------------------------------------


def pad_series(inputs: FusedTickInputs, s_pad: int) -> FusedTickInputs:
    """Pad the forecast group to `s_pad` series so fused compile keys
    bucket on S like the standalone forecast family. Pad series carry
    an impossible sample requirement, a False blend gate, and the
    trash row N — they cannot touch a live cell on any path."""
    if inputs.forecast is None:
        return inputs
    s = int(np.asarray(inputs.forecast.values).shape[0])
    if s == s_pad:
        return inputs
    pad = s_pad - s
    if pad < 0:
        raise ValueError(f"cannot shrink {s} series to {s_pad}")
    n = int(np.asarray(inputs.decision.spec_replicas).shape[0])
    return replace(
        inputs,
        forecast=M.concat_forecast_inputs([inputs.forecast], s_pad),
        series_row=np.concatenate(
            [
                np.asarray(inputs.series_row, np.int32),
                np.full(pad, n, np.int32),
            ]
        ),
        series_col=np.concatenate(
            [
                np.asarray(inputs.series_col, np.int32),
                np.zeros(pad, np.int32),
            ]
        ),
        series_need=np.concatenate(
            [
                np.asarray(inputs.series_need, np.int32),
                np.full(pad, _I32_MAX, np.int32),
            ]
        ),
        series_blend=np.concatenate(
            [
                np.asarray(inputs.series_blend, bool),
                np.zeros(pad, bool),
            ]
        ),
    )
