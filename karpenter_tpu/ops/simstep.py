"""SimLab cluster-stepping kernels: one tick / one rollout as array programs.

The simulator plane (karpenter_tpu/simlab, docs/simulator.md) advances a
simulated cluster's columnar state — per-row replica counts under a
seeded demand/price/fault trail — with the SAME batch-everything
discipline as the decision kernels: the whole fleet of simulated
clusters is ONE array program, `sim_rollout_vmapped` stacks N
independently-seeded clusters behind a single vmapped dispatch, and
`sim_*_numpy` are bit-identical host mirrors (pinned in
tests/test_simlab.py).

Two entry points:

  sim_step     one tick, ACTION GIVEN (the gym `SimEnv.step` seam): the
               caller's policy already chose per-row replica targets;
               the kernel applies the actuation rate limit and the
               fault gate, then scores the tick.
  sim_rollout  a whole T-tick episode with the IN-KERNEL tuned policy
               (parameterized by a per-cluster knob vector), so policy
               search evaluates a full candidate population in one
               device program (simlab/policy.py SearchTunedPolicy).

Tick semantics (all f32, elementwise over the row axis R):

  target   = clip(action, min, max)
  delta    = clip(target - replicas, ±step_limit) * (1 - fault)
  replicas'= clip(replicas + delta, min, max)         # fault holds state
  violation= demand > replicas' * cap                 # SLO-violation tick
  cost     = replicas' * hourly * price               # priced replica-ticks
  backlog  = |target - replicas'|                     # reconcile lead debt

The in-kernel policy (sim_rollout) is the 3-knob decision surface the
search plane tunes — forecast blend floor, cost shed weight,
scale-down stabilization window:

  blend  = max(demand_prev, blend_floor * forecast_prev)
  raw    = ceil(blend / cap)
  shed   = floor(raw * cost_weight * max(price_prev - 1, 0))
  tgt    = clip(raw - shed, min, max)
  target = tgt held at current replicas while a scale-down streak is
           younger than stab_window ticks

knobs = (0, 0, 0) IS the reactive baseline (chase observed demand,
price-blind, no hold), so tuned-vs-reactive comparisons share one
program.

Parity contract (pinned bit-for-bit by tests/test_simlab.py, the
ops/cost.py discipline): every operation is IEEE-exact elementwise on
both sides — mul, sub, div-into-ceil, clip, abs, compare, where — and
the only multiply feeding an add (`replicas + delta * can_act`) has an
EXACT multiplicand (can_act is 0.0 or 1.0), so XLA:CPU's FMA
contraction cannot round differently from the two-op host form. No
reductions happen in-kernel: per-tick per-row components come back
whole and the composite reward is summed on host in float64, so
batched, sequential, and numpy paths reduce in one order.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

_ONE = np.float32(1.0)
_ZERO = np.float32(0.0)

# knob vector layout (simlab/policy.py builds/search-tunes these)
KNOB_BLEND_FLOOR = 0
KNOB_COST_WEIGHT = 1
KNOB_STAB_WINDOW = 2
KNOBS = 3


@jax.tree_util.register_dataclass
@dataclass
class SimStepInputs:
    """One tick's operands. Row arrays are f32[..., R]; `price` and
    `fault` are per-cluster f32[...] (the kernel broadcasts them over
    rows); the five scalars are f32[] shared across the batch."""

    replicas: jax.Array  # f32[..., R] current replicas per HA row
    target: jax.Array  # f32[..., R] the action: requested replicas
    demand: jax.Array  # f32[..., R] this tick's observed demand
    price: jax.Array  # f32[...] price multiplier (spot spike > 1)
    fault: jax.Array  # f32[...] 1.0 = actuation blocked this tick
    cap: jax.Array  # f32[] demand served per replica
    hourly: jax.Array  # f32[] on-demand price per replica-tick
    step_limit: jax.Array  # f32[] max replica movement per tick
    min_replicas: jax.Array  # f32[]
    max_replicas: jax.Array  # f32[]


@jax.tree_util.register_dataclass
@dataclass
class SimStepOutputs:
    replicas: jax.Array  # f32[..., R] post-actuation replicas
    violation: jax.Array  # f32[..., R] 1.0 where demand outran capacity
    cost: jax.Array  # f32[..., R] priced replica-ticks
    backlog: jax.Array  # f32[..., R] |target - replicas'| lead debt


@jax.tree_util.register_dataclass
@dataclass
class SimRolloutInputs:
    """A whole episode's operands: time-major trails f32[..., T, R]
    (f32[..., T] for the per-cluster price/fault schedules), the initial
    cluster state, and the per-cluster policy knob vector f32[..., 3]."""

    replicas0: jax.Array  # f32[..., R]
    streak0: jax.Array  # f32[..., R] scale-down streak ages
    demand: jax.Array  # f32[..., T, R]
    forecast: jax.Array  # f32[..., T, R] preview of the NEXT demand
    price: jax.Array  # f32[..., T]
    fault: jax.Array  # f32[..., T]
    knobs: jax.Array  # f32[..., KNOBS]
    cap: jax.Array  # f32[]
    hourly: jax.Array  # f32[]
    step_limit: jax.Array  # f32[]
    min_replicas: jax.Array  # f32[]
    max_replicas: jax.Array  # f32[]


@jax.tree_util.register_dataclass
@dataclass
class SimRolloutOutputs:
    """Whole per-tick component trails (no in-kernel reductions — the
    module docstring's parity contract) plus the final carry state."""

    replicas: jax.Array  # f32[..., R] final replicas
    streak: jax.Array  # f32[..., R] final scale-down streaks
    violation: jax.Array  # f32[..., T, R]
    cost: jax.Array  # f32[..., T, R]
    backlog: jax.Array  # f32[..., T, R]
    target: jax.Array  # f32[..., T, R] the actions the policy took


def _step_math(m, replicas, target, demand, price, fault, inputs):
    """The shared tick program (module docstring), generic over the
    array module `m` (jnp on device, np on the mirror)."""
    tgt = m.clip(target, inputs.min_replicas, inputs.max_replicas)
    can_act = _ONE - fault  # exactly 0.0 or 1.0: FMA-safe multiplicand
    delta = (
        m.clip(tgt - replicas, -inputs.step_limit, inputs.step_limit)
        * can_act[..., None]
    )
    new = m.clip(
        replicas + delta, inputs.min_replicas, inputs.max_replicas
    )
    served = new * inputs.cap
    violation = (demand > served).astype(np.float32)
    cost = new * inputs.hourly * price[..., None]
    backlog = m.abs(tgt - new)
    return new, violation, cost, backlog


def _policy_math(
    m, knobs, demand_prev, forecast_prev, price_prev, replicas, streak,
    inputs,
):
    """The in-kernel 3-knob tuned policy (module docstring), generic
    over the array module. knobs[..., 0]=blend floor, [..., 1]=cost
    shed weight, [..., 2]=stabilization window in ticks."""
    blend_floor = knobs[..., 0:1]
    cost_weight = knobs[..., 1:2]
    stab_window = knobs[..., 2:3]
    blend = m.maximum(demand_prev, blend_floor * forecast_prev)
    raw = m.ceil(blend / inputs.cap)
    spike = m.maximum(price_prev - _ONE, _ZERO)
    shed = m.floor(raw * cost_weight * spike[..., None])
    tgt = m.clip(
        raw - shed, inputs.min_replicas, inputs.max_replicas
    )
    down = tgt < replicas
    streak2 = m.where(down, streak + _ONE, _ZERO)
    hold = down & (streak2 <= stab_window)
    target = m.where(hold, replicas, tgt)
    return target, streak2


def sim_step(inputs: SimStepInputs) -> SimStepOutputs:
    """One tick on device (elementwise: any leading batch shape rides
    the same program)."""
    new, violation, cost, backlog = _step_math(
        jnp, inputs.replicas, inputs.target, inputs.demand,
        inputs.price, inputs.fault, inputs,
    )
    return SimStepOutputs(
        replicas=new, violation=violation, cost=cost, backlog=backlog
    )


sim_step_jit = jax.jit(sim_step)


def sim_step_numpy(inputs: SimStepInputs) -> SimStepOutputs:
    """Bit-identical host mirror of sim_step."""
    new, violation, cost, backlog = _step_math(
        np, np.asarray(inputs.replicas), np.asarray(inputs.target),
        np.asarray(inputs.demand), np.asarray(inputs.price),
        np.asarray(inputs.fault), inputs,
    )
    return SimStepOutputs(
        replicas=new, violation=violation, cost=cost, backlog=backlog
    )


def sim_rollout(inputs: SimRolloutInputs) -> SimRolloutOutputs:
    """One UNBATCHED episode (trails [T, R]) as a lax.scan device
    program; `sim_rollout_vmapped` stacks clusters on a leading axis."""
    rows = inputs.replicas0.shape[-1]
    zeros = jnp.zeros((rows,), jnp.float32)

    def tick(carry, xs):
        replicas, streak, d_prev, f_prev, p_prev = carry
        demand_t, forecast_t, price_t, fault_t = xs
        target, streak2 = _policy_math(
            jnp, inputs.knobs, d_prev, f_prev, p_prev, replicas,
            streak, inputs,
        )
        new, violation, cost, backlog = _step_math(
            jnp, replicas, target, demand_t, price_t, fault_t, inputs
        )
        carry2 = (new, streak2, demand_t, forecast_t, price_t)
        return carry2, (violation, cost, backlog, target)

    init = (inputs.replicas0, inputs.streak0, zeros, zeros, _ONE)
    (replicas, streak, _d, _f, _p), (violation, cost, backlog, target) = (
        jax.lax.scan(
            tick, init,
            (inputs.demand, inputs.forecast, inputs.price, inputs.fault),
        )
    )
    return SimRolloutOutputs(
        replicas=replicas, streak=streak, violation=violation,
        cost=cost, backlog=backlog, target=target,
    )


sim_rollout_jit = jax.jit(sim_rollout)

# the batched program: N clusters' trails/knobs stack on a leading axis
# and advance as ONE vmapped dispatch; the five scalars broadcast
_BATCH_AXES = SimRolloutInputs(
    replicas0=0, streak0=0, demand=0, forecast=0, price=0, fault=0,
    knobs=0, cap=None, hourly=None, step_limit=None, min_replicas=None,
    max_replicas=None,
)
sim_rollout_vmapped = jax.jit(jax.vmap(sim_rollout, in_axes=(_BATCH_AXES,)))


def _rollout_numpy_one(inputs: SimRolloutInputs) -> SimRolloutOutputs:
    ticks, rows = inputs.demand.shape
    replicas = np.asarray(inputs.replicas0, np.float32).copy()
    streak = np.asarray(inputs.streak0, np.float32).copy()
    d_prev = np.zeros(rows, np.float32)
    f_prev = np.zeros(rows, np.float32)
    # 0-d arrays, not numpy scalars: the kernels broadcast per-cluster
    # price/fault over rows with `[..., None]`, which scalars reject
    p_prev = np.asarray(_ONE)
    violation = np.zeros((ticks, rows), np.float32)
    cost = np.zeros((ticks, rows), np.float32)
    backlog = np.zeros((ticks, rows), np.float32)
    target = np.zeros((ticks, rows), np.float32)
    for t in range(ticks):
        tgt, streak = _policy_math(
            np, inputs.knobs, d_prev, f_prev, p_prev, replicas, streak,
            inputs,
        )
        replicas, violation[t], cost[t], backlog[t] = _step_math(
            np, replicas, tgt, inputs.demand[t],
            np.asarray(inputs.price[t]), np.asarray(inputs.fault[t]),
            inputs,
        )
        target[t] = tgt
        d_prev, f_prev, p_prev = (
            inputs.demand[t], inputs.forecast[t],
            np.asarray(inputs.price[t]),
        )
    return SimRolloutOutputs(
        replicas=replicas, streak=streak, violation=violation,
        cost=cost, backlog=backlog, target=target,
    )


def sim_rollout_numpy(inputs: SimRolloutInputs) -> SimRolloutOutputs:
    """Bit-identical host mirror of sim_rollout/sim_rollout_vmapped:
    unbatched trails run one episode loop; batched trails loop the
    clusters (the sequential reference the property pins compare)."""
    if np.asarray(inputs.replicas0).ndim == 1:
        return _rollout_numpy_one(inputs)
    outs = [
        _rollout_numpy_one(_cluster_slice(inputs, b))
        for b in range(np.asarray(inputs.replicas0).shape[0])
    ]
    return SimRolloutOutputs(
        replicas=np.stack([o.replicas for o in outs]),
        streak=np.stack([o.streak for o in outs]),
        violation=np.stack([o.violation for o in outs]),
        cost=np.stack([o.cost for o in outs]),
        backlog=np.stack([o.backlog for o in outs]),
        target=np.stack([o.target for o in outs]),
    )


def _cluster_slice(inputs: SimRolloutInputs, b: int) -> SimRolloutInputs:
    """Cluster b's unbatched view of a batched SimRolloutInputs."""
    return SimRolloutInputs(
        replicas0=np.asarray(inputs.replicas0)[b],
        streak0=np.asarray(inputs.streak0)[b],
        demand=np.asarray(inputs.demand)[b],
        forecast=np.asarray(inputs.forecast)[b],
        price=np.asarray(inputs.price)[b],
        fault=np.asarray(inputs.fault)[b],
        knobs=np.asarray(inputs.knobs)[b],
        cap=inputs.cap,
        hourly=inputs.hourly,
        step_limit=inputs.step_limit,
        min_replicas=inputs.min_replicas,
        max_replicas=inputs.max_replicas,
    )
