"""Fleet-wide eviction planning: the "what do I evict to place this?" kernel.

The bin-pack (ops/binpack.py) answers which GROUP a pending pod should
scale up; it treats the fleet's existing occupancy as immovable. Real
clusters ask a second question constantly — can a high-priority pending
pod be placed NOW by evicting lower-priority occupancy, and if so, what
is the cheapest eviction set? This module answers that for every
candidate pod against every node in ONE fixed-shape device program:

1. EVICTABILITY [C, V]: victim v may be evicted for candidate c iff the
   victim is valid, policy allows it (``victim_evictable`` — the host
   folds do-not-disrupt and coordination holds into this mask), and
   either the victim's priority is STRICTLY below the candidate's or the
   victim's node is a preemptible/spot tier (capacity that is reclaimable
   by contract, regardless of priority).
2. MINIMAL EVICTION PREFIX: victims arrive SORTED by (node, priority,
   index) — the input contract the planner/encoder upholds — so for each
   node the evictable victims form a lowest-priority-first order. The
   kernel computes, per (candidate, node), the shortest prefix of that
   order whose freed capacity (plus the node's current free capacity)
   fits the candidate: within-node prefix sums of freed resources via
   one global cumsum minus per-node base offsets. "Minimal" is minimal
   UNDER THE PRIORITY ORDER (evict the lowest-priority occupants first,
   the kube-scheduler's preemption posture), not minimal cardinality
   over arbitrary subsets — the latter is a knapsack.
3. PLACEMENT [C]: each candidate takes the (evictions, node-index)
   lexicographically smallest feasible placement — zero-eviction fits
   win outright, ties break to the lowest node column. Candidates are
   planned INDEPENDENTLY (the whole [C] axis is data-parallel), so a
   batched plan equals C single-candidate plans row for row; the host
   engine resolves cross-candidate conflicts (two plans claiming one
   victim) where policy lives.

BIT-IDENTICAL BACKENDS BY CONSTRUCTION: all capacity arithmetic is
integer. Resources are quantized to QUANT units per axis-max (need
rounds UP, free/freed round DOWN — an integer fit implies a real fit,
so a plan never under-evicts), after which every accumulation (cumsum),
comparison, and reduction (min over placement keys) is exact i32 math
whose result is association-independent. The only float ops are
elementwise scale/multiply/floor/ceil, identical ops in identical order
on both backends — so ``preempt_numpy`` mirrors ``preempt_plan`` with
no f32-reduction caveats at all (tests/test_preemption.py pins it).
The price is quantization slack: a fit within 1/QUANT of exact may be
judged infeasible, always in the conservative direction.

Production callers submit through ``SolverService.preempt`` (coalescing
queue, shape bucketing, numpy-fallback ladder, health FSM); this module
is the kernel-level entry.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

# Integer capacity resolution: every resource axis is scaled so its
# largest operand maps to QUANT units. 1/65536 relative resolution, and
# V victims * QUANT stays inside i32 for V <= MAX_VICTIMS.
QUANT = 65536
MAX_VICTIMS = 16384
# i32 sentinel for "no feasible placement" in the key minimum
_NO_FIT = np.int32(2**31 - 1)


@jax.tree_util.register_dataclass
@dataclass
class PreemptInputs:
    """Structure-of-arrays eviction-planning problem.

    C = candidate pending pods, N = node columns (real nodes for the
    planner; node groups for coarse what-if/bench runs), V = victim
    occupancy rows, R = resource axes.

    Input contract (the planner/encoder and the service's padding both
    uphold it; the kernel does not re-verify):
      * victims are sorted by (node, priority, index) — within one node,
        ascending priority;
      * invalid rows/columns are ZEROED (padding must not perturb the
        per-resource maxima the quantization scales derive from);
      * padding node columns are forbidden for every candidate.
    """

    pod_requests: jax.Array  # f32[C, R] candidate requests
    pod_priority: jax.Array  # i32[C]
    pod_valid: jax.Array  # bool[C]
    pod_node_forbidden: jax.Array  # bool[C, N] host-folded feasibility
    node_free: jax.Array  # f32[N, R] free (unreserved) capacity
    node_tier: jax.Array  # i32[N] 0 = on-demand, >0 = preemptible/spot
    victim_requests: jax.Array  # f32[V, R] scheduler-effective requests
    victim_priority: jax.Array  # i32[V]
    victim_node: jax.Array  # i32[V] column index (sorted axis)
    victim_valid: jax.Array  # bool[V]
    victim_evictable: jax.Array  # bool[V] policy mask (do-not-disrupt, holds)


@jax.tree_util.register_dataclass
@dataclass
class PreemptOutputs:
    chosen_node: jax.Array  # i32[C] placement column, -1 = unplaceable
    evict_count: jax.Array  # i32[C] evictions the placement needs
    evict_mask: jax.Array  # bool[C, V] the minimal eviction set per plan
    unplaceable: jax.Array  # i32 scalar: valid candidates with no plan


# `need` values above every possible free+freed total clip here BEFORE
# the f32->i32 conversion: the largest left-hand side is one node's free
# (<= QUANT) plus a full victim prefix (<= MAX_VICTIMS * QUANT = 2^30),
# so any need at the clip is genuinely unplaceable — and the clip keeps
# a pod requesting vastly more than any node from overflowing i32
# (conversion of out-of-range floats is undefined and backend-divergent).
_NEED_CLIP = np.float32(2**30 + 2**17)


def _quantize(inputs: PreemptInputs):
    """(need i32[C,R], free i32[N,R], shed i32[V,R]): per-resource
    integer capacities. The scale denominator is the max over the NODE
    and VICTIM families only — never the candidates — so it is a pure
    function of the fleet and a single-candidate subproblem over the
    same fleet quantizes identically (the batched == independent
    property rests on this; a candidate-derived scale would shift the
    ceil/floor rounding when the batch composition changes)."""
    xp = jnp if isinstance(inputs.pod_requests, jax.Array) else np
    denom = np.float32(1e-30) * xp.ones(
        inputs.pod_requests.shape[1], np.float32
    )
    if inputs.node_free.shape[0]:  # static: N=0 has no node max
        denom = xp.maximum(denom, xp.max(inputs.node_free, axis=0))
    if inputs.victim_requests.shape[0]:  # static: V=0 likewise
        denom = xp.maximum(
            denom, xp.max(inputs.victim_requests, axis=0)
        )  # f32[R]
    scale = np.float32(QUANT) / denom  # f32[R], elementwise
    need = xp.minimum(
        xp.ceil(inputs.pod_requests * scale[None, :]), _NEED_CLIP
    ).astype(np.int32)
    free = xp.floor(inputs.node_free * scale[None, :]).astype(np.int32)
    shed = xp.floor(
        inputs.victim_requests * scale[None, :]
    ).astype(np.int32)
    return need, free, shed


def _evictable(inputs: PreemptInputs):
    """bool[C, V]: victim v may be evicted to admit candidate c."""
    xp = jnp if isinstance(inputs.pod_requests, jax.Array) else np
    victim_tier = inputs.node_tier[inputs.victim_node]  # i32[V]
    outranked = (
        inputs.victim_priority[None, :] < inputs.pod_priority[:, None]
    )
    reclaimable = (victim_tier > 0)[None, :]
    return (
        (inputs.victim_valid & inputs.victim_evictable)[None, :]
        & (outranked | reclaimable)
        & inputs.pod_valid[:, None]
    ), xp


def _node_base_index(victim_node, n_nodes: int, xp):
    """i32[N]: index of the last victim BEFORE each node's segment (the
    sorted-victim contract makes segments contiguous), -1 when a node's
    segment starts at row 0. O(V + N) via bincount + exclusive cumsum
    (a [V, N] comparison matrix would be hundreds of MB at the victim
    ceiling on a large cluster); integer throughout, so both backends
    agree exactly."""
    if xp is np:
        counts = np.bincount(
            victim_node, minlength=n_nodes
        )[:n_nodes].astype(np.int32)
    else:
        counts = jnp.bincount(victim_node, length=n_nodes).astype(
            np.int32
        )
    before = xp.cumsum(counts, dtype=np.int32) - counts
    return before - 1


def _plan(inputs: PreemptInputs):
    """The shared program: identical operations on either jnp or np
    arrays — integer accumulation makes the two backends bit-equal
    without mirrored-scan tricks (module docstring)."""
    evictable, xp = _evictable(inputs)  # bool[C, V]
    n_nodes = inputs.node_free.shape[0]
    n_victims = inputs.victim_requests.shape[0]
    if n_nodes == 0:  # static: a nodeless fleet (e.g. a full spot
        # reclaim) places nothing — every valid candidate is
        # unplaceable, on BOTH backends (the device path only ever saw
        # this through bucket padding; the raw mirror must agree)
        c = inputs.pod_requests.shape[0]
        return PreemptOutputs(
            chosen_node=xp.full(c, -1, np.int32),
            evict_count=xp.zeros(c, np.int32),
            evict_mask=xp.zeros((c, n_victims), bool),
            unplaceable=xp.sum(
                inputs.pod_valid.astype(np.int32), dtype=np.int32
            ),
        )
    need, free, shed = _quantize(inputs)

    if n_victims:  # static shape branch: V=0 plans from free space only
        # within-node inclusive prefix of freed capacity per candidate:
        # one global cumsum along the sorted victim axis, re-based per
        # node (victims of earlier node columns subtract out)
        shed_c = shed[None, :, :] * evictable[:, :, None].astype(np.int32)
        gcum = xp.cumsum(shed_c, axis=1, dtype=np.int32)  # i32[C, V, R]
        base_idx = _node_base_index(inputs.victim_node, n_nodes, xp)
        base = xp.where(
            (base_idx >= 0)[None, :, None],
            xp.take(gcum, xp.maximum(base_idx, 0), axis=1),
            np.int32(0),
        )  # i32[C, N, R]: freed total on all earlier nodes
        prefix = gcum - xp.take(base, inputs.victim_node, axis=1)

        # same re-based prefix over eviction COUNTS
        cnt_g = xp.cumsum(evictable.astype(np.int32), axis=1)
        cnt_base = xp.where(
            (base_idx >= 0)[None, :],
            xp.take(cnt_g, xp.maximum(base_idx, 0), axis=1),
            np.int32(0),
        )  # i32[C, N]
        cnt = cnt_g - xp.take(cnt_base, inputs.victim_node, axis=1)

        # placement keys: (evictions, node) packed lexicographically.
        # The victim-prefix keys and the zero-eviction keys share one
        # i32 space; min over both is the plan. Forbidden columns and
        # invalid candidates never produce a finite key.
        victim_col = inputs.victim_node  # i32[V]
        fit_v = xp.all(
            xp.take(free, victim_col, axis=0)[None, :, :] + prefix
            >= need[:, None, :],
            axis=2,
        )  # bool[C, V]
        allowed_v = ~xp.take(
            inputs.pod_node_forbidden, victim_col, axis=1
        )  # bool[C, V]
        key_v = xp.where(
            fit_v & allowed_v & inputs.pod_valid[:, None],
            cnt * np.int32(n_nodes) + victim_col[None, :],
            _NO_FIT,
        )  # i32[C, V]
        best_v = xp.min(key_v, axis=1)
    else:
        cnt = xp.zeros(
            (inputs.pod_requests.shape[0], 0), np.int32
        )
        best_v = _NO_FIT

    fit_0 = xp.all(
        free[None, :, :] >= need[:, None, :], axis=2
    )  # bool[C, N]
    key_0 = xp.where(
        fit_0 & ~inputs.pod_node_forbidden & inputs.pod_valid[:, None],
        xp.arange(n_nodes, dtype=np.int32)[None, :],
        _NO_FIT,
    )  # i32[C, N]

    best = xp.minimum(best_v, xp.min(key_0, axis=1))  # i32[C]
    placed = best != _NO_FIT
    chosen = xp.where(placed, best % np.int32(n_nodes), np.int32(-1))
    evict_count = xp.where(placed, best // np.int32(n_nodes), np.int32(0))
    evict_mask = (
        placed[:, None]
        & (inputs.victim_node[None, :] == chosen[:, None])
        & evictable
        & (cnt <= evict_count[:, None])
    )
    unplaceable = xp.sum(
        (inputs.pod_valid & ~placed).astype(np.int32), dtype=np.int32
    )
    return PreemptOutputs(
        chosen_node=chosen,
        evict_count=evict_count,
        evict_mask=evict_mask,
        unplaceable=unplaceable,
    )


@jax.jit
def preempt_plan(inputs: PreemptInputs) -> PreemptOutputs:
    """The XLA program (CPU/TPU). One dispatch plans every candidate."""
    return _plan(inputs)


def preempt_numpy(inputs: PreemptInputs) -> PreemptOutputs:
    """The host mirror — the numpy-fallback rung of the service ladder.
    Bit-identical to preempt_plan (integer arithmetic; module
    docstring), pinned by tests/test_preemption.py."""
    host = PreemptInputs(
        pod_requests=np.asarray(inputs.pod_requests, np.float32),
        pod_priority=np.asarray(inputs.pod_priority, np.int32),
        pod_valid=np.asarray(inputs.pod_valid, bool),
        pod_node_forbidden=np.asarray(inputs.pod_node_forbidden, bool),
        node_free=np.asarray(inputs.node_free, np.float32),
        node_tier=np.asarray(inputs.node_tier, np.int32),
        victim_requests=np.asarray(inputs.victim_requests, np.float32),
        victim_priority=np.asarray(inputs.victim_priority, np.int32),
        victim_node=np.asarray(inputs.victim_node, np.int32),
        victim_valid=np.asarray(inputs.victim_valid, bool),
        victim_evictable=np.asarray(inputs.victim_evictable, bool),
    )
    return _plan(host)


def solve_preempt(
    inputs: PreemptInputs, backend: str = "auto"
) -> PreemptOutputs:
    """Kernel-level dispatcher: 'xla', 'numpy', or 'auto' (numpy on a
    CPU default backend — the same degraded-mode posture as
    ops/binpack.solve; there is no Mosaic preempt kernel, so TPU runs
    the XLA program). Production callers use SolverService.preempt."""
    if inputs.victim_requests.shape[0] > MAX_VICTIMS:
        raise ValueError(
            f"preempt solve supports at most {MAX_VICTIMS} victims "
            f"(i32 capacity headroom), got "
            f"{inputs.victim_requests.shape[0]}"
        )
    if backend == "auto":
        backend = (
            "numpy" if jax.default_backend() == "cpu" else "xla"
        )
    if backend == "numpy":
        return preempt_numpy(inputs)
    if backend in ("xla", "pallas"):
        return preempt_plan(jax.device_put(inputs))
    raise ValueError(f"unknown preempt backend {backend!r}")
