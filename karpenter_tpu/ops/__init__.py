"""Device kernels: the compute-heavy paths of the framework, as XLA programs.

The reference has no native/CUDA components (SURVEY.md §2) — its hot math is
scalar Go. The TPU build's obligation is that every hot path (HPA decision
math, reserved-capacity aggregation, pending-pods bin-packing) runs as
batched, jitted array programs instead of host loops.
"""
