/* Fused assignment kernel for the degraded-mode CPU bin-pack.
 *
 * reference: none (the reference stubs the whole producer,
 * pkg/metrics/producers/pendingcapacity/producer.go:29-31). This is the
 * native half of ops/numpy_binpack.py: feasibility + first-feasible (or
 * preference-argmax) assignment + dominant-share bucketing + all
 * post-assignment aggregates, in ONE row-major pass with per-pod early
 * exit. The dense formulations (XLA for the MXU, numpy BLAS for the CPU
 * fallback) always touch every (pod, group) pair; a scalar scan stops at
 * the first feasible group when no preference scores steer, which is the
 * common case and makes the pass nearly O(P) on realistic inputs.
 *
 * Semantics contract (pinned by tests/test_numpy_binpack.py):
 *  - feasibility: resource fit (req <= alloc, all R), group has any
 *    allocatable, no intolerated taint (packed uint64 words), no missing
 *    required label, not forbidden, pod valid — identical boolean
 *    outcome to ops/binpack._feasibility;
 *  - choice: first feasible group, or among feasible the highest score
 *    with lowest-index tie-break (argmax semantics);
 *  - share/bucket: float32 arithmetic in the same operation order as
 *    _dominant_share, bucket = clamp(ceilf(share * B), 1, B);
 *  - demand: float64 accumulation in pod order (bitwise-identical to the
 *    numpy np.add.at path).
 *
 * The per-pod CHOICE exists exactly once (karpenter_choose_pod): the
 * fused single pass and the threaded variant both call it, so the four
 * scan shapes (fast/generic x fused/threaded) can never drift apart.
 *
 * Plain C + ctypes (no CPython API): the loader compiles it on demand
 * and callers fall back to the numpy path when no toolchain exists.
 */

#include <math.h>
#include <pthread.h>
#include <stddef.h>
#include <stdint.h>
#include <stdlib.h>

/* Shelf best-fit-decreasing over bucket histograms: the exact semantics
 * of ops/binpack._shelf_bfd / oracle_shelf_bfd (repeated passes of
 * "every open bin with sufficient remaining capacity takes one item,
 * smallest remaining first"; leftovers open fresh bins). The data is
 * tiny ([T, B+1] state) — this exists because the vectorized numpy form
 * costs ~1000 array-op dispatches of pure interpreter overhead per
 * solve, which dominates the degraded tick once assignment is native. */
void karpenter_shelf_bfd(
    long long n_groups,
    long long buckets,
    const long long *histogram, /* [T, B] */
    long long *total            /* out [T], zeroed by caller */
) {
    for (long long t = 0; t < n_groups; t++) {
        long long bins[buckets + 1]; /* count by remaining capacity */
        for (long long i = 0; i <= buckets; i++) {
            bins[i] = 0;
        }
        for (long long k = buckets; k >= 1; k--) {
            long long c = histogram[t * buckets + (k - 1)];
            while (c > 0) {
                int placed = 0;
                for (long long rem = k; rem <= buckets && c > 0; rem++) {
                    long long m = bins[rem] < c ? bins[rem] : c;
                    if (m > 0) {
                        bins[rem] -= m;
                        bins[rem - k] += m;
                        c -= m;
                        placed = 1;
                    }
                }
                if (!placed) {
                    break;
                }
            }
            if (c > 0) {
                long long per_bin = buckets / k;
                long long full = c / per_bin;
                long long leftover = c - full * per_bin;
                total[t] += full + (leftover > 0 ? 1 : 0);
                bins[buckets - per_bin * k] += full;
                if (leftover > 0) {
                    bins[buckets - leftover * k] += 1;
                }
            }
        }
    }
}

/* ---------------------------------------------------------------------
 * Per-pod choice — the ONE implementation of feasibility + selection.
 * ------------------------------------------------------------------ */

/* read-only operands of one solve, shared by every scan shape */
typedef struct {
    long long n_groups, n_resources, taint_words, label_words, buckets;
    const float *requests;          /* [P, R] */
    const unsigned char *valid;     /* [P] */
    const uint64_t *intolerant;     /* [P, KW] */
    const uint64_t *required;       /* [P, LW] */
    const float *alloc;             /* [T, R] */
    const uint64_t *taints;         /* [T, KW] */
    const uint64_t *missing;        /* [T, LW] (~labels, packed) */
    const unsigned char *forbidden; /* [P, T] or NULL */
    const float *score;             /* [P, T] or NULL */
    const unsigned char *usable;    /* [T] or NULL: fast shape applies */
} karpenter_scan;

/* Fast shape (usable != NULL): no steering scores, no forbidden mask,
 * both bitsets within one 64-bit word (any fleet with <= 64 distinct
 * hard taints and <= 64 label items — the bench shape and most
 * production fleets). The pod's two words load once, the per-group
 * checks collapse to one OR of two ANDs, and the resource fit runs
 * branch-free (R is small; `&=` lets the compiler unroll instead of
 * predicting a break). Choice semantics are IDENTICAL to the generic
 * scan: first feasible group wins. */
static inline long long karpenter_choose_pod_fast(
    const karpenter_scan *S, long long p
) {
    const float *req = S->requests + p * S->n_resources;
    const uint64_t iw = S->intolerant[p];
    const uint64_t nw = S->required[p];
    for (long long t = 0; t < S->n_groups; t++) {
        if (!S->usable[t]) {
            continue;
        }
        const float *a = S->alloc + t * S->n_resources;
        int fit = 1;
        for (long long r = 0; r < S->n_resources; r++) {
            fit &= (req[r] <= a[r]);
        }
        if (!fit || ((iw & S->taints[t]) | (nw & S->missing[t]))) {
            continue;
        }
        return t;
    }
    return -1;
}

/* Generic shape: multi-word bitsets, optional forbidden mask, optional
 * score argmax (which disables the first-feasible early exit — the
 * dense case, where the per-pod `a[r] > 0` probes measurably beat a
 * hoisted usability mask's extra load+branch per (pod, group) pair). */
static inline long long karpenter_choose_pod_generic(
    const karpenter_scan *S, long long p
) {
    const float *req = S->requests + p * S->n_resources;
    const uint64_t *intol = S->intolerant + p * S->taint_words;
    const uint64_t *need = S->required + p * S->label_words;
    long long best = -1;
    float best_score = 0.0f;
    for (long long t = 0; t < S->n_groups; t++) {
        if (S->forbidden && S->forbidden[p * S->n_groups + t]) {
            continue;
        }
        const float *a = S->alloc + t * S->n_resources;
        int ok = 0;
        for (long long r = 0; r < S->n_resources; r++) {
            if (req[r] > a[r]) {
                ok = -1;
                break;
            }
            if (a[r] > 0.0f) {
                ok = 1; /* group has SOME allocatable */
            }
        }
        if (ok != 1) {
            continue;
        }
        const uint64_t *tw = S->taints + t * S->taint_words;
        int violated = 0;
        for (long long w = 0; w < S->taint_words; w++) {
            if (intol[w] & tw[w]) {
                violated = 1;
                break;
            }
        }
        if (violated) {
            continue;
        }
        const uint64_t *mw = S->missing + t * S->label_words;
        for (long long w = 0; w < S->label_words; w++) {
            if (need[w] & mw[w]) {
                violated = 1;
                break;
            }
        }
        if (violated) {
            continue;
        }
        if (S->score == NULL) {
            return t; /* first feasible wins */
        }
        float s = S->score[p * S->n_groups + t];
        if (best < 0 || s > best_score) {
            best = t;
            best_score = s;
        }
    }
    return best;
}

static inline long long karpenter_choose_pod(
    const karpenter_scan *S, long long p
) {
    return S->usable ? karpenter_choose_pod_fast(S, p)
                     : karpenter_choose_pod_generic(S, p);
}

/* Group usability (any allocatable > 0), precomputed once for the FAST
 * shape only: its first-feasible scan gains from skipping dead groups
 * before the fit check; the generic dense scan keeps its per-pod probes
 * and never pays for the precompute. NULL = fast shape not applicable
 * (or allocation pressure: the generic scan is always correct). */
static unsigned char *karpenter_usable_mask(
    long long n_groups, long long n_resources, long long taint_words,
    long long label_words, const float *alloc,
    const unsigned char *forbidden, const float *score
) {
    if (score != NULL || forbidden != NULL || taint_words != 1
        || label_words != 1) {
        return NULL;
    }
    unsigned char *usable = (unsigned char *)malloc((size_t)n_groups);
    if (usable == NULL) {
        return NULL;
    }
    for (long long t = 0; t < n_groups; t++) {
        unsigned char any = 0;
        const float *a = alloc + t * n_resources;
        for (long long r = 0; r < n_resources; r++) {
            any |= (a[r] > 0.0f);
        }
        usable[t] = any;
    }
    return usable;
}

/* Dominant-share bucket of one assigned pod: same f32 formula/order as
 * _dominant_share; feasibility guarantees req <= alloc, so share stays
 * in [0, 1]. ONE implementation — the fused record and the threaded
 * choice phase both call it, so buckets are identical by
 * construction. */
static inline long long karpenter_pod_bucket(
    const float *req, const float *a, long long n_resources,
    long long buckets
) {
    float share = 0.0f;
    for (long long r = 0; r < n_resources; r++) {
        float s;
        if (a[r] > 0.0f) {
            float denom = a[r] > 1e-30f ? a[r] : 1e-30f;
            s = req[r] / denom;
        } else {
            s = (req[r] <= 0.0f) ? 0.0f : INFINITY;
        }
        if (s > share) {
            share = s;
        }
    }
    long long bucket = (long long)ceilf(share * (float)buckets);
    if (bucket < 1) {
        bucket = 1;
    }
    if (bucket > buckets) {
        bucket = buckets;
    }
    return bucket;
}

/* Post-choice accounting for one assigned pod: count, dominant-share
 * bucket, histogram, f64 demand. */
static inline void karpenter_assign_record(
    long long p, long long best, long long n_resources, long long buckets,
    const float *req, const float *a, const long long *weight,
    const unsigned char *exclusive, int32_t *assigned,
    long long *assigned_count, long long *histogram, double *demand
) {
    assigned[p] = (int32_t)best;
    long long w_of = weight ? weight[p] : 1;
    assigned_count[best] += w_of;
    long long bucket = karpenter_pod_bucket(req, a, n_resources, buckets);
    for (long long r = 0; r < n_resources; r++) {
        demand[best * n_resources + r] += (double)req[r] * (double)w_of;
    }
    if (exclusive && exclusive[p]) {
        /* hostname self-anti-affinity: the pod takes a whole node */
        bucket = buckets;
    }
    histogram[best * buckets + (bucket - 1)] += w_of;
}

void karpenter_assign(
    long long n_pods,
    long long n_groups,
    long long n_resources,
    long long taint_words,
    long long label_words,
    long long buckets,
    const float *requests,          /* [P, R] */
    const unsigned char *valid,     /* [P] */
    const uint64_t *intolerant,     /* [P, KW] */
    const uint64_t *required,       /* [P, LW] */
    const float *alloc,             /* [T, R] */
    const uint64_t *taints,         /* [T, KW] */
    const uint64_t *missing,        /* [T, LW] (~labels, packed) */
    const unsigned char *forbidden, /* [P, T] or NULL */
    const float *score,             /* [P, T] or NULL */
    const long long *weight,        /* [P] or NULL */
    const unsigned char *exclusive, /* [P] or NULL: bucket forced to B */
    int32_t *assigned,              /* out [P] */
    long long *assigned_count,      /* out [T], zeroed by caller */
    long long *histogram,           /* out [T, B], zeroed by caller */
    double *demand,                 /* out [T, R], zeroed by caller */
    long long *unschedulable        /* out [1], zeroed by caller */
) {
    karpenter_scan S = {
        .n_groups = n_groups, .n_resources = n_resources,
        .taint_words = taint_words, .label_words = label_words,
        .buckets = buckets,
        .requests = requests, .valid = valid,
        .intolerant = intolerant, .required = required,
        .alloc = alloc, .taints = taints, .missing = missing,
        .forbidden = forbidden, .score = score,
        .usable = karpenter_usable_mask(
            n_groups, n_resources, taint_words, label_words, alloc,
            forbidden, score),
    };
    for (long long p = 0; p < n_pods; p++) {
        assigned[p] = -1;
        if (!valid[p]) {
            continue;
        }
        long long best = karpenter_choose_pod(&S, p);
        if (best < 0) {
            *unschedulable += (weight ? weight[p] : 1);
            continue;
        }
        karpenter_assign_record(
            p, best, n_resources, buckets, requests + p * n_resources,
            alloc + best * n_resources, weight, exclusive, assigned,
            assigned_count, histogram, demand);
    }
    free((void *)S.usable);
}

/* ---------------------------------------------------------------------
 * Multithreaded assignment: the CHOICE phase (per-pod, pure — no shared
 * writes except each pod's own assigned/bucket slot) fans out across
 * threads; every aggregate (count, histogram, f64 demand, unschedulable)
 * is then accumulated in ONE sequential pod-order pass, so outputs are
 * bitwise identical to karpenter_assign and to the numpy oracle —
 * float addition order never depends on the thread count. The sandbox
 * this ships from has one core, so the speedup is deliberately
 * UNCLAIMED; the identity is what the tests pin.
 * ------------------------------------------------------------------ */

typedef struct {
    const karpenter_scan *scan;
    long long lo, hi;
    int32_t *assigned;
    int32_t *bucket;
} karpenter_choose_task;

static void *karpenter_choose_thread(void *arg) {
    const karpenter_choose_task *T = (const karpenter_choose_task *)arg;
    const karpenter_scan *S = T->scan;
    for (long long p = T->lo; p < T->hi; p++) {
        T->assigned[p] = -1;
        T->bucket[p] = 0;
        if (!S->valid[p]) {
            continue;
        }
        long long best = karpenter_choose_pod(S, p);
        if (best >= 0) {
            T->assigned[p] = (int32_t)best;
            T->bucket[p] = (int32_t)karpenter_pod_bucket(
                S->requests + p * S->n_resources,
                S->alloc + best * S->n_resources, S->n_resources,
                S->buckets);
        }
    }
    return NULL;
}

#define KARPENTER_MAX_THREADS 64

void karpenter_assign_mt(
    long long n_pods,
    long long n_groups,
    long long n_resources,
    long long taint_words,
    long long label_words,
    long long buckets,
    const float *requests,
    const unsigned char *valid,
    const uint64_t *intolerant,
    const uint64_t *required,
    const float *alloc,
    const uint64_t *taints,
    const uint64_t *missing,
    const unsigned char *forbidden,
    const float *score,
    const long long *weight,
    const unsigned char *exclusive,
    int32_t *assigned,
    long long *assigned_count,
    long long *histogram,
    double *demand,
    long long *unschedulable,
    long long n_threads
) {
    int32_t *bucket = (int32_t *)malloc((size_t)(n_pods ? n_pods : 1)
                                        * sizeof(int32_t));
    if (bucket == NULL) {
        /* allocation pressure: fall back to the fused single pass */
        karpenter_assign(
            n_pods, n_groups, n_resources, taint_words, label_words,
            buckets, requests, valid, intolerant, required, alloc, taints,
            missing, forbidden, score, weight, exclusive, assigned,
            assigned_count, histogram, demand, unschedulable);
        return;
    }
    karpenter_scan S = {
        .n_groups = n_groups, .n_resources = n_resources,
        .taint_words = taint_words, .label_words = label_words,
        .buckets = buckets,
        .requests = requests, .valid = valid,
        .intolerant = intolerant, .required = required,
        .alloc = alloc, .taints = taints, .missing = missing,
        .forbidden = forbidden, .score = score,
        .usable = karpenter_usable_mask(
            n_groups, n_resources, taint_words, label_words, alloc,
            forbidden, score),
    };

    if (n_threads < 1) {
        n_threads = 1;
    }
    if (n_threads > KARPENTER_MAX_THREADS) {
        n_threads = KARPENTER_MAX_THREADS;
    }
    if (n_threads > n_pods) {
        n_threads = n_pods ? n_pods : 1;
    }
    karpenter_choose_task tasks[KARPENTER_MAX_THREADS];
    pthread_t tids[KARPENTER_MAX_THREADS];
    long long chunk = (n_pods + n_threads - 1) / n_threads;
    long long spawned = 0;
    for (long long i = 0; i < n_threads; i++) {
        long long lo = i * chunk;
        long long hi = lo + chunk < n_pods ? lo + chunk : n_pods;
        if (lo >= hi) {
            break;
        }
        tasks[i] = (karpenter_choose_task){
            .scan = &S, .lo = lo, .hi = hi,
            .assigned = assigned, .bucket = bucket,
        };
        if (i == n_threads - 1
            || pthread_create(&tids[spawned], NULL,
                              karpenter_choose_thread, &tasks[i]) != 0) {
            /* last chunk (and any failed spawn) runs inline */
            karpenter_choose_thread(&tasks[i]);
        } else {
            spawned++;
        }
    }
    for (long long i = 0; i < spawned; i++) {
        pthread_join(tids[i], NULL);
    }

    /* sequential pod-order accumulation: identical addition order to the
     * fused pass and the numpy oracle, whatever n_threads was */
    for (long long p = 0; p < n_pods; p++) {
        long long best = assigned[p];
        if (best < 0) {
            if (valid[p]) {
                *unschedulable += (weight ? weight[p] : 1);
            }
            continue;
        }
        long long w_of = weight ? weight[p] : 1;
        assigned_count[best] += w_of;
        const float *req = requests + p * n_resources;
        for (long long r = 0; r < n_resources; r++) {
            demand[best * n_resources + r] += (double)req[r] * (double)w_of;
        }
        long long b = bucket[p];
        if (exclusive && exclusive[p]) {
            b = buckets;
        }
        histogram[best * buckets + (b - 1)] += w_of;
    }
    free(bucket);
    free((void *)S.usable);
}

/* bool[N, K] row-major (as uint8) -> uint64[N, W] little-endian bit
 * words — the taint/label operand packer. numpy's packbits pays
 * per-row overhead on narrow matrices and a full 64-column bool pad on
 * wide ones (profiled r4: the pack was most of the degraded-mode
 * solve); one scalar pass is memory-bound and shape-indifferent. */
void karpenter_pack_bits(
    long long n, long long k, long long words,
    const unsigned char *matrix, unsigned long long *out
) {
    /* 8 bools at a time: bytes are 0/1 (the caller feeds numpy bool
     * storage), and for a uint64 of 0/1 bytes the multiply by
     * 0x0102040810204080 gathers byte i into bit 56+i (all cross terms
     * land outside bits 56..63 or overflow away) — one load + multiply
     * + shift packs a byte octet. Each output word accumulates in a
     * register across its 8 octets before one store. */
    const unsigned long long GATHER = 0x0102040810204080ull;
    for (long long i = 0; i < n; i++) {
        const unsigned char *row = matrix + i * k;
        unsigned long long *orow = out + i * words;
        long long j = 0;
        for (long long w = 0; w < words; w++) {
            unsigned long long word = 0ull;
            long long hi = (w + 1) * 64 < k ? (w + 1) * 64 : k;
            for (; j + 8 <= hi; j += 8) {
                unsigned long long chunk;
                __builtin_memcpy(&chunk, row + j, 8);
                word |= ((chunk * GATHER) >> 56) << (unsigned)(j & 63);
            }
            for (; j < hi; j++) {
                if (row[j]) {
                    word |= 1ull << (unsigned)(j & 63);
                }
            }
            orow[w] = word;
        }
    }
}
