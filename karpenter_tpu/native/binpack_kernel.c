/* Fused assignment kernel for the degraded-mode CPU bin-pack.
 *
 * reference: none (the reference stubs the whole producer,
 * pkg/metrics/producers/pendingcapacity/producer.go:29-31). This is the
 * native half of ops/numpy_binpack.py: feasibility + first-feasible (or
 * preference-argmax) assignment + dominant-share bucketing + all
 * post-assignment aggregates, in ONE row-major pass with per-pod early
 * exit. The dense formulations (XLA for the MXU, numpy BLAS for the CPU
 * fallback) always touch every (pod, group) pair; a scalar scan stops at
 * the first feasible group when no preference scores steer, which is the
 * common case and makes the pass nearly O(P) on realistic inputs.
 *
 * Semantics contract (pinned by tests/test_numpy_binpack.py):
 *  - feasibility: resource fit (req <= alloc, all R), group has any
 *    allocatable, no intolerated taint (packed uint64 words), no missing
 *    required label, not forbidden, pod valid — identical boolean
 *    outcome to ops/binpack._feasibility;
 *  - choice: first feasible group, or among feasible the highest score
 *    with lowest-index tie-break (argmax semantics);
 *  - share/bucket: float32 arithmetic in the same operation order as
 *    _dominant_share, bucket = clamp(ceilf(share * B), 1, B);
 *  - demand: float64 accumulation in pod order (bitwise-identical to the
 *    numpy np.add.at path).
 *
 * Plain C + ctypes (no CPython API): the loader compiles it on demand
 * and callers fall back to the numpy path when no toolchain exists.
 */

#include <math.h>
#include <stddef.h>
#include <stdint.h>
#include <stdlib.h>

/* Shelf best-fit-decreasing over bucket histograms: the exact semantics
 * of ops/binpack._shelf_bfd / oracle_shelf_bfd (repeated passes of
 * "every open bin with sufficient remaining capacity takes one item,
 * smallest remaining first"; leftovers open fresh bins). The data is
 * tiny ([T, B+1] state) — this exists because the vectorized numpy form
 * costs ~1000 array-op dispatches of pure interpreter overhead per
 * solve, which dominates the degraded tick once assignment is native. */
void karpenter_shelf_bfd(
    long long n_groups,
    long long buckets,
    const long long *histogram, /* [T, B] */
    long long *total            /* out [T], zeroed by caller */
) {
    for (long long t = 0; t < n_groups; t++) {
        long long bins[buckets + 1]; /* count by remaining capacity */
        for (long long i = 0; i <= buckets; i++) {
            bins[i] = 0;
        }
        for (long long k = buckets; k >= 1; k--) {
            long long c = histogram[t * buckets + (k - 1)];
            while (c > 0) {
                int placed = 0;
                for (long long rem = k; rem <= buckets && c > 0; rem++) {
                    long long m = bins[rem] < c ? bins[rem] : c;
                    if (m > 0) {
                        bins[rem] -= m;
                        bins[rem - k] += m;
                        c -= m;
                        placed = 1;
                    }
                }
                if (!placed) {
                    break;
                }
            }
            if (c > 0) {
                long long per_bin = buckets / k;
                long long full = c / per_bin;
                long long leftover = c - full * per_bin;
                total[t] += full + (leftover > 0 ? 1 : 0);
                bins[buckets - per_bin * k] += full;
                if (leftover > 0) {
                    bins[buckets - leftover * k] += 1;
                }
            }
        }
    }
}

/* Post-choice accounting for one assigned pod: count, dominant-share
 * bucket, histogram, f64 demand — shared by the fast and generic scans
 * so the f32/f64 arithmetic order stays identical on both. */
static inline void karpenter_assign_record(
    long long p, long long best, long long n_resources, long long buckets,
    const float *req, const float *a, const long long *weight,
    const unsigned char *exclusive, int32_t *assigned,
    long long *assigned_count, long long *histogram, double *demand
) {
    assigned[p] = (int32_t)best;
    long long w_of = weight ? weight[p] : 1;
    assigned_count[best] += w_of;
    float share = 0.0f;
    for (long long r = 0; r < n_resources; r++) {
        /* same f32 formula/order as _dominant_share; feasibility
         * guarantees req <= alloc, so share stays in [0, 1] */
        float s;
        if (a[r] > 0.0f) {
            float denom = a[r] > 1e-30f ? a[r] : 1e-30f;
            s = req[r] / denom;
        } else {
            s = (req[r] <= 0.0f) ? 0.0f : INFINITY;
        }
        if (s > share) {
            share = s;
        }
        demand[best * n_resources + r] += (double)req[r] * (double)w_of;
    }
    long long bucket = (long long)ceilf(share * (float)buckets);
    if (bucket < 1) {
        bucket = 1;
    }
    if (bucket > buckets) {
        bucket = buckets;
    }
    if (exclusive && exclusive[p]) {
        /* hostname self-anti-affinity: the pod takes a whole node */
        bucket = buckets;
    }
    histogram[best * buckets + (bucket - 1)] += w_of;
}

void karpenter_assign(
    long long n_pods,
    long long n_groups,
    long long n_resources,
    long long taint_words,
    long long label_words,
    long long buckets,
    const float *requests,          /* [P, R] */
    const unsigned char *valid,     /* [P] */
    const uint64_t *intolerant,     /* [P, KW] */
    const uint64_t *required,       /* [P, LW] */
    const float *alloc,             /* [T, R] */
    const uint64_t *taints,         /* [T, KW] */
    const uint64_t *missing,        /* [T, LW] (~labels, packed) */
    const unsigned char *forbidden, /* [P, T] or NULL */
    const float *score,             /* [P, T] or NULL */
    const long long *weight,        /* [P] or NULL */
    const unsigned char *exclusive, /* [P] or NULL: bucket forced to B */
    int32_t *assigned,              /* out [P] */
    long long *assigned_count,      /* out [T], zeroed by caller */
    long long *histogram,           /* out [T, B], zeroed by caller */
    double *demand,                 /* out [T, R], zeroed by caller */
    long long *unschedulable        /* out [1], zeroed by caller */
) {
    /* Fast path for the dominant shape: no steering scores, no
     * forbidden mask, and both bitsets within one 64-bit word (any
     * fleet with <= 64 distinct hard taints and <= 64 label items —
     * the bench shape and most production fleets). The pod's two words
     * load once, the per-group checks collapse to one OR of two ANDs,
     * and the resource fit runs branch-free (R is small; `&=` lets the
     * compiler unroll instead of predicting a break). Choice semantics
     * are IDENTICAL to the generic scan: first feasible group wins.
     *
     * Group usability (any allocatable > 0) is precomputed once, for
     * this path ONLY: its first-feasible scan gains from skipping dead
     * groups before the fit check, while the generic dense scan
     * (scores disable the early exit) measurably loses a cycle per
     * (pod, group) pair to the extra load+branch, so it keeps its
     * original per-pod probes and never pays for the precompute. */
    unsigned char *usable = NULL;
    if (score == NULL && forbidden == NULL && taint_words == 1
        && label_words == 1) {
        usable = (unsigned char *)malloc((size_t)n_groups);
    }
    if (usable) {
        for (long long t = 0; t < n_groups; t++) {
            unsigned char any = 0;
            const float *a = alloc + t * n_resources;
            for (long long r = 0; r < n_resources; r++) {
                any |= (a[r] > 0.0f);
            }
            usable[t] = any;
        }
        for (long long p = 0; p < n_pods; p++) {
            assigned[p] = -1;
            if (!valid[p]) {
                continue;
            }
            const float *req = requests + p * n_resources;
            const uint64_t iw = intolerant[p];
            const uint64_t nw = required[p];
            long long best = -1;
            for (long long t = 0; t < n_groups; t++) {
                if (!usable[t]) {
                    continue;
                }
                const float *a = alloc + t * n_resources;
                int fit = 1;
                for (long long r = 0; r < n_resources; r++) {
                    fit &= (req[r] <= a[r]);
                }
                if (!fit || ((iw & taints[t]) | (nw & missing[t]))) {
                    continue;
                }
                best = t;
                break;
            }
            if (best < 0) {
                *unschedulable += (weight ? weight[p] : 1);
                continue;
            }
            karpenter_assign_record(
                p, best, n_resources, buckets, req,
                alloc + best * n_resources, weight, exclusive, assigned,
                assigned_count, histogram, demand);
        }
        free(usable);
        return;
    }

    for (long long p = 0; p < n_pods; p++) {
        assigned[p] = -1;
        if (!valid[p]) {
            continue;
        }
        const float *req = requests + p * n_resources;
        const uint64_t *intol = intolerant + p * taint_words;
        const uint64_t *need = required + p * label_words;
        long long best = -1;
        float best_score = 0.0f;
        for (long long t = 0; t < n_groups; t++) {
            if (forbidden && forbidden[p * n_groups + t]) {
                continue;
            }
            const float *a = alloc + t * n_resources;
            int ok = 0;
            for (long long r = 0; r < n_resources; r++) {
                if (req[r] > a[r]) {
                    ok = -1;
                    break;
                }
                if (a[r] > 0.0f) {
                    ok = 1; /* group has SOME allocatable */
                }
            }
            if (ok != 1) {
                continue;
            }
            const uint64_t *tw = taints + t * taint_words;
            int violated = 0;
            for (long long w = 0; w < taint_words; w++) {
                if (intol[w] & tw[w]) {
                    violated = 1;
                    break;
                }
            }
            if (violated) {
                continue;
            }
            const uint64_t *mw = missing + t * label_words;
            for (long long w = 0; w < label_words; w++) {
                if (need[w] & mw[w]) {
                    violated = 1;
                    break;
                }
            }
            if (violated) {
                continue;
            }
            if (score == NULL) {
                best = t; /* first feasible wins */
                break;
            }
            float s = score[p * n_groups + t];
            if (best < 0 || s > best_score) {
                best = t;
                best_score = s;
            }
        }
        if (best < 0) {
            *unschedulable += (weight ? weight[p] : 1);
            continue;
        }
        karpenter_assign_record(
            p, best, n_resources, buckets, req, alloc + best * n_resources,
            weight, exclusive, assigned, assigned_count, histogram, demand);
    }
}

/* bool[N, K] row-major (as uint8) -> uint64[N, W] little-endian bit
 * words — the taint/label operand packer. numpy's packbits pays
 * per-row overhead on narrow matrices and a full 64-column bool pad on
 * wide ones (profiled r4: the pack was most of the degraded-mode
 * solve); one scalar pass is memory-bound and shape-indifferent. */
void karpenter_pack_bits(
    long long n, long long k, long long words,
    const unsigned char *matrix, unsigned long long *out
) {
    /* 8 bools at a time: bytes are 0/1 (the caller feeds numpy bool
     * storage), and for a uint64 of 0/1 bytes the multiply by
     * 0x0102040810204080 gathers byte i into bit 56+i (all cross terms
     * land outside bits 56..63 or overflow away) — one load + multiply
     * + shift packs a byte octet. Each output word accumulates in a
     * register across its 8 octets before one store. */
    const unsigned long long GATHER = 0x0102040810204080ull;
    for (long long i = 0; i < n; i++) {
        const unsigned char *row = matrix + i * k;
        unsigned long long *orow = out + i * words;
        long long j = 0;
        for (long long w = 0; w < words; w++) {
            unsigned long long word = 0ull;
            long long hi = (w + 1) * 64 < k ? (w + 1) * 64 : k;
            for (; j + 8 <= hi; j += 8) {
                unsigned long long chunk;
                __builtin_memcpy(&chunk, row + j, 8);
                word |= ((chunk * GATHER) >> 56) << (unsigned)(j & 63);
            }
            for (; j < hi; j++) {
                if (row[j]) {
                    word |= 1ull << (unsigned)(j & 63);
                }
            }
            orow[w] = word;
        }
    }
}
