"""Native (C) accelerators with build-on-demand and pure-Python fallback.

The reference is pure Go with no native components (SURVEY.md §2), so
nothing here is a parity obligation — these are host-feed accelerations
for paths the TPU build made hot (quantity parsing on manifest ingest and
pod watch-event re-encode). Every native entry point has a Python oracle
(utils/quantity.py) and parity is fuzz-tested; absence of a C toolchain
degrades to the oracle silently.

Build: compiled once into native/_build/ with the running interpreter's
sysconfig flags; rebuilt when the .c source is newer than the .so.
"""

from __future__ import annotations

import os
import subprocess
import sysconfig
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "_build")
_lock = threading.Lock()
_kquantity = None
_tried = False


def _compile(src: str, out: str) -> bool:
    include = sysconfig.get_path("include")
    cc = sysconfig.get_config_var("CC") or "cc"
    # compile to a private temp path, then atomically publish: a concurrent
    # or killed compile must never leave a torn .so at the final path (it
    # would carry a fresh mtime and silently disable the accelerator
    # forever after)
    tmp = f"{out}.{os.getpid()}.tmp"
    cmd = [
        *cc.split(),
        "-O2",
        "-fPIC",
        "-shared",
        "-pthread",  # the threaded assignment variant (binpack_kernel.c)
        f"-I{include}",
        src,
        "-o",
        tmp,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=120)
        if proc.returncode != 0 or not os.path.exists(tmp):
            return False
        os.replace(tmp, out)
        return True
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _build_and_load(src_name: str, so_name: str, bind):
    """The shared build-on-demand scaffold: staleness check, compile,
    bind. Returns the bound handle or None; callers own the caching."""
    src = os.path.join(_HERE, src_name)
    so = os.path.join(_BUILD_DIR, so_name)
    try:
        os.makedirs(_BUILD_DIR, exist_ok=True)
        stale = (
            not os.path.exists(so)
            or os.path.getmtime(so) < os.path.getmtime(src)
        )
        if stale and not _compile(src, so):
            return None
        return bind(so)
    except Exception:
        return None


def _bind_extension(so: str):
    import importlib.util

    spec = importlib.util.spec_from_file_location("_kquantity", so)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def _bind_ctypes(so: str):
    import ctypes

    lib = ctypes.CDLL(so)
    lib.karpenter_assign.restype = None
    lib.karpenter_shelf_bfd.restype = None
    lib.karpenter_pack_bits.restype = None
    if hasattr(lib, "karpenter_assign_mt"):  # older prebuilt .so lacks it
        lib.karpenter_assign_mt.restype = None
    return lib


def load_kquantity() -> Optional[object]:
    """The _kquantity extension module, building it if needed; None when
    no toolchain is available (callers use the Python path)."""
    global _kquantity, _tried
    with _lock:
        if _kquantity is not None or _tried:
            return _kquantity
        _tried = True
        _kquantity = _build_and_load(
            "quantity.c", "_kquantity.so", _bind_extension
        )
        return _kquantity


_kbinpack = None
_kbinpack_tried = False
_kbinpack_async_started = False


def load_kbinpack() -> Optional[object]:
    """ctypes handle to the fused assignment kernel (binpack_kernel.c),
    building it on demand; None without a toolchain (callers use the
    numpy path). Plain C, no CPython API — loaded with ctypes.CDLL, and
    the call releases the GIL for its whole O(P*T) worst-case scan."""
    global _kbinpack, _kbinpack_tried
    with _lock:
        if _kbinpack is not None or _kbinpack_tried:
            return _kbinpack
        _kbinpack_tried = True
        _kbinpack = _build_and_load(
            "binpack_kernel.c", "_kbinpack.so", _bind_ctypes
        )
        return _kbinpack


def peek_kbinpack() -> Optional[object]:
    """The kernel if it has finished loading, else None. Never blocks —
    the degraded-mode solve must not spend its tick budget inside a cc
    subprocess; it runs the numpy stages until the handle appears."""
    return _kbinpack


def ensure_kbinpack_async() -> None:
    """Kick off the kernel build/load in a daemon thread (the
    ensure_kquantity_async pattern)."""
    global _kbinpack_async_started
    with _lock:
        if _kbinpack_async_started or _kbinpack is not None:
            return
        _kbinpack_async_started = True
    threading.Thread(target=load_kbinpack, daemon=True).start()


_async_started = False


def peek_kquantity() -> Optional[object]:
    """The extension if it has finished loading, else None. Never blocks."""
    return _kquantity


def ensure_kquantity_async() -> None:
    """Kick off the build/load in a daemon thread. Callers use the Python
    path until peek_kquantity() turns non-None, so a cold compile never
    blocks a latency-sensitive first request (e.g. an admission webhook)."""
    global _async_started
    with _lock:
        if _async_started or _kquantity is not None:
            return
        _async_started = True
    threading.Thread(target=load_kquantity, daemon=True).start()
