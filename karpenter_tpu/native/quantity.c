/* Native k8s resource.Quantity parser.
 *
 * The quantity grammar (sign, decimal mantissa, binary/decimal SI suffix or
 * scientific exponent) is parsed on every manifest ingest and every pod
 * watch-event re-encode — the host-side hot path feeding the device solver
 * (reference semantics: k8s.io/apimachinery resource.Quantity, modeled in
 * karpenter_tpu/utils/quantity.py whose parser this accelerates; the pure-
 * Python path remains the fallback and the semantic oracle).
 *
 * parse(s) -> (numerator, denominator, format) with exact integer
 * arithmetic in unsigned __int128; anything that would overflow or that
 * this parser does not recognize raises ValueError and the caller falls
 * back to Python. format: 0=DecimalSI, 1=BinarySI, 2=DecimalExponent.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

typedef unsigned __int128 u128;

static const u128 U128_MAX = ~(u128)0;

/* multiply with overflow check; returns 0 on overflow */
static int mul_u128(u128 a, u128 b, u128 *out) {
    if (a != 0 && b > U128_MAX / a) return 0;
    *out = a * b;
    return 1;
}

static PyObject *u128_to_pylong(u128 v) {
    /* split into two 64-bit halves: (hi << 64) | lo; every intermediate is
     * NULL-checked — an allocation failure must raise, not crash */
    uint64_t hi = (uint64_t)(v >> 64), lo = (uint64_t)v;
    if (hi == 0) return PyLong_FromUnsignedLongLong(lo);
    PyObject *phi = NULL, *shift = NULL, *plo = NULL, *hs = NULL,
             *res = NULL;
    phi = PyLong_FromUnsignedLongLong(hi);
    if (phi == NULL) goto done;
    shift = PyLong_FromLong(64);
    if (shift == NULL) goto done;
    plo = PyLong_FromUnsignedLongLong(lo);
    if (plo == NULL) goto done;
    hs = PyNumber_Lshift(phi, shift);
    if (hs == NULL) goto done;
    res = PyNumber_Or(hs, plo);
done:
    Py_XDECREF(phi);
    Py_XDECREF(shift);
    Py_XDECREF(plo);
    Py_XDECREF(hs);
    return res;
}

static int pow_u128(u128 base, int exp, u128 *out) {
    u128 r = 1;
    while (exp-- > 0) {
        if (!mul_u128(r, base, &r)) return 0;
    }
    *out = r;
    return 1;
}

static PyObject *parse_error(const char *s) {
    PyErr_Format(PyExc_ValueError, "unable to parse quantity '%s'", s);
    return NULL;
}

static PyObject *quantity_parse(PyObject *self, PyObject *arg) {
    Py_ssize_t len;
    const char *s = PyUnicode_AsUTF8AndSize(arg, &len);
    if (s == NULL) return NULL;

    /* strip() like the Python parser */
    const char *p = s, *end = s + len;
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
        p++;
    while (end > p && (end[-1] == ' ' || end[-1] == '\t' ||
                       end[-1] == '\n' || end[-1] == '\r'))
        end--;
    if (p == end) return parse_error(s);

    int negative = 0;
    if (*p == '+' || *p == '-') {
        negative = (*p == '-');
        p++;
    }

    /* mantissa: \d+(\.\d*)? | \.\d+  -> digits with implicit scale */
    u128 mantissa = 0;
    int int_digits = 0, frac_digits = 0, seen_dot = 0;
    while (p < end) {
        if (*p >= '0' && *p <= '9') {
            if (!mul_u128(mantissa, 10, &mantissa)) return parse_error(s);
            u128 add = (u128)(*p - '0');
            if (mantissa > U128_MAX - add) return parse_error(s);
            mantissa += add;
            if (seen_dot) frac_digits++;
            else int_digits++;
            p++;
        } else if (*p == '.' && !seen_dot) {
            seen_dot = 1;
            p++;
        } else {
            break;
        }
    }
    if (int_digits == 0 && frac_digits == 0) return parse_error(s);
    if (seen_dot && int_digits == 0 && frac_digits == 0)
        return parse_error(s);

    /* value so far = mantissa / 10^frac_digits */
    u128 num = mantissa, den;
    if (!pow_u128(10, frac_digits, &den)) return parse_error(s);

    int format = 0; /* DecimalSI */

    if (p < end) {
        Py_ssize_t rest = end - p;
        u128 scale;
        if (rest == 2 && p[1] == 'i') {
            /* binary suffix Ki Mi Gi Ti Pi Ei */
            int power;
            switch (p[0]) {
            case 'K': power = 1; break;
            case 'M': power = 2; break;
            case 'G': power = 3; break;
            case 'T': power = 4; break;
            case 'P': power = 5; break;
            case 'E': power = 6; break;
            default: return parse_error(s);
            }
            if (!pow_u128(1024, power, &scale)) return parse_error(s);
            if (!mul_u128(num, scale, &num)) return parse_error(s);
            format = 1; /* BinarySI */
        } else if (rest == 1 && p[0] != '\0' &&
                   strchr("numkMGTPE", p[0]) != NULL) {
            format = 0; /* DecimalSI */
            switch (p[0]) {
            case 'n':
                if (!mul_u128(den, 1000000000ULL, &den))
                    return parse_error(s);
                break;
            case 'u':
                if (!mul_u128(den, 1000000ULL, &den)) return parse_error(s);
                break;
            case 'm':
                if (!mul_u128(den, 1000ULL, &den)) return parse_error(s);
                break;
            case 'k':
                if (!mul_u128(num, 1000ULL, &num)) return parse_error(s);
                break;
            case 'M':
                if (!mul_u128(num, 1000000ULL, &num)) return parse_error(s);
                break;
            case 'G':
                if (!mul_u128(num, 1000000000ULL, &num))
                    return parse_error(s);
                break;
            case 'T':
                if (!mul_u128(num, 1000000000000ULL, &num))
                    return parse_error(s);
                break;
            case 'P':
                if (!mul_u128(num, 1000000000000000ULL, &num))
                    return parse_error(s);
                break;
            case 'E':
                if (!mul_u128(num, 1000000000000000000ULL, &num))
                    return parse_error(s);
                break;
            }
        } else if ((p[0] == 'e' || p[0] == 'E') && rest >= 2) {
            /* scientific exponent [eE][+-]?\d+ */
            const char *q = p + 1;
            int eneg = 0;
            if (*q == '+' || *q == '-') {
                eneg = (*q == '-');
                q++;
            }
            if (q == end) return parse_error(s);
            long exp = 0;
            while (q < end) {
                if (*q < '0' || *q > '9') return parse_error(s);
                exp = exp * 10 + (*q - '0');
                if (exp > 64) return parse_error(s); /* fallback to Python */
                q++;
            }
            if (eneg) {
                if (!pow_u128(10, (int)exp, &scale)) return parse_error(s);
                if (!mul_u128(den, scale, &den)) return parse_error(s);
            } else {
                if (!pow_u128(10, (int)exp, &scale)) return parse_error(s);
                if (!mul_u128(num, scale, &num)) return parse_error(s);
            }
            format = 2; /* DecimalExponent */
        } else {
            return parse_error(s);
        }
    }

    /* reduce by gcd so Fraction construction is cheap */
    u128 a = num, b = den;
    while (b != 0) {
        u128 t = a % b;
        a = b;
        b = t;
    }
    if (a > 1) {
        num /= a;
        den /= a;
    }

    PyObject *pnum = u128_to_pylong(num);
    if (pnum == NULL) return NULL;
    if (negative) {
        PyObject *neg = PyNumber_Negative(pnum);
        Py_DECREF(pnum);
        pnum = neg;
        if (pnum == NULL) return NULL;
    }
    PyObject *pden = u128_to_pylong(den);
    if (pden == NULL) {
        Py_DECREF(pnum);
        return NULL;
    }
    PyObject *result = Py_BuildValue("(NNi)", pnum, pden, format);
    return result;
}

static PyMethodDef methods[] = {
    {"parse", quantity_parse, METH_O,
     "parse(s) -> (numerator, denominator, format_code); exact."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_kquantity",
    "Native k8s resource.Quantity parser", -1, methods,
};

PyMODINIT_FUNC PyInit__kquantity(void) { return PyModule_Create(&module); }
